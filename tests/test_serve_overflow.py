"""Host-RAM KV overflow tier (ISSUE 15): demote→promote must be
invisible to exactness, both tiers leak-free on every failure path,
and the warm tier machinery compile-free.

The load-bearing properties:

- **Token-identical across a demote→promote cycle.**  A request whose
  prefix was demoted to host RAM and promoted back emits exactly the
  tokens the same request emits via recompute prefill — greedy,
  sampled, speculative, CoW-triggering partial hits, and mid-stream
  admissions, across {fp, kv_int8, kv_int4} × pipeline depth {1, 2} —
  because the host copy is a bit-copy of the pool blocks (quantized
  payloads and scale planes included) and promotion rides the same
  ingest program a KV ship uses.
- **Exact slot parking.**  A mid-stream request swapped out by an
  admission it could not coexist with resumes, after restore, with
  the same tokens a never-parked run emits (the PRNG key is a function
  of seed + absolute token index; every other per-slot input is
  rebuilt from host truth).
- **Zero leaked blocks in either tier.**  Finish, deadline reap,
  cancel, and abort all return device AND host blocks; a parked
  request that dies mid-swap self-cleans.
- **Degrade = today's behavior.**  No tier, budget exhausted, or a
  full device pool at promote time → recompute/evict exactly as
  before, with the demote-vs-evict split telling "moved to host" from
  "lost forever".
- **Zero steady-state compiles.**  A warm engine demotes, promotes,
  parks, and restores without a single new XLA compile (the
  warmup-precompiled read/ingest/restore programs — the jit-guard
  stance).

Engines are shared per quant config with pipeline depth switched on
the warm engine (the PR 5 A/B lever), the test-serve compile-budget
discipline; this file backs ``make test-serve-overflow`` (210 s cap).
"""

import threading
import time

import jax
import numpy as np
import pytest

from test_jit_guard import compile_delta

from oim_tpu.autoscale import decode_load, encode_load
from oim_tpu.models import TransformerConfig, init_params
from oim_tpu.serve import Engine, GenRequest

pytestmark = pytest.mark.serve_overflow

CFG = dict(
    vocab_size=101,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
    use_pallas=False,
)

HOST_BYTES = 1 << 20

# One engine per quant × {plain, spec} config, warmed once and shared
# by every scenario (pipeline depth is a runtime A/B on the warm
# engine).  kv_blocks=10 with 5-block worst cases is the pressure
# geometry: one resident 2-block entry + two concurrent requests
# overflow the pool by exactly enough that the planner must demote.
BASE = dict(
    n_slots=4, max_len=64, chunk=4, prompt_buckets=(16, 32),
    kv_block=8, kv_blocks=10, prefix_cache_size=2,
)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


_ENGINES: dict = {}


def _engine(setup, **kw):
    cfg, params = setup
    key = tuple(sorted(kw.items()))
    if key not in _ENGINES:
        args = dict(BASE)
        args.update(kw)
        _ENGINES[key] = Engine(
            params, cfg, kv_host_bytes=HOST_BYTES, **args
        ).warmup()
    return _ENGINES[key]


def _prompt(seed: int, n: int) -> list[int]:
    rng = np.random.RandomState(seed)
    return rng.randint(0, CFG["vocab_size"], size=n).tolist()


def _flush_tiers(e: Engine) -> None:
    """Drop every prefix entry in BOTH tiers (idle engine) so the next
    run of the same request takes the recompute path — the oracle
    reset.  Counter-silent (warming guard), so tests can assert on the
    demote/evict split without subtracting flush noise."""
    e._warming = True
    try:
        with e._lock:
            e._clear_prefix_cache_locked()
            e._flush_host_tier_locked()
    finally:
        e._warming = False


def _gen(e: Engine, tokens, mn=4, **kw) -> list[int]:
    rid = e.submit(GenRequest(tokens=tokens, max_new_tokens=mn, **kw))
    e.run()
    return e.result(rid, timeout=0)


def _store_entry(e: Engine, tokens) -> None:
    rid = e.submit(GenRequest(
        tokens=tokens, max_new_tokens=2, cache_prefix=True,
    ))
    e.run()
    e.result(rid, timeout=0)


def _pressure(e: Engine, spec: bool) -> None:
    """Three concurrent worst-case admissions against the 10-block
    pool: the resident entry's blocks are the shortfall, so the
    planner demotes it (the reclaimable precheck holds — the entry is
    idle and exclusive)."""
    mn = 20 if spec else 24  # 5 worst-case blocks either way
    rids = [
        e.submit(GenRequest(tokens=_prompt(100 + i, 16), max_new_tokens=mn))
        for i in range(3)
    ]
    e.run()
    for rid in rids:
        e.result(rid, timeout=0)


def _no_leaks(e: Engine) -> None:
    """Device blocks = resident entries' refs only; host blocks =
    demoted entries + parked slots only (both tiers drained of
    transient owners)."""
    s = e.stats()
    assert s["active_slots"] == 0 and s["queued"] == 0
    assert s["parked_slots"] == 0
    with e._lock:
        entry_blocks = set()
        for blocks, _ in e._prefix_cache.values():
            entry_blocks.update(blocks)
        assert e._alloc.used_blocks == len(entry_blocks), (
            e._alloc.used_blocks, entry_blocks,
        )
        host_blocks = set()
        for blocks, _ in e._host_prefix.values():
            host_blocks.update(blocks)
        assert e._host.alloc.used_blocks == len(host_blocks), (
            e._host.alloc.used_blocks, host_blocks,
        )


# ---------------------------------------------------------------------------
# The demote→promote exactness matrix:
# {greedy, temp>0, spec-decode, prefix-CoW hit, mid-stream admission}
# × {fp, kv_int8, kv_int4} × pipeline depth {1, 2}, token-identical to
# the never-swapped oracle (same engine, both tiers flushed).

QUANTS = [
    {},
    {"kv_int8": True},
    {"kv_int4": True},
]


def _demote_promote_cycle(e, spec, hit_tokens, depth, **gkw):
    """Seed an entry, demote it under pressure, then serve
    ``hit_tokens`` (which promotes + hits) — returns (tokens, oracle
    tokens from the recompute path)."""
    e.set_pipeline_depth(depth)
    _flush_tiers(e)
    oracle = _gen(e, hit_tokens, **gkw)
    _flush_tiers(e)
    base = _prompt(1, 16)
    _store_entry(e, base)
    d0 = e.stats()["prefix_demotions"]
    _pressure(e, spec)
    s = e.stats()
    assert s["prefix_demotions"] > d0, "pressure did not demote"
    assert s["host_prefix_entries"] >= 1
    p0 = e.stats()["kv_promotions"]
    h0 = e.stats()["prefix_hits"]
    out = _gen(e, hit_tokens, **gkw)
    s = e.stats()
    assert s["kv_promotions"] > p0, "hit did not promote"
    assert s["prefix_hits"] > h0, "promoted entry did not hit"
    return out, oracle


@pytest.mark.parametrize("quant", QUANTS, ids=["fp", "kv8", "kv4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demote_promote_greedy(setup, quant, depth):
    e = _engine(setup, **quant)
    hit = _prompt(1, 16) + _prompt(2, 8)  # block-aligned extension
    out, oracle = _demote_promote_cycle(e, False, hit, depth)
    assert out == oracle
    _no_leaks(e)


@pytest.mark.parametrize("quant", QUANTS, ids=["fp", "kv8", "kv4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demote_promote_sampled(setup, quant, depth):
    e = _engine(setup, **quant)
    hit = _prompt(1, 16) + _prompt(3, 8)
    out, oracle = _demote_promote_cycle(
        e, False, hit, depth, temperature=0.8, seed=11,
    )
    assert out == oracle
    _no_leaks(e)


@pytest.mark.parametrize("quant", QUANTS, ids=["fp", "kv8", "kv4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demote_promote_cow_hit(setup, quant, depth):
    # The hit extends the promoted entry by a NON-block-aligned tail:
    # the partial entry block copy-on-writes right after the promote
    # ingest, device-stream-ordered before the tail prefill.
    e = _engine(setup, **quant)
    hit = _prompt(1, 16) + _prompt(4, 3)
    out, oracle = _demote_promote_cycle(e, False, hit, depth)
    assert out == oracle
    _no_leaks(e)


@pytest.mark.parametrize("quant", QUANTS, ids=["fp", "kv8", "kv4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demote_promote_spec_decode(setup, quant, depth):
    e = _engine(setup, spec_decode=2, **quant)
    hit = _prompt(1, 16) + _prompt(5, 8)
    out, oracle = _demote_promote_cycle(e, True, hit, depth)
    assert out == oracle
    _no_leaks(e)


@pytest.mark.parametrize("quant", QUANTS, ids=["fp", "kv8", "kv4"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demote_promote_mid_stream_admission(setup, quant, depth):
    """The promoted hit admits MID-STREAM beside an active request —
    the promote's staged install lands at the admission boundary the
    pipelined step loop grants, not on an idle engine."""
    e = _engine(setup, **quant)
    e.set_pipeline_depth(depth)
    hit = _prompt(1, 16) + _prompt(6, 8)
    _flush_tiers(e)
    oracle = _gen(e, hit)
    _flush_tiers(e)
    _store_entry(e, _prompt(1, 16))
    _pressure(e, False)
    assert e.stats()["host_prefix_entries"] >= 1
    long_rid = e.submit(GenRequest(tokens=_prompt(7, 16),
                                   max_new_tokens=24))
    e.step()  # long request admitted + first chunks in flight
    e.step()
    rid = e.submit(GenRequest(tokens=hit, max_new_tokens=4))
    e.run()
    assert e.result(rid, timeout=0) == oracle
    assert len(e.result(long_rid, timeout=0)) == 24
    assert e.stats()["prefix_hits"] > 0
    _no_leaks(e)


# ---------------------------------------------------------------------------
# Swap-based slot parking: restore is exact, lifecycle paths leak-free.


@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("sampled", [False, True], ids=["greedy", "temp"])
def test_park_restore_token_identical(setup, depth, sampled):
    e = _engine(setup, kv_blocks=8, prefix_cache_size=0)
    e.set_pipeline_depth(depth)
    gkw = dict(temperature=0.8) if sampled else {}
    # Solo oracles first (same engine, nothing else running).
    pA, pB = _prompt(20, 16), _prompt(21, 16)
    oA = _gen(e, pA, mn=30, seed=7, **gkw)
    oB = _gen(e, pB, mn=30, seed=9, **gkw)
    # Concurrent: 6-block worst cases cannot coexist in the 8-block
    # pool — B's admission parks A, restore resumes A exactly.
    ra = e.submit(GenRequest(tokens=pA, max_new_tokens=30, seed=7, **gkw))
    rb = e.submit(GenRequest(tokens=pB, max_new_tokens=30, seed=9, **gkw))
    e.run()
    s = e.stats()
    assert s["kv_parks"] > 0 and s["kv_unparks"] == s["kv_parks"]
    assert e.result(ra, timeout=0) == oA
    assert e.result(rb, timeout=0) == oB
    _no_leaks(e)


def test_park_spec_ngram_restore(setup):
    # n-gram speculative state (device history row) is rebuilt from
    # host truth on restore.
    e = _engine(setup, kv_blocks=8, prefix_cache_size=0, spec_decode=2)
    pA, pB = _prompt(22, 16), _prompt(23, 16)
    oA = _gen(e, pA, mn=26, seed=7)
    oB = _gen(e, pB, mn=26, seed=9)
    ra = e.submit(GenRequest(tokens=pA, max_new_tokens=26, seed=7))
    rb = e.submit(GenRequest(tokens=pB, max_new_tokens=26, seed=9))
    e.run()
    assert e.stats()["kv_parks"] > 0
    assert e.result(ra, timeout=0) == oA
    assert e.result(rb, timeout=0) == oB
    _no_leaks(e)


def test_parked_deadline_reaped(setup):
    e = _engine(setup, kv_blocks=8, prefix_cache_size=0)
    pA = _prompt(24, 16)
    ra = e.submit(GenRequest(
        tokens=pA, max_new_tokens=30,
        deadline=time.monotonic() + 0.25,
    ))
    rb = e.submit(GenRequest(tokens=_prompt(25, 16), max_new_tokens=30))
    # A admits in wave 1; B's admission parks A at the next boundary.
    for _ in range(8):
        e.step()
        if e.stats()["parked_slots"]:
            break
    assert e.stats()["parked_slots"] == 1
    # Expire A WHILE parked: the reap must fail it and return its
    # host blocks — a swap-out is invisible to the failure taxonomy.
    time.sleep(0.3)
    e.run()
    from oim_tpu.serve.engine import RequestFailedError

    assert len(e.result(rb, timeout=0)) == 30
    with pytest.raises(RequestFailedError, match="parked"):
        e.result_full(ra, timeout=0)
    _no_leaks(e)


def test_parked_cancel_and_abort(setup):
    e = _engine(setup, kv_blocks=8, prefix_cache_size=0)
    from oim_tpu.serve.engine import RequestFailedError

    # cancel() a parked request: reaped at the next step, blocks home.
    ra = e.submit(GenRequest(tokens=_prompt(26, 16), max_new_tokens=30))
    rb = e.submit(GenRequest(tokens=_prompt(27, 16), max_new_tokens=30))
    for _ in range(3):
        e.step()  # admit A, park A for B, B decoding
    if e.stats()["parked_slots"]:
        assert e.cancel(ra)
        e.run()
        with pytest.raises(RequestFailedError):
            e.result_full(ra, timeout=0)
        assert len(e.result(rb, timeout=0)) == 30
    else:  # scheduling landed differently: still drain clean
        e.run()
    _no_leaks(e)
    # abort() with a slot parked AND a swap-out in flight: everything
    # fails, both tiers drain.
    ra = e.submit(GenRequest(tokens=_prompt(28, 16), max_new_tokens=30))
    rb = e.submit(GenRequest(tokens=_prompt(29, 16), max_new_tokens=30))
    for _ in range(2):
        e.step()
    e.abort("test abort")
    for rid in (ra, rb):
        with pytest.raises((RequestFailedError, RuntimeError)):
            e.result_full(rid, timeout=0)
    e.run()  # drains the in-flight host write, if any
    _no_leaks(e)


def test_cancel_during_restore_window_not_dropped(setup):
    """A cancel() landing while _unpark_wave has the lock released for
    the restore's device writes must still take effect: the record
    stays in _parked (restoring=True) through the window, so the
    cancel marks it and the next reap fails the restored slot —
    instead of returning False and streaming to a dead client."""
    e = _engine(setup, kv_blocks=8, prefix_cache_size=0)
    from oim_tpu.serve.engine import RequestFailedError

    ra = e.submit(GenRequest(tokens=_prompt(33, 16), max_new_tokens=30))
    rb = e.submit(GenRequest(tokens=_prompt(34, 16), max_new_tokens=30))
    orig = e._write_host_payload
    cancelled = []

    def mid_restore(host_blocks, dev_blocks):
        # First restore write = ra coming back: cancel it right here,
        # inside the lock-released device-write window.
        if not cancelled:
            cancelled.append(e.cancel(ra))
        orig(host_blocks, dev_blocks)

    e._write_host_payload = mid_restore
    try:
        e.run()
    finally:
        e._write_host_payload = orig
    assert cancelled == [True]  # visible mid-window, not "unknown"
    with pytest.raises(RequestFailedError):
        e.result_full(ra, timeout=0)
    assert len(e.result(rb, timeout=0)) == 30
    _no_leaks(e)


def test_draft_model_engine_refuses_parking(setup):
    cfg, params = setup
    draft_cfg = TransformerConfig(**{**CFG, "n_layers": 1})
    draft_params = init_params(jax.random.PRNGKey(1), draft_cfg)
    e = Engine(
        params, cfg, **{**BASE, "prefix_cache_size": 0},
        kv_host_bytes=HOST_BYTES, spec_decode=2,
        draft_params=draft_params, draft_cfg=draft_cfg,
    )
    # The draft slot cache is device-derived state restore cannot
    # rebuild — parking stays off, demote/promote stays available.
    assert not e.kv_park
    assert e._host is not None


# ---------------------------------------------------------------------------
# Degrade paths and accounting.


def test_no_tier_still_evicts(setup):
    cfg, params = setup
    e = Engine(params, cfg, **BASE).warmup()
    _store_entry(e, _prompt(1, 16))
    ev0 = e.stats()["prefix_evictions"]
    _pressure(e, False)
    s = e.stats()
    assert s["prefix_evictions"] > ev0  # today's behavior, now counted
    assert s["prefix_demotions"] == 0
    assert s["kv_host_blocks_total"] == 0


def test_host_budget_exhausted_evicts_lru(setup):
    cfg, params = setup
    # Budget = 2 blocks: exactly one demoted entry fits; the second
    # demotion host-LRU-evicts the first (lost forever → eviction
    # counter), never leaks, never wedges.
    row_bytes = Engine(
        params, cfg, **BASE, kv_host_bytes=HOST_BYTES
    )._kv_row_bytes
    e = Engine(
        params, cfg, **BASE, kv_host_bytes=2 * 8 * row_bytes,
    ).warmup()
    assert e.stats()["kv_host_blocks_total"] == 2
    _store_entry(e, _prompt(1, 16))
    _pressure(e, False)
    assert e.stats()["host_prefix_entries"] == 1
    _store_entry(e, _prompt(40, 16))
    ev0 = e.stats()["prefix_evictions"]
    _pressure(e, False)
    s = e.stats()
    assert s["host_prefix_entries"] == 1  # LRU replaced, not grown
    assert s["prefix_evictions"] > ev0
    _no_leaks(e)


def test_host_evict_skips_pinned_entries(setup):
    """A host entry pinned by an in-flight promotion snapshot frees
    nothing on decref: the host-LRU evictor must neither count it as
    reclaimable nor destroy it for zero gained capacity (the
    refcount-aware precheck, mirroring the device twin)."""
    e = _engine(setup)
    _flush_tiers(e)
    _store_entry(e, _prompt(1, 16))
    _pressure(e, False)
    with e._lock:
        assert e._host_prefix
        key, (blocks, _) = next(iter(e._host_prefix.items()))
        e._host.alloc.incref(blocks)  # the promote snapshot's pin
        free0 = e._host.alloc.free_blocks
        ev0 = e.prefix_evictions
        e._evict_host_for_locked(free0 + 1)
        # Pinned: survives, nothing counted, nothing freed.
        assert key in e._host_prefix
        assert e.prefix_evictions == ev0
        assert e._host.alloc.free_blocks == free0
        e._host.alloc.decref(blocks)  # pin released
        e._evict_host_for_locked(free0 + 1)
        # Exclusive again: LRU eviction proceeds and covers the need.
        assert key not in e._host_prefix
        assert e.prefix_evictions == ev0 + 1
        assert e._host.alloc.free_blocks > free0
    _flush_tiers(e)
    _no_leaks(e)


def test_promote_capacity_shortfall_recomputes(setup):
    """A demoted entry whose promotion cannot reserve device blocks
    degrades to recompute — token-identical, entry retained in the
    host tier for a later promote."""
    e = _engine(setup)
    _flush_tiers(e)
    base = _prompt(1, 16)
    hit = base + _prompt(8, 8)
    oracle = _gen(e, hit)
    _flush_tiers(e)
    _store_entry(e, base)
    _pressure(e, False)
    assert e.stats()["host_prefix_entries"] >= 1
    # Pin the device pool nearly shut so the promote staging's
    # free-space-only reservation fails.
    with e._lock:
        pinned = e._alloc.alloc(e._alloc.free_blocks - 1)
    p0 = e.stats()["kv_promotions"]
    try:
        rid = e.submit(GenRequest(tokens=hit, max_new_tokens=4))
        with e._lock:  # promote must NOT have been staged
            assert not e._prefix_installs
    finally:
        with e._lock:
            e._alloc.decref(pinned)
            e._update_kv_gauges_locked()
    e.run()
    assert e.result(rid, timeout=0) == oracle
    s = e.stats()
    assert s["kv_promotions"] == p0
    assert s["host_prefix_entries"] >= 1  # retained for later
    _no_leaks(e)


def test_demote_evict_split_surfaces(setup):
    e = _engine(setup)
    _flush_tiers(e)
    _store_entry(e, _prompt(1, 16))
    _pressure(e, False)
    s = e.stats()
    for k in (
        "kv_demotions", "kv_promotions", "kv_demote_seconds",
        "kv_promote_seconds", "kv_host_blocks_total",
        "kv_host_blocks_free", "prefix_demotions", "prefix_evictions",
        "parked_slots", "kv_park", "kv_promote_wall_p50",
    ):
        assert k in s
    assert s["kv_demote_seconds"] >= 0.0
    load = e.load()
    snap = decode_load(encode_load(load))
    assert snap["kv_host_blocks_total"] == s["kv_host_blocks_total"]
    assert snap["kv_demotions"] == s["kv_demotions"]
    assert snap["prefix_demotions"] == s["prefix_demotions"]
    info = e.info()["engine"]
    assert info["kv_host_bytes"] == HOST_BYTES
    assert info["kv_host_blocks"] == s["kv_host_blocks_total"]
    assert info["kv_park"] is True
    # The shared metric carries the demote|evict outcomes and the
    # tier gauge the host state.
    from oim_tpu.common import metrics as _metrics

    text = _metrics.registry().render()
    assert 'oim_serve_prefix_cache_total{outcome="demote"}' in text
    assert "oim_serve_kv_tier_moves_total" in text
    assert 'state="host"' in text


def test_validation_guards(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="paged cache"):
        Engine(params, cfg, n_slots=2, max_len=64,
               kv_host_bytes=HOST_BYTES)
    with pytest.raises(ValueError, match="holds no block"):
        Engine(params, cfg, **BASE, kv_host_bytes=8)
    with pytest.raises(ValueError, match=">= 0"):
        Engine(params, cfg, **BASE, kv_host_bytes=-1)


def test_concurrent_ingest_demote_thread_safety(setup):
    """A handler-thread demotion (the KV-ingest shortfall path) racing
    the driver's donating dispatches must retry through the donation
    race and never corrupt either tier — the _read_blocks_dispatch
    re-snapshot contract."""
    e = _engine(setup)
    _flush_tiers(e)
    stop = threading.Event()
    errors = []

    def hammer():
        try:
            while not stop.is_set():
                with e._lock:
                    # free+1 makes any idle exclusive entry the
                    # shortfall's cover: demote it (handler-thread
                    # read_block dispatches racing the driver).
                    e._evict_prefix_for_locked(
                        e._alloc.free_blocks + 1
                    )
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for i in range(6):
            _store_entry(e, _prompt(1, 16))
            _gen(e, _prompt(60 + i, 16), mn=8)
    finally:
        stop.set()
        t.join()
    assert not errors
    e.run()
    _no_leaks(e)


# ---------------------------------------------------------------------------
# The recompile guard row: warm demote/promote/park at ZERO compiles.


def test_warm_tier_machinery_zero_compiles(setup):
    e = _engine(setup)
    e.set_pipeline_depth(2)
    _flush_tiers(e)
    # Prime every path once (entries, pressure shapes) on the warm
    # engine, then pin the second full cycle at zero.
    base = _prompt(1, 16)
    for _ in range(2):
        delta = compile_delta()
        with delta:
            _store_entry(e, base)
            _pressure(e, False)
            assert e.stats()["host_prefix_entries"] >= 1
            out = _gen(e, base + _prompt(9, 8))
            assert e.stats()["kv_promotions"] > 0
            ra = e.submit(GenRequest(tokens=_prompt(30, 16),
                                     max_new_tokens=30))
            rb = e.submit(GenRequest(tokens=_prompt(31, 16),
                                     max_new_tokens=30))
            rc = e.submit(GenRequest(tokens=_prompt(32, 16),
                                     max_new_tokens=30))
            e.run()
            assert out  # streams completed
        _flush_tiers(e)
    assert delta.count == 0, (
        f"{delta.count} steady-state compile(s) in the warm "
        f"demote/promote/park cycle"
    )
    _no_leaks(e)
