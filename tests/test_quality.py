"""Dependency-free static quality gates.

≙ the reference's `make test` lint battery (gofmt + gometalinter + the
"no glog in binaries" grep, reference test/test.make:24-56, :119-124).
No linter ships in this image, so the gates are AST-level and exact:

- every library module parses and carries a docstring;
- no unused imports (the one lint class that reliably signals dead code);
- no ``print()`` in library code — the structured logger is the output
  surface (printing is the CLI's and tools' job);
- no mutable default arguments.
"""

from __future__ import annotations

import ast
import functools
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "oim_tpu")

# print() is the user interface of the CLI binaries and demo tools.
PRINT_ALLOWED = ("oim_tpu/cli/",)


def _library_files():
    out = []
    for root, _dirs, files in os.walk(LIB):
        rel = os.path.relpath(root, LIB)
        if "gen" in rel.split(os.sep):
            continue  # generated protobuf bindings
        for name in files:
            if name.endswith(".py"):
                out.append(os.path.join(root, name))
    return sorted(out)


FILES = _library_files()
assert FILES, "library file discovery broke"


@functools.lru_cache(maxsize=None)
def _parse(path):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return ast.parse(source, filename=path), source


@pytest.mark.parametrize("path", FILES, ids=lambda p: os.path.relpath(p, REPO))
def test_module_docstring(path):
    tree, _ = _parse(path)
    if os.path.basename(path) == "__init__.py" and not tree.body:
        return  # empty package marker
    assert ast.get_docstring(tree), "module lacks a docstring"


@pytest.mark.parametrize("path", FILES, ids=lambda p: os.path.relpath(p, REPO))
def test_no_unused_imports(path):
    tree, source = _parse(path)
    if os.path.basename(path) == "__init__.py":
        pytest.skip("packages re-export")
    imported: dict[str, ast.stmt] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                imported[name] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directives, not names
            for alias in node.names:
                if alias.name == "*":
                    continue
                imported[alias.asname or alias.name] = node
    used = {
        node.id
        for node in ast.walk(tree)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
    }
    # Strings in __all__ count as uses (re-export surface).
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            used |= {
                elt.value
                for elt in node.value.elts
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            }
    unused = sorted(name for name in imported if name not in used)
    assert not unused, f"unused imports: {unused}"


@pytest.mark.parametrize("path", FILES, ids=lambda p: os.path.relpath(p, REPO))
def test_no_print_in_library(path):
    rel = os.path.relpath(path, REPO).replace(os.sep, "/")
    if any(rel.startswith(prefix) for prefix in PRINT_ALLOWED):
        pytest.skip("CLI surface prints deliberately")
    tree, _ = _parse(path)
    offenders = [
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    ]
    assert not offenders, (
        f"print() at lines {offenders} — use oim_tpu.log (the reference "
        "bans glog from its binaries the same way, test.make:119-124)"
    )


@pytest.mark.parametrize("path", FILES, ids=lambda p: os.path.relpath(p, REPO))
def test_no_mutable_default_args(path):
    tree, _ = _parse(path)
    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in node.args.defaults + node.args.kw_defaults:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    offenders.append(f"{node.name}:{node.lineno}")
    assert not offenders, f"mutable default arguments: {offenders}"
