"""Steady-state recompile guard (ISSUE 11): a WARM serve engine pays
zero XLA compiles under live traffic.

The static retrace-risk pass catches the statically-visible recompile
shapes (python branches on traced params, scalar cache-key churn, jit
rebuilt per step); this suite is the runtime complement for everything
it cannot see — shape-dependent recompiles, weak-type promotion, an
unwarmed code path reached first by live traffic.  It counts backend
compiles via ``jax.monitoring``'s per-compile duration event around a
warmed engine driving the steady-state traffic mix the serve plane
actually runs:

    N decode chunks + one mid-stream admission + one prefix hit
    (CoW-triggering on paged), across {dense, paged} x {pipeline
    depth 1, depth 2}

and pins the count at **zero**.  Negative controls prove the counter
works: a fresh jit trips it, and a cold (never-warmed) engine trips it
from the very first admission.

On a TPU one stray compile is 20-40 s of dead air mid-stream; on the
CPU CI backend the same event is milliseconds — which is exactly why
this is pinned by COUNT, not by latency.
"""

from __future__ import annotations

import jax
import jax.monitoring
import jax.numpy as jnp
import numpy as np
import pytest

from oim_tpu.models import TransformerConfig, init_params
from oim_tpu.serve import Engine, GenRequest

pytestmark = pytest.mark.jit_guard

CFG = dict(
    vocab_size=101,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
    use_pallas=False,
)

# One backend-compile duration event fires per XLA compilation; the
# steady-state assertion is "no NEW events", so a process-wide counter
# plus deltas is race-free within the (single-threaded) test.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compiles = [0]


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event == _COMPILE_EVENT:
        _compiles[0] += 1


jax.monitoring.register_event_duration_secs_listener(_on_duration)


class compile_delta:
    """``with compile_delta() as d: ...; d.count`` — compiles inside."""

    def __enter__(self):
        self._start = _compiles[0]
        return self

    def __exit__(self, *exc):
        self.count = _compiles[0] - self._start
        return False

    @property
    def so_far(self) -> int:
        return _compiles[0] - self._start


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _prompt(seed: int, n: int, vocab: int) -> list[int]:
    rng = np.random.RandomState(seed)
    return rng.randint(0, vocab, size=n).tolist()


def _make_engine(
    setup, *, paged: bool, depth: int, kernel: bool = False
) -> Engine:
    cfg, params = setup
    kwargs = dict(
        n_slots=3, max_len=64, chunk=4, prompt_buckets=(16, 32),
        prefix_cache_size=2, pipeline_depth=depth,
    )
    if paged:
        kwargs["kv_block"] = 8
    if kernel:
        kwargs["paged_kernel"] = True  # interpret-mode pallas on CPU
    return Engine(params, cfg, **kwargs)


def _steady_traffic(engine: Engine, vocab: int) -> dict:
    """The serve plane's steady-state mix: a cached system prompt, a
    few decode chunks, a mid-stream admission joining at a pipeline
    boundary, and a prefix hit whose length is deliberately NOT
    block-aligned (12 tokens, kv_block 8) so the paged planner takes
    the copy-on-write path too."""
    system = _prompt(1, 12, vocab)
    r1 = engine.submit(GenRequest(
        tokens=system, max_new_tokens=10, cache_prefix=True,
    ))
    engine.step()
    engine.step()
    # Mid-stream admission: r1 still decoding, r2 joins at a boundary.
    r2 = engine.submit(GenRequest(
        tokens=_prompt(2, 6, vocab), max_new_tokens=6,
        temperature=0.8, seed=7,
    ))
    engine.step()
    # Prefix hit: shares the cached system prompt, adds a tail.
    r3 = engine.submit(GenRequest(
        tokens=system + _prompt(3, 5, vocab), max_new_tokens=5,
    ))
    results = engine.run()
    assert len(results[r1]) == 10
    assert len(results[r2]) == 6
    assert len(results[r3]) == 5
    return results


@pytest.mark.parametrize(
    "paged,depth,kernel",
    [
        (False, 1, False), (False, 2, False),
        (True, 1, False), (True, 2, False),
        # The paged flash-decode kernel (ISSUE 13): the pallas call is
        # traced into the decode programs, so a warm kernel engine
        # must hold the same zero — an unwarmed kernel variant would
        # be a 20-40s mid-stream stall on a live TPU.
        (True, 1, True), (True, 2, True),
    ],
    ids=[
        "dense-d1", "dense-d2", "paged-d1", "paged-d2",
        "paged-kernel-d1", "paged-kernel-d2",
    ],
)
def test_warm_engine_steady_state_compiles_zero(setup, paged, depth, kernel):
    """THE pin: {dense, paged, paged+kernel} x {depth 1, 2}, zero
    compiles after warmup across decode chunks, a mid-stream
    admission, and a prefix hit (CoW-triggering on paged)."""
    engine = _make_engine(setup, paged=paged, depth=depth, kernel=kernel)
    engine.warmup()
    with compile_delta() as d:
        _steady_traffic(engine, CFG["vocab_size"])
    assert d.count == 0, (
        f"steady state recompiled {d.count}x (paged={paged}, "
        f"depth={depth}, kernel={kernel}) — a live TPU pays 20-40s of "
        f"dead air per event"
    )


def test_prefix_hit_is_copy_free_reuse(setup):
    """The zero-compile run above must actually have exercised the
    prefix machinery (a vacuous guard would pass on any engine)."""
    engine = _make_engine(setup, paged=True, depth=2)
    engine.warmup()
    before = engine.prefix_hits + engine.prefix_injects
    _steady_traffic(engine, CFG["vocab_size"])
    assert engine.prefix_hits + engine.prefix_injects > before


def test_negative_control_fresh_jit_trips_counter():
    """The counter counts: a brand-new jit program is one compile."""
    with compile_delta() as d:
        jax.jit(lambda x: x * 2 + 1)(jnp.arange(7))
    assert d.count >= 1


def test_negative_control_cold_engine_trips_guard(setup):
    """The deliberate-retrace injection: the same traffic on a NEVER
    warmed engine compiles on the spot — the guard assertion would
    fail, proving it can."""
    engine = _make_engine(setup, paged=False, depth=2)
    with compile_delta() as d:
        _steady_traffic(engine, CFG["vocab_size"])
    assert d.count >= 1, "cold engine compiled nothing — counter broken"


def test_negative_control_unwarmed_surface_trips_guard(setup):
    """A subtler injected retrace: warm the engine WITHOUT the embed
    surface (``warmup(embed=False)``, the default), then hit
    ``engine.embed`` — an unwarmed program, so the guard counts its
    compile.  This is the exact failure mode the guard exists for: a
    surface the warmup recipe forgot, found by count instead of by a
    20-40s TPU stall on live traffic."""
    cfg, _params = setup
    engine = _make_engine(setup, paged=False, depth=1)
    engine.warmup()
    with compile_delta() as d:
        engine.embed(_prompt(5, 6, cfg.vocab_size))
    assert d.count >= 1, "unwarmed embed surface compiled nothing"


def test_warm_migrate_export_import_resume_compiles_zero(setup):
    """Live migration on the serving hot path (ISSUE 17): suspending
    a warm source, exporting its slot, staging it on a warm target,
    and resuming the continuation through ``kv_import`` must all ride
    warmup-precompiled programs — the export gathers through the
    prefix ship path, the import writes through the precompiled
    ingest, and the continuation's tail prefill lands in an existing
    bucket.  A compile here would stall BOTH backends of a drain
    mid-migration, exactly when the fleet is short one replica."""
    from oim_tpu.serve import disagg
    from oim_tpu.serve.engine import RequestFailedError

    cfg, _params = setup
    src = _make_engine(setup, paged=True, depth=2)
    dst = _make_engine(setup, paged=True, depth=2)
    src.warmup()
    dst.warmup()

    def cycle(seed: int) -> None:
        got: list = []
        rid = src.submit(
            GenRequest(tokens=_prompt(seed, 12, cfg.vocab_size),
                       max_new_tokens=10),
            on_token=lambda t, lp: got.append(t) if t is not None
            else None,
        )
        for _ in range(40):
            src.step()
            if got:
                break
        src.begin_migrate_out()
        src.run()
        with pytest.raises(RequestFailedError):
            src.result(rid, timeout=5)
        manifest, arrays = src.export_slot(rid)
        body = disagg.pack_transfer(manifest, arrays)
        import_id, _rows, slot = dst.import_slot(
            *disagg.unpack_transfer(body)
        )
        crid = dst.submit(GenRequest(
            tokens=list(manifest["prompt_tokens"])
            + list(manifest["tokens"]),
            max_new_tokens=10 - len(manifest["tokens"]),
            kv_import=import_id,
            sample_base=slot["sample_base"],
        ))
        dst.run()
        assert dst.result(crid, timeout=5)
        src.release_migrated(rid)
        src._draining = False
        src._migrate_out = False

    cycle(31)  # shake out any first-use program
    with compile_delta() as d:
        cycle(32)
    assert d.count == 0, (
        f"warm migrate cycle recompiled {d.count}x — export, import, "
        f"or the kv_import continuation missed the warmup recipe"
    )
