"""Fleet autoscaler: policy boundaries, deterministic simulation, chaos.

Three tiers, all hardware-free:

- **Policy units**: the band decision and its edges — exact-watermark
  no-flap, the anti-flap projection, min/max clamps, step bounds,
  cooldown-expiry instants, ENOSPC backoff — as pure functions of
  explicit inputs (oim_tpu/autoscale/policy.py).
- **Simulation harness** (ISSUE 8 acceptance): a MemRegistryDB, a fake
  actuator/launcher pair that flips the same registry keys real
  components would, an injectable clock, and a synthetic load
  generator.  Ramp-to-overload converges idle→max in a bounded number
  of evaluation periods; ramp-down converges with zero flap cycles
  under oscillating load at the band edge; a killed backend is
  replaced without operator action; an eviction replaces onto a FRESH
  slice; restarting the autoscaler between decision and actuation
  provisions exactly one slice.
- **Chaos soak**: the autoscaler driving a REAL Controller + fake
  agent through the registry proxy at 20% injected transport failure
  (the PR 2 harness) — zero leaked slices, zero double-provisions.

Plus the serving-plane integration seams: Engine.load(), the
load/<cn> registry contract end-to-end through ServeRegistration, the
router's per-backend load surface, and the streamed weight-fetch /
restore-from-peer bring-up path.
"""

from __future__ import annotations

import json
import time
import urllib.request

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.autoscale import (
    SCALE_IN,
    SCALE_OUT,
    Autoscaler,
    AutoscalePolicy,
    ControllerActuator,
    FleetSnapshot,
    InProcessLauncher,
    PolicyState,
    PoolExhaustedError,
    ReplicaRecord,
    decide,
    decode_load,
    encode_load,
    load_key,
    parse_load_path,
)
from oim_tpu.autoscale.autoscaler import PROVISIONING, replica_record_key
from oim_tpu.common import events, metrics, resilience
from oim_tpu.common.chaos import FlakyAgent
from oim_tpu.controller import Controller
from oim_tpu.health import FleetMonitor, states
from oim_tpu.registry import MemRegistryDB, Registry
from tests.helpers import FakeServicerContext, wait_for

pytestmark = pytest.mark.autoscale


# ---------------------------------------------------------------------------
# Policy units: the band decision's exact boundaries


def _policy(**kw):
    defaults = dict(
        min_replicas=1,
        max_replicas=4,
        slots_per_replica=4,
        high_watermark=0.8,
        low_watermark=0.3,
        max_step=1,
        scale_out_cooldown_s=10.0,
        scale_in_cooldown_s=20.0,
        enospc_backoff_s=30.0,
    )
    defaults.update(kw)
    return AutoscalePolicy(**defaults)


class TestPolicy:
    def test_scale_out_above_high(self):
        d = decide(_policy(), FleetSnapshot(replicas=2, busy=7, capacity=8))
        assert d.direction == SCALE_OUT and d.count == 1

    def test_scale_in_below_low(self):
        d = decide(_policy(), FleetSnapshot(replicas=2, busy=1, capacity=8))
        assert d.direction == SCALE_IN and d.count == 1

    def test_exact_high_watermark_holds(self):
        """Load exactly AT the high watermark takes no action — the
        band is strict, so watermark-exact load cannot flap."""
        d = decide(
            _policy(), FleetSnapshot(replicas=2, busy=0.8 * 8, capacity=8)
        )
        assert d.direction is None

    def test_exact_low_watermark_holds(self):
        d = decide(
            _policy(), FleetSnapshot(replicas=2, busy=0.3 * 8, capacity=8)
        )
        assert d.direction is None

    def test_projection_blocks_flapping_scale_in(self):
        """Below the low watermark but removing a replica would project
        utilization past the HIGH watermark: stay put (the very next
        evaluation would otherwise scale back out — a flap cycle)."""
        policy = _policy(low_watermark=0.45)
        # util = 3.4/8 = 0.425 < 0.45; projected = 3.4/4 = 0.85 > 0.8.
        d = decide(policy, FleetSnapshot(replicas=2, busy=3.4, capacity=8))
        assert d.direction is None
        assert "project" in d.reason

    def test_projection_allows_safe_scale_in(self):
        policy = _policy(low_watermark=0.45)
        # util = 1.4/8 = 0.175; projected = 1.4/4 = 0.35 < 0.8: safe.
        d = decide(policy, FleetSnapshot(replicas=2, busy=1.4, capacity=8))
        assert d.direction == SCALE_IN

    def test_max_replicas_clamp(self):
        d = decide(_policy(), FleetSnapshot(replicas=4, busy=16, capacity=16))
        assert d.direction is None
        assert "max_replicas" in d.reason

    def test_min_replicas_clamp(self):
        d = decide(_policy(), FleetSnapshot(replicas=1, busy=0, capacity=4))
        assert d.direction is None

    def test_bootstrap_below_min(self):
        """An empty fleet bootstraps to min_replicas with zero load."""
        d = decide(_policy(), FleetSnapshot(replicas=0, busy=0, capacity=0))
        assert d.direction == SCALE_OUT and d.count == 1
        assert "min_replicas" in d.reason

    def test_above_max_sheds(self):
        d = decide(
            _policy(max_step=2),
            FleetSnapshot(replicas=7, busy=20, capacity=28),
        )
        assert d.direction == SCALE_IN and d.count == 2

    def test_max_step_bounds_scale_out(self):
        policy = _policy(max_step=2)
        d = decide(policy, FleetSnapshot(replicas=1, busy=40, capacity=4))
        assert d.direction == SCALE_OUT and d.count == 2

    def test_zero_capacity_with_backlog_is_overload(self):
        snap = FleetSnapshot(replicas=1, busy=3, capacity=0)
        assert snap.utilization == float("inf")

    def test_cooldown_blocks_then_expiry_instant_allows(self):
        state = PolicyState(_policy(scale_out_cooldown_s=10.0))
        state.note_action(SCALE_OUT, now=100.0)
        assert state.cooldown_blocks(SCALE_OUT, now=109.999)
        # The expiry instant itself is allowed (>=, not >).
        assert not state.cooldown_blocks(SCALE_OUT, now=110.0)

    def test_cooldowns_are_per_direction(self):
        state = PolicyState(_policy())
        state.note_action(SCALE_OUT, now=100.0)
        assert state.cooldown_blocks(SCALE_OUT, now=101.0)
        assert not state.cooldown_blocks(SCALE_IN, now=101.0)

    def test_enospc_backoff_blocks_until_expiry(self):
        state = PolicyState(_policy(enospc_backoff_s=30.0))
        state.note_enospc(now=50.0)
        assert state.enospc_blocks(now=79.9)
        assert not state.enospc_blocks(now=80.0)

    def test_successful_scale_out_clears_backoff(self):
        state = PolicyState(_policy(enospc_backoff_s=1000.0))
        state.note_enospc(now=50.0)
        state.note_action(SCALE_OUT, now=60.0)
        assert not state.enospc_blocks(now=61.0)

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalePolicy(low_watermark=0.9, high_watermark=0.8)
        with pytest.raises(ValueError):
            AutoscalePolicy(max_step=0)


# ---------------------------------------------------------------------------
# Load schema


class TestLoadSchema:
    def test_round_trip(self):
        snap = {
            "queue_depth": 3,
            "active_slots": 2,
            "total_slots": 8,
            "kv_blocks_total": 64,
            "kv_blocks_free": 16,
            "kv_blocks_shared": 4,
            "kv_fragmentation": 0.25,
            # Fast-path discovery (ISSUE 13): flash-decode kernel +
            # kv4 quant rung flags ride the same tolerant schema.
            "paged_kernel": True,
            "kv_int4": False,
            # Chunked flash-prefill (ISSUE 20): staging-kernel admission
            # flag + segment length + cumulative segment dispatches.
            "prefill_kernel": True,
            "prefill_chunk": 16,
            "prefill_segments": 42,
            # Disaggregation fields (ISSUE 12): pool role + this
            # backend's share of the fleet's KV-ship traffic.
            "pool": "prefill",
            "kv_exports": 5,
            "kv_imports": 2,
            "kv_ship_bytes": 4096,
            # Fleet prefix residency (ISSUE 14): the capped resident-
            # digest summary + the hit/miss counters the router's
            # fleet prefix-hit rate sums.
            "prefix_digests": [
                {"digest": "ab12", "tokens": 128, "blocks": 2,
                 "age_s": 1.5, "hits": 3, "origin": "local"},
            ],
            "prefix_hits": 3,
            "prefix_misses": 1,
            # Host-RAM KV overflow tier (ISSUE 15): second-tier
            # headroom, demote/promote movement, parked slots, and
            # the demote-vs-evict split.
            "kv_host_blocks_total": 128,
            "kv_host_blocks_free": 100,
            "kv_host_fragmentation": 0.1,
            "kv_demotions": 6,
            "kv_promotions": 4,
            "parked_slots": 1,
            "prefix_demotions": 3,
            "prefix_evictions": 1,
            # KV-tier flow telemetry (ISSUE 18): park/restore counts
            # plus per-direction wall seconds and bytes — the `oimctl
            # kv` fleet view's bandwidth denominators.
            "kv_parks": 2,
            "kv_unparks": 1,
            "kv_demote_seconds": 0.25,
            "kv_promote_seconds": 0.125,
            "kv_demote_bytes": 98304,
            "kv_promote_bytes": 65536,
            "token_rate": 41.5,
            "shed_queue_full": 1,
            "shed_deadline": 0,
            "shed_brownout": 2,
            "brownout": True,
            # Multi-tenant QoS (ISSUE 16): per-tenant pressure rows +
            # the engine's priority-preemption total, merged fleet-wide
            # by the router for `oimctl tenants`.
            "tenants": {
                "user.gold": {
                    "tier": "premium", "weight": 8.0, "queued": 1,
                    "active": 1, "parked": 0, "admitted": 9,
                    "preempted": 2, "parked_victim": 0, "requests": 8,
                    "tokens_out": 512,
                },
            },
            "qos_preemptions": 2,
            # Live migration (ISSUE 17): drain state so the router's
            # _pick can exclude backends mid-migration.
            "draining": True,
            "ts": 123.5,
        }
        assert decode_load(encode_load(snap)) == snap

    def test_malformed_values_decode_none(self):
        assert decode_load("not json") is None
        assert decode_load("[1,2]") is None
        assert decode_load(json.dumps({"queue_depth": "nan"})) is None

    def test_missing_fields_default(self):
        decoded = decode_load("{}")
        assert decoded["queue_depth"] == 0 and decoded["total_slots"] == 0
        # Publishers predating the QoS fields (ISSUE 16) decode to
        # empty tenant tables, not errors.
        assert decoded["tenants"] == {} and decoded["qos_preemptions"] == 0
        # Publishers predating the KV-tier flow fields (ISSUE 18)
        # decode to zero flow, not errors — the mixed-fleet guarantee
        # `oimctl kv` leans on.
        assert decoded["kv_parks"] == 0 and decoded["kv_unparks"] == 0
        assert decoded["kv_demote_seconds"] == 0.0
        assert decoded["kv_promote_seconds"] == 0.0
        assert decoded["kv_demote_bytes"] == 0
        assert decoded["kv_promote_bytes"] == 0

    def test_path_helpers(self):
        assert load_key("serve.a") == "load/serve.a"
        assert parse_load_path("load/serve.a") == "serve.a"
        assert parse_load_path("load/serve.a/x") is None
        assert parse_load_path("serve/a/address") is None

    def test_registry_authz_grants_own_key_only(self):
        registry = Registry()
        try:
            assert (
                registry._check_set_allowed(
                    "load/serve.a1", FakeServicerContext("serve.a1")
                )
                is None
            )
            from tests.helpers import FakeAbort

            with pytest.raises(FakeAbort) as err:
                registry._check_set_allowed(
                    "load/serve.b2", FakeServicerContext("serve.a1")
                )
            assert err.value.code == grpc.StatusCode.PERMISSION_DENIED
        finally:
            registry.close()


# ---------------------------------------------------------------------------
# Deterministic simulation harness (fake actuator/launcher + fake clock)


class FakeClock:
    def __init__(self, start: float = 1000.0):
        self.t = start

    def monotonic(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeActuator:
    """Registry-of-record for fake slices.  Mimics the controller's
    idempotency: provisioning an id that already holds a slice returns
    the existing placement (exactly what ProvisionSlice + the MapVolume
    cache guarantee), so double-provision bugs show up as
    ``provisioned`` growing, not silently re-placing."""

    def __init__(self, pool_chips: int | None = None):
        self.pool_chips = pool_chips
        self.provisioned: dict[str, int] = {}
        self.provision_calls: list[str] = []
        self.sequence: list[tuple[str, str]] = []

    def provision(self, replica_id: str, chip_count: int) -> dict:
        self.provision_calls.append(replica_id)
        if replica_id not in self.provisioned:
            used = sum(self.provisioned.values())
            if (
                self.pool_chips is not None
                and used + chip_count > self.pool_chips
            ):
                raise PoolExhaustedError(
                    f"pool full: {used}/{self.pool_chips} chips used"
                )
            self.provisioned[replica_id] = chip_count
        self.sequence.append(("provision", replica_id))
        return {
            "volume_id": replica_id,
            "chips": [{"chip_id": i} for i in range(chip_count)],
            "mesh": [chip_count, 1, 1],
            "controller": "c0",
        }

    def deprovision(self, replica_id: str, controller_id: str) -> None:
        self.provisioned.pop(replica_id, None)
        self.sequence.append(("deprovision", replica_id))

    def close(self) -> None:
        pass


class FakeLauncher:
    """Flips the same registry keys a real launched oim-serve would:
    launch registers ``serve/<id>/address``, stop deregisters.  Load
    keys are the test's synthetic load generator's job."""

    def __init__(self, db):
        self.db = db
        self.running: dict[str, dict] = {}
        self.stops: list[tuple[str, bool]] = []
        self.launches: list[str] = []

    def launch(self, replica_id: str, placement: dict) -> None:
        self.launches.append(replica_id)
        self.running[replica_id] = placement
        self.db.store(f"serve/{replica_id}/address", f"http://{replica_id}")

    def stop(self, replica_id: str, drain: bool = True) -> None:
        self.stops.append((replica_id, drain))
        self.running.pop(replica_id, None)
        self.db.store(f"serve/{replica_id}/address", "")
        self.db.store(load_key(f"serve.{replica_id}"), "")

    def close(self) -> None:
        for rid in list(self.running):
            self.stop(rid, drain=False)


def set_load(db, sid: str, queue: int, active: int, total: int) -> None:
    db.store(
        load_key(f"serve.{sid}"),
        encode_load(
            {
                "queue_depth": queue,
                "active_slots": active,
                "total_slots": total,
                "token_rate": 10.0,
                "ts": time.time(),
            }
        ),
    )


class Sim:
    """One deterministic autoscaler universe."""

    def __init__(self, policy: AutoscalePolicy, pool_chips=None):
        self.db = MemRegistryDB()
        self.actuator = FakeActuator(pool_chips=pool_chips)
        self.launcher = FakeLauncher(self.db)
        self.clock = FakeClock()
        self.autoscaler = Autoscaler(
            self.db,
            policy,
            self.actuator,
            self.launcher,
            clock=self.clock.monotonic,
        )
        self.autoscaler.start(run_loop=False)

    def offer(self, busy_per_backend: float) -> None:
        """Synthetic load generator: spread ``busy_per_backend`` over
        every RUNNING backend (queue beyond the slot capacity)."""
        policy = self.autoscaler.policy
        for rid in list(self.launcher.running):
            total = policy.slots_per_replica
            active = min(int(busy_per_backend), total)
            queue = max(0, int(busy_per_backend) - total)
            set_load(self.db, rid, queue, active, total)

    def tick(self, busy_per_backend: float | None = None):
        if busy_per_backend is not None:
            self.offer(busy_per_backend)
        decision = self.autoscaler.evaluate_once()
        self.clock.advance(self.autoscaler.policy.eval_period_s)
        return decision

    def replica_count(self) -> int:
        return len(self.launcher.running)

    def close(self) -> None:
        self.autoscaler.close()
        self.db.close()


@pytest.fixture
def sim():
    sims: list[Sim] = []

    def make(policy=None, pool_chips=None) -> Sim:
        if policy is None:
            policy = _policy(
                scale_out_cooldown_s=5.0,
                scale_in_cooldown_s=5.0,
                eval_period_s=10.0,
            )
        instance = Sim(policy, pool_chips=pool_chips)
        sims.append(instance)
        return instance

    yield make
    for instance in sims:
        instance.close()


def _action_kinds() -> list[str]:
    return [
        e.kind
        for e in events.all_events()
        if e.kind.startswith("autoscale.")
    ]


class TestSimulation:
    def test_bootstrap_to_min_with_no_traffic(self, sim):
        s = sim()
        s.tick()
        assert s.replica_count() == 1
        assert "asr-0" in s.launcher.running

    def test_ramp_to_overload_converges_to_max_bounded(self, sim):
        """ISSUE acceptance: idle → sustained overload scales min → max
        within a bounded number of evaluation periods (one step per
        period once the cooldown is inside the period), and never past
        max."""
        s = sim()
        s.tick()  # bootstrap to min
        policy = s.autoscaler.policy
        budget = (policy.max_replicas - policy.min_replicas) + 2
        periods = 0
        while s.replica_count() < policy.max_replicas and periods < budget:
            s.tick(busy_per_backend=20)  # every backend drowning
            periods += 1
        assert s.replica_count() == policy.max_replicas, (
            f"did not reach max in {periods} periods"
        )
        # Sustained overload past max: clamped, never exceeded.
        for _ in range(3):
            s.tick(busy_per_backend=20)
        assert s.replica_count() == policy.max_replicas
        assert metrics.AUTOSCALE_DESIRED.value() == policy.max_replicas

    def test_ramp_down_zero_flap_under_band_edge_oscillation(self, sim):
        """ISSUE acceptance: after the ramp ends, load oscillating at
        the low-watermark edge converges down with ZERO flap cycles
        (no scale-out ever follows a scale-in)."""
        events.clear_all()
        s = sim()
        s.tick()
        # Ramp to max.
        for _ in range(6):
            s.tick(busy_per_backend=20)
        assert s.replica_count() == s.autoscaler.policy.max_replicas
        # Oscillate fleet-wide busy around the low watermark edge:
        # util alternates just above/below 0.3 while capacity shrinks.
        fleet_busy = [4.6, 5.0, 4.6, 5.0, 4.6, 5.0, 4.6, 5.0, 4.6, 5.0]
        for busy in fleet_busy:
            s.tick(busy_per_backend=busy / max(1, s.replica_count()))
        kinds = _action_kinds()
        first_in = kinds.index("autoscale.scale_in")
        assert "autoscale.scale_out" not in kinds[first_in:], (
            f"flap cycle detected: {kinds}"
        )
        # Converged to a size where the oscillation sits inside the
        # band, and stays there.
        settled = s.replica_count()
        for busy in fleet_busy:
            s.tick(busy_per_backend=busy / max(1, s.replica_count()))
        assert s.replica_count() == settled

    def test_killed_backend_replaced_without_operator_action(self, sim):
        """ISSUE acceptance: a killed backend (discovery key lost while
        its record says up) is relaunched on its recorded placement —
        no operator, no control-plane round trip."""
        events.clear_all()
        s = sim()
        s.tick()
        assert "asr-0" in s.launcher.running
        provisions_before = len(s.actuator.provision_calls)
        # Kill: the process dies, its leased discovery key expires.
        s.launcher.running.pop("asr-0")
        s.db.store("serve/asr-0/address", "")
        s.tick(busy_per_backend=1)
        assert "asr-0" in s.launcher.running, "not relaunched"
        assert s.db.lookup("serve/asr-0/address") != ""
        # Same slice: replacement took zero provision calls.
        assert len(s.actuator.provision_calls) == provisions_before
        assert "autoscale.replace" in _action_kinds()
        assert metrics.AUTOSCALE_ACTIONS.value("replace", "ok") >= 1

    def test_eviction_replaces_on_fresh_slice(self, sim):
        """A chip-failure eviction invalidates the slice: the old
        replica is torn down, the evicted volume id is retired, and
        capacity returns on a NEW id with a new slice."""
        s = sim()
        s.tick()
        assert s.actuator.provisioned == {"asr-0": 1}
        s.db.store(
            states.eviction_key("asr-0"),
            json.dumps({"state": "evicted", "reason": "chip-failed"}),
        )
        s.tick(busy_per_backend=1)
        assert "asr-0" not in s.actuator.provisioned
        assert "asr-0" not in s.launcher.running
        assert "asr-1" in s.launcher.running  # never reuses an evicted id
        assert s.actuator.provisioned == {"asr-1": 1}
        record = s.db.lookup(replica_record_key("asr-1"))
        assert json.loads(record)["state"] == "up"

    def test_monitor_listener_drives_replacement(self, sim):
        """Satellite: the autoscaler wired through FleetMonitor's
        listener API — a FAILED chip report classifying to an eviction
        replaces the replica with no second registry watch."""
        s = sim()
        monitor = FleetMonitor(s.db).start()
        try:
            s.autoscaler.attach_monitor(monitor)
            s.tick()
            assert "asr-0" in s.launcher.running
            s.db.store(
                states.health_key("h0", "0"),
                states.encode_report("FAILED", 0, "asr-0", time.time()),
            )
            assert wait_for(
                lambda: s.db.lookup(states.eviction_key("asr-0")) != ""
            )
            s.tick(busy_per_backend=1)
            assert "asr-0" not in s.launcher.running
            assert "asr-1" in s.launcher.running
        finally:
            monitor.close()

    def test_enospc_clamps_backs_off_and_recovers(self, sim):
        """Satellite: desire beyond the chip pool clamps with a
        WARNING event and a backoff — no crash-loop hammering — and
        the pool is re-probed once the backoff expires."""
        events.clear_all()
        policy = _policy(
            min_replicas=1,
            max_replicas=4,
            scale_out_cooldown_s=5.0,
            enospc_backoff_s=25.0,
            eval_period_s=10.0,
        )
        s = sim(policy=policy, pool_chips=2)
        s.tick()
        s.tick(busy_per_backend=20)
        assert s.replica_count() == 2
        calls_at_full = len(s.actuator.provision_calls)
        s.tick(busy_per_backend=20)  # pool full → clamp + backoff
        assert s.replica_count() == 2
        assert "autoscale.clamped" in _action_kinds()
        clamp_event = [
            e for e in events.all_events() if e.kind == "autoscale.clamped"
        ][-1]
        assert clamp_event.severity == events.WARNING
        assert metrics.AUTOSCALE_ACTIONS.value("out", "clamped") >= 1
        # Inside the backoff: no provisioning attempts at all.
        calls_after_clamp = len(s.actuator.provision_calls)
        s.tick(busy_per_backend=20)
        assert len(s.actuator.provision_calls) == calls_after_clamp
        # Pool grows (operator added chips); past the backoff the next
        # evaluation probes again and succeeds.
        s.actuator.pool_chips = 4
        s.clock.advance(30.0)
        s.tick(busy_per_backend=20)
        assert s.replica_count() == 3
        assert len(s.actuator.provision_calls) > calls_at_full

    def test_restart_between_decision_and_actuation_single_slice(self, sim):
        """ISSUE acceptance: an autoscaler that crashed after recording
        its decision (PROVISIONING) but before/amid actuation re-drives
        on restart and the fleet ends with EXACTLY one slice for the
        replica — ProvisionSlice's name-keyed idempotency, surfaced
        through deterministic id derivation."""
        s = sim()
        # Incarnation A decides (durable record) and half-actuates:
        # the slice lands but the launch never happens.
        record = ReplicaRecord(
            replica_id="asr-0", state=PROVISIONING, chips=1
        )
        s.db.store(replica_record_key("asr-0"), record.encode())
        s.actuator.provision("asr-0", 1)
        s.autoscaler.close()
        # Incarnation B: fresh autoscaler, same registry.
        b = Autoscaler(
            s.db,
            s.autoscaler.policy,
            s.actuator,
            s.launcher,
            clock=s.clock.monotonic,
        ).start(run_loop=False)
        try:
            b.evaluate_once()
            assert s.launcher.running.keys() == {"asr-0"}
            assert s.actuator.provisioned == {"asr-0": 1}, "slice leaked"
            assert (
                json.loads(s.db.lookup(replica_record_key("asr-0")))["state"]
                == "up"
            )
            # And the next id derivation never collides with it.
            assert b._next_replica_id() == "asr-1"
        finally:
            b.close()

    def test_decision_journal_records_acts_and_holds(self, sim):
        """ISSUE 9 satellite: every evaluation that wants to act leaves
        an `autoscale.decision` flight-recorder row carrying the
        FleetSnapshot it decided on — including evaluations HELD by a
        cooldown gate, so "why did (or didn't) it scale?" is
        answerable from `oimctl events` alone."""
        events.clear_all()
        s = sim()

        def decisions():
            return [
                e for e in events.all_events()
                if e.kind == "autoscale.decision"
            ]

        s.tick()  # bootstrap to min — itself a journaled decision
        n0 = len(decisions())
        s.tick(busy_per_backend=20)  # acts: scale out
        acted = decisions()[n0:]
        assert any(
            e.fields["direction"] == "out" and e.fields["held"] == ""
            for e in acted
        ), [e.fields for e in acted]
        row = acted[-1].fields
        for key in ("count", "reason", "utilization", "busy",
                    "capacity", "replicas", "high_watermark",
                    "low_watermark"):
            assert key in row, row
        assert row["utilization"] > row["high_watermark"]
        # Act once more (tick advanced the clock past the cooldown),
        # then re-evaluate WITHOUT advancing it: still overloaded, but
        # the fresh scale-out cooldown holds the action — journaled as
        # held.
        s.offer(20)
        s.autoscaler.evaluate_once()
        n1 = len(decisions())
        s.offer(20)
        s.autoscaler.evaluate_once()
        held = decisions()[n1:]
        assert any(e.fields["held"] == "cooldown" for e in held), (
            [e.fields for e in held]
        )

    def test_scale_in_drain_sequence_and_least_loaded_pick(self, sim):
        """The scale-in contract (doc/serving.md): discovery withdrawn
        BEFORE the drain-stop, unmap after, record dropped last — and
        the victim is the least-loaded backend."""
        s = sim()
        s.tick()
        for _ in range(2):
            s.tick(busy_per_backend=20)
        assert s.replica_count() == 3
        withdrawn_at_stop = {}
        original_stop = s.launcher.stop

        def asserting_stop(rid, drain=True):
            withdrawn_at_stop[rid] = s.db.lookup(f"serve/{rid}/address")
            original_stop(rid, drain)

        s.launcher.stop = asserting_stop
        # asr-1 is the least loaded.
        set_load(s.db, "asr-0", 0, 2, 4)
        set_load(s.db, "asr-1", 0, 0, 4)
        set_load(s.db, "asr-2", 0, 1, 4)
        s.autoscaler.evaluate_once()
        assert "asr-1" not in s.launcher.running
        assert {"asr-0", "asr-2"} <= set(s.launcher.running)
        # Withdraw-before-stop: by stop time the key was already gone.
        assert withdrawn_at_stop == {"asr-1": ""}
        assert ("asr-1", True) in s.launcher.stops  # drained, not killed
        assert "asr-1" not in s.actuator.provisioned  # unmapped + deleted
        assert s.db.lookup(replica_record_key("asr-1")) == ""

    def test_static_backends_never_scaled_in(self, sim):
        """Operator-provisioned backends participate in utilization but
        are never scale-in victims; with no managed replica to remove
        the autoscaler logs and holds."""
        s = sim(policy=_policy(min_replicas=1, max_replicas=4,
                               scale_out_cooldown_s=5.0,
                               scale_in_cooldown_s=5.0))
        s.db.store("serve/static-a/address", "http://static-a")
        s.db.store("serve/static-b/address", "http://static-b")
        set_load(s.db, "static-a", 0, 0, 4)
        set_load(s.db, "static-b", 0, 0, 4)
        decision = s.autoscaler.evaluate_once()
        # 2 live backends, idle: the band wants 1, but nothing managed
        # exists to remove.
        assert decision.direction == SCALE_IN
        assert s.db.lookup("serve/static-a/address") != ""
        assert s.db.lookup("serve/static-b/address") != ""
        assert not s.launcher.stops

    def test_transient_actuation_failure_redrives(self, sim):
        """A provision that dies mid-flight (non-ENOSPC) leaves the
        durable PROVISIONING record; the next evaluation re-drives it
        to completion instead of forgetting the replica."""
        s = sim()
        boom = {"armed": True}
        original = s.actuator.provision

        def flaky_provision(rid, chips):
            if boom.pop("armed", False):
                raise ConnectionError("proxy hop died")
            return original(rid, chips)

        s.actuator.provision = flaky_provision
        s.tick()  # bootstrap attempt fails mid-actuation
        assert s.replica_count() == 0
        assert metrics.AUTOSCALE_ACTIONS.value("out", "failed") >= 1
        s.tick()  # re-drive completes
        assert s.replica_count() == 1
        assert s.actuator.provisioned == {"asr-0": 1}


# ---------------------------------------------------------------------------
# Chaos soak: the real control plane at 20% injected transport failure


@pytest.fixture
def control_plane(tmp_path):
    """fake agent → controller → registry proxy (the PR 2 fleet
    fixture), with the registry's own DB doubling as the autoscaler's
    observation plane (the embedded deployment)."""
    store = ChipStore(mesh=(2, 2, 1), device_dir=str(tmp_path / "dev"))
    agent_srv = FakeAgentServer(store, str(tmp_path / "agent.sock")).start()
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    controller = Controller(
        "h0",
        agent_srv.socket_path,
        registry_address=str(reg_srv.addr()),
        registry_delay=0.2,
    )
    ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
    controller.start(str(ctrl_srv.addr()))
    assert wait_for(lambda: registry.db.lookup("h0/address") != "")
    yield store, agent_srv, registry, reg_srv
    controller.close()
    ctrl_srv.stop()
    reg_srv.stop()
    registry.close()
    agent_srv.stop()


@pytest.mark.chaos
def test_chaos_soak_no_leaks_no_double_provision(control_plane, monkeypatch):
    """ISSUE acceptance: 20% injected control-plane failure across a
    scale-out/in soak leaks no slices and never double-provisions —
    every settle point the device plane holds EXACTLY one allocation
    per managed replica, every chip accounted."""
    monkeypatch.setenv("OIM_RETRY_MAX_ATTEMPTS", "6")
    monkeypatch.setenv("OIM_RETRY_INITIAL_BACKOFF_S", "0.004")
    monkeypatch.setenv("OIM_RETRY_MAX_BACKOFF_S", "0.02")
    store, agent_srv, registry, reg_srv = control_plane
    actuator = ControllerActuator(
        str(reg_srv.addr()),
        ["h0"],
        retry=resilience.RetryPolicy.from_env(),
    )
    launcher = FakeLauncher(registry.db)
    clock = FakeClock()
    policy = _policy(
        min_replicas=1,
        max_replicas=3,
        chips_per_replica=1,
        scale_out_cooldown_s=1.0,
        scale_in_cooldown_s=1.0,
        eval_period_s=10.0,
    )
    autoscaler = Autoscaler(
        registry.db, policy, actuator, launcher, clock=clock.monotonic
    ).start(run_loop=False)

    def settle(target: int, busy: float, budget: int = 40) -> None:
        def settled() -> bool:
            # Target reached AND no half-done record pending re-drive:
            # a chaos-failed teardown must finish before the invariant
            # check reads the device plane.
            records = autoscaler.stats()["replicas"]
            return len(launcher.running) == target and all(
                rec["state"] == "up" for rec in records.values()
            )

        for _ in range(budget):
            for rid in list(launcher.running):
                total = policy.slots_per_replica
                active = min(int(busy), total)
                set_load(
                    registry.db, rid, max(0, int(busy) - total), active, total
                )
            autoscaler.evaluate_once()
            clock.advance(policy.eval_period_s)
            if settled():
                break
        assert settled(), (
            f"did not settle at {target}: running={sorted(launcher.running)} "
            f"records={autoscaler.stats()['replicas']}"
        )

    def assert_invariants() -> None:
        managed = {
            rid
            for rid, rec in autoscaler.stats()["replicas"].items()
            if rec["state"] == "up"
        }
        allocs = {
            name: alloc
            for name, alloc in store.allocations.items()
            if name.startswith("asr-")
        }
        assert set(allocs) == managed, (
            f"slice/replica drift: allocs={sorted(allocs)} "
            f"managed={sorted(managed)}"
        )
        for name, alloc in allocs.items():
            assert len(alloc.chip_ids) == policy.chips_per_replica, (
                f"{name} double-provisioned: {len(alloc.chip_ids)} chips"
            )

    try:
        with FlakyAgent(
            agent_srv.socket_path, "chaos_disconnect", rate=0.2, seed=1729
        ):
            for cycle in range(4):
                settle(3, busy=20)
                assert_invariants()
                settle(1, busy=0)
                assert_invariants()
        # Final settle with chaos off: nothing stranded mid-teardown.
        settle(1, busy=0)
        assert_invariants()
    finally:
        autoscaler.close()
        actuator.close()


# ---------------------------------------------------------------------------
# Serving-plane seams: Engine.load, registration, router, peer weights

CFG = dict(
    vocab_size=101,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
    use_pallas=False,
)


@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from oim_tpu.models import TransformerConfig, init_params

    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def serving(tiny_model):
    from oim_tpu.serve import Engine
    from oim_tpu.serve.server import ServeServer

    cfg, params = tiny_model
    server = ServeServer(
        Engine(params, cfg, n_slots=2, max_len=64, chunk=4)
    ).start()
    yield server
    server.stop()


def _get(url: str, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


class TestServingSeams:
    def test_engine_load_shape_and_shed_counters(self, tiny_model):
        from oim_tpu.serve import Engine
        from oim_tpu.serve.engine import GenRequest, QueueFullError

        cfg, params = tiny_model
        # No warmup/step: submit only queues, so this engine never
        # compiles — cheap enough to build per test.
        engine = Engine(params, cfg, n_slots=1, max_len=64, chunk=4,
                        max_queue=1)
        load = engine.load()
        assert load["queue_depth"] == 0 and load["active_slots"] == 0
        assert load["total_slots"] == 1 and load["ts"] > 0
        engine.submit(GenRequest(tokens=[1, 2], max_new_tokens=2))
        with pytest.raises(QueueFullError):
            engine.submit(GenRequest(tokens=[1, 2], max_new_tokens=2))
        load = engine.load()
        assert load["queue_depth"] == 1
        assert load["shed_queue_full"] == 1
        assert decode_load(encode_load(load)) == decode_load(
            encode_load(load)
        )

    def test_v1_info_mirrors_load(self, serving):
        info = _get(f"http://{serving.host}:{serving.port}/v1/info")
        assert "load" in info
        assert info["load"]["total_slots"] == 2
        assert set(info["load"]) >= {
            "queue_depth",
            "active_slots",
            "token_rate",
            "brownout",
            "shed_queue_full",
        }

    def test_registration_publishes_and_withdraws_load(self, serving):
        registry = Registry()
        srv = registry.start_server("tcp://127.0.0.1:0")
        try:
            from oim_tpu.serve import ServeRegistration

            reg = ServeRegistration(
                "lt1",
                str(srv.addr()),
                f"http://{serving.host}:{serving.port}",
                delay=0.1,
                load=serving.engine.load,
            )
            reg.start()
            try:
                assert wait_for(
                    lambda: registry.db.lookup("load/serve.lt1") != ""
                )
                decoded = decode_load(registry.db.lookup("load/serve.lt1"))
                assert decoded is not None
                assert decoded["total_slots"] == 2
            finally:
                reg.stop()
            # Deregistration withdraws BOTH keys in one beat.
            assert registry.db.lookup("serve/lt1/address") == ""
            assert registry.db.lookup("load/serve.lt1") == ""
        finally:
            srv.stop()
            registry.close()

    def test_router_stats_surface_backend_load(self, serving):
        from oim_tpu.serve import Router

        router = Router(
            backends=(f"http://{serving.host}:{serving.port}",),
            health_interval=0.1,
        ).start()
        try:
            def loaded():
                stats = _get(
                    f"http://{router.host}:{router.port}/v1/stats", timeout=5
                )
                backends = list(stats["backends"].values())
                return backends and backends[0]["load"]

            assert wait_for(loaded, timeout=15)
            stats = _get(f"http://{router.host}:{router.port}/v1/stats")
            load = next(iter(stats["backends"].values()))["load"]
            assert load["total_slots"] == 2
            assert "queue_depth" in load and "token_rate" in load
        finally:
            router.stop()

    def test_weight_fetch_restores_identical_params(self, serving, tiny_model):
        import jax
        import numpy as np

        from oim_tpu.checkpoint import load_params_from_peer
        from oim_tpu.models import init_params

        cfg, params = tiny_model
        template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
        restored = load_params_from_peer(
            f"http://{serving.host}:{serving.port}", template
        )
        assert set(restored) == set(params)
        for name in params:
            assert restored[name].dtype == params[name].dtype
            assert np.array_equal(
                np.asarray(restored[name]), np.asarray(params[name])
            ), f"leaf {name} differs"

    def test_weight_fetch_rejects_geometry_mismatch(self, serving, tiny_model):
        import jax

        from oim_tpu.checkpoint import load_params_from_peer
        from oim_tpu.models import TransformerConfig, init_params

        wrong = TransformerConfig(**{**CFG, "d_model": 64, "n_heads": 8})
        template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), wrong)
        )
        with pytest.raises(ValueError, match="different model geometry"):
            load_params_from_peer(
                f"http://{serving.host}:{serving.port}", template
            )

    def test_peer_restored_engine_generates_identically(
        self, serving, tiny_model
    ):
        """The bring-up claim end-to-end: an engine built from
        peer-fetched weights produces token-identical greedy output."""
        import jax

        from oim_tpu.checkpoint import load_params_from_peer
        from oim_tpu.models import init_params
        from oim_tpu.serve import Engine
        from oim_tpu.serve.engine import GenRequest

        cfg, params = tiny_model
        template = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg)
        )
        restored = load_params_from_peer(
            f"http://{serving.host}:{serving.port}", template
        )
        req = dict(tokens=[3, 1, 4, 1, 5], max_new_tokens=8)
        sibling = Engine(restored, cfg, n_slots=1, max_len=64, chunk=4)
        rid = sibling.submit(GenRequest(**req))
        want = sibling.run()[rid]
        via_http = _post_generate(serving, req)
        assert via_http == want

    def test_serve_main_params_peer_flag(self, serving):
        """make_engine's --params-peer branch end-to-end: an engine
        built by the CLI path from a sibling's /v1/weights."""
        from oim_tpu.cli.serve_main import build_parser, make_engine

        geometry = [
            "--vocab-size", str(CFG["vocab_size"]),
            "--d-model", str(CFG["d_model"]),
            "--n-layers", str(CFG["n_layers"]),
            "--n-heads", str(CFG["n_heads"]),
            "--d-ff", str(CFG["d_ff"]),
            "--dtype", CFG["dtype"],
            "--max-len", "64", "--n-slots", "1",
        ]
        with pytest.raises(SystemExit, match="exclusive"):
            make_engine(build_parser().parse_args(
                geometry + ["--params-dir", "/x", "--params-peer", "http://y"]
            ))
        args = build_parser().parse_args(
            geometry
            + ["--params-peer", f"http://{serving.host}:{serving.port}"]
        )
        engine = make_engine(args)
        load = engine.load()
        assert load["total_slots"] == 1

    def test_autoscale_metrics_registered(self):
        """Satellite: the fleet gauges + action counter render through
        the shared registry (the metrics lint's runtime half)."""
        metrics.AUTOSCALE_DESIRED.set(2.0)
        metrics.AUTOSCALE_ACTIONS.inc("out", "ok", by=0)
        metrics.SERVE_QUEUE_DEPTH.set(1.0, "t0")
        metrics.SERVE_ACTIVE_SLOTS.set(1.0, "t0")
        text = metrics.registry().render()
        for name in (
            "oim_autoscale_desired_replicas",
            "oim_autoscale_actions_total",
            "oim_serve_queue_depth",
            "oim_serve_active_slots",
        ):
            assert name in text, f"{name} missing from exposition"


def _post_generate(server, payload: dict) -> list[int]:
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())["tokens"]
