"""mTLS security matrix with a parallel evil-CA certificate tree.

≙ reference pkg/oim-registry/registry_test.go:251-390 + test/setup-ca.sh's
``_work/ca`` / ``_work/evil-ca`` trees: table-driven proof that
man-in-the-middle, wrong-host and wrong-peer are rejected in both directions
across the registry and controller surfaces.
"""

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.common.ca import CertAuthority
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.controller import Controller
from oim_tpu.registry import Registry
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """A secure deployment plus an evil CA: registry + controller + agent."""
    tmp = tmp_path_factory.mktemp("secmatrix")
    ca = CertAuthority("GOOD CA")
    evil = CertAuthority("EVIL CA")

    def tls(authority, cn, peer=""):
        cred = authority.issue(cn)
        return TLSConfig(ca.ca_pem, cred.cert_pem, cred.key_pem, peer)

    store = ChipStore(mesh=(2,), device_dir=str(tmp))
    agent_srv = FakeAgentServer(store, str(tmp / "agent.sock")).start()

    controller = Controller(
        "ctrl-1",
        agent_srv.socket_path,
        tls=tls(ca, "controller.ctrl-1"),
    )
    ctrl_srv = controller.start_server("tcp://127.0.0.1:0")

    registry = Registry(tls=tls(ca, "component.registry"))
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    registry.db.store("ctrl-1/address", str(ctrl_srv.addr()))

    yield {
        "ca": ca,
        "evil": evil,
        "registry_addr": reg_srv.addr(),
        "controller_addr": ctrl_srv.addr(),
    }
    reg_srv.stop()
    ctrl_srv.stop()
    controller.close()
    agent_srv.stop()


def _client_tls(ca_trusted: CertAuthority, issuer: CertAuthority, cn: str, peer: str):
    cred = issuer.issue(cn)
    return TLSConfig(ca_trusted.ca_pem, cred.cert_pem, cred.key_pem, peer)


def _registry_set(addr, tls: TLSConfig, path="x/y", value="z", timeout=5):
    channel = grpc.secure_channel(
        addr.grpc_target(), tls.channel_credentials(), options=tls.channel_options()
    )
    try:
        REGISTRY.stub(channel).SetValue(
            oim_pb2.SetValueRequest(value=oim_pb2.Value(path=path, value=value)),
            timeout=timeout,
        )
    finally:
        channel.close()


def _proxy_map(addr, tls: TLSConfig, controller_id="ctrl-1", timeout=5):
    channel = grpc.secure_channel(
        addr.grpc_target(), tls.channel_credentials(), options=tls.channel_options()
    )
    try:
        return CONTROLLER.stub(channel).MapVolume(
            oim_pb2.MapVolumeRequest(
                volume_id="vol-sec", slice=oim_pb2.SliceParams(chip_count=1)
            ),
            metadata=(("controllerid", controller_id),),
            timeout=timeout,
        )
    finally:
        channel.close()


# Table: (description, action, expect_ok, expected_code_or_None)
def test_security_matrix(world):
    ca, evil = world["ca"], world["evil"]
    reg, ctrl = world["registry_addr"], world["controller_addr"]

    cases = [
        (
            "admin with good CA may SetValue",
            lambda: _registry_set(reg, _client_tls(ca, ca, "user.admin", "component.registry")),
            None,
        ),
        (
            "evil-CA admin cert rejected by registry",
            lambda: _registry_set(reg, _client_tls(ca, evil, "user.admin", "component.registry")),
            grpc.StatusCode.UNAVAILABLE,  # TLS handshake failure
        ),
        (
            "client pinning wrong server CN rejects the registry (MITM guard)",
            lambda: _registry_set(reg, _client_tls(ca, ca, "user.admin", "controller.ctrl-1")),
            grpc.StatusCode.UNAVAILABLE,
        ),
        (
            "host.ctrl-1 may proxy to its controller",
            lambda: _proxy_map(reg, _client_tls(ca, ca, "host.ctrl-1", "component.registry")),
            None,
        ),
        (
            "host.ctrl-2 may NOT proxy to ctrl-1",
            lambda: _proxy_map(reg, _client_tls(ca, ca, "host.ctrl-2", "component.registry")),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "host CN may not SetValue",
            lambda: _registry_set(reg, _client_tls(ca, ca, "host.ctrl-1", "component.registry")),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "controller.ctrl-1 may set its own address",
            lambda: _registry_set(
                reg,
                _client_tls(ca, ca, "controller.ctrl-1", "component.registry"),
                path="ctrl-1/address",
                value="tcp://127.0.0.1:1",
            ),
            None,
        ),
        (
            "controller.ctrl-1 may NOT set another controller's address",
            lambda: _registry_set(
                reg,
                _client_tls(ca, ca, "controller.ctrl-1", "component.registry"),
                path="ctrl-2/address",
                value="tcp://evil:1",
            ),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "direct client→controller bypass rejected (controller only trusts the registry)",
            lambda: grpc_call_direct(ctrl, _client_tls(ca, ca, "user.admin", "controller.ctrl-1")),
            grpc.StatusCode.UNAUTHENTICATED,
        ),
        (
            "evil-CA host cert rejected at the TLS layer",
            lambda: _proxy_map(reg, _client_tls(ca, evil, "host.ctrl-1", "component.registry")),
            grpc.StatusCode.UNAVAILABLE,
        ),
    ]

    failures = []
    for description, action, expected_code in cases:
        try:
            action()
            if expected_code is not None:
                failures.append(f"{description}: unexpectedly succeeded")
        except grpc.RpcError as exc:
            if expected_code is None:
                failures.append(f"{description}: failed with {exc.code()}")
            elif exc.code() != expected_code:
                failures.append(
                    f"{description}: got {exc.code()}, want {expected_code}"
                )
    assert not failures, "\n".join(failures)


def grpc_call_direct(ctrl_addr, tls: TLSConfig):
    channel = grpc.secure_channel(
        ctrl_addr.grpc_target(),
        tls.channel_credentials(),
        options=tls.channel_options(),
    )
    try:
        return CONTROLLER.stub(channel).MapVolume(
            oim_pb2.MapVolumeRequest(
                volume_id="direct", slice=oim_pb2.SliceParams(chip_count=1)
            ),
            timeout=5,
        )
    finally:
        channel.close()


def test_evil_registry_mitm(world):
    """A fake registry presenting an evil-CA 'component.registry' cert:
    the controller's registration client must refuse it."""
    ca, evil = world["ca"], world["evil"]
    evil_cred = evil.issue("component.registry")
    evil_tls = TLSConfig(
        evil.ca_pem, evil_cred.cert_pem, evil_cred.key_pem, ""
    )
    evil_registry = Registry(tls=evil_tls)
    evil_srv = evil_registry.start_server("tcp://127.0.0.1:0")
    try:
        good_cred = ca.issue("controller.ctrl-1")
        controller = Controller(
            "ctrl-1",
            "/nonexistent.sock",
            registry_address=str(evil_srv.addr()),
            tls=TLSConfig(ca.ca_pem, good_cred.cert_pem, good_cred.key_pem),
        )
        controller._advertised_address = "tcp://127.0.0.1:9"
        with pytest.raises(grpc.RpcError):
            controller.register()
        # Nothing leaked into the evil registry.
        assert evil_registry.db.lookup("ctrl-1/address") == ""
    finally:
        evil_srv.stop()
