"""mTLS security matrix with a parallel evil-CA certificate tree.

≙ reference pkg/oim-registry/registry_test.go:251-390 + test/setup-ca.sh's
``_work/ca`` / ``_work/evil-ca`` trees: table-driven proof that
man-in-the-middle, wrong-host and wrong-peer are rejected in both directions
across the registry and controller surfaces.
"""

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.common.ca import CertAuthority
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.controller import Controller
from oim_tpu.registry import Registry
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """A secure deployment plus an evil CA: registry + controller + agent."""
    tmp = tmp_path_factory.mktemp("secmatrix")
    ca = CertAuthority("GOOD CA")
    evil = CertAuthority("EVIL CA")

    def tls(authority, cn, peer=""):
        cred = authority.issue(cn)
        return TLSConfig(ca.ca_pem, cred.cert_pem, cred.key_pem, peer)

    store = ChipStore(mesh=(2,), device_dir=str(tmp))
    agent_srv = FakeAgentServer(store, str(tmp / "agent.sock")).start()

    controller = Controller(
        "ctrl-1",
        agent_srv.socket_path,
        tls=tls(ca, "controller.ctrl-1"),
    )
    ctrl_srv = controller.start_server("tcp://127.0.0.1:0")

    registry = Registry(tls=tls(ca, "component.registry"))
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    registry.db.store("ctrl-1/address", str(ctrl_srv.addr()))

    yield {
        "ca": ca,
        "evil": evil,
        "registry_addr": reg_srv.addr(),
        "controller_addr": ctrl_srv.addr(),
    }
    reg_srv.stop()
    ctrl_srv.stop()
    controller.close()
    agent_srv.stop()


def _client_tls(ca_trusted: CertAuthority, issuer: CertAuthority, cn: str, peer: str):
    cred = issuer.issue(cn)
    return TLSConfig(ca_trusted.ca_pem, cred.cert_pem, cred.key_pem, peer)


def _registry_set(addr, tls: TLSConfig, path="x/y", value="z", timeout=5):
    channel = grpc.secure_channel(
        addr.grpc_target(), tls.channel_credentials(), options=tls.channel_options()
    )
    try:
        REGISTRY.stub(channel).SetValue(
            oim_pb2.SetValueRequest(value=oim_pb2.Value(path=path, value=value)),
            timeout=timeout,
        )
    finally:
        channel.close()


def _proxy_map(addr, tls: TLSConfig, controller_id="ctrl-1", timeout=5):
    channel = grpc.secure_channel(
        addr.grpc_target(), tls.channel_credentials(), options=tls.channel_options()
    )
    try:
        return CONTROLLER.stub(channel).MapVolume(
            oim_pb2.MapVolumeRequest(
                volume_id="vol-sec", slice=oim_pb2.SliceParams(chip_count=1)
            ),
            metadata=(("controllerid", controller_id),),
            timeout=timeout,
        )
    finally:
        channel.close()


# Table: (description, action, expect_ok, expected_code_or_None)
def test_security_matrix(world):
    ca, evil = world["ca"], world["evil"]
    reg, ctrl = world["registry_addr"], world["controller_addr"]

    cases = [
        (
            "admin with good CA may SetValue",
            lambda: _registry_set(reg, _client_tls(ca, ca, "user.admin", "component.registry")),
            None,
        ),
        (
            "evil-CA admin cert rejected by registry",
            lambda: _registry_set(reg, _client_tls(ca, evil, "user.admin", "component.registry")),
            grpc.StatusCode.UNAVAILABLE,  # TLS handshake failure
        ),
        (
            "client pinning wrong server CN rejects the registry (MITM guard)",
            lambda: _registry_set(reg, _client_tls(ca, ca, "user.admin", "controller.ctrl-1")),
            grpc.StatusCode.UNAVAILABLE,
        ),
        (
            "host.ctrl-1 may proxy to its controller",
            lambda: _proxy_map(reg, _client_tls(ca, ca, "host.ctrl-1", "component.registry")),
            None,
        ),
        (
            "host.ctrl-2 may NOT proxy to ctrl-1",
            lambda: _proxy_map(reg, _client_tls(ca, ca, "host.ctrl-2", "component.registry")),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "host CN may not SetValue",
            lambda: _registry_set(reg, _client_tls(ca, ca, "host.ctrl-1", "component.registry")),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "controller.ctrl-1 may set its own address",
            lambda: _registry_set(
                reg,
                _client_tls(ca, ca, "controller.ctrl-1", "component.registry"),
                path="ctrl-1/address",
                value="tcp://127.0.0.1:1",
            ),
            None,
        ),
        (
            "controller.ctrl-1 may NOT set another controller's address",
            lambda: _registry_set(
                reg,
                _client_tls(ca, ca, "controller.ctrl-1", "component.registry"),
                path="ctrl-2/address",
                value="tcp://evil:1",
            ),
            grpc.StatusCode.PERMISSION_DENIED,
        ),
        (
            "direct client→controller bypass rejected (controller only trusts the registry)",
            lambda: grpc_call_direct(ctrl, _client_tls(ca, ca, "user.admin", "controller.ctrl-1")),
            grpc.StatusCode.UNAUTHENTICATED,
        ),
        (
            "evil-CA host cert rejected at the TLS layer",
            lambda: _proxy_map(reg, _client_tls(ca, evil, "host.ctrl-1", "component.registry")),
            grpc.StatusCode.UNAVAILABLE,
        ),
    ]

    failures = []
    for description, action, expected_code in cases:
        try:
            action()
            if expected_code is not None:
                failures.append(f"{description}: unexpectedly succeeded")
        except grpc.RpcError as exc:
            if expected_code is None:
                failures.append(f"{description}: failed with {exc.code()}")
            elif exc.code() != expected_code:
                failures.append(
                    f"{description}: got {exc.code()}, want {expected_code}"
                )
    assert not failures, "\n".join(failures)


def grpc_call_direct(ctrl_addr, tls: TLSConfig):
    channel = grpc.secure_channel(
        ctrl_addr.grpc_target(),
        tls.channel_credentials(),
        options=tls.channel_options(),
    )
    try:
        return CONTROLLER.stub(channel).MapVolume(
            oim_pb2.MapVolumeRequest(
                volume_id="direct", slice=oim_pb2.SliceParams(chip_count=1)
            ),
            timeout=5,
        )
    finally:
        channel.close()


def test_evil_registry_mitm(world):
    """A fake registry presenting an evil-CA 'component.registry' cert:
    the controller's registration client must refuse it."""
    ca, evil = world["ca"], world["evil"]
    evil_cred = evil.issue("component.registry")
    evil_tls = TLSConfig(
        evil.ca_pem, evil_cred.cert_pem, evil_cred.key_pem, ""
    )
    evil_registry = Registry(tls=evil_tls)
    evil_srv = evil_registry.start_server("tcp://127.0.0.1:0")
    try:
        good_cred = ca.issue("controller.ctrl-1")
        controller = Controller(
            "ctrl-1",
            "/nonexistent.sock",
            registry_address=str(evil_srv.addr()),
            tls=TLSConfig(ca.ca_pem, good_cred.cert_pem, good_cred.key_pem),
        )
        controller._advertised_address = "tcp://127.0.0.1:9"
        with pytest.raises(grpc.RpcError):
            controller.register()
        # Nothing leaked into the evil registry.
        assert evil_registry.db.lookup("ctrl-1/address") == ""
    finally:
        evil_srv.stop()


# ---------------------------------------------------------------------------
# Serving data plane (HTTP) — the same matrix applied to /v1/generate
# end-to-end: client → oim-route → oim-serve, every hop mTLS
# (≙ the reference's mTLS-everywhere stance, reference README.md:84-120,
# extended to the one outward-facing API).

import json
import ssl
import time
import urllib.request


@pytest.fixture(scope="module")
def serving_world(tmp_path_factory):
    """mTLS backend + mTLS router discovered statically, plus cert trees."""
    import jax

    from oim_tpu.models import TransformerConfig, init_params
    from oim_tpu.serve import Engine, Router
    from oim_tpu.serve.httptls import client_ssl_context, server_ssl_context
    from oim_tpu.serve.server import ServeServer

    tmp = tmp_path_factory.mktemp("servetls")
    ca = CertAuthority("GOOD CA")
    evil = CertAuthority("EVIL CA")

    def certfiles(authority, cn, trust=None):
        cred = authority.issue(cn)
        cafile = tmp / f"{id(authority)}.ca.crt"
        cafile.write_bytes((trust or authority).ca_pem)
        crt = tmp / f"{cn}.{id(authority)}.crt"
        key = tmp / f"{cn}.{id(authority)}.key"
        crt.write_bytes(cred.cert_pem)
        key.write_bytes(cred.key_pem)
        return str(cafile), str(crt), str(key)

    cfg = TransformerConfig(
        vocab_size=101, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        dtype="float32", use_pallas=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = Engine(params, cfg, n_slots=2, max_len=64, chunk=4)

    b_ca, b_crt, b_key = certfiles(ca, "serve.a")
    backend = ServeServer(
        engine,
        ssl_context=server_ssl_context(b_ca, b_crt, b_key),
    ).start()

    r_ca, r_crt, r_key = certfiles(ca, "route.r1")
    router = Router(
        backends=(f"https://127.0.0.1:{backend.port}",),
        health_interval=0.2,
        unhealthy_after=2,
        ssl_context=server_ssl_context(r_ca, r_crt, r_key),
        client_ssl_context=client_ssl_context(r_ca, r_crt, r_key),
    ).start()
    deadline = time.time() + 30
    while time.time() < deadline and not router.healthy_backends():
        time.sleep(0.05)
    assert router.healthy_backends(), "mTLS router↔backend health failed"

    yield {
        "ca": ca,
        "evil": evil,
        "tmp": tmp,
        "certfiles": certfiles,
        "backend_port": backend.port,
        "router_port": router.port,
    }
    router.stop()
    backend.stop()


def _serving_request(port, context, path="/v1/generate", timeout=30):
    from oim_tpu.serve.httptls import opener

    req = urllib.request.Request(
        f"https://127.0.0.1:{port}{path}",
        data=json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 2}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with opener(context).open(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_serving_mtls_good_client_end_to_end(serving_world):
    """A deployment-CA client generates through router → backend, every
    hop authenticated."""
    from oim_tpu.serve.httptls import client_ssl_context

    w = serving_world
    ca_f, crt, key = w["certfiles"](w["ca"], "user.admin")
    out = _serving_request(
        w["router_port"], client_ssl_context(ca_f, crt, key)
    )
    assert len(out["tokens"]) == 2


@pytest.mark.parametrize("target", ["router", "backend"])
def test_serving_mtls_rejects_certless_client(serving_world, target):
    """No client cert → handshake failure before any request is read, on
    BOTH the router and the backend listener."""
    from oim_tpu.serve.httptls import client_ssl_context

    w = serving_world
    ca_f, _, _ = w["certfiles"](w["ca"], "user.nobody")
    port = w[f"{target}_port"]
    with pytest.raises((ssl.SSLError, urllib.error.URLError, OSError)):
        _serving_request(port, client_ssl_context(ca_f), timeout=10)


@pytest.mark.parametrize("target", ["router", "backend"])
def test_serving_mtls_rejects_non_serving_cn(serving_world, target):
    """CN pinning beyond the CA gate: a GOOD-CA cert whose CN is not a
    serving-plane identity (a controller's ctrl.*) passes the TLS
    handshake but is refused 403 by router AND backend — a compromised
    control-plane component cannot call the serving API or impersonate
    a backend to a router (gRPC-plane parity, httptls module)."""
    from oim_tpu.serve.httptls import client_ssl_context

    w = serving_world
    ca_f, crt, key = w["certfiles"](w["ca"], "controller.ctrl-1")
    port = w[f"{target}_port"]
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _serving_request(
            port, client_ssl_context(ca_f, crt, key), timeout=10
        )
    assert exc_info.value.code == 403


@pytest.mark.parametrize("target", ["router", "backend"])
def test_serving_mtls_rejects_evil_ca_client(serving_world, target):
    """A client whose cert chains to a DIFFERENT CA is refused at the
    handshake — holding a cert is not enough, it must be OUR CA."""
    from oim_tpu.serve.httptls import client_ssl_context

    w = serving_world
    # Evil-issued client cert, but trusting the good CA for the server
    # side (the strongest attacker: knows the real CA's public half).
    ca_f, crt, key = w["certfiles"](w["evil"], "user.admin", trust=w["ca"])
    port = w[f"{target}_port"]
    with pytest.raises((ssl.SSLError, urllib.error.URLError, OSError)):
        _serving_request(port, client_ssl_context(ca_f, crt, key), timeout=10)


def test_serving_client_rejects_evil_server(serving_world, tmp_path):
    """The CLIENT side of the matrix: a client pinned to the deployment
    CA refuses a server presenting an evil-CA cert (MITM)."""
    from oim_tpu.serve.httptls import (
        client_ssl_context,
        server_ssl_context,
    )

    w = serving_world
    evil_ca_f, evil_crt, evil_key = w["certfiles"](w["evil"], "serve.mitm")

    import http.server
    import threading

    class Quiet(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

    from oim_tpu.serve.httptls import TLSThreadingHTTPServer

    mitm = TLSThreadingHTTPServer(
        ("127.0.0.1", 0), Quiet,
        server_ssl_context(
            evil_ca_f, evil_crt, evil_key, require_client_cert=False
        ),
    )
    threading.Thread(target=mitm.serve_forever, daemon=True).start()
    try:
        good_ca_f, crt, key = w["certfiles"](w["ca"], "user.admin")
        with pytest.raises((ssl.SSLError, urllib.error.URLError, OSError)):
            _serving_request(
                mitm.server_address[1],
                client_ssl_context(good_ca_f, crt, key),
                timeout=10,
            )
    finally:
        mitm.shutdown()
        mitm.server_close()
