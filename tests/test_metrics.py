"""Metrics: instruments, exposition format, RPC instrumentation, scraping.

The reference ships no metrics at all (SURVEY.md §5: "No Prometheus
metrics in OIM"; its only perf artifact is the vendored perfdash schema,
reference test/e2e/perftype/perftype.go:26-53).  This subsystem is new
capability: every daemon exposes standard Prometheus text format.
"""

from __future__ import annotations

import time
import urllib.request

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.common import metrics
from oim_tpu.controller import Controller
from oim_tpu.csi import OIMDriver
from oim_tpu.registry import Registry
from oim_tpu.spec import CSI_CONTROLLER, csi_pb2


class TestInstruments:
    def test_counter(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("reqs_total", "Requests.", ("method",))
        c.inc("Get")
        c.inc("Get", by=2)
        c.inc("Set")
        assert c.value("Get") == 3
        assert c.value("Set") == 1
        text = reg.render()
        assert '# TYPE reqs_total counter' in text
        assert 'reqs_total{method="Get"} 3' in text

    def test_gauge_set_add_and_function(self):
        reg = metrics.MetricsRegistry()
        g = reg.gauge("temp", "Temperature.")
        g.set(5)
        g.add(-2)
        assert g.value() == 3
        live = reg.gauge("live", "Scrape-time value.")
        box = {"v": 7}
        live.set_function(lambda: box["v"])
        assert live.value() == 7
        box["v"] = 9
        assert "live 9" in reg.render()

    def test_gauge_failing_callback_does_not_break_scrape(self):
        reg = metrics.MetricsRegistry()
        reg.gauge("bad", "x").set_function(lambda: 1 / 0)
        reg.gauge("good", "y").set(1)
        assert "good 1" in reg.render()

    def test_histogram_buckets_cumulative(self):
        reg = metrics.MetricsRegistry()
        h = reg.histogram("lat", "Latency.", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.render()
        assert 'lat_bucket{le="0.01"} 1' in text
        assert 'lat_bucket{le="0.1"} 2' in text
        assert 'lat_bucket{le="1"} 3' in text
        assert 'lat_bucket{le="+Inf"} 4' in text
        assert "lat_count 4" in text
        assert h.count() == 4

    def test_label_escaping(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("odd", "x", ("v",))
        c.inc('a"b\\c\nd')
        assert r'odd{v="a\"b\\c\nd"} 1' in reg.render()

    def test_register_is_idempotent_by_name(self):
        reg = metrics.MetricsRegistry()
        a = reg.counter("same", "x", ("l",))
        b = reg.counter("same", "x", ("l",))
        assert a is b

    def test_label_escaping_round_trips(self):
        """Escaped label values must parse back to the original — a
        scraper seeing ``\\n`` where a newline was (or vice versa) would
        corrupt every query on that series."""
        nasty = 'a"b\\c\nd\\ne'
        reg = metrics.MetricsRegistry()
        reg.counter("odd_rt", "x", ("v",)).inc(nasty)
        (line,) = [
            l for l in reg.render().splitlines() if l.startswith("odd_rt{")
        ]
        quoted = line[line.index('v="') + 2 : line.rindex('"') + 1]

        def unescape(s: str) -> str:
            out, i = [], 1  # strip quotes
            while i < len(s) - 1:
                if s[i] == "\\" and i + 1 < len(s) - 1:
                    out.append(
                        {"n": "\n", "\\": "\\", '"': '"'}[s[i + 1]]
                    )
                    i += 2
                else:
                    out.append(s[i])
                    i += 1
            return "".join(out)

        assert unescape(quoted) == nasty

    def test_fast_buckets_resolve_sub_millisecond(self):
        """FAST_BUCKETS exist for the data plane / per-token latencies:
        DEFAULT_BUCKETS' 1ms floor lumps a 60µs and a 900µs observation
        into one bucket; FAST_BUCKETS keep them apart."""
        assert metrics.FAST_BUCKETS[0] == 0.00005
        reg = metrics.MetricsRegistry()
        h = reg.histogram("oim_fast_demo_seconds", "x",
                          buckets=metrics.FAST_BUCKETS)
        h.observe(0.00006)
        h.observe(0.0009)
        text = reg.render()
        assert 'oim_fast_demo_seconds_bucket{le="0.0001"} 1' in text
        assert 'oim_fast_demo_seconds_bucket{le="0.001"} 2' in text


class TestHTTPExposition:
    def test_failing_gauge_callback_does_not_break_http_scrape(self):
        """A raising scrape-time callback must cost its own series only:
        the HTTP response stays 200 and every healthy series renders."""
        reg = metrics.MetricsRegistry()
        reg.gauge("bad_http", "x").set_function(lambda: 1 / 0)
        reg.counter("good_http", "y").inc()
        srv = metrics.MetricsServer("127.0.0.1:0", reg).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5
            )
            assert body.status == 200
            text = body.read().decode()
            assert "good_http 1" in text
            assert "\nbad_http " not in text  # series absent, scrape alive
        finally:
            srv.stop()

    def test_scrape(self):
        reg = metrics.MetricsRegistry()
        reg.counter("hits", "x").inc()
        srv = metrics.MetricsServer("127.0.0.1:0", reg).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5
            )
            assert body.status == 200
            text = body.read().decode()
            assert "hits 1" in text
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=5
                )
        finally:
            srv.stop()


def test_metrics_address_parsing():
    from oim_tpu.common.metrics import _split_host_port

    assert _split_host_port("127.0.0.1:9090") == ("127.0.0.1", "9090")
    assert _split_host_port(":9090") == ("", "9090")
    assert _split_host_port("[::1]:9090") == ("::1", "9090")
    with pytest.raises(ValueError):
        _split_host_port("9090")  # no colon: ambiguous, not bind-all
    with pytest.raises(ValueError):
        _split_host_port("::1:9090")  # unbracketed IPv6
    with pytest.raises(ValueError):
        _split_host_port("host:port")


def test_metrics_server_ipv6():
    try:
        srv = metrics.MetricsServer("[::1]:0").start()
    except OSError:
        pytest.skip("IPv6 unavailable on this host")
    try:
        import urllib.request

        reg = metrics.registry()
        reg.counter("oim_v6_probe_total", "ipv6 exposition probe").inc()
        body = urllib.request.urlopen(
            f"http://[::1]:{srv.port}/metrics", timeout=5
        ).read()
        assert b"# HELP" in body
        assert b"oim_v6_probe_total" in body
    finally:
        srv.stop()


def _expire_cache(controller) -> None:
    """Age every cached scrape past the TTL without losing the last-good
    values (a cleared cache would have nothing to serve stale)."""
    controller._scrape_cache = {
        k: (v, t - 2 * Controller.SCRAPE_CACHE_TTL)
        for k, (v, t) in controller._scrape_cache.items()
    }


def test_chip_gauges_survive_agent_restart(tmp_path):
    """A dead agent must not vanish the series: the scrape serves the last
    good value, bumps oim_metrics_scrape_errors_total, drops its
    connection, and recovers on the next fresh scrape after restart."""
    store = ChipStore(mesh=(2,), device_dir=str(tmp_path / "dev"))
    sock = str(tmp_path / "agent.sock")
    agent_srv = FakeAgentServer(store, sock).start()
    controller = Controller("restart-host", sock)
    reg = metrics.registry()
    total = reg.gauge("oim_chips_total", "", ("controller",))
    errors = reg.counter("oim_metrics_scrape_errors_total", "", ("controller",))
    try:
        assert total.value("restart-host") == 2
        errors_before = errors.value("restart-host")
        agent_srv.stop()
        # stop() only closes the listener; a real crash also severs the
        # established connection — do that part ourselves.
        import socket as socketlib

        controller._scrape_conn.peek().client._sock.shutdown(socketlib.SHUT_RDWR)
        _expire_cache(controller)  # force past the TTL, keep last-good
        # Stale value served; staleness is visible via the error counter.
        assert total.value("restart-host") == 2
        assert errors.value("restart-host") == errors_before + 1
        # render() keeps working during the outage — the chips series is
        # freshly re-stamped stale, the allocated series fails once more.
        assert 'oim_chips_total{controller="restart-host"} 2' in reg.render()
        assert errors.value("restart-host") == errors_before + 2
        # Within the TTL nothing re-scrapes: no new errors, no stall.
        assert total.value("restart-host") == 2
        assert errors.value("restart-host") == errors_before + 2
        agent_srv = FakeAgentServer(store, sock).start()
        _expire_cache(controller)
        assert total.value("restart-host") == 2  # fresh dial, recovered
        assert errors.value("restart-host") == errors_before + 2
    finally:
        controller.close()
        agent_srv.stop()


def test_scrape_failure_cooldown_without_prior_value(tmp_path):
    """Agent down from controller startup: the first render pays the
    scrape attempt, renders within the TTL fail fast (cooldown) with no
    further agent dials."""
    controller = Controller("cold-host", str(tmp_path / "nope.sock"))
    reg = metrics.registry()
    total = reg.gauge("oim_chips_total", "", ("controller",))
    errors = reg.counter("oim_metrics_scrape_errors_total", "", ("controller",))
    try:
        with pytest.raises(Exception):
            total.value("cold-host")
        after_first = errors.value("cold-host")
        import time as time_mod

        t0 = time_mod.monotonic()
        with pytest.raises(Exception):
            total.value("cold-host")  # cooldown: no 2s dial, no new error
        assert time_mod.monotonic() - t0 < 0.5
        assert errors.value("cold-host") == after_first
    finally:
        controller.close()


def test_close_deregisters_gauges_unless_taken_over(tmp_path):
    store = ChipStore(mesh=(2,), device_dir=str(tmp_path / "dev"))
    sock = str(tmp_path / "agent.sock")
    agent_srv = FakeAgentServer(store, sock).start()
    reg = metrics.registry()
    total = reg.gauge("oim_chips_total", "", ("controller",))
    try:
        first = Controller("lifecycle-host", sock)
        assert total.value("lifecycle-host") == 2
        first.close()
        assert 'controller="lifecycle-host"' not in reg.render()

        # A replacement that takes the series over must survive the OLD
        # instance's (late) close.
        second = Controller("lifecycle-host", sock)
        first.close()  # idempotent, must not strip second's callback
        assert total.value("lifecycle-host") == 2
        second.close()
        assert 'controller="lifecycle-host"' not in reg.render()

        # Registry KV gauge follows the same ownership rules.
        r1 = Registry()
        r2 = Registry()  # takes over the (unlabelled) series
        r1.close()
        keys = reg.gauge("oim_registry_keys", "")
        r2.db.store("x/y", "1")
        assert keys.value() == 1
        r2.close()
    finally:
        agent_srv.stop()


def test_rpc_and_chip_metrics_through_full_stack(tmp_path):
    """Drive CreateVolume through driver→registry→controller and assert
    the interceptor counters, proxy counter, and scrape-time chip gauges
    all observe it."""
    store = ChipStore(mesh=(2, 2, 1), device_dir=str(tmp_path / "dev"))
    agent_srv = FakeAgentServer(store, str(tmp_path / "agent.sock")).start()
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    controller = Controller(
        "metrics-host",
        agent_srv.socket_path,
        registry_address=str(reg_srv.addr()),
        registry_delay=30.0,
    )
    ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
    controller.start(str(ctrl_srv.addr()))
    driver = OIMDriver(
        csi_endpoint=f"unix://{tmp_path}/csi.sock",
        registry_address=str(reg_srv.addr()),
        controller_id="metrics-host",
    )
    csi_srv = driver.start_server()
    channel = grpc.insecure_channel(csi_srv.addr().grpc_target())
    try:
        deadline = time.time() + 5
        while registry.db.lookup("metrics-host/address") != str(ctrl_srv.addr()):
            assert time.time() < deadline
            time.sleep(0.01)

        reg = metrics.registry()
        handled = reg.counter(
            "oim_rpc_handled_total", "", ("component", "method", "code")
        )
        proxied = reg.counter("oim_registry_proxied_total", "", ("controller",))
        before = handled.value(
            "oim-csi-driver", "/csi.v1.Controller/CreateVolume", "OK"
        )
        proxied_before = proxied.value("metrics-host")

        cap = csi_pb2.VolumeCapability()
        cap.mount.SetInParent()
        cap.access_mode.mode = (
            csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_WRITER
        )
        vol = CSI_CONTROLLER.stub(channel).CreateVolume(
            csi_pb2.CreateVolumeRequest(
                name="metered", volume_capabilities=[cap],
                parameters={"chipCount": "2"},
            ),
            timeout=30,
        ).volume
        from oim_tpu.spec import CSI_NODE

        CSI_NODE.stub(channel).NodeStageVolume(
            csi_pb2.NodeStageVolumeRequest(
                volume_id=vol.volume_id,
                staging_target_path=str(tmp_path / "staging"),
                volume_capability=cap,
                volume_context=dict(vol.volume_context),
            ),
            timeout=30,
        )
        assert (
            handled.value(
                "oim-csi-driver", "/csi.v1.Controller/CreateVolume", "OK"
            )
            == before + 1
        )
        assert (
            handled.value(
                "oim-controller", "/oim.v1.Controller/MapVolume", "OK"
            )
            >= 1
        )
        assert proxied.value("metrics-host") > proxied_before
        # Latency histogram observed the same calls.
        latency = reg.histogram(
            "oim_rpc_handling_seconds", "", ("component", "method")
        )
        assert (
            latency.count("oim-csi-driver", "/csi.v1.Controller/CreateVolume")
            >= 1
        )
        # Chip gauges ask the agent at scrape time.
        total = reg.gauge("oim_chips_total", "", ("controller",))
        allocated = reg.gauge("oim_chips_allocated", "", ("controller",))
        assert total.value("metrics-host") == 4
        assert allocated.value("metrics-host") == 2
        # Registry KV gauge sees the registration + volume rows.
        assert reg.gauge("oim_registry_keys", "").value() >= 1
        # And the whole lot renders as valid exposition text.
        text = reg.render()
        assert "# TYPE oim_rpc_handling_seconds histogram" in text
        assert 'oim_chips_total{controller="metrics-host"} 4' in text
    finally:
        channel.close()
        csi_srv.stop()
        driver.close()
        ctrl_srv.stop()
        controller.close()
        reg_srv.stop()
        registry.close()
        agent_srv.stop()


def test_resilience_instruments_record_and_render():
    """The shared retry/breaker layer's instruments (defined in
    oim_tpu/common/metrics.py, driven by oim_tpu/common/resilience.py):
    attempts by outcome, retry count, whole-operation latency, and
    breaker transitions, all in standard exposition text."""
    from oim_tpu.common import resilience

    policy = resilience.RetryPolicy(
        max_attempts=3, initial_backoff_s=0.0, sleep=lambda s: None
    )
    state = {"n": 0}

    def flaky(_attempt):
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    breaker = resilience.CircuitBreaker(
        "metrics-demo", failure_threshold=1, reset_timeout_s=60.0
    )
    assert (
        resilience.call_with_retry(
            flaky, policy, component="metrics-demo", op="Demo",
            breaker=breaker,
        )
        == "ok"
    )
    assert metrics.RPC_ATTEMPTS.value("metrics-demo", "Demo", "ok") == 1
    assert metrics.RPC_ATTEMPTS.value("metrics-demo", "Demo", "retryable") == 2
    assert metrics.RPC_RETRIES.value("metrics-demo", "Demo") == 2
    assert metrics.RPC_LATENCY.count("metrics-demo", "Demo") == 1

    # A one-failure breaker opens on the next (unretried) failure...
    with pytest.raises(ConnectionError):
        resilience.call_with_retry(
            lambda _a: (_ for _ in ()).throw(ConnectionError("down")),
            resilience.RetryPolicy.one_shot(),
            component="metrics-demo",
            op="Demo",
            breaker=breaker,
        )
    assert metrics.BREAKER_TRANSITIONS.value("metrics-demo", "open") == 1

    text = metrics.registry().render()
    assert "# TYPE oim_rpc_attempts_total counter" in text
    assert (
        'oim_rpc_attempts_total{component="metrics-demo",op="Demo",'
        'outcome="ok"} 1' in text
    )
    assert (
        'oim_rpc_retries_total{component="metrics-demo",op="Demo"} 2' in text
    )
    assert "# TYPE oim_rpc_latency_seconds histogram" in text
    assert (
        'oim_rpc_latency_seconds_count{component="metrics-demo",op="Demo"} '
        in text
    )
    assert (
        'oim_breaker_transitions_total{target="metrics-demo",state="open"} 1'
        in text
    )


def test_serve_fault_tolerance_instruments_render():
    """The serve-plane fault-tolerance instruments (PR 6: stalls,
    sheds by reason, failovers by outcome, deadline expirations) are
    shared definitions in oim_tpu/common/metrics.py — one series shape
    fleet-wide — and render in standard exposition text."""
    # Deltas, not absolutes: these are process-global counters other
    # suites in the same run may legitimately have driven.
    before = {
        "shed": metrics.SERVE_SHED.value("queue_full"),
        "failover": metrics.SERVE_FAILOVERS.value("spliced"),
        "deadline": metrics.SERVE_DEADLINE_EXPIRED.value(),
    }
    metrics.SERVE_STALLS.inc("metrics-demo")
    metrics.SERVE_SHED.inc("queue_full")
    metrics.SERVE_SHED.inc("brownout")
    metrics.SERVE_FAILOVERS.inc("spliced")
    metrics.SERVE_FAILOVERS.inc("gave_up")
    metrics.SERVE_DEADLINE_EXPIRED.inc()
    assert metrics.SERVE_STALLS.value("metrics-demo") == 1
    assert metrics.SERVE_SHED.value("queue_full") == before["shed"] + 1
    assert (
        metrics.SERVE_FAILOVERS.value("spliced") == before["failover"] + 1
    )
    assert (
        metrics.SERVE_DEADLINE_EXPIRED.value() == before["deadline"] + 1
    )
    text = metrics.registry().render()
    assert "# TYPE oim_serve_stalls_total counter" in text
    assert 'oim_serve_stalls_total{engine="metrics-demo"} 1' in text
    assert 'oim_serve_shed_total{reason="queue_full"}' in text
    assert 'oim_serve_shed_total{reason="brownout"}' in text
    assert 'oim_serve_failovers_total{outcome="spliced"}' in text
    assert 'oim_serve_failovers_total{outcome="gave_up"}' in text
    assert "# TYPE oim_serve_deadline_expired_total counter" in text
    assert "oim_serve_deadline_expired_total" in text


def test_serve_disagg_instruments_render():
    """The disaggregated prefill/decode instruments (ISSUE 12: ship
    latency/bytes, request outcomes) are shared definitions in
    oim_tpu/common/metrics.py and render in standard exposition text."""
    before = {
        "shipped": metrics.SERVE_DISAGG.value("shipped"),
        "fell_back": metrics.SERVE_DISAGG.value("fell_back"),
        "bytes": metrics.SERVE_KV_SHIP_BYTES.value(),
        "ships": metrics.SERVE_KV_SHIP_SECONDS.count(),
    }
    metrics.SERVE_DISAGG.inc("shipped")
    metrics.SERVE_DISAGG.inc("fell_back")
    metrics.SERVE_KV_SHIP_BYTES.inc(by=4096.0)
    metrics.SERVE_KV_SHIP_SECONDS.observe(0.05)
    assert metrics.SERVE_DISAGG.value("shipped") == before["shipped"] + 1
    assert (
        metrics.SERVE_DISAGG.value("fell_back")
        == before["fell_back"] + 1
    )
    assert (
        metrics.SERVE_KV_SHIP_BYTES.value() == before["bytes"] + 4096.0
    )
    assert (
        metrics.SERVE_KV_SHIP_SECONDS.count() == before["ships"] + 1
    )
    text = metrics.registry().render()
    assert "# TYPE oim_serve_disagg_requests_total counter" in text
    assert 'oim_serve_disagg_requests_total{outcome="shipped"}' in text
    assert 'oim_serve_disagg_requests_total{outcome="fell_back"}' in text
    assert "# TYPE oim_serve_kv_ship_bytes_total counter" in text
    assert "# TYPE oim_serve_kv_ship_seconds histogram" in text
    assert "oim_serve_kv_ship_seconds_bucket" in text
    assert "oim_serve_kv_ship_seconds_count" in text


def test_qos_instruments_render():
    """The multi-tenant QoS instruments (ISSUE 16: enforcement actions
    by tenant tier, generated tokens by tenant CN) are shared
    definitions in oim_tpu/common/metrics.py and render in standard
    exposition text — including the new shed reason `quota` on the
    PR 6 taxonomy."""
    before = {
        "admitted": metrics.SERVE_QOS.value("premium", "admitted"),
        "throttled": metrics.SERVE_QOS.value("best_effort", "throttled"),
        "preempted": metrics.SERVE_QOS.value("premium", "preempted"),
        "victim": metrics.SERVE_QOS.value("best_effort", "parked_victim"),
        "tokens": metrics.SERVE_TENANT_TOKENS.value("user.gold"),
        "quota": metrics.SERVE_SHED.value("quota"),
    }
    metrics.SERVE_QOS.inc("premium", "admitted")
    metrics.SERVE_QOS.inc("best_effort", "throttled")
    metrics.SERVE_QOS.inc("premium", "preempted")
    metrics.SERVE_QOS.inc("best_effort", "parked_victim")
    metrics.SERVE_TENANT_TOKENS.inc("user.gold", by=128.0)
    metrics.SERVE_SHED.inc("quota")
    assert (
        metrics.SERVE_QOS.value("premium", "admitted")
        == before["admitted"] + 1
    )
    assert (
        metrics.SERVE_QOS.value("best_effort", "throttled")
        == before["throttled"] + 1
    )
    assert (
        metrics.SERVE_QOS.value("premium", "preempted")
        == before["preempted"] + 1
    )
    assert (
        metrics.SERVE_QOS.value("best_effort", "parked_victim")
        == before["victim"] + 1
    )
    assert (
        metrics.SERVE_TENANT_TOKENS.value("user.gold")
        == before["tokens"] + 128.0
    )
    assert metrics.SERVE_SHED.value("quota") == before["quota"] + 1
    text = metrics.registry().render()
    assert "# TYPE oim_serve_qos_total counter" in text
    assert (
        'oim_serve_qos_total{tenant_tier="premium",action="admitted"}'
        in text
    )
    assert (
        'oim_serve_qos_total{tenant_tier="best_effort",'
        'action="throttled"}' in text
    )
    assert (
        'oim_serve_qos_total{tenant_tier="premium",action="preempted"}'
        in text
    )
    assert (
        'oim_serve_qos_total{tenant_tier="best_effort",'
        'action="parked_victim"}' in text
    )
    assert "# TYPE oim_serve_tenant_tokens_total counter" in text
    assert 'oim_serve_tenant_tokens_total{tenant="user.gold"}' in text
    assert 'oim_serve_shed_total{reason="quota"}' in text


def test_prefix_residency_instruments_render():
    """The fleet prefix-residency instruments (ISSUE 14: ship latency,
    fetch outcomes, residency-map size, the source-labeled bytes-saved
    split) are shared definitions in oim_tpu/common/metrics.py and
    render in standard exposition text."""
    before = {
        "fetched": metrics.SERVE_PREFIX_FETCH.value("fetched"),
        "fell_back": metrics.SERVE_PREFIX_FETCH.value("fell_back"),
        "ineligible": metrics.SERVE_PREFIX_FETCH.value("ineligible"),
        "fetches": metrics.SERVE_PREFIX_FETCH_SECONDS.count(),
    }
    metrics.SERVE_PREFIX_FETCH.inc("fetched")
    metrics.SERVE_PREFIX_FETCH.inc("fell_back")
    metrics.SERVE_PREFIX_FETCH.inc("ineligible")
    metrics.SERVE_PREFIX_FETCH_SECONDS.observe(0.02)
    metrics.ROUTE_RESIDENCY_DIGESTS.set(3.0)
    # The savings split: alias (local entry) vs fetched (installed
    # from a sibling's export) must be distinct series — the ISSUE 14
    # accounting-gap fix.
    metrics.SERVE_PREFIX_BYTES_SAVED.inc("e0", "alias", by=1024.0)
    metrics.SERVE_PREFIX_BYTES_SAVED.inc("e0", "fetched", by=2048.0)
    assert (
        metrics.SERVE_PREFIX_FETCH.value("fetched")
        == before["fetched"] + 1
    )
    assert (
        metrics.SERVE_PREFIX_FETCH.value("fell_back")
        == before["fell_back"] + 1
    )
    assert (
        metrics.SERVE_PREFIX_FETCH.value("ineligible")
        == before["ineligible"] + 1
    )
    assert (
        metrics.SERVE_PREFIX_FETCH_SECONDS.count()
        == before["fetches"] + 1
    )
    text = metrics.registry().render()
    assert "# TYPE oim_serve_prefix_fetch_total counter" in text
    assert 'oim_serve_prefix_fetch_total{outcome="fetched"}' in text
    assert 'oim_serve_prefix_fetch_total{outcome="fell_back"}' in text
    assert 'oim_serve_prefix_fetch_total{outcome="ineligible"}' in text
    assert "# TYPE oim_serve_prefix_fetch_seconds histogram" in text
    assert "oim_serve_prefix_fetch_seconds_bucket" in text
    assert "# TYPE oim_route_residency_digests gauge" in text
    assert "oim_route_residency_digests 3" in text
    assert (
        'oim_serve_prefix_bytes_saved_total{engine="e0",source="alias"}'
        in text
    )
    assert (
        'oim_serve_prefix_bytes_saved_total{engine="e0",'
        'source="fetched"}' in text
    )


def test_perf_forensics_instruments_render():
    """The performance-forensics instruments (ISSUE 18: process-wide
    XLA compile counters, the shared ring-dropped counter, KV-tier
    flow bytes + per-tier residency, slow captures) are shared
    definitions in oim_tpu/common/metrics.py and render in standard
    exposition text."""
    before = {
        "compiles": metrics.XLA_COMPILES.value(),
        "compile_obs": metrics.XLA_COMPILE_SECONDS.count(),
        "ring": metrics.SERVE_REQUEST_RING_DROPPED.value("e0"),
        "demote": metrics.SERVE_KV_TIER_BYTES.value("demote"),
        "slow": metrics.SERVE_SLOW_CAPTURES.value("e0", "e2e"),
    }
    metrics.XLA_COMPILES.inc()
    metrics.XLA_COMPILE_SECONDS.observe(0.5)
    metrics.SERVE_REQUEST_RING_DROPPED.inc("e0")
    metrics.SERVE_KV_TIER_BYTES.inc("demote", by=4096.0)
    metrics.SERVE_KV_TIER_BYTES.inc("promote", by=2048.0)
    metrics.SERVE_KV_TIER_RESIDENT.set(8192.0, "e0", "device")
    metrics.SERVE_KV_TIER_RESIDENT.set(1024.0, "e0", "host")
    metrics.SERVE_SLOW_CAPTURES.inc("e0", "e2e")
    assert metrics.XLA_COMPILES.value() == before["compiles"] + 1
    assert (
        metrics.XLA_COMPILE_SECONDS.count() == before["compile_obs"] + 1
    )
    assert (
        metrics.SERVE_REQUEST_RING_DROPPED.value("e0")
        == before["ring"] + 1
    )
    assert (
        metrics.SERVE_KV_TIER_BYTES.value("demote")
        == before["demote"] + 4096.0
    )
    assert (
        metrics.SERVE_SLOW_CAPTURES.value("e0", "e2e")
        == before["slow"] + 1
    )
    text = metrics.registry().render()
    assert "# TYPE oim_xla_compiles_total counter" in text
    assert "# TYPE oim_xla_compile_seconds histogram" in text
    assert "oim_xla_compile_seconds_bucket" in text
    assert "# TYPE oim_serve_request_ring_dropped_total counter" in text
    assert 'oim_serve_request_ring_dropped_total{engine="e0"}' in text
    assert "# TYPE oim_serve_kv_tier_bytes_total counter" in text
    assert 'oim_serve_kv_tier_bytes_total{op="demote"} 4096' in text
    assert 'oim_serve_kv_tier_bytes_total{op="promote"} 2048' in text
    assert "# TYPE oim_serve_kv_tier_resident_bytes gauge" in text
    assert (
        'oim_serve_kv_tier_resident_bytes{engine="e0",tier="device"} 8192'
        in text
    )
    assert (
        'oim_serve_kv_tier_resident_bytes{engine="e0",tier="host"} 1024'
        in text
    )
    assert "# TYPE oim_serve_slow_captures_total counter" in text
    assert (
        'oim_serve_slow_captures_total{engine="e0",trigger="e2e"}' in text
    )


def test_process_self_telemetry_installs_and_renders():
    """install_process_metrics() (ISSUE 18) is idempotent and wires the
    RSS/CPU/threads gauges + GC pause counters onto the default
    registry — live values, since every daemon's MetricsServer calls
    it at start()."""
    import gc

    metrics.install_process_metrics()
    callbacks_after_first = len(gc.callbacks)
    metrics.install_process_metrics()  # second call must be a no-op
    assert len(gc.callbacks) == callbacks_after_first
    text = metrics.registry().render()
    assert "# TYPE oim_process_resident_bytes gauge" in text
    assert "# TYPE oim_process_cpu_seconds gauge" in text
    assert "# TYPE oim_process_threads gauge" in text
    assert "# TYPE oim_process_gc_pause_seconds_total counter" in text

    def rendered_value(name: str) -> float:
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.split()[1])
        raise AssertionError(f"{name} not rendered")

    # A test process certainly has memory, CPU time, and >= 1 thread.
    assert rendered_value("oim_process_resident_bytes") > 0
    assert rendered_value("oim_process_cpu_seconds") > 0
    assert rendered_value("oim_process_threads") >= 1
    # A forced collection books a (tiny but nonzero-count) pause.
    pauses = metrics.PROCESS_GC_COLLECTIONS.value("2")
    gc.collect()
    assert metrics.PROCESS_GC_COLLECTIONS.value("2") >= pauses + 1
