"""Paged KV cache: block sharing must be invisible, capacity real.

The load-bearing properties (ISSUE 10):

- **Token-identical to dense.**  A paged engine (global block pool +
  per-slot block tables) emits exactly the tokens the dense per-slot
  engine emits — greedy, sampled, speculative (prompt-lookup AND draft
  model), prefix-cache hits, mid-stream admissions, dense and MoE
  models, pipeline depth 1 and 2.  Not approximately: the paged view
  is gathered into the dense region shape and the attention math is
  the SAME code, so the matrix below asserts strict equality.
- **Copy-free prefix sharing.**  Concurrent requests sharing a cached
  prefix alias its full blocks (refcounts prove single residency, the
  bytes-saved counter proves no copy), and a shared block's pool
  contents are bit-identical before and after concurrent readers — it
  is never mutated in place.  Divergence (the tail prefill writing
  into a partially-covered entry block) goes through copy-on-write.
- **OOM-of-blocks is backpressure.**  A pool too small for the
  offered load defers admissions (requests stay queued and complete
  as blocks free); a request whose worst case cannot EVER fit rejects
  at submit; abort/deadline-reap/cancel all return blocks; the chaos
  soak asserts zero leaked blocks every cycle.

And the flash-decode kernel's (ISSUE 13):

- **Kernel == gather == dense, token for token.**  The Pallas
  flash-decode kernel (``ops/paged_attention.py``, interpret mode on
  this CPU backend) replaces the per-layer dense gather on decode
  chunks; its output is pinned token-identical across {greedy,
  temp>0, spec-decode, prefix-cache hit with CoW, mid-stream
  admission} × {fp, kv_int8, kv_int4} × pipeline depth {1, 2}.  The
  oracle is the dense engine where one exists (fp, int8); kv4 exists
  only on the paged layout, so its oracle is the gather path at the
  same quant — kernel-vs-gather is exactly the A/B the serve flag
  (``--paged-kernel``) switches.
- **The sentinel-clamp contract, both ways.**  The gather clamps
  sentinel table entries to the LAST pool block and relies on the
  causal mask to zero whatever that block now holds — including
  another slot's live KV after a free-and-reallocate.  The kernel
  upholds the same contract by never reading a sentinel block at all.
  Both regressions below watch a freed-then-reallocated last block
  while a sentinel-holding slot keeps decoding.

Engines are shared per model config (the test-serve compile-budget
discipline); this file backs ``make test-serve-paged`` (together with
``tests/test_jit_guard.py``; ~70 s nominal, 210 s cap).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oim_tpu.common import metrics as _metrics
from oim_tpu.models import TransformerConfig, init_params
from oim_tpu.models.decode import generate
from oim_tpu.ops.paged import paged_view
from oim_tpu.ops.paged_attention import paged_flash_decode
from oim_tpu.serve import Engine, GenRequest
from oim_tpu.serve.disagg import KvIneligibleError
from oim_tpu.serve.engine import BlockAllocator, RequestFailedError

CFG = dict(
    vocab_size=101,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
    use_pallas=False,
)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, params = setup
    return Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                  prompt_buckets=(16, 32), prefix_cache_size=2)


@pytest.fixture(scope="module")
def paged_engine(setup):
    cfg, params = setup
    # Same geometry, paged: 8-token blocks, default pool (= the dense
    # cache's footprint) so exactness runs are never block-constrained.
    return Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                  prompt_buckets=(16, 32), prefix_cache_size=2,
                  kv_block=8)


@pytest.fixture(scope="module")
def kernel_engine(setup):
    cfg, params = setup
    # The paged engine again, decoding through the flash-decode kernel
    # (interpret mode on CPU — the exactness-matrix configuration).
    return Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                  prompt_buckets=(16, 32), prefix_cache_size=2,
                  kv_block=8, paged_kernel=True)


def _prompt(seed: int, n: int, vocab: int) -> list[int]:
    rng = np.random.RandomState(seed)
    return rng.randint(0, vocab, size=n).tolist()


def _echo_prompt(n: int, vocab: int) -> list[int]:
    pattern = [7, 21, 40, 3]
    return [t % vocab for t in (pattern * ((n // 4) + 1))[:n]]


def _oracle(params, cfg, tokens, max_new) -> list[int]:
    prompt = jax.numpy.asarray(tokens, jax.numpy.int32)[None]
    out = generate(params, prompt, cfg, max_new_tokens=max_new)
    return np.asarray(out)[0, len(tokens):].tolist()


def _clear_prefix(engine):
    with engine._lock:
        engine._clear_prefix_cache_locked()


def _matrix_workload(engine, vocab, system):
    """test_serve_pipeline's exactness-matrix traffic shape: queue
    pressure, greedy + sampled rows, a cache_prefix system prompt plus
    a request sharing it, and a mid-stream admission wave."""
    specs = [
        (system, 8, 0.0, 0, True),
        (_prompt(21, 9, vocab), 10, 0.8, 7, False),
        (_prompt(22, 5, vocab), 6, 0.0, 0, False),
    ]
    rids = [
        engine.submit(GenRequest(
            tokens=t, max_new_tokens=m, temperature=temp, seed=s,
            cache_prefix=c,
        ))
        for t, m, temp, s, c in specs
    ]
    engine.step()
    engine.step()
    late = [
        (system + _prompt(23, 4, vocab), 7, 0.0, 0, False),
        (_prompt(24, 6, vocab), 5, 0.5, 3, False),
    ]
    rids += [
        engine.submit(GenRequest(
            tokens=t, max_new_tokens=m, temperature=temp, seed=s,
            cache_prefix=c,
        ))
        for t, m, temp, s, c in late
    ]
    results = engine.run()
    return [results[r] for r in rids], [s[:2] for s in specs + late]


# ---------------------------------------------------------------------------
# Allocator units


def test_allocator_refcounts():
    a = BlockAllocator(4)
    ids = a.alloc(2)
    assert sorted(ids) == [0, 1] and a.free_blocks == 2
    assert a.used_blocks == 2 and a.shared_blocks == 0
    a.incref(ids)  # a second owner
    assert a.shared_blocks == 2
    assert a.decref(ids) == 0  # first deref frees nothing
    assert a.free_blocks == 2 and a.shared_blocks == 0
    assert a.decref(ids) == 2  # free-on-last-deref
    assert a.free_blocks == 4 and a.used_blocks == 0


def test_allocator_all_or_nothing_and_errors():
    a = BlockAllocator(3)
    assert a.alloc(4) is None  # all-or-nothing: nothing reserved
    assert a.free_blocks == 3
    ids = a.alloc(3)
    assert a.alloc(1) is None
    a.decref(ids)
    with pytest.raises(ValueError):
        a.decref([0])  # double free
    with pytest.raises(ValueError):
        a.incref([0])  # incref of a free block
    with pytest.raises(ValueError):
        BlockAllocator(0)


# ---------------------------------------------------------------------------
# The exactness matrix: paged == dense, token for token


def test_exactness_matrix_dense_model(setup, dense_engine, paged_engine):
    """Paged == dense across greedy / sampled / prefix-cache /
    mid-stream admission, under pipeline depth 1 AND 2 — and the
    greedy rows equal the solo oracle, so both layouts are exact, not
    merely identical."""
    cfg, params = setup
    system = _prompt(20, 10, cfg.vocab_size)

    dense_engine.set_pipeline_depth(1)
    reference, shapes = _matrix_workload(
        dense_engine, cfg.vocab_size, system
    )
    for depth in (1, 2):
        _clear_prefix(paged_engine)  # same cold-then-warm hit pattern
        paged_engine.set_pipeline_depth(depth)
        hits_before = paged_engine.stats()["prefix_hits"]
        got, _ = _matrix_workload(paged_engine, cfg.vocab_size, system)
        assert got == reference, f"paged depth {depth} diverged"
        # The run really exercised block aliasing, not just prefill.
        assert paged_engine.stats()["prefix_hits"] > hits_before
    dense_engine.set_pipeline_depth(2)
    for idx in (0, 2):  # greedy rows vs the solo oracle
        tokens, max_new = shapes[idx]
        assert reference[idx] == _oracle(params, cfg, tokens, max_new)


def test_exactness_matrix_moe(setup):
    """Same matrix on a MoE model: drop-free per-token routing keeps
    the paged gather invisible there too."""
    cfg = TransformerConfig(**{**CFG, "n_experts": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    dense = Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                   prompt_buckets=(16,), prefix_cache_size=2)
    paged = Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                   prompt_buckets=(16,), prefix_cache_size=2, kv_block=8)
    system = _prompt(40, 10, cfg.vocab_size)
    reference, shapes = _matrix_workload(dense, cfg.vocab_size, system)
    got, _ = _matrix_workload(paged, cfg.vocab_size, system)
    assert got == reference
    tokens, max_new = shapes[0]
    assert reference[0] == _oracle(params, cfg, tokens, max_new)


def test_exactness_kv_int8(setup):
    """int8 KV over the paged layout: the scale pools ride the same
    scatter/gather (paged_store/paged_view) and CoW copies them too —
    paged int8 output must equal dense int8, including a prefix hit
    whose mid-block divergence exercises the int8 CoW path."""
    cfg, params = setup
    kwargs = dict(n_slots=2, max_len=64, chunk=4, prompt_buckets=(16,),
                  kv_int8=True, prefix_cache_size=2)
    dense = Engine(params, cfg, **kwargs)
    paged = Engine(params, cfg, kv_block=8, **kwargs)

    def workload(engine):
        system = _prompt(55, 16, cfg.vocab_size)
        rid = engine.submit(GenRequest(tokens=system, max_new_tokens=2,
                                       cache_prefix=True))
        out = [engine.run()[rid]]
        engine.result(rid, timeout=0)
        # Identical prompt resubmitted: usable = len-1 ends mid-block
        # → int8 CoW on the paged engine.
        rid = engine.submit(GenRequest(tokens=system, max_new_tokens=6))
        out.append(engine.run()[rid])
        rid = engine.submit(GenRequest(
            tokens=_prompt(56, 9, cfg.vocab_size), max_new_tokens=8,
            temperature=0.7, seed=5,
        ))
        out.append(engine.run()[rid])
        return out

    assert workload(paged) == workload(dense)
    assert paged.stats()["prefix_hits"] >= 1  # the CoW hit really ran


def test_exactness_spec_decode(setup):
    """Speculative engine (prompt-lookup drafting) over a paged target
    cache: multi-token verify emission and the fold_in key chaining
    survive the block-table layout."""
    cfg, params = setup

    def workload(engine):
        rids = [
            engine.submit(GenRequest(
                tokens=_echo_prompt(12, cfg.vocab_size), max_new_tokens=10,
            )),
            engine.submit(GenRequest(
                tokens=_prompt(50, 9, cfg.vocab_size), max_new_tokens=7,
                temperature=0.8, seed=11,
            )),
        ]
        engine.step()
        rids.append(engine.submit(GenRequest(
            tokens=_echo_prompt(8, cfg.vocab_size), max_new_tokens=6,
        )))
        results = engine.run()
        return [results[r] for r in rids]

    dense = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                   prompt_buckets=(16,), spec_decode=2)
    paged = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                   prompt_buckets=(16,), spec_decode=2, kv_block=16)
    reference = workload(dense)
    assert workload(paged) == reference
    assert reference[0] == _oracle(
        params, cfg, _echo_prompt(12, cfg.vocab_size), 10
    )


def test_exactness_spec_draft_model(setup):
    """Model-drafted speculation: paged target cache + dense draft
    cache share one lengths vector through the block-table layout."""
    cfg, params = setup
    draft_cfg = TransformerConfig(**{**CFG, "d_model": 16, "n_layers": 1,
                                     "n_heads": 2, "d_ff": 32})
    draft_params = init_params(jax.random.PRNGKey(1), draft_cfg)
    kwargs = dict(n_slots=2, max_len=64, chunk=2, prompt_buckets=(16,),
                  spec_decode=2, draft_params=draft_params,
                  draft_cfg=draft_cfg)
    dense = Engine(params, cfg, **kwargs)
    paged = Engine(params, cfg, kv_block=8, **kwargs)
    req = dict(tokens=_prompt(60, 7, cfg.vocab_size), max_new_tokens=6)
    rid = dense.submit(GenRequest(**req))
    reference = dense.run()[rid]
    rid = paged.submit(GenRequest(**req))
    assert paged.run()[rid] == reference == _oracle(
        params, cfg, req["tokens"], req["max_new_tokens"]
    )


# ---------------------------------------------------------------------------
# The flash-decode kernel exactness matrix (ISSUE 13): kernel == gather
# == dense oracle across {greedy, temp>0, spec-decode, prefix-cache hit
# with CoW, mid-stream admission} × {fp, kv_int8, kv_int4} × pipeline
# depth {1, 2}.  (_matrix_workload carries the traffic shape: its
# system prompt is 10 tokens against kv_block 8, so the prefix hit ends
# mid-block and the paged planner takes the copy-on-write path.)


def test_kernel_exactness_matrix_fp(setup, dense_engine, kernel_engine):
    """Full-precision rung: the kernel engine's matrix output equals
    the dense engine's at both pipeline depths, and the greedy rows
    equal the solo oracle."""
    cfg, params = setup
    system = _prompt(200, 10, cfg.vocab_size)
    dense_engine.set_pipeline_depth(1)
    reference, shapes = _matrix_workload(
        dense_engine, cfg.vocab_size, system
    )
    dense_engine.set_pipeline_depth(2)
    for depth in (1, 2):
        _clear_prefix(kernel_engine)
        kernel_engine.set_pipeline_depth(depth)
        hits_before = kernel_engine.stats()["prefix_hits"]
        got, _ = _matrix_workload(kernel_engine, cfg.vocab_size, system)
        assert got == reference, f"kernel depth {depth} diverged"
        # The run really decoded through aliased + CoW'd blocks.
        assert kernel_engine.stats()["prefix_hits"] > hits_before
    kernel_engine.set_pipeline_depth(2)
    tokens, max_new = shapes[0]
    assert reference[0] == _oracle(params, cfg, tokens, max_new)


def test_kernel_exactness_kv_int8(setup):
    """int8 rung: kernel(kv_int8) == dense(kv_int8) — the scale pools
    ride the kernel's fused dequant instead of the gathered view."""
    cfg, params = setup
    kwargs = dict(n_slots=3, max_len=64, chunk=4, prompt_buckets=(16, 32),
                  kv_int8=True, prefix_cache_size=2)
    dense = Engine(params, cfg, **kwargs)
    kernel = Engine(params, cfg, kv_block=8, paged_kernel=True, **kwargs)
    system = _prompt(210, 10, cfg.vocab_size)
    reference, _ = _matrix_workload(dense, cfg.vocab_size, system)
    for depth in (1, 2):
        _clear_prefix(kernel)
        kernel.set_pipeline_depth(depth)
        got, _ = _matrix_workload(kernel, cfg.vocab_size, system)
        assert got == reference, f"kernel int8 depth {depth} diverged"


def test_kernel_exactness_kv_int4(setup):
    """kv4 rung: kernel(kv_int4) == gather(kv_int4).  int4 KV exists
    only on the paged layout (dense engines reject it — no block
    scales), so the gather path at the same quant IS the oracle here:
    exactly the A/B ``--paged-kernel on/off`` switches in production."""
    cfg, params = setup
    kwargs = dict(n_slots=3, max_len=64, chunk=4, prompt_buckets=(16, 32),
                  kv_block=8, kv_int4=True, prefix_cache_size=2)
    gather = Engine(params, cfg, paged_kernel=False, **kwargs)
    kernel = Engine(params, cfg, paged_kernel=True, **kwargs)
    system = _prompt(220, 10, cfg.vocab_size)
    reference, _ = _matrix_workload(gather, cfg.vocab_size, system)
    for depth in (1, 2):
        _clear_prefix(kernel)
        kernel.set_pipeline_depth(depth)
        got, _ = _matrix_workload(kernel, cfg.vocab_size, system)
        assert got == reference, f"kernel int4 depth {depth} diverged"
    # The int4 pool really is int4 — the capacity math in
    # doc/operations.md rests on the payload dtype.
    assert kernel._cache.k.dtype == jnp.int4


def test_kernel_exactness_spec_decode(setup):
    """Speculative rung: the verify forward's multi-token q tile goes
    through the kernel too (t = draft_len + 1 > 1)."""
    cfg, params = setup

    def workload(engine):
        rids = [
            engine.submit(GenRequest(
                tokens=_echo_prompt(12, cfg.vocab_size), max_new_tokens=10,
            )),
            engine.submit(GenRequest(
                tokens=_prompt(230, 9, cfg.vocab_size), max_new_tokens=7,
                temperature=0.8, seed=11,
            )),
        ]
        engine.step()
        rids.append(engine.submit(GenRequest(
            tokens=_echo_prompt(8, cfg.vocab_size), max_new_tokens=6,
        )))
        results = engine.run()
        return [results[r] for r in rids]

    dense = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                   prompt_buckets=(16,), spec_decode=2)
    kernel = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                    prompt_buckets=(16,), spec_decode=2, kv_block=16,
                    paged_kernel=True)
    assert workload(kernel) == workload(dense)


def test_kernel_ops_unit_matches_gather_and_ignores_sentinels(setup):
    """Ops-level pin: paged_flash_decode over a hand-built pool equals
    the gathered-view reference within fp tolerance, and scrambling a
    block only sentinels reach changes NOTHING (bit-equal outputs) —
    the zero-contribution half of the sentinel-clamp contract."""
    rng = np.random.RandomState(3)
    b, t, h, kvh, hd = 2, 1, 4, 2, 8
    n_blocks, bs, n_tables = 6, 8, 4
    q = jnp.asarray(rng.randn(b, t, h, hd).astype(np.float32))
    k_pool = jnp.asarray(rng.randn(n_blocks, bs, kvh, hd).astype(np.float32))
    v_pool = jnp.asarray(rng.randn(n_blocks, bs, kvh, hd).astype(np.float32))
    # Row 0 owns 3 blocks; row 1 owns 1; the rest are sentinels.
    tables = jnp.asarray(
        [[0, 1, 2, n_blocks], [3, n_blocks, n_blocks, n_blocks]], jnp.int32
    )
    starts = jnp.asarray([20, 5], jnp.int32)
    got = paged_flash_decode(
        q, k_pool, v_pool, None, None, tables, starts
    )

    def reference(kp, vp):
        k_view, _ = paged_view(kp, None, tables)
        v_view, _ = paged_view(vp, None, tables)
        group = h // kvh
        q_g = q.reshape(b, t, kvh, group, hd)
        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q_g.astype(jnp.float32),
            k_view.astype(jnp.float32),
        ) / (hd ** 0.5)
        positions = starts[:, None] + jnp.arange(t)
        keep = (
            jnp.arange(k_view.shape[1])[None, None, None, None, :]
            <= positions[:, None, None, :, None]
        )
        scores = jnp.where(keep, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum(
            "bhgqk,bkhd->bqhgd", probs, v_view.astype(jnp.float32)
        ).reshape(b, t, h, hd)

    np.testing.assert_allclose(
        np.asarray(got), np.asarray(reference(k_pool, v_pool)),
        rtol=1e-5, atol=1e-5,
    )
    # Scramble the LAST pool block — the one every sentinel entry
    # clamps to on the gather side — plus an unreferenced block.
    k2 = k_pool.at[n_blocks - 1].set(100.0).at[4].set(-50.0)
    v2 = v_pool.at[n_blocks - 1].set(100.0).at[4].set(-50.0)
    got2 = paged_flash_decode(q, k2, v2, None, None, tables, starts)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(got2))


@pytest.mark.parametrize("use_kernel", [False, True],
                         ids=["gather", "kernel"])
def test_sentinel_reallocated_last_block_never_leaks(setup, use_kernel):
    """THE sentinel-clamp hazard regression (ops/paged.py module
    docstring): slot C decodes with sentinel table entries while the
    LAST pool block — which every sentinel clamps to on the gather
    path — is freed by a finished request and reallocated to a new
    one that fills it with live KV.  C's masked region now gathers
    another slot's real data; the causal mask must hide every byte of
    it.  Run symmetrically through the gather (clamp + mask) and the
    kernel (never reads the block at all)."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                    prompt_buckets=(16,), kv_block=8, kv_blocks=6,
                    paged_kernel=use_kernel)
    c_tokens = _prompt(240, 5, cfg.vocab_size)
    a_tokens = _prompt(241, 5, cfg.vocab_size)
    b_tokens = _prompt(242, 5, cfg.vocab_size)
    rid_c = engine.submit(GenRequest(tokens=c_tokens, max_new_tokens=20))
    rid_a = engine.submit(GenRequest(tokens=a_tokens, max_new_tokens=2))
    engine.step()  # one wave admits both: C → [0..3], A → [4, 5]
    with engine._lock:
        slot_c, = [s for s, st in engine._slots.items() if st.rid == rid_c]
        row_c = engine._tables_host[slot_c].copy()
    assert (row_c[4:] == 6).all(), "C's table should end in sentinels"
    for _ in range(20):  # drive until A completes and frees its blocks
        with engine._lock:
            if rid_a in engine._results:
                break
        engine.step()
    # B reallocates A's freed blocks — the LAST pool block first (the
    # allocator's free list is LIFO) — and fills them with its KV
    # while C keeps decoding against its sentinel-padded table.
    rid_b = engine.submit(GenRequest(tokens=b_tokens, max_new_tokens=2))
    engine.step()
    with engine._lock:
        slot_b, = [s for s, st in engine._slots.items() if st.rid == rid_b]
        row_b = engine._tables_host[slot_b]
        assert 5 in row_b.tolist(), "B should hold the last pool block"
    results = engine.run()
    assert results[rid_c] == _oracle(params, cfg, c_tokens, 20)
    assert results[rid_b] == _oracle(params, cfg, b_tokens, 2)


def test_kv_int4_validation_and_ship_refusal(setup):
    """kv4 is paged-only and never ships: dense layouts have no block
    scales to carry it, and the manifest framing has no stable numpy
    int4 wire dtype — export/import refuse (KvIneligibleError → the
    router's recompute fallback), and holds are never taken."""
    cfg, params = setup
    with pytest.raises(ValueError, match="kv_int4 needs the paged"):
        Engine(params, cfg, n_slots=1, max_len=64, kv_int4=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        Engine(params, cfg, n_slots=1, max_len=64, kv_block=8,
               kv_int8=True, kv_int4=True)
    with pytest.raises(ValueError, match="paged_kernel needs"):
        Engine(params, cfg, n_slots=1, max_len=64, paged_kernel=True)
    # A block size the kernel's lane tiling cannot cover (>128 and not
    # a multiple of 128) must fail AT CONSTRUCTION with the constraint
    # named — the gather path accepts the same geometry.
    with pytest.raises(ValueError, match="lane tiling"):
        Engine(params, cfg, n_slots=1, max_len=960, kv_block=192,
               paged_kernel=True)
    Engine(params, cfg, n_slots=1, max_len=960, kv_block=192)  # gather ok
    engine = Engine(params, cfg, n_slots=1, max_len=64, chunk=4,
                    prompt_buckets=(16,), kv_block=8, kv_int4=True)
    rid = engine.submit(GenRequest(
        tokens=_prompt(250, 5, cfg.vocab_size), max_new_tokens=2,
        hold_kv=True,
    ))
    engine.run()
    with pytest.raises(KvIneligibleError, match="kv_int4"):
        engine.export_kv(rid)
    with pytest.raises(KvIneligibleError, match="kv_int4"):
        engine.import_kv({}, {})
    # hold_kv was a no-op: nothing pinned once the request finished.
    engine.result(rid, timeout=0)
    assert engine.stats()["kv_blocks_used"] == 0


# ---------------------------------------------------------------------------
# Copy-free sharing, refcounts, copy-on-write


def _pool_blocks(engine, block_ids):
    """Fetch the pool contents of ``block_ids`` (k and v, all layers)
    — the mutation witness for the shared-block-immutability tests."""
    k = np.asarray(jax.device_get(engine._cache.k[:, list(block_ids)]))
    v = np.asarray(jax.device_get(engine._cache.v[:, list(block_ids)]))
    return k, v


def test_prefix_blocks_shared_once_across_concurrent_readers(
    setup, paged_engine
):
    """Two concurrent requests over one cached prefix consume its
    blocks ONCE: refcounts show entry + both slots on the same block
    ids, the shared gauge and bytes-saved counter advance, and the
    shared blocks' pool contents are bit-identical before vs after the
    concurrent run (never mutated in place)."""
    cfg, params = setup
    engine = paged_engine
    _clear_prefix(engine)
    label = engine._engine_label
    system = _prompt(30, 16, cfg.vocab_size)  # 2 full 8-token blocks

    rid = engine.submit(GenRequest(tokens=system, max_new_tokens=2,
                                   cache_prefix=True))
    engine.run()
    engine.result(rid, timeout=0)
    with engine._lock:
        (entry_blocks, entry_rows), = [
            v for v in engine._prefix_cache.values()
        ]
    assert entry_rows == 16 and len(entry_blocks) == 2
    before_k, before_v = _pool_blocks(engine, entry_blocks)
    saved_before = engine.stats()["prefix_bytes_saved"]

    # Both admitted in ONE wave: concurrent readers of the same blocks.
    reqs = [system + _prompt(31 + i, 3, cfg.vocab_size) for i in range(2)]
    rids = [
        engine.submit(GenRequest(tokens=t, max_new_tokens=5))
        for t in reqs
    ]
    engine.step()
    st = engine.stats()
    with engine._lock:
        refs = [int(engine._alloc._refs[b]) for b in entry_blocks]
    assert refs == [3, 3]  # entry + two aliasing slots
    assert st["kv_blocks_shared"] >= 2
    assert _metrics.SERVE_KV_BLOCKS.value(label, "shared") >= 2
    results = engine.run()
    for rid, tokens in zip(rids, reqs):
        assert results[rid] == _oracle(params, cfg, tokens, 5)

    after_k, after_v = _pool_blocks(engine, entry_blocks)
    np.testing.assert_array_equal(before_k, after_k)
    np.testing.assert_array_equal(before_v, after_v)
    st = engine.stats()
    assert st["prefix_bytes_saved"] > saved_before
    assert st["prefix_injects"] >= 1
    with engine._lock:  # readers gone: entry holds the last ref
        assert [int(engine._alloc._refs[b]) for b in entry_blocks] == [1, 1]


def test_cow_divergence_never_mutates_shared_block(setup, paged_engine):
    """Resubmitting the cached prompt itself makes the usable prefix
    end mid-block (len - 1): the tail prefill would write into the
    entry's last block, so admission copy-on-writes it — the entry
    block's contents stay bit-identical and the output still matches
    the oracle."""
    cfg, params = setup
    engine = paged_engine
    _clear_prefix(engine)
    system = _prompt(33, 16, cfg.vocab_size)
    rid = engine.submit(GenRequest(tokens=system, max_new_tokens=2,
                                   cache_prefix=True))
    engine.run()
    engine.result(rid, timeout=0)
    with engine._lock:
        (entry_blocks, _), = [v for v in engine._prefix_cache.values()]
    before_k, before_v = _pool_blocks(engine, entry_blocks)

    rid = engine.submit(GenRequest(tokens=system, max_new_tokens=4))
    result = engine.run()[rid]
    assert result == _oracle(params, cfg, system, 4)
    after_k, after_v = _pool_blocks(engine, entry_blocks)
    np.testing.assert_array_equal(before_k, after_k)
    np.testing.assert_array_equal(before_v, after_v)


# ---------------------------------------------------------------------------
# Block exhaustion, release paths, leaks


def test_oom_of_blocks_is_admission_backpressure(setup):
    """A pool holding 2 blocks against 6 one-block requests: waves
    defer (kv_admit_deferrals counts them), everything completes as
    finishing requests free blocks, nothing crashes or leaks."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=4, max_len=64, chunk=4,
                    prompt_buckets=(16,), kv_block=16, kv_blocks=2)
    rids = [
        engine.submit(GenRequest(
            tokens=_prompt(70 + i, 9, cfg.vocab_size), max_new_tokens=4,
        ))
        for i in range(6)
    ]
    results = engine.run()
    assert all(len(results[r]) == 4 for r in rids)
    st = engine.stats()
    assert st["kv_admit_deferrals"] > 0
    assert st["kv_blocks_free"] == 2 and st["kv_blocks_used"] == 0

    # A request whose WORST case exceeds the whole pool can never be
    # admitted: reject at submit, don't deadlock the queue.
    with pytest.raises(ValueError, match="KV blocks"):
        engine.submit(GenRequest(
            tokens=_prompt(76, 9, cfg.vocab_size), max_new_tokens=50,
        ))


def test_matched_entry_pinning_pool_is_evicted_not_deadlocked(setup):
    """Review regression: a request that fits the pool but NOT the
    pool minus its own matched prefix entry must not wedge the queue.
    Entry pins 3 of 4 blocks; the sharing request's aliased plan needs
    2 fresh against 1 free, every other entry is already gone, and no
    slot will ever free anything — the planner must sacrifice the
    matched entry and re-plan prefix-free."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=2, max_len=256, chunk=4,
                    prompt_buckets=(64, 128, 255), prefix_cache_size=2,
                    kv_block=64, kv_blocks=4)
    system = _prompt(120, 200, cfg.vocab_size)  # entry: 3 full blocks
    rid = engine.submit(GenRequest(tokens=system, max_new_tokens=2,
                                   cache_prefix=True))
    engine.run()
    engine.result(rid, timeout=0)
    assert engine.stats()["kv_blocks_used"] == 3

    rid = engine.submit(GenRequest(tokens=system[:128] + [5],
                                   max_new_tokens=100))
    for _ in range(200):  # bounded: pre-fix this spun forever
        if not engine.pending():
            break
        engine.step()
    assert not engine.pending(), "queue wedged on the pinned entry"
    assert len(engine.result(rid, timeout=0)) == 100
    st = engine.stats()
    assert st["prefix_entries"] == 0  # the matched entry was sacrificed
    assert st["kv_blocks_used"] == 0 and st["kv_blocks_free"] == 4


def test_mutually_aliased_entries_cleared_not_deadlocked(setup):
    """Review regression (round 2): two prefix entries sharing the
    SAME block set leave every block at ref 2, so no per-entry
    exclusivity test can free anything — an unrelated request that
    fits the pool but not pool-minus-the-pinned-set must still admit
    (the idle fallback clears the whole cache) instead of wedging the
    queue on an idle engine."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                    prompt_buckets=(16, 32, 48), prefix_cache_size=2,
                    kv_block=16, kv_blocks=4)
    base = _prompt(130, 32, cfg.vocab_size)  # 2 full blocks
    for tokens in (base, base + _prompt(131, 7, cfg.vocab_size)):
        rid = engine.submit(GenRequest(tokens=tokens, max_new_tokens=2,
                                       cache_prefix=True))
        engine.run()
        engine.result(rid, timeout=0)
    with engine._lock:
        sets = [tuple(b) for b, _ in engine._prefix_cache.values()]
    assert len(sets) == 2 and sets[0] == sets[1]  # same blocks, ref 2
    assert engine.stats()["kv_blocks_shared"] == 2

    # Unrelated request: worst case 3 blocks vs 2 free.
    rid = engine.submit(GenRequest(
        tokens=_prompt(132, 20, cfg.vocab_size), max_new_tokens=25,
    ))
    for _ in range(100):  # bounded: pre-fix this spun forever
        if not engine.pending():
            break
        engine.step()
    assert not engine.pending(), "queue wedged on mutually-aliased set"
    assert len(engine.result(rid, timeout=0)) == 25
    st = engine.stats()
    assert st["prefix_entries"] == 0 and st["kv_blocks_used"] == 0


def test_transient_shortage_keeps_unreclaimable_entries(setup):
    """Review regression (round 2): with slots RUNNING, a shortage
    that eviction cannot cover must not flush the prefix cache — the
    entries' future hits are worth more than zero freed blocks."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                    prompt_buckets=(16, 32, 48), prefix_cache_size=2,
                    kv_block=16, kv_blocks=4)
    system = _prompt(140, 32, cfg.vocab_size)
    rid = engine.submit(GenRequest(tokens=system, max_new_tokens=2,
                                   cache_prefix=True))
    engine.run()
    engine.result(rid, timeout=0)
    # A long-running request sharing the entry: the entry's blocks are
    # aliased by a LIVE slot (exclusive = 0), one fresh block in use.
    long_rid = engine.submit(GenRequest(tokens=system + [3],
                                        max_new_tokens=12))
    engine.step()
    # Head-of-line request needs 2 fresh blocks; free == 1 and the
    # entry is unreclaimable — must defer WITHOUT evicting it.
    short_rid = engine.submit(GenRequest(
        tokens=_prompt(141, 16, cfg.vocab_size), max_new_tokens=12,
    ))
    engine.step()
    st = engine.stats()
    assert st["kv_admit_deferrals"] >= 1
    assert st["prefix_entries"] == 1, "transient shortage flushed cache"
    results = engine.run()  # the long request frees; short admits
    assert len(results[long_rid]) == 12 and len(results[short_rid]) == 12


def test_abort_and_deadline_reap_release_blocks(setup, paged_engine):
    """The two failure funnels give their blocks back: abort() with a
    chunk in flight, and a deadline reaped mid-decode."""
    cfg, params = setup
    engine = paged_engine
    _clear_prefix(engine)

    rids = [
        engine.submit(GenRequest(
            tokens=_prompt(80 + i, 5, cfg.vocab_size), max_new_tokens=12,
        ))
        for i in range(2)
    ]
    engine.step()
    assert engine.stats()["kv_blocks_used"] > 0
    engine.abort("test abort")
    st = engine.stats()
    assert st["kv_blocks_used"] == 0 and st["kv_blocks_free"] == 24
    for rid in rids:
        with pytest.raises(RuntimeError, match="test abort"):
            engine.result(rid, timeout=0)

    rid = engine.submit(GenRequest(
        tokens=_prompt(82, 5, cfg.vocab_size), max_new_tokens=40,
        deadline=time.monotonic() + 0.2,
    ))
    engine.step()
    assert engine.stats()["kv_blocks_used"] > 0
    time.sleep(0.25)
    while engine.pending():  # _reap frees the slot at a step boundary
        engine.step()
    assert engine.stats()["kv_blocks_used"] == 0
    with pytest.raises(RequestFailedError, match="deadline"):
        engine.result(rid, timeout=0)


def test_chaos_soak_zero_leaked_blocks(setup, paged_engine):
    """Mixed traffic (greedy/sampled/prefix-marked), client cancels,
    and a mid-flight abort every third cycle: after every cycle the
    allocator's books balance — used blocks are exactly the prefix
    cache's holdings, free + used == total."""
    cfg, params = setup
    engine = paged_engine
    _clear_prefix(engine)
    rng = np.random.RandomState(7)

    for cycle in range(6):
        rids = []
        for i in range(4):
            rids.append(engine.submit(GenRequest(
                tokens=_prompt(100 + 10 * cycle + i,
                               int(rng.randint(4, 14)), cfg.vocab_size),
                max_new_tokens=int(rng.randint(2, 10)),
                temperature=0.8 if i % 2 else 0.0, seed=i,
                cache_prefix=(i == 0),
            )))
        engine.step()
        engine.cancel(rids[int(rng.randint(0, 4))])
        if cycle % 3 == 2:
            engine.step()
            engine.abort("chaos")
        else:
            engine.run()
        for rid in rids:
            try:
                engine.result(rid, timeout=0)
            except (RuntimeError, KeyError, TimeoutError):
                pass
        st = engine.stats()
        with engine._lock:
            entry_held = sum(
                len(blocks) for blocks, _ in engine._prefix_cache.values()
            )
        assert st["kv_blocks_used"] == entry_held, f"cycle {cycle} leaked"
        assert st["kv_blocks_free"] + st["kv_blocks_used"] == 24
    assert engine.in_flight() == 0


# ---------------------------------------------------------------------------
# Observability surfaces


def test_stats_info_load_surface_kv_occupancy(setup, paged_engine,
                                              dense_engine, kernel_engine):
    cfg, params = setup
    st = paged_engine.stats()
    assert st["kv_block_size"] == 8 and st["kv_blocks_total"] == 24
    assert set(st) >= {
        "kv_blocks_free", "kv_blocks_used", "kv_blocks_shared",
        "kv_fragmentation", "kv_admit_deferrals", "prefix_bytes_saved",
        "prefix_injects",
    }
    info = paged_engine.info()["engine"]
    assert info["paged"] is True and info["kv_block"] == 8
    assert info["kv_blocks"] == 24
    load = paged_engine.load()
    assert load["kv_blocks_total"] == 24
    assert {"kv_blocks_free", "kv_blocks_shared"} <= set(load)
    # Fast-path flags (ISSUE 13) on all three surfaces: the gather
    # engine reports the kernel off (CPU auto-resolution), the kernel
    # engine on; kv quant rung rides beside them.
    assert info["paged_kernel"] is False and info["kv_int4"] is False
    assert st["paged_kernel"] is False and st["kv_quant"] == ""
    assert load["paged_kernel"] is False and load["kv_int4"] is False
    kinfo = kernel_engine.info()["engine"]
    assert kinfo["paged_kernel"] is True
    assert kernel_engine.stats()["paged_kernel"] is True
    assert kernel_engine.load()["paged_kernel"] is True
    # Dense engines export the same schema, zeroed.
    dst = dense_engine.stats()
    assert dst["kv_block_size"] == 0 and dst["kv_blocks_total"] == 0
    assert dense_engine.info()["engine"]["paged"] is False
    assert dense_engine.load()["kv_blocks_total"] == 0
    assert dense_engine.load()["paged_kernel"] is False


def test_fragmentation_reflects_block_rounding(setup, paged_engine):
    """A 5-token prompt + 3-token budget reserves 2 whole 8-token
    blocks (prefill bucket 16): mid-flight fragmentation is the
    allocated-but-idle tail, and it returns to the prefix-entries-only
    baseline once the request completes."""
    cfg, params = setup
    engine = paged_engine
    _clear_prefix(engine)
    rid = engine.submit(GenRequest(
        tokens=_prompt(90, 5, cfg.vocab_size), max_new_tokens=3,
    ))
    engine.step()
    st = engine.stats()
    assert st["kv_blocks_used"] == 2  # bucket 16 rows -> 2 blocks
    assert 0.0 < st["kv_fragmentation"] < 1.0
    engine.run()
    engine.result(rid, timeout=0)
    assert engine.stats()["kv_fragmentation"] == 0.0


def test_paged_engine_validation(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="divide"):
        Engine(params, cfg, n_slots=1, max_len=64, kv_block=7)
    with pytest.raises(ValueError, match="kv_blocks needs"):
        Engine(params, cfg, n_slots=1, max_len=64, kv_blocks=4)
    with pytest.raises(ValueError, match="kv_block"):
        Engine(params, cfg, n_slots=1, max_len=64, kv_block=-1)
