"""Tier-4 REAL Kubernetes e2e: kind cluster + real kubelet + CSI sidecars.

≙ reference test/e2e/storage/csi_volumes.go:57-220 (upstream storage
suite driving the manifest-deployed driver) on the clear-kvm cluster
(reference test/clear-kvm.make:1-120).  The kubelet-sim tier
(test_k8s_e2e.py) executes the same manifests in-process; THIS tier
hands them to an actual kubelet, external-provisioner, and
node-driver-registrar, which exercise the protocol corners no
simulation can vouch for: plugin-registration socket handshake,
capability negotiation ordering, staging-path ownership, mount
propagation.

Env-gated: ``TEST_KIND=1`` plus ``kind``/``kubectl``/``docker`` on PATH
— cleanly SKIPPED (never simulated) otherwise, exactly like the
reference's QEMU tier on machines without KVM.  The agent runs in
``--fake-chips`` mode (a kind node has no /dev/accel*), which is the
same device-plane stand-in every other tier uses.

Flow:
  1. ``make image`` → ``kind create cluster`` → ``kind load`` the image.
  2. Generate the mTLS tree (CertAuthority) for the actual node name and
     create the ``oim-ca`` secret the manifests mount.
  3. Apply rbac/registry/storageclass, resolve the registry Service's
     ClusterIP, substitute ``@OIM_REGISTRY_ADDRESS@`` (the reference's
     manifest-substitution step, csi_volumes.go:288-300), apply the
     daemonset with the agent patched to fake-chip inventory.
  4. Apply the example workload: a real external-provisioner turns the
     PVC into CreateVolume, kubelet stages/publishes through the real
     registrar socket, the pod runs the repo's own coordinator+collective
     snippet against the staged bootstrap, and MUST exit 0.
  5. Delete the workload; the provisioner's DeleteVolume must unmap.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
DEPLOY = os.path.join(REPO, "deploy", "kubernetes")
CLUSTER = "oim-e2e"

pytestmark = pytest.mark.skipif(
    os.environ.get("TEST_KIND") != "1",
    reason="set TEST_KIND=1 (and have kind/kubectl/docker) for the real-k8s tier",
)


def _need(binary: str) -> str:
    path = shutil.which(binary)
    if path is None:
        pytest.skip(f"{binary} not on PATH")
    return path


def _run(args, timeout=300, env=None, check=True, input=None):
    proc = subprocess.run(
        args,
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        input=input,
    )
    if check and proc.returncode != 0:
        raise AssertionError(
            f"{' '.join(args)} rc={proc.returncode}\n"
            f"stdout: {proc.stdout[-4000:]}\nstderr: {proc.stderr[-4000:]}"
        )
    return proc


class _Kind:
    def __init__(self, tmp_path):
        self.kind = _need("kind")
        self.kubectl = _need("kubectl")
        _need("docker")
        self.kubeconfig = str(tmp_path / "kubeconfig")
        self.env = dict(os.environ, KUBECONFIG=self.kubeconfig)
        self.tmp = tmp_path

    def kc(self, *args, timeout=180, check=True, input=None):
        return _run(
            [self.kubectl, *args], timeout=timeout, env=self.env,
            check=check, input=input,
        )

    def up(self):
        _run(["make", "-C", REPO, "image"], timeout=1800)
        _run(
            [self.kind, "delete", "cluster", "--name", CLUSTER],
            env=self.env, check=False,
        )
        _run(
            [self.kind, "create", "cluster", "--name", CLUSTER,
             "--wait", "180s"],
            timeout=600, env=self.env,
        )
        _run(
            [self.kind, "load", "docker-image", "oim-tpu:latest",
             "--name", CLUSTER],
            timeout=600, env=self.env,
        )
        self.node = self.kc(
            "get", "nodes", "-o", "jsonpath={.items[0].metadata.name}"
        ).stdout.strip()
        assert self.node

    def down(self):
        _run(
            [self.kind, "delete", "cluster", "--name", CLUSTER],
            env=self.env, check=False, timeout=300,
        )

    # -- deploy ------------------------------------------------------------

    def secret_from_certs(self):
        import sys

        sys.path.insert(0, REPO)
        from oim_tpu.common.ca import CertAuthority

        certdir = self.tmp / "certs"
        certdir.mkdir(exist_ok=True)
        ca = CertAuthority()
        ca.write_tree(
            str(certdir),
            [
                "component.registry",
                f"controller.{self.node}",
                f"host.{self.node}",
                "user.admin",
            ],
        )
        files = sorted(os.listdir(certdir))
        args = ["-n", "oim-system", "create", "secret", "generic", "oim-ca"]
        args += [f"--from-file={f}={certdir / f}" for f in files]
        self.kc(*args)

    def apply_stack(self):
        # Namespace (+ registry Deployment/Service/PVC) first; the
        # oim-ca secret must exist before the pods mount it, so create
        # the namespace alone, then the secret, then the rest.
        self.kc("create", "namespace", "oim-system", check=False)
        self.secret_from_certs()
        self.kc("apply", "-f", os.path.join(DEPLOY, "rbac.yaml"))
        self.kc("apply", "-f", os.path.join(DEPLOY, "registry.yaml"))
        self.kc("apply", "-f", os.path.join(DEPLOY, "storageclass.yaml"))
        self.kc(
            "-n", "oim-system", "rollout", "status",
            "deployment/oim-registry", "--timeout=240s", timeout=300,
        )
        cluster_ip = self.kc(
            "-n", "oim-system", "get", "svc", "oim-registry",
            "-o", "jsonpath={.spec.clusterIP}",
        ).stdout.strip()
        assert cluster_ip

        # The reference substitutes the registry address into manifests
        # before applying (csi_volumes.go:288-300); hostNetwork pods use
        # the node resolver, so substitute the ClusterIP, not the DNS
        # name.  The agent gets fake-chip inventory: no /dev/accel* on a
        # kind node.
        with open(os.path.join(DEPLOY, "tpu-daemonset.yaml")) as f:
            manifest = f.read()
        manifest = manifest.replace(
            "@OIM_REGISTRY_ADDRESS@", f"tcp://{cluster_ip}:8999"
        )
        manifest = manifest.replace(
            "- --devices=/dev/accel*", "- --fake-chips=8"
        )
        self.kc("label", "node", self.node, "oim.io/tpu=true", "--overwrite")
        self.kc("apply", "-f", "-", input=manifest)
        self.kc(
            "-n", "oim-system", "rollout", "status",
            "daemonset/oim-tpu-node", "--timeout=300s", timeout=360,
        )


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    kind = _Kind(tmp_path_factory.mktemp("kind"))
    kind.up()
    try:
        kind.apply_stack()
        yield kind
    finally:
        # Always tear the cluster down — a leaked kind cluster squats
        # docker resources the way a leaked daemon squats the TPU.
        kind.down()


def test_real_kubelet_provisions_and_runs_workload(cluster):
    """The upstream-sidecar path: PVC → external-provisioner →
    CreateVolume → kubelet NodeStage/NodePublish → pod runs the repo's
    coordinator+allreduce snippet on the staged bootstrap → Succeeded."""
    with open(os.path.join(DEPLOY, "example-workload.yaml")) as f:
        workload = f.read()
    # The cluster image carries libtpu but a kind node has no TPU;
    # force the CPU backend for the pod's JAX snippet (the fake-chip
    # analog on the compute side).
    workload = workload.replace(
        'value: /tpu/tpu-bootstrap.json',
        'value: /tpu/tpu-bootstrap.json\n'
        '        - name: JAX_PLATFORMS\n'
        '          value: cpu',
    )
    cluster.kc("apply", "-f", "-", input=workload)
    try:
        deadline = time.time() + 600
        phase = ""
        while time.time() < deadline:
            phase = cluster.kc(
                "get", "pod", "jax-allreduce",
                "-o", "jsonpath={.status.phase}", check=False,
            ).stdout.strip()
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(5)
        logs = cluster.kc(
            "logs", "pod/jax-allreduce", check=False
        ).stdout
        assert phase == "Succeeded", (
            f"pod phase={phase}\nlogs:\n{logs[-4000:]}\n"
            + cluster.kc(
                "describe", "pod", "jax-allreduce", check=False
            ).stdout[-3000:]
        )
        # The PVC must have bound through the real provisioner.
        bound = cluster.kc(
            "get", "pvc", "tpu-slice-4", "-o", "jsonpath={.status.phase}"
        ).stdout.strip()
        assert bound == "Bound"
    finally:
        cluster.kc(
            "delete", "-f", os.path.join(DEPLOY, "example-workload.yaml"),
            "--ignore-not-found", timeout=240, check=False,
        )


def test_delete_volume_reaches_driver(cluster):
    """After the workload PVC is deleted, the external-provisioner calls
    DeleteVolume on the driver (reclaimPolicy Delete): the driver logs
    prove a real sidecar, not the sim, drove the call."""
    deadline = time.time() + 240
    while time.time() < deadline:
        gone = cluster.kc(
            "get", "pvc", "tpu-slice-4", check=False
        ).returncode != 0
        if gone:
            break
        time.sleep(5)
    logs = cluster.kc(
        "-n", "oim-system", "logs", "daemonset/oim-tpu-node",
        "-c", "csi-driver", "--tail=-1", check=False,
    ).stdout
    assert "DeleteVolume" in logs, logs[-3000:]
