"""Flagship model tests: forward correctness across parallelism mixes and
actual learning (loss decrease) on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oim_tpu.models import (
    TrainState,
    TransformerConfig,
    init_params,
    make_train_step,
)
from oim_tpu.models.train import shard_state, data_pspec
from oim_tpu.parallel import build_mesh

import optax

TINY = dict(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
)


def _data(batch, seq, vocab, seed=0):
    key = jax.random.PRNGKey(seed)
    # A learnable pattern: token t+1 = (token t + 1) mod vocab.
    start = jax.random.randint(key, (batch, 1), 0, vocab)
    ramp = jnp.arange(seq)[None, :]
    return (start + ramp) % vocab


def _run_steps(cfg, mesh, batch=8, seq=16, steps=8, seed=0):
    params = init_params(jax.random.PRNGKey(seed), cfg)
    optimizer = optax.adamw(1e-2)
    state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
    step_fn = make_train_step(cfg, mesh, optimizer)
    tokens = jax.device_put(
        _data(batch, seq, cfg.vocab_size),
        jax.sharding.NamedSharding(mesh, data_pspec()),
    )
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state, tokens)
        losses.append(float(metrics["ce"]))
    return losses


class TestTrainingMixes:
    def test_single_device_mesh(self):
        mesh = build_mesh(devices=jax.devices()[:1])
        losses = _run_steps(TransformerConfig(**TINY), mesh, batch=4)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9

    def test_scanned_loop_matches_stepwise(self):
        """make_train_loop (lax.scan, one dispatch) must produce the same
        loss trajectory as N make_train_step dispatches."""
        from oim_tpu.models import make_train_loop

        cfg = TransformerConfig(**TINY)
        mesh = build_mesh(devices=jax.devices()[:1])
        stepwise = _run_steps(cfg, mesh, batch=4, steps=6)

        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        loop = make_train_loop(cfg, mesh, optimizer)
        tokens = jax.device_put(
            _data(4, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        batches = jnp.broadcast_to(tokens, (6, *tokens.shape))
        state, metrics = loop(state, batches)
        np.testing.assert_allclose(
            np.asarray(metrics["ce"]), np.asarray(stepwise), rtol=1e-4
        )
        assert int(state.step) == 6

    def test_dp_sp_mix(self):
        mesh = build_mesh(dp=2, sp=4)
        losses = _run_steps(TransformerConfig(**TINY), mesh)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9

    def test_dp_sp_mix_ulysses(self):
        """Same mix with the all-to-all sequence-parallel scheme; the two
        attn_impls must train to the same losses (exact attention both)."""
        mesh = build_mesh(dp=2, sp=4)
        ring = _run_steps(TransformerConfig(**TINY), mesh)
        ulysses = _run_steps(
            TransformerConfig(**TINY, attn_impl="ulysses"), mesh
        )
        np.testing.assert_allclose(ulysses, ring, rtol=1e-4, atol=1e-5)
        assert ulysses[-1] < ulysses[0] * 0.9

    def test_dp_tp_mix(self):
        mesh = build_mesh(dp=2, tp=4)
        losses = _run_steps(TransformerConfig(**TINY), mesh)
        assert losses[-1] < losses[0] * 0.9

    def test_pp_pipeline(self):
        mesh = build_mesh(pp=2, tp=2, dp=2)
        cfg = TransformerConfig(**TINY, n_stages=2, n_microbatches=2)
        losses = _run_steps(cfg, mesh)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9

    def test_moe_aux_collected_under_pp(self):
        """The MoE load-balance aux loss must not vanish under pipeline
        parallelism (round-1 known limit).  pp=2/n_micro=2 routes the same
        token groups as dp=2 (batch halves), so the full loss — ce AND aux
        — must match between the two meshes."""
        cfg_pp = TransformerConfig(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0},
            n_stages=2, n_microbatches=2,
        )
        cfg_dp = TransformerConfig(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0}
        )
        from oim_tpu.models.train import AUX_LOSS_WEIGHT

        def first_metrics(cfg, mesh):
            params = init_params(jax.random.PRNGKey(0), cfg)
            optimizer = optax.adamw(1e-2)
            state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
            step_fn = make_train_step(cfg, mesh, optimizer)
            tokens = jax.device_put(
                _data(8, 16, cfg.vocab_size, seed=5),
                jax.sharding.NamedSharding(mesh, data_pspec()),
            )
            _, metrics = step_fn(state, tokens)
            return float(metrics["loss"]), float(metrics["ce"])

        loss_pp, ce_pp = first_metrics(cfg_pp, build_mesh(pp=2))
        loss_dp, ce_dp = first_metrics(cfg_dp, build_mesh(dp=2))
        aux_pp = (loss_pp - ce_pp) / AUX_LOSS_WEIGHT
        aux_dp = (loss_dp - ce_dp) / AUX_LOSS_WEIGHT
        assert aux_pp > 0.5, f"aux under pp vanished: {aux_pp}"
        np.testing.assert_allclose(ce_pp, ce_dp, rtol=1e-4)
        np.testing.assert_allclose(aux_pp, aux_dp, rtol=1e-3)

    def test_stage_remat_lowers_peak_memory(self):
        """stage_remat must cut compiled peak temp memory vs storing every
        layer activation per schedule step, at identical loss."""
        from dataclasses import replace

        from oim_tpu.models.train import _build_train_step

        cfg = TransformerConfig(
            **{**TINY, "n_layers": 4}, n_stages=2, n_microbatches=4
        )
        mesh = build_mesh(pp=2)
        tokens = jax.device_put(
            _data(8, 32, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)

        def peak_and_loss(remat):
            c = replace(cfg, remat=remat)
            state = shard_state(TrainState.create(params, optimizer), c, mesh)
            step = jax.jit(_build_train_step(c, mesh, optimizer))
            compiled = step.lower(state, tokens).compile()
            mem = compiled.memory_analysis()
            _, metrics = compiled(state, tokens)
            return mem.temp_size_in_bytes, float(metrics["loss"])

        peak_remat, loss_remat = peak_and_loss(True)
        peak_full, loss_full = peak_and_loss(False)
        np.testing.assert_allclose(loss_remat, loss_full, rtol=1e-4)
        assert peak_remat < peak_full, (
            f"remat {peak_remat} !< full {peak_full}"
        )

    def test_1f1b_matches_gpipe_trajectory(self):
        """The interleaved 1F1B schedule must train identically to GPipe
        (same math, different interleaving): loss trajectories match."""
        mesh = build_mesh(pp=2)
        base = dict(**TINY, n_stages=2, n_microbatches=4)
        gpipe = _run_steps(
            TransformerConfig(**base), mesh, batch=8, steps=4
        )
        f1b = _run_steps(
            TransformerConfig(**base, pp_schedule="1f1b"), mesh,
            batch=8, steps=4,
        )
        np.testing.assert_allclose(f1b, gpipe, rtol=1e-4)
        assert f1b[-1] < f1b[0] * 0.9

    def test_1f1b_all_manual_axes(self):
        """1F1B composed with dp and sp (ring attention inside the stage,
        label hop across sequence shards) matches GPipe on the same mesh."""
        mesh = build_mesh(dp=2, pp=2, sp=2)
        base = dict(**TINY, n_stages=2, n_microbatches=2)
        gpipe = _run_steps(TransformerConfig(**base), mesh, steps=3)
        f1b = _run_steps(
            TransformerConfig(**base, pp_schedule="1f1b"), mesh, steps=3
        )
        np.testing.assert_allclose(f1b, gpipe, rtol=1e-4)

    def test_1f1b_moe_aux_matches_gpipe(self):
        """MoE aux-loss collection under the 1F1B schedule."""
        base = dict(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0},
            n_stages=2, n_microbatches=2,
        )
        mesh = build_mesh(pp=2)

        def first_loss(cfg):
            params = init_params(jax.random.PRNGKey(0), cfg)
            optimizer = optax.adamw(1e-2)
            state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
            step_fn = make_train_step(cfg, mesh, optimizer)
            tokens = jax.device_put(
                _data(8, 16, cfg.vocab_size, seed=5),
                jax.sharding.NamedSharding(mesh, data_pspec()),
            )
            _, metrics = step_fn(state, tokens)
            return float(metrics["loss"]), float(metrics["ce"])

        loss_g, ce_g = first_loss(TransformerConfig(**base))
        loss_f, ce_f = first_loss(
            TransformerConfig(**base, pp_schedule="1f1b")
        )
        np.testing.assert_allclose(ce_f, ce_g, rtol=1e-4)
        np.testing.assert_allclose(loss_f, loss_g, rtol=1e-3)

    def test_1f1b_lower_peak_memory_than_gpipe(self):
        """The schedule's reason to exist: bounded in-flight activations
        and a per-microbatch loss head must beat GPipe's compiled peak
        temp memory at M >> S."""
        from oim_tpu.models.train import _build_train_step

        base = dict(
            **{**TINY, "vocab_size": 512, "d_model": 64, "d_ff": 128},
            n_stages=2, n_microbatches=8,
        )
        mesh = build_mesh(pp=2)
        tokens = jax.device_put(
            _data(16, 32, 512),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        optimizer = optax.adamw(1e-2)

        def peak(cfg):
            params = init_params(jax.random.PRNGKey(0), cfg)
            state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
            step = jax.jit(_build_train_step(cfg, mesh, optimizer))
            compiled = step.lower(state, tokens).compile()
            return compiled.memory_analysis().temp_size_in_bytes

        peak_gpipe = peak(TransformerConfig(**base))
        peak_1f1b = peak(TransformerConfig(**base, pp_schedule="1f1b"))
        assert peak_1f1b < peak_gpipe, (
            f"1f1b {peak_1f1b} !< gpipe {peak_gpipe}"
        )

    def test_moe_ep(self):
        cfg = TransformerConfig(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0}
        )
        mesh = build_mesh(dp=2, ep=4)
        losses = _run_steps(cfg, mesh)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9

    def test_all_axes_at_once(self):
        """dp·pp·sp·tp·ep = 2·2·2·1·1 with tp/ep exercised at size 1; the
        8-device full mix (all >1) needs 32 devices — shape-checked in
        dryrun_multichip instead."""
        cfg = TransformerConfig(**TINY, n_stages=2, n_microbatches=2)
        mesh = build_mesh(dp=2, pp=2, sp=2)
        losses = _run_steps(cfg, mesh)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9


class TestParallelConsistency:
    def test_same_loss_across_meshes(self):
        """The first-step loss must not depend on how the mesh is sliced."""
        cfg = TransformerConfig(**TINY)
        results = []
        for kwargs in [dict(dp=1), dict(dp=2, sp=2), dict(dp=4, tp=2)]:
            mesh = build_mesh(**kwargs)
            losses = _run_steps(cfg, mesh, batch=4, seq=8, steps=1, seed=7)
            results.append(losses[0])
        np.testing.assert_allclose(results[0], results[1], rtol=1e-4)
        np.testing.assert_allclose(results[0], results[2], rtol=1e-4)

    def test_moe_params_stay_replicated_across_dp(self):
        """The MoE aux loss is per-device; without pmean over dp the
        gradients desynchronize replicated params (regression)."""
        cfg = TransformerConfig(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0}
        )
        mesh = build_mesh(dp=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        step_fn = make_train_step(cfg, mesh, optimizer)
        tokens = jax.device_put(
            _data(4, 16, cfg.vocab_size, seed=3),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        for _ in range(3):
            state, _ = step_fn(state, tokens)
        for name in ("router", "wq", "wte", "wlm"):
            shards = [
                np.asarray(s.data) for s in state.params[name].addressable_shards
            ]
            for shard in shards[1:]:
                np.testing.assert_array_equal(shards[0], shard, err_msg=name)

    def test_params_stay_replicated_under_pp(self):
        """Replicated params (wte/wlm/final_norm) must receive identical
        gradients on every pipeline stage (regression)."""
        cfg = TransformerConfig(**TINY, n_stages=2, n_microbatches=2)
        mesh = build_mesh(dp=2, pp=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        step_fn = make_train_step(cfg, mesh, optimizer)
        tokens = jax.device_put(
            _data(4, 16, cfg.vocab_size, seed=4),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        first = None
        for _ in range(4):
            state, metrics = step_fn(state, tokens)
            first = first if first is not None else float(metrics["ce"])
        assert float(metrics["ce"]) < first  # wte/wlm actually learn
        for name in ("wte", "wlm", "final_norm"):
            shards = [
                np.asarray(s.data) for s in state.params[name].addressable_shards
            ]
            for shard in shards[1:]:
                np.testing.assert_array_equal(shards[0], shard, err_msg=name)

    def test_stage_mesh_mismatch_rejected(self):
        """n_stages > mesh pp would silently drop layers (regression)."""
        cfg = TransformerConfig(**TINY, n_stages=2)
        mesh = build_mesh(dp=2)
        with pytest.raises(ValueError, match="n_stages"):
            make_train_step(cfg, mesh)


class TestGQA:
    def test_gqa_trains(self):
        """GQA config end to end: flash path (single device) AND the
        broadcast path (sp ring) both learn."""
        cfg = TransformerConfig(**{**TINY, "n_heads": 4, "n_kv_heads": 2})
        losses = _run_steps(cfg, build_mesh(devices=jax.devices()[:1]), batch=4)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9
        losses_sp = _run_steps(cfg, build_mesh(dp=2, sp=2), batch=4)
        assert losses_sp[-1] < losses_sp[0] * 0.9

    def test_gqa_param_shapes(self):
        cfg = TransformerConfig(**{**TINY, "n_heads": 4, "n_kv_heads": 2})
        params = init_params(jax.random.PRNGKey(0), cfg)
        hd = cfg.head_dim
        assert params["wq"].shape[-1] == 4 * hd
        assert params["wk"].shape[-1] == 2 * hd
        assert params["wv"].shape[-1] == 2 * hd

    def test_gqa_bad_group_rejected(self):
        with pytest.raises(ValueError):
            TransformerConfig(**{**TINY, "n_heads": 4, "n_kv_heads": 3})


class TestTopKRouting:
    """GShard-style top-k expert routing (moe_top_k >= 2)."""

    def _layer_params(self, cfg, key):
        d, f, e = cfg.d_model, cfg.d_ff, cfg.n_experts
        keys = jax.random.split(key, 5)
        return {
            "mlp_norm": jnp.ones((d,), jnp.float32),
            "router": jax.random.normal(keys[0], (d, e)) * 0.5,
            "w_gate": jax.random.normal(keys[1], (e, d, f)) * 0.1,
            "w_in": jax.random.normal(keys[2], (e, d, f)) * 0.1,
            "w_out": jax.random.normal(keys[3], (e, f, d)) * 0.1,
        }

    def test_top_k_validation(self):
        with pytest.raises(ValueError, match="moe_top_k"):
            TransformerConfig(**TINY, n_experts=2, moe_top_k=3)
        with pytest.raises(ValueError, match="moe_top_k"):
            TransformerConfig(**TINY, moe_top_k=0)

    def test_top2_drop_free_matches_exact_routing(self):
        """With capacity high enough that nothing drops, the capacity
        dispatch must agree with the drop-free per-token formulation —
        the same equivalence the decode path relies on."""
        from oim_tpu.models.decode import _moe_exact
        from oim_tpu.models.transformer import _switch_moe

        cfg = TransformerConfig(
            **TINY, n_experts=4, moe_top_k=2, expert_capacity_factor=8.0,
        )
        lp = self._layer_params(cfg, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model))
        switch_out, aux = _switch_moe(x, lp, cfg)
        exact_out = _moe_exact(x, lp, cfg)
        np.testing.assert_allclose(
            np.asarray(switch_out), np.asarray(exact_out), atol=1e-5
        )
        assert float(aux) > 0

    def test_top2_gates_normalized_top1_raw(self):
        from oim_tpu.models.transformer import _router_gates

        probs = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(0), (6, 4)), axis=-1
        )
        _, _, g1 = _router_gates(probs, 1)
        np.testing.assert_allclose(
            np.asarray(g1[:, 0]), np.asarray(probs.max(axis=-1)), rtol=1e-6
        )
        _, _, g2 = _router_gates(probs, 2)
        np.testing.assert_allclose(
            np.asarray(g2.sum(axis=-1)), np.ones(6), rtol=1e-6
        )

    def test_top2_capacity_priority_drops_second_choices_first(self):
        """Choice-rank priority, hand-computed: with capacity 2 and
        4 tokens routing [first, second] = [0,1],[0,1],[1,0],[0,1]:
        expert 0's slots go to tokens 0,1 (token 3's FIRST choice drops —
        queue full); expert 1's slots go to token 2 (rank 0) then token 0
        (rank 1); tokens 1,3 lose their second choice.  Inverting rank
        priority would hand expert-1 slots to tokens 0,1 instead."""
        from oim_tpu.models.transformer import _capacity_dispatch

        top_idx = jnp.asarray([[0, 1], [0, 1], [1, 0], [0, 1]])
        gates = jnp.full((4, 2), 0.5)
        dispatch, combine = _capacity_dispatch(
            top_idx, gates, e=2, capacity=2
        )
        got = np.asarray(dispatch)
        # [token, expert, slot]
        assert got[0, 0, 0] == 1 and got[1, 0, 1] == 1  # rank-0 keeps
        assert got[2, 1, 0] == 1                        # rank-0 keeps
        assert got[0, 1, 1] == 1                        # rank-1 fills slot
        assert got[3].sum() == 0                        # fully dropped
        assert got[1, 1].sum() == 0                     # 2nd choice dropped
        assert got.sum() == 4                           # exactly 4 kept
        # token 2's rank-1 pick (expert 0) must NOT displace rank-0 work:
        assert got[2, 0].sum() == 0
        np.testing.assert_allclose(np.asarray(combine).sum(), 4 * 0.5)

    def test_top2_trains(self):
        cfg = TransformerConfig(**TINY, n_experts=4, moe_top_k=2)
        mesh = build_mesh(devices=jax.devices()[:1])
        losses = _run_steps(cfg, mesh, steps=6)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    def test_top2_generate(self):
        from oim_tpu.models.decode import generate

        cfg = TransformerConfig(
            **TINY, n_experts=4, moe_top_k=2, expert_capacity_factor=8.0,
            use_pallas=False,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.arange(2 * 6).reshape(2, 6) % cfg.vocab_size
        out = generate(params, prompt, cfg, max_new_tokens=5)
        assert out.shape == (2, 11)
        assert np.asarray(out).max() < cfg.vocab_size


class TestEvalStep:
    def test_eval_ce_matches_train_metric_pre_update(self):
        """The eval step on the SAME params and batch must report exactly
        the ce the train step computed before applying its update — they
        share _local_loss."""
        from oim_tpu.models import make_eval_step

        cfg = TransformerConfig(**TINY)
        mesh = build_mesh(dp=2, sp=2, devices=jax.devices()[:4])
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        eval_step = make_eval_step(cfg, mesh)
        eval_ce = float(eval_step(state.params, tokens))
        step_fn = make_train_step(cfg, mesh, optimizer)
        _, metrics = step_fn(state, tokens)
        assert eval_ce == pytest.approx(float(metrics["ce"]), rel=1e-6)

    def test_eval_under_pp(self):
        from oim_tpu.models import make_eval_step

        cfg = TransformerConfig(
            **{**TINY, "n_layers": 4}, n_stages=2, n_microbatches=2,
        )
        mesh = build_mesh(dp=2, pp=2, devices=jax.devices()[:4])
        params = init_params(jax.random.PRNGKey(0), cfg)
        state = shard_state(
            TrainState.create(params, optax.sgd(1e-2)), cfg, mesh
        )
        eval_step = make_eval_step(cfg, mesh)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        ce = float(eval_step(state.params, tokens))
        assert np.isfinite(ce) and ce > 0


class TestGradAccum:
    def test_accum_matches_full_batch_trajectory(self):
        """grad_accum=2 must train identically to the full-batch step
        (equal splits average to the same gradient)."""
        mesh = build_mesh(dp=2)
        full = _run_steps(TransformerConfig(**TINY), mesh, batch=8, steps=4)
        accum = _run_steps(
            TransformerConfig(**TINY, grad_accum=2), mesh, batch=8, steps=4
        )
        np.testing.assert_allclose(accum, full, rtol=2e-4)

    def test_accum_lowers_peak_memory(self):
        from oim_tpu.models.train import _build_train_step

        cfg = TransformerConfig(**TINY)
        mesh = build_mesh(devices=jax.devices()[:1])
        tokens = jax.device_put(
            _data(16, 64, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)

        def peak(accum):
            from dataclasses import replace

            c = replace(cfg, grad_accum=accum)
            state = shard_state(TrainState.create(params, optimizer), c, mesh)
            step = jax.jit(_build_train_step(c, mesh, optimizer))
            return step.lower(state, tokens).compile().memory_analysis(
            ).temp_size_in_bytes

        assert peak(4) < peak(1), (peak(4), peak(1))

    def test_accum_indivisible_batch_rejected(self):
        cfg = TransformerConfig(**TINY, grad_accum=3)
        mesh = build_mesh(devices=jax.devices()[:1])
        params = init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        step = make_train_step(cfg, mesh, optimizer)
        tokens = jax.device_put(
            _data(4, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        with pytest.raises(ValueError, match="grad_accum"):
            step(state, tokens)

    def test_accum_with_pp_1f1b(self):
        """Orthogonal to pipeline microbatching: both at once still train."""
        cfg = TransformerConfig(
            **{**TINY, "n_layers": 4}, n_stages=2, n_microbatches=2,
            pp_schedule="1f1b", grad_accum=2,
        )
        mesh = build_mesh(pp=2, dp=2)
        losses = _run_steps(cfg, mesh, batch=8, steps=4)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestLoRA:
    def _setup(self, cfg, rank=4, alpha=8.0, lr=1e-2):
        from oim_tpu.models.lora import init_lora, make_lora_train_step

        base = init_params(jax.random.PRNGKey(0), cfg)
        adapters = init_lora(jax.random.PRNGKey(1), cfg, rank)
        optimizer = optax.adamw(lr)
        state = TrainState.create(adapters, optimizer)
        mesh = build_mesh(devices=jax.devices()[:1])
        step = make_lora_train_step(cfg, mesh, optimizer, alpha, rank)
        tokens = jax.device_put(
            _data(4, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        return base, state, step, tokens

    def test_step0_equals_base_model(self):
        """B starts at zero: the merged model IS the base model, so the
        first LoRA loss equals the full train step's first loss."""
        from oim_tpu.models.lora import merge_lora

        cfg = TransformerConfig(**TINY)
        base, state, step, tokens = self._setup(cfg)
        merged0 = merge_lora(base, state.params, alpha=8.0, rank=4)
        for name in base:
            np.testing.assert_array_equal(
                np.asarray(merged0[name]), np.asarray(base[name])
            )
        _, metrics = step(state, base, tokens)
        mesh = build_mesh(devices=jax.devices()[:1])
        full_state = shard_state(
            TrainState.create(base, optax.adamw(1e-2)), cfg, mesh
        )
        _, full_metrics = make_train_step(cfg, mesh, optax.adamw(1e-2))(
            full_state, tokens
        )
        np.testing.assert_allclose(
            float(metrics["ce"]), float(full_metrics["ce"]), rtol=1e-5
        )

    def test_adapters_learn_base_frozen(self):
        from oim_tpu.models.lora import LORA_TARGETS

        cfg = TransformerConfig(**TINY)
        base, state, step, tokens = self._setup(cfg)
        base_before = jax.tree.map(lambda x: np.asarray(x).copy(), base)
        losses = []
        for _ in range(8):
            state, metrics = step(state, base, tokens)
            losses.append(float(metrics["ce"]))
        assert losses[-1] < losses[0] * 0.98, losses
        for name, value in base.items():
            np.testing.assert_array_equal(
                np.asarray(value), base_before[name],
                err_msg=f"frozen base weight {name} changed",
            )
        # And the adapters did move (B leaves zero).
        moved = any(
            float(np.abs(np.asarray(state.params[f"{n}_b"])).max()) > 0
            for n in LORA_TARGETS
        )
        assert moved

    def test_adapter_state_is_tiny(self):
        from oim_tpu.models.lora import init_lora

        cfg = TransformerConfig(**TINY)
        base = init_params(jax.random.PRNGKey(0), cfg)
        adapters = init_lora(jax.random.PRNGKey(1), cfg, rank=4)
        base_bytes = sum(x.nbytes for x in jax.tree.leaves(base))
        lora_bytes = sum(x.nbytes for x in jax.tree.leaves(adapters))
        assert lora_bytes < base_bytes * 0.2, (lora_bytes, base_bytes)

    def test_merged_decodes(self):
        from oim_tpu.models.decode import generate
        from oim_tpu.models.lora import merge_lora

        cfg = TransformerConfig(**TINY, use_pallas=False)
        base, state, step, tokens = self._setup(cfg)
        state, _ = step(state, base, tokens)
        merged = merge_lora(base, state.params, alpha=8.0, rank=4)
        prompt = jnp.arange(2 * 5).reshape(2, 5) % cfg.vocab_size
        out = generate(merged, prompt, cfg, max_new_tokens=4)
        assert out.shape == (2, 9)

    def test_lora_under_pp_1f1b(self):
        """The merge-then-chain-rule seam composes with the pipeline."""
        from oim_tpu.models.lora import init_lora, make_lora_train_step

        cfg = TransformerConfig(
            **{**TINY, "n_layers": 4}, n_stages=2, n_microbatches=2,
            pp_schedule="1f1b",
        )
        mesh = build_mesh(pp=2, dp=2)
        base = init_params(jax.random.PRNGKey(0), cfg)
        from oim_tpu.models.train import shard_state as ss

        base_sharded = ss(
            TrainState.create(base, optax.sgd(1e-2)), cfg, mesh
        ).params
        adapters = init_lora(jax.random.PRNGKey(1), cfg, 4)
        optimizer = optax.adamw(1e-2)
        state = TrainState.create(adapters, optimizer)
        step = make_lora_train_step(cfg, mesh, optimizer, 8.0, 4)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        losses = []
        for _ in range(4):
            state, metrics = step(state, base_sharded, tokens)
            losses.append(float(metrics["ce"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestRouterZLoss:
    def _objective(self, cfg, zero_router=False):
        """One train step's objective; fresh params per run (the step
        donates its buffers).  zero_router zeroes every router weight —
        logits become exactly 0, so each layer's z-loss term is exactly
        log(n_experts)² (hand-computable, data-independent)."""
        mesh = build_mesh()
        optimizer = optax.adamw(1e-2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        if zero_router:
            params = jax.tree_util.tree_map_with_path(
                lambda path, leaf: (
                    jnp.zeros_like(leaf)
                    if any(
                        getattr(k, "key", None) == "router" for k in path
                    )
                    else leaf
                ),
                params,
            )
        tokens = _data(4, 16, cfg.vocab_size, seed=7)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        _, metrics = make_train_step(cfg, mesh, optimizer)(
            state,
            jax.device_put(
                tokens, jax.sharding.NamedSharding(mesh, data_pspec())
            ),
        )
        return float(metrics["loss"])

    def _cfg(self, coef):
        return TransformerConfig(
            **{**TINY, "n_experts": 4, "expert_capacity_factor": 2.0},
            router_z_loss=coef,
        )

    def test_z_loss_exact_scale_by_hand(self):
        """With zeroed routers every logit is 0, logsumexp = log(E), and
        the objective must exceed the coef=0 run by EXACTLY
        coef · n_layers · log(E)² — an absolute hand computation that a
        constant-factor scale bug (e.g. a wrong AUX_LOSS_WEIGHT
        pre-division) cannot pass.  The coef=0 side doubles as the
        off-is-off guard: its delta contribution must be zero."""
        import math

        coef = 1e-2
        base = self._objective(self._cfg(0.0), zero_router=True)
        withz = self._objective(self._cfg(coef), zero_router=True)
        expected = coef * TINY["n_layers"] * math.log(4) ** 2
        # Relative tolerance: the two f32 objectives round independently
        # through the mesh psum, so a couple of ulps (~1e-6 at this
        # magnitude) of absolute error is legitimate; a constant-factor
        # scale bug is orders of magnitude, not 1e-4 relative.
        assert abs((withz - base) - expected) < 1e-4 * expected, (
            withz - base, expected
        )

    def test_z_loss_linear_on_real_routers(self):
        """On real (random) router weights the term must be exactly
        coefficient-linear."""
        coef = 1e-2
        base = self._objective(self._cfg(0.0))
        d1 = self._objective(self._cfg(coef)) - base
        d2 = self._objective(self._cfg(2 * coef)) - base
        assert d1 > 0
        assert abs(d2 - 2 * d1) < 1e-5 * max(1.0, abs(d2)), (d1, d2)


class TestAttnBias:
    def test_bias_trains_and_learns(self):
        """attn_bias=True (Qwen2-family geometry) through the FULL train
        step on a dp2·sp2 mesh: loss falls, and the bias parameters
        actually move (a bias silently dropped from the graph would
        leave them at zero init forever)."""
        cfg = TransformerConfig(**TINY, attn_bias=True)
        mesh = build_mesh(dp=2, sp=2, devices=jax.devices()[:4])
        params = init_params(jax.random.PRNGKey(0), cfg)
        assert params["bq"].shape == (1, 2, 32)
        optimizer = optax.adamw(1e-2)
        state = shard_state(TrainState.create(params, optimizer), cfg, mesh)
        step_fn = make_train_step(cfg, mesh, optimizer)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        losses = []
        for _ in range(8):
            state, metrics = step_fn(state, tokens)
            losses.append(float(metrics["ce"]))
        assert losses[-1] < losses[0]
        moved = float(jnp.max(jnp.abs(state.params["bq"])))
        assert moved > 0.0, "bq never received a gradient"

    def test_bias_changes_forward(self):
        """A nonzero bias must change logits (guards against a key that
        exists but is ignored by the projection sites)."""
        from oim_tpu.models.transformer import forward_local, manual_pspecs
        from jax.sharding import PartitionSpec as P

        cfg = TransformerConfig(**TINY, attn_bias=True)
        mesh = build_mesh(devices=jax.devices()[:1])
        params = init_params(jax.random.PRNGKey(1), cfg)
        tokens = _data(2, 8, cfg.vocab_size)

        def fwd(p):
            logits, _ = jax.jit(
                jax.shard_map(
                    lambda pp, t: forward_local(pp, t, cfg),
                    mesh=mesh,
                    in_specs=(manual_pspecs(cfg), P("dp", "sp")),
                    out_specs=(P("dp", "sp"), P()),
                    check_vma=False,
                )
            )(p, tokens)
            return np.asarray(logits)

        zero = fwd(params)
        biased = fwd({**params, "bq": params["bq"] + 0.5})
        assert np.abs(biased - zero).max() > 1e-3


class TestGemmaNumerics:
    def test_train_and_inference_paths_agree(self):
        """The Gemma flags (GeGLU, (1+w) norm, sqrt(d) embed scale) must
        be live in BOTH forwards: the train-path eval CE equals the CE
        computed from the inference path's (prefill) logits.  Round-5
        review caught the train pipeline path silently dropping
        embed_scale — this is the invariant that makes that loud."""
        from oim_tpu.models import make_eval_step
        from oim_tpu.models.decode import prefill

        cfg = TransformerConfig(
            **TINY, mlp_act="gelu_tanh", norm_offset=True,
            embed_scale=True, use_pallas=False,
        )
        mesh = build_mesh(devices=jax.devices()[:1])
        params = init_params(jax.random.PRNGKey(2), cfg)
        tokens = np.asarray(_data(2, 16, cfg.vocab_size, seed=3))
        ce_train = float(make_eval_step(cfg, mesh)(params, tokens))
        logits, _ = prefill(
            params, jnp.asarray(tokens, jnp.int32), cfg, max_len=16
        )
        lp = jax.nn.log_softmax(
            np.asarray(logits, np.float32), axis=-1
        )
        labels = tokens[:, 1:]
        picked = np.take_along_axis(
            np.asarray(lp)[:, :-1], labels[..., None], axis=-1
        )[..., 0]
        ce_infer = float(-picked.mean())
        assert abs(ce_train - ce_infer) < 1e-4, (ce_train, ce_infer)

    def test_pipeline_path_carries_embed_scale(self):
        """The pp>1 train path has its own embedding closure
        (models/train.py); with embed_scale on, its loss must match the
        pp=1 path's on the same weights — a dropped scale in either
        diverges immediately."""
        from oim_tpu.models import make_eval_step

        tokens = np.asarray(_data(4, 16, TINY["vocab_size"], seed=4))
        ces = []
        for stages in (1, 2):
            cfg = TransformerConfig(
                **TINY, mlp_act="gelu_tanh", norm_offset=True,
                embed_scale=True, use_pallas=False,
                n_stages=stages, n_microbatches=stages,
            )
            mesh = build_mesh(
                pp=stages, devices=jax.devices()[: max(1, stages)]
            )
            params = init_params(jax.random.PRNGKey(2), cfg)
            ces.append(float(make_eval_step(cfg, mesh)(params, tokens)))
        assert abs(ces[0] - ces[1]) < 1e-4, ces


class TestZero1:
    def test_trajectory_identical_and_moments_sharded(self):
        """ZeRO-1 (optimizer moments sharded over dp) is a pure
        PLACEMENT change: the loss trajectory matches the replicated
        optimizer bitwise-close, while each adamw moment shard holds
        1/dp of the bytes — the optimizer-memory lever for large dp
        (the update runs at GSPMD level, so XLA computes each shard's
        slice and all-gathers the params: the ZeRO-1 exchange)."""
        cfg = TransformerConfig(**TINY)
        mesh = build_mesh(dp=4, sp=2, devices=jax.devices())
        opt = optax.adamw(1e-2)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        losses = {}
        for zero1 in (False, True):
            state = shard_state(
                TrainState.create(
                    init_params(jax.random.PRNGKey(0), cfg), opt
                ),
                cfg, mesh, zero1=zero1,
            )
            if zero1:
                mu = state.opt_state[0].mu
                assert any(
                    "dp" in str(v.sharding.spec) for v in mu.values()
                ), {k: str(v.sharding.spec) for k, v in mu.items()}
                wq = mu["wq"]
                assert (
                    wq.addressable_shards[0].data.nbytes * 4 == wq.nbytes
                )
            step_fn = make_train_step(cfg, mesh, opt)
            ls = []
            for _ in range(6):
                state, m = step_fn(state, tokens)
                ls.append(float(m["loss"]))
            if zero1:
                # The placement must SURVIVE the jitted step (no
                # out_shardings are pinned — GSPMD propagation carries
                # it); a regression here would silently erase the
                # memory saving.
                mu_after = state.opt_state[0].mu["wq"]
                assert (
                    mu_after.addressable_shards[0].data.nbytes * 4
                    == mu_after.nbytes
                ), str(mu_after.sharding.spec)
            losses[zero1] = ls
        assert max(
            abs(a - b) for a, b in zip(losses[False], losses[True])
        ) < 1e-6, losses

    def test_zero1_checkpoint_resume(self, tmp_path):
        """A zero1 run checkpoints and resumes with the sharded
        placement; the resumed trajectory continues exactly."""
        from oim_tpu.checkpoint import Checkpointer

        cfg = TransformerConfig(**TINY)
        mesh = build_mesh(dp=4, sp=2, devices=jax.devices())
        opt = optax.adamw(1e-2)
        tokens = jax.device_put(
            _data(8, 16, cfg.vocab_size),
            jax.sharding.NamedSharding(mesh, data_pspec()),
        )
        init_fn = lambda: TrainState.create(  # noqa: E731
            init_params(jax.random.PRNGKey(0), cfg), opt
        )
        step_fn = make_train_step(cfg, mesh, opt)
        # Uninterrupted reference: 5 steps straight through.
        ref_state = shard_state(init_fn(), cfg, mesh, zero1=True)
        ref = []
        for _ in range(5):
            ref_state, m = step_fn(ref_state, tokens)
            ref.append(float(m["loss"]))
        with Checkpointer(
            str(tmp_path / "ck"), cfg, mesh, zero1=True
        ) as ck:
            state, _, resumed = ck.restore_or_init(init_fn)
            assert not resumed
            for i in range(4):
                state, m = step_fn(state, tokens)
                assert abs(float(m["loss"]) - ref[i]) < 1e-6
            ck.save(state, {"next_step": 4}, force=True)
        with Checkpointer(
            str(tmp_path / "ck"), cfg, mesh, zero1=True
        ) as ck2:
            state2, data, resumed = ck2.restore_or_init(init_fn)
            assert resumed and data["next_step"] == 4
            mu = state2.opt_state[0].mu
            assert any(
                "dp" in str(v.sharding.spec) for v in mu.values()
            )
            state2, m = step_fn(state2, tokens)
        # Resumed step 5 equals the uninterrupted run's step 5 — a
        # mis-sliced or zeroed moment restore diverges here.
        assert abs(float(m["loss"]) - ref[4]) < 1e-6, (
            float(m["loss"]), ref[4]
        )
