"""Fleet health & fault management (oim_tpu/health).

Covers all four layers: the device plane's deterministic fault injection,
the controller's HealthReporter lease-publishing, the registry-side
FleetMonitor/EvictionEngine classification (chip-failed, chip-degraded
drain-after-grace, controller-dead, operator drain), the CSI RemoteBackend
eviction refusal, and the oimctl operator surface — plus the two
acceptance scenarios end to end (chip failure and controller death).
"""

import json
import time

import grpc
import pytest

from oim_tpu.agent import Agent, AgentError, ChipStore, FakeAgentServer
from oim_tpu.cli import oimctl
from oim_tpu.common import metrics
from oim_tpu.controller import Controller
from oim_tpu.csi.backend import RemoteBackend, VolumeError
from oim_tpu.health import (
    EvictionEngine,
    EvictionPolicy,
    FleetMonitor,
    HealthReporter,
    states,
)
from oim_tpu.registry import MemRegistryDB, Registry
from tests.helpers import wait_for

pytestmark = pytest.mark.health


def evictions_total(reason: str) -> float:
    return metrics.registry().counter(
        "oim_evictions_total", "", ("reason",)
    ).value(reason)


# ---------------------------------------------------------------------------
# Device plane: fault injection + get_health


class TestDevicePlaneHealth:
    def test_inject_and_clear(self):
        store = ChipStore(mesh=(2, 2, 1))
        store.inject_fault(0, "failed")
        store.inject_fault(1, "degraded")
        store.inject_fault(1, "link_errors")
        store.inject_fault(1, "link_errors")
        health = {c["chip_id"]: c for c in store.get_health()}
        assert health[0]["health"] == "FAILED"
        assert health[1]["health"] == "DEGRADED"
        assert health[1]["ici_link_errors"] == 2
        assert health[2]["health"] == "OK"
        store.inject_fault(1, "clear")
        health = {c["chip_id"]: c for c in store.get_health()}
        assert health[1]["health"] == "OK"
        assert health[1]["ici_link_errors"] == 0

    def test_failed_wins_over_degraded(self):
        store = ChipStore(mesh=(2,))
        store.inject_fault(0, "failed")
        store.inject_fault(0, "degraded")
        assert store.get_health()[0]["health"] == "FAILED"

    def test_deferred_fault_is_deterministic(self):
        """after_n_calls=N: exactly the Nth subsequent get_health call
        observes the fault — no wall clock anywhere."""
        store = ChipStore(mesh=(2,))
        store.inject_fault(0, "failed", after_n_calls=3)
        assert store.get_health()[0]["health"] == "OK"  # call 1
        assert store.get_health()[0]["health"] == "OK"  # call 2
        assert store.get_health()[0]["health"] == "FAILED"  # call 3
        # clear also cancels still-pending scripted faults for the chip
        store.inject_fault(0, "clear")
        store.inject_fault(1, "degraded", after_n_calls=1)
        store.inject_fault(1, "clear")
        assert [c["health"] for c in store.get_health()] == ["OK", "OK"]

    def test_validation(self):
        store = ChipStore(mesh=(2,))
        with pytest.raises(Exception) as err:
            store.inject_fault(0, "meltdown")
        assert getattr(err.value, "code", None) == -32602
        with pytest.raises(Exception) as err:
            store.inject_fault(99, "failed")
        assert getattr(err.value, "code", None) == -19

    def test_health_over_the_wire(self, tmp_path):
        """The JSON-RPC surface: inject_fault + get_health round-trip
        through the NDJSON socket via the typed client."""
        store = ChipStore(mesh=(2, 1, 1), device_dir=str(tmp_path))
        server = FakeAgentServer(store, str(tmp_path / "a.sock")).start()
        try:
            with Agent(server.socket_path) as agent:
                reply = agent.inject_fault(1, "failed")
                assert reply["health"] == "FAILED"
                health = agent.get_health()
                assert [c["health"] for c in health] == ["OK", "FAILED"]
                with pytest.raises(AgentError) as err:
                    agent.inject_fault(5, "failed")
                assert err.value.code == -19
        finally:
            server.stop()

    def test_allocation_travels_in_health(self, tmp_path):
        store = ChipStore(mesh=(2, 1, 1), device_dir=str(tmp_path))
        store.create_allocation("vol-h", 2)
        assert all(c["allocation"] == "vol-h" for c in store.get_health())


# ---------------------------------------------------------------------------
# Controller layer: HealthReporter


class TestHealthReporter:
    @pytest.fixture
    def stack(self, tmp_path):
        store = ChipStore(mesh=(2, 1, 1), device_dir=str(tmp_path))
        agent_srv = FakeAgentServer(store, str(tmp_path / "a.sock")).start()
        registry = Registry()
        reg_srv = registry.start_server("tcp://127.0.0.1:0")
        yield store, agent_srv, registry, reg_srv
        reg_srv.stop()
        registry.close()
        agent_srv.stop()

    def test_report_once_publishes_leased_keys(self, stack):
        store, agent_srv, registry, reg_srv = stack
        store.create_allocation("vol-r", 1)
        store.inject_fault(1, "degraded")
        reporter = HealthReporter(
            "h0", agent_srv.socket_path, str(reg_srv.addr()), interval=0.5
        )
        try:
            assert reporter.report_once() == 2
            report = states.decode_report(
                registry.db.lookup(states.health_key("h0", 0))
            )
            assert report["state"] == "OK"
            assert report["allocation"] == "vol-r"
            report = states.decode_report(
                registry.db.lookup(states.health_key("h0", 1))
            )
            assert report["state"] == "DEGRADED"
            # Leased: with nobody refreshing, the subtree expires (ttl =
            # 3 intervals = max(1, int(1.5)) = 1s here).
            assert wait_for(
                lambda: registry.db.lookup(states.health_key("h0", 0)) == "",
                timeout=10,
            )
        finally:
            reporter.close()

    def test_loop_tolerates_agent_death(self, stack):
        """An agent crash mid-loop costs intervals, not the reporter: once
        the agent is back the next cycle publishes again."""
        store, agent_srv, registry, reg_srv = stack
        reporter = HealthReporter(
            "h0", agent_srv.socket_path, str(reg_srv.addr()), interval=0.05
        ).start()
        try:
            assert wait_for(
                lambda: registry.db.lookup(states.health_key("h0", 0)) != ""
            )
            agent_srv.stop()
            time.sleep(0.2)  # loop hits the dead socket, must survive
            # Same store, same socket path: "the daemon restarted".
            revived = FakeAgentServer(store, agent_srv.socket_path).start()
            try:
                registry.db.store(states.health_key("h0", 0), "")
                assert wait_for(
                    lambda: registry.db.lookup(states.health_key("h0", 0))
                    != ""
                )
            finally:
                revived.stop()
        finally:
            reporter.close()

    def test_start_and_close_idempotent(self, stack):
        _, agent_srv, _, reg_srv = stack
        reporter = HealthReporter(
            "h0", agent_srv.socket_path, str(reg_srv.addr()), interval=10
        )
        assert reporter.start() is reporter
        thread = reporter._thread
        assert reporter.start()._thread is thread  # no second thread
        reporter.close()
        reporter.close()

    def test_wedged_scrape_dial_never_blocks_close(
        self, tmp_path, monkeypatch
    ):
        """_get_agent dials outside the connection-cache lock (oimlint
        lock-discipline harvest, resilience.ConnCache): a wedged daemon
        must cost a scrape its timeout, never stall close().  close()
        latches, so the dial in flight is closed on arrival — no leak."""
        import threading

        from oim_tpu.health import reporter as reporter_mod

        entered = threading.Event()
        release = threading.Event()
        closed = []

        class WedgedAgent:
            def __init__(self, *args, **kwargs):
                entered.set()
                release.wait(timeout=10)

            def close(self):
                closed.append(self)

        monkeypatch.setattr(reporter_mod, "Agent", WedgedAgent)
        reporter = HealthReporter(
            "h-lk", str(tmp_path / "none.sock"), "tcp://127.0.0.1:1"
        )
        def dial():
            try:
                reporter._get_agent()
            except RuntimeError:
                pass  # the latched cache refusing the late dial

        dialer = threading.Thread(target=dial, daemon=True)
        dialer.start()
        try:
            assert entered.wait(timeout=5)
            t0 = time.monotonic()
            reporter.close()
            assert time.monotonic() - t0 < 2, "close() stalled behind dial"
            assert not closed
        finally:
            release.set()
            dialer.join(timeout=5)
        # Closed on arrival, not installed into the closed cache.
        assert len(closed) == 1


# ---------------------------------------------------------------------------
# Registry side: FleetMonitor + EvictionEngine (pure-DB, no gRPC)


def report(db, cid, chip, state, alloc="", ts=None, link_errors=0):
    db.store(
        states.health_key(cid, chip),
        states.encode_report(state, link_errors, alloc, ts or time.time()),
    )


class TestFleetMonitor:
    @pytest.fixture
    def db(self):
        db = MemRegistryDB()
        yield db
        db.close()

    def test_failed_chip_evicts_immediately(self, db):
        monitor = FleetMonitor(db).start()
        try:
            before = evictions_total("chip-failed")
            report(db, "h0", "0", states.OK, alloc="vol-1")
            assert db.lookup(states.eviction_key("vol-1")) == ""
            report(db, "h0", "0", states.FAILED, alloc="vol-1")
            record = json.loads(db.lookup(states.eviction_key("vol-1")))
            assert record["reason"] == "chip-failed"
            assert record["controller"] == "h0"
            assert evictions_total("chip-failed") == before + 1
            # Flapping re-reports do not inflate the counter.
            report(db, "h0", "0", states.FAILED, alloc="vol-1")
            assert evictions_total("chip-failed") == before + 1
        finally:
            monitor.close()

    def test_degraded_drains_after_grace_only(self, db):
        monitor = FleetMonitor(
            db, policy=EvictionPolicy(degraded_grace_s=0.15)
        ).start()
        try:
            report(db, "h0", "0", states.DEGRADED, alloc="vol-d")
            time.sleep(0.05)  # inside the grace: not evicted yet
            assert db.lookup(states.eviction_key("vol-d")) == ""
            assert wait_for(
                lambda: db.lookup(states.eviction_key("vol-d")) != ""
            )
            record = json.loads(db.lookup(states.eviction_key("vol-d")))
            assert record["reason"] == "chip-degraded"
        finally:
            monitor.close()

    def test_recovery_within_grace_cancels_drain(self, db):
        monitor = FleetMonitor(
            db, policy=EvictionPolicy(degraded_grace_s=0.15)
        ).start()
        try:
            report(db, "h0", "0", states.DEGRADED, alloc="vol-r")
            report(db, "h0", "0", states.OK, alloc="vol-r")  # recovered
            time.sleep(0.3)  # past the grace deadline
            assert db.lookup(states.eviction_key("vol-r")) == ""
        finally:
            monitor.close()

    def test_degraded_refresh_does_not_extend_grace(self, db):
        """Re-reports of a still-degraded chip must not push the drain
        deadline out forever — the timer arms on the TRANSITION."""
        monitor = FleetMonitor(
            db, policy=EvictionPolicy(degraded_grace_s=0.2)
        ).start()
        try:
            report(db, "h0", "0", states.DEGRADED, alloc="vol-g")
            deadline = time.monotonic() + 2.0
            while (
                db.lookup(states.eviction_key("vol-g")) == ""
                and time.monotonic() < deadline
            ):
                report(db, "h0", "0", states.DEGRADED, alloc="vol-g")
                time.sleep(0.02)
            assert db.lookup(states.eviction_key("vol-g")) != ""
        finally:
            monitor.close()

    def test_controller_death_evicts_from_cached_state(self, db):
        """Address deletion (lease expiry) evicts every allocation last
        seen on the controller — even though its health keys expired
        FIRST.  No RPC towards the controller exists to hang on."""
        monitor = FleetMonitor(db).start()
        try:
            before = evictions_total("controller-dead")
            db.store("h0/address", "tcp://10.0.0.9:1")
            report(db, "h0", "0", states.OK, alloc="vol-a")
            report(db, "h0", "1", states.OK, alloc="vol-a")
            report(db, "h0", "2", states.OK, alloc="vol-b")
            # Health subtree expires first (the crash ordering).
            for chip in ("0", "1", "2"):
                db.store(states.health_key("h0", chip), "")
            db.store("h0/address", "")  # lease expiry event
            assert json.loads(db.lookup(states.eviction_key("vol-a")))[
                "reason"
            ] == "controller-dead"
            assert db.lookup(states.eviction_key("vol-b")) != ""
            # ONE eviction per allocation, not per chip.
            assert evictions_total("controller-dead") == before + 2
        finally:
            monitor.close()

    def test_listener_api_eviction_and_controller_dead(self, db):
        """Regression (ISSUE 8 satellite): FleetMonitor classifies
        faults but offered no programmatic subscription — a consumer
        (the autoscaler's replacement trigger) had to run a SECOND
        registry watch.  add_listener delivers the classification
        directly: one eviction notification per FRESH mark (flapping
        dedupes through the EvictionEngine), controller-death as its
        own callback, and remove() unsubscribes."""
        monitor = FleetMonitor(db).start()
        evictions: list[tuple[str, str, str]] = []
        deaths: list[str] = []
        remove = monitor.add_listener(
            on_eviction=lambda vol, cid, reason: evictions.append(
                (vol, cid, reason)
            ),
            on_controller_dead=deaths.append,
        )
        try:
            report(db, "h0", "0", states.FAILED, alloc="vol-l")
            assert evictions == [("vol-l", "h0", "chip-failed")]
            # Flapping re-reports: the mark already exists, no repeat.
            report(db, "h0", "0", states.FAILED, alloc="vol-l")
            assert len(evictions) == 1
            db.store("h0/address", "tcp://10.0.0.9:1")
            db.store("h0/address", "")  # lease expiry
            assert deaths == ["h0"]
            # Unsubscribed: later classifications are not delivered.
            remove()
            report(db, "h0", "1", states.FAILED, alloc="vol-m")
            db.store("h1/address", "x")
            db.store("h1/address", "")
            assert len(evictions) == 1 and deaths == ["h0"]
        finally:
            monitor.close()

    def test_listener_exception_never_kills_classification(self, db):
        """A broken listener costs its own notification, never the
        watch dispatch or the other listeners."""
        monitor = FleetMonitor(db).start()
        seen: list[str] = []

        def broken(vol, cid, reason):
            raise RuntimeError("listener bug")

        monitor.add_listener(on_eviction=broken)
        monitor.add_listener(on_eviction=lambda vol, *_: seen.append(vol))
        try:
            report(db, "h0", "0", states.FAILED, alloc="vol-x")
            assert seen == ["vol-x"]
            # The eviction itself landed despite the broken listener.
            assert db.lookup(states.eviction_key("vol-x")) != ""
        finally:
            monitor.close()

    def test_serve_address_deletion_is_not_controller_death(self, db):
        monitor = FleetMonitor(db).start()
        try:
            report(db, "serve", "0", states.OK, alloc="vol-s")
            db.store("serve/web-1/address", "x")
            db.store("serve/web-1/address", "")  # 3 parts: serving plane
            assert db.lookup(states.eviction_key("vol-s")) == ""
        finally:
            monitor.close()

    def test_drain_evicts_and_cordons(self, db):
        monitor = FleetMonitor(db).start()
        try:
            report(db, "h0", "0", states.OK, alloc="vol-1")
            db.store(states.drain_key("h0"), "maintenance")
            assert json.loads(db.lookup(states.eviction_key("vol-1")))[
                "reason"
            ] == "drained"
            # Cordon is sticky: an allocation surfacing later is evicted
            # on sight, until uncordon.
            report(db, "h0", "1", states.OK, alloc="vol-2")
            assert db.lookup(states.eviction_key("vol-2")) != ""
            db.store(states.drain_key("h0"), "")  # uncordon
            report(db, "h0", "2", states.OK, alloc="vol-3")
            assert db.lookup(states.eviction_key("vol-3")) == ""
        finally:
            monitor.close()

    def test_snapshot_rebuilds_cordons_before_health(self, db):
        """A monitor started over existing state must honor pre-existing
        drain marks (restart resilience)."""
        db.store(states.drain_key("h0"), "pre-existing")
        report(db, "h0", "0", states.OK, alloc="vol-old")
        monitor = FleetMonitor(db).start()
        try:
            assert wait_for(
                lambda: db.lookup(states.eviction_key("vol-old")) != ""
            )
        finally:
            monitor.close()

    def test_gauge_tracks_states(self, db):
        monitor = FleetMonitor(db).start()
        gauge = metrics.registry().gauge(
            "oim_health_chips", "", ("controller", "state")
        )
        try:
            report(db, "h0", "0", states.OK)
            report(db, "h0", "1", states.DEGRADED)
            assert gauge.value("h0", "OK") == 1
            assert gauge.value("h0", "DEGRADED") == 1
            assert gauge.value("h0", "FAILED") == 0
            report(db, "h0", "1", states.FAILED)
            assert gauge.value("h0", "DEGRADED") == 0
            assert gauge.value("h0", "FAILED") == 1
        finally:
            monitor.close()

    def test_malformed_values_never_kill_the_watcher(self, db):
        monitor = FleetMonitor(db).start()
        try:
            db.store(states.health_key("h0", "0"), "not json")
            db.store(states.health_key("h0", "1"), '{"state": "BOGUS"}')
            report(db, "h0", "2", states.FAILED, alloc="vol-m")
            assert db.lookup(states.eviction_key("vol-m")) != ""
        finally:
            monitor.close()

    def test_spoofed_foreign_allocation_not_evicted(self, db):
        """Defense in depth behind the health-subtree authz: a report
        from controller A naming a volume another controller's telemetry
        claims must NOT evict it (one spoofed key would otherwise DoS
        any volume fleet-wide)."""
        monitor = FleetMonitor(db).start()
        try:
            report(db, "hB", "0", states.OK, alloc="victim")
            report(db, "hA", "0", states.FAILED, alloc="victim")  # spoof
            assert db.lookup(states.eviction_key("victim")) == ""
            # A's own allocations still evict normally.
            report(db, "hA", "1", states.FAILED, alloc="a-own")
            assert db.lookup(states.eviction_key("a-own")) != ""
            # ...and A dying must not take the foreign volume down either
            # (the spoofed claim is still cached in A's alloc map).
            db.store("hA/address", "x")
            db.store("hA/address", "")
            assert db.lookup(states.eviction_key("victim")) == ""
        finally:
            monitor.close()

    def test_volume_landing_on_degraded_chip_gets_own_grace(self, db):
        """A chip that degraded while unallocated (grace fired, nothing
        to drain) must still drain a volume placed on it LATER — the
        allocation change re-arms the grace timer."""
        monitor = FleetMonitor(
            db, policy=EvictionPolicy(degraded_grace_s=0.1)
        ).start()
        try:
            report(db, "h0", "0", states.DEGRADED)  # unallocated
            time.sleep(0.3)  # grace fires; nothing to evict
            report(db, "h0", "0", states.DEGRADED, alloc="late-vol")
            assert wait_for(
                lambda: db.lookup(states.eviction_key("late-vol")) != ""
            )
        finally:
            monitor.close()

    def test_pre_clear_telemetry_cannot_re_evict(self, db):
        """After an operator clears an eviction (remap), an in-flight
        report PUBLISHED before the clear must not re-evict the volume;
        telemetry published after the clear still can."""
        monitor = FleetMonitor(db).start()
        try:
            stale_ts = time.time()
            report(db, "h0", "0", states.FAILED, alloc="vol-rc", ts=stale_ts)
            assert db.lookup(states.eviction_key("vol-rc")) != ""
            db.store(states.eviction_key("vol-rc"), "")  # remap cleared it
            # The old controller's in-flight report (pre-clear ts) lands.
            report(db, "h0", "0", states.FAILED, alloc="vol-rc", ts=stale_ts)
            assert db.lookup(states.eviction_key("vol-rc")) == ""
            # Fresh telemetry after the clear is real news again.
            time.sleep(0.01)
            report(
                db, "h0", "0", states.FAILED, alloc="vol-rc",
                ts=time.time() + 1,
            )
            assert db.lookup(states.eviction_key("vol-rc")) != ""
        finally:
            monitor.close()

    def test_remap_backoff_recorded(self, db):
        engine = EvictionEngine(db, EvictionPolicy(remap_backoff_s=60.0))
        engine.evict("vol-b", "h0", "chip-failed")
        record = json.loads(db.lookup(states.eviction_key("vol-b")))
        assert record["remap_after"] >= record["ts"] + 59.0
        engine.clear("vol-b")
        assert db.lookup(states.eviction_key("vol-b")) == ""


# ---------------------------------------------------------------------------
# End-to-end acceptance scenarios


@pytest.fixture
def fleet(tmp_path):
    """Full in-process stack with fault management attached: fake agent →
    controller (health reporting) → registry + FleetMonitor → CSI remote
    backend, all insecure (the mTLS path is covered by the authz test)."""
    store = ChipStore(mesh=(2, 2, 1), device_dir=str(tmp_path / "dev"))
    agent_srv = FakeAgentServer(store, str(tmp_path / "agent.sock")).start()
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    monitor = FleetMonitor(
        registry.db, policy=EvictionPolicy(degraded_grace_s=0.2)
    ).start()
    controller = Controller(
        "h0",
        agent_srv.socket_path,
        registry_address=str(reg_srv.addr()),
        registry_delay=0.2,
        health_interval=0.05,
    )
    ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
    controller.start(str(ctrl_srv.addr()))
    backend = RemoteBackend(str(reg_srv.addr()), "h0")
    assert wait_for(lambda: registry.db.lookup("h0/address") != "")
    yield store, agent_srv, registry, reg_srv, monitor, controller, backend
    backend.close()
    controller.close()
    ctrl_srv.stop()
    monitor.close()
    reg_srv.stop()
    registry.close()
    agent_srv.stop()


def test_e2e_chip_failure_to_refused_staging(fleet, capsys):
    """ISSUE acceptance: inject chip FAILED → FleetMonitor detects within
    one reporting interval → EvictionEngine marks the allocation →
    RemoteBackend stage returns FAILED_PRECONDITION → oimctl health shows
    FAILED and evictions_total incremented."""
    store, agent_srv, registry, reg_srv, monitor, controller, backend = fleet
    before = evictions_total("chip-failed")

    staged = backend.create_device("vol-e2e", {"chipCount": "2"}, None)
    assert len(staged.chips) == 2
    chip_id = staged.chips[0]["chip_id"]

    with Agent(agent_srv.socket_path) as agent:
        agent.inject_fault(chip_id, "failed")

    # Detection is event-driven off the next report (interval 0.05s).
    assert wait_for(
        lambda: registry.db.lookup(states.eviction_key("vol-e2e")) != ""
    )
    record = json.loads(registry.db.lookup(states.eviction_key("vol-e2e")))
    assert record["reason"] == "chip-failed"
    assert evictions_total("chip-failed") == before + 1

    # The CSI plane refuses to stage the evicted volume.
    with pytest.raises(VolumeError) as err:
        backend.create_device("vol-e2e", {"chipCount": "2"}, None)
    assert err.value.code == grpc.StatusCode.FAILED_PRECONDITION
    assert "evicted" in err.value.message

    # Operator surface: the chip shows FAILED, the eviction is listed.
    assert oimctl.main(["--registry", str(reg_srv.addr()), "health"]) == 0
    out = capsys.readouterr().out
    assert "FAILED" in out
    assert "evicted: vol-e2e" in out

    # Time-to-detect histogram observed the event.
    assert (
        metrics.registry()
        .histogram("oim_health_detect_seconds", "")
        .count()
        > 0
    )


def test_e2e_controller_death_bounded_by_lease(fleet):
    """ISSUE acceptance: kill the heartbeat → address lease expires → the
    controller's allocations evict with no RPC to the dead controller,
    bounded by lease TTL (1s at this registry_delay) + sweep."""
    store, agent_srv, registry, reg_srv, monitor, controller, backend = fleet
    backend.create_device("vol-dead", {"chipCount": "2"}, None)
    # The monitor must have seen the allocation via health telemetry.
    assert wait_for(
        lambda: any(
            (states.decode_report(v) or {}).get("allocation") == "vol-dead"
            for _, v in registry.db.items("health/h0")
        )
    )
    controller.close()  # heartbeat + health reporting stop (crash analog)
    start = time.monotonic()
    assert wait_for(
        lambda: registry.db.lookup(states.eviction_key("vol-dead")) != "",
        timeout=15,
    )
    # TTL is max(1, int(0.2*3)) = 1s; detection must be lease-bounded,
    # not connect-timeout-bounded (no RPC to the dead controller exists).
    assert time.monotonic() - start < 10
    record = json.loads(registry.db.lookup(states.eviction_key("vol-dead")))
    assert record["reason"] == "controller-dead"


def test_e2e_drain_uncordon_remap_via_oimctl(fleet, capsys):
    store, agent_srv, registry, reg_srv, monitor, controller, backend = fleet
    addr = str(reg_srv.addr())
    backend.create_device("vol-op", {"chipCount": "1"}, None)
    assert wait_for(
        lambda: any(
            (states.decode_report(v) or {}).get("allocation") == "vol-op"
            for _, v in registry.db.items("health/h0")
        )
    )
    assert oimctl.main(["--registry", addr, "drain", "h0",
                        "--reason", "kernel upgrade"]) == 0
    assert wait_for(
        lambda: registry.db.lookup(states.eviction_key("vol-op")) != ""
    )
    assert oimctl.main(["--registry", addr, "health"]) == 0
    out = capsys.readouterr().out
    assert "cordoned: h0 (kernel upgrade)" in out
    assert "evicted: vol-op" in out

    # Staging is refused while evicted.
    with pytest.raises(VolumeError):
        backend.create_device("vol-op", {"chipCount": "1"}, None)

    assert oimctl.main(["--registry", addr, "uncordon", "h0"]) == 0
    capsys.readouterr()
    # remap clears the mark and maps again (same fleet here; in anger the
    # operator points --controller at a healthy host).
    assert oimctl.main(
        ["--registry", addr, "remap", "vol-op", "--controller", "h0",
         "--chips", "1"]
    ) == 0
    out = capsys.readouterr().out
    assert "remapped vol-op onto h0" in out
    assert registry.db.lookup(states.eviction_key("vol-op")) == ""
    # And the CSI plane stages it again.
    staged = backend.create_device("vol-op", {"chipCount": "1"}, None)
    assert len(staged.chips) == 1


def test_remap_respects_backoff(tmp_path, capsys):
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    engine = EvictionEngine(
        registry.db, EvictionPolicy(remap_backoff_s=3600.0)
    )
    try:
        engine.evict("vol-bo", "h0", "chip-failed")
        addr = str(reg_srv.addr())
        assert oimctl.main(
            ["--registry", addr, "remap", "vol-bo", "--controller", "h0"]
        ) == 1
        assert "remap backoff" in capsys.readouterr().out
        assert registry.db.lookup(states.eviction_key("vol-bo")) != ""
        # --force overrides the window; the map itself fails (no such
        # controller registered) — and a FAILED remap must PRESERVE the
        # eviction mark (clearing only happens after a successful map,
        # else a retried NodeStage lands back on the faulted slice).
        assert oimctl.main(
            ["--registry", addr, "remap", "vol-bo", "--controller", "h0",
             "--force"]
        ) == 1
        assert registry.db.lookup(states.eviction_key("vol-bo")) != ""
    finally:
        reg_srv.stop()
        registry.close()


# ---------------------------------------------------------------------------
# Registry satellites: lease-expiry observability + proxy-channel invariant
# (they live here, not in test_registry.py, because that module needs the
# `cryptography` package to collect and this suite must run everywhere the
# health loop does)


@pytest.mark.parametrize("backend", ["mem", "sqlite"])
def test_lease_expirations_counted(backend, tmp_path):
    """The lease sweep exports oim_registry_lease_expirations_total: real
    expiries count; stale expiries (key refreshed/deleted since the
    deadline was armed) do not.

    The counter is process-global and other tests' leases drain on their
    own schedule, so exact-delta assertions over sleep windows are flaky
    (seen in CI).  Instead: drive the sweep's expiry callback directly in
    tight no-sleep windows (deterministic attribution), plus one
    black-box `>=` check that the real sweeper thread reaches the same
    code path."""
    from oim_tpu.registry import SqliteRegistryDB
    from oim_tpu.registry.db import LEASE_EXPIRATIONS

    db = (
        MemRegistryDB()
        if backend == "mem"
        else SqliteRegistryDB(str(tmp_path / "reg.db"))
    )

    def current_seq(path):
        with db._sweeper._cond:
            return db._sweeper._seq[path]

    # A real expiry counts: current-seq callback deletes the key.
    db.store("lc/a", "v", ttl=60)
    seq = current_seq("lc/a")
    before = LEASE_EXPIRATIONS.value()
    db._expire("lc/a", seq)
    assert LEASE_EXPIRATIONS.value() == before + 1
    assert db.lookup("lc/a") == ""

    # A stale expiry (the key was refreshed to persistent since the
    # deadline was armed) must neither delete nor count.
    db.store("lc/b", "v", ttl=60)
    stale_seq = current_seq("lc/b")
    db.store("lc/b", "v")  # un-leased: seq bumped, deadline void
    before = LEASE_EXPIRATIONS.value()
    db._expire("lc/b", stale_seq)
    assert LEASE_EXPIRATIONS.value() == before
    assert db.lookup("lc/b") == "v"

    # Same for an explicit delete racing the deadline.
    db.store("lc/c", "v", ttl=60)
    stale_seq = current_seq("lc/c")
    db.store("lc/c", "")
    before = LEASE_EXPIRATIONS.value()
    db._expire("lc/c", stale_seq)
    assert LEASE_EXPIRATIONS.value() == before

    # Black-box: the real sweeper thread takes the counting path too
    # (>= because foreign leases may drain concurrently).
    floor = LEASE_EXPIRATIONS.value()
    db.store("lc/d", "v", ttl=0.1)
    assert wait_for(lambda: db.lookup("lc/d") == "")
    assert wait_for(lambda: LEASE_EXPIRATIONS.value() >= floor + 1)
    db.close()


def test_heartbeat_reput_does_not_churn_proxy_channel():
    """Regression for registry._on_address_event (registry.py:92-95): a
    heartbeat re-put of the SAME controller address must not invalidate
    the cached proxy channel — only deletion (explicit or lease expiry)
    may.  Observed via the chancache churn counter."""
    reg = Registry()
    try:
        reg.db.store("hb-ctrl/address", "tcp://10.0.0.1:1")

        class FakeChannel:
            def close(self):
                pass

        channel = reg._proxy_channels.get(
            "hb-ctrl", ("tcp://10.0.0.1:1", None), FakeChannel
        )
        base = reg._proxy_channels.churn
        # Heartbeat re-puts of the unchanged address: zero churn, the
        # cached channel survives.
        for _ in range(5):
            reg.db.store("hb-ctrl/address", "tcp://10.0.0.1:1")
        assert reg._proxy_channels.churn == base
        assert (
            reg._proxy_channels.get(
                "hb-ctrl", ("tcp://10.0.0.1:1", None), FakeChannel
            )
            is channel
        )
        # Deletion (what lease expiry also emits) invalidates: churn +1.
        reg.db.store("hb-ctrl/address", "")
        assert reg._proxy_channels.churn == base + 1
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# mTLS authz: a controller may publish only ITS OWN health subtree


def test_health_key_authz():
    from tests.helpers import FakeAbort, FakeServicerContext
    from oim_tpu.spec import oim_pb2

    registry = Registry()  # authz keys off the peer CN, not server TLS

    def set_value(cn, path):
        registry.SetValue(
            oim_pb2.SetValueRequest(
                value=oim_pb2.Value(path=path, value="v")
            ),
            FakeServicerContext(cn),
        )

    set_value("controller.h0", "health/h0/0")  # own subtree: allowed
    set_value("controller.h0", "h0/address")  # address still allowed
    for path in ("health/h1/0", "drain/h0", "evictions/vol-1"):
        with pytest.raises(FakeAbort) as err:
            set_value("controller.h0", path)
        assert err.value.code == grpc.StatusCode.PERMISSION_DENIED
    set_value("user.admin", "drain/h0")  # operator writes: admin
    registry.close()


# ---------------------------------------------------------------------------
# Soak variant (excluded from tier-1 and make test-health by the slow mark)


@pytest.mark.slow
def test_soak_flapping_chip_never_falsely_evicts():
    """Hundreds of degrade/recover flaps inside the grace window must
    produce zero evictions and no timer-thread leak."""
    import threading

    db = MemRegistryDB()
    monitor = FleetMonitor(
        db, policy=EvictionPolicy(degraded_grace_s=5.0)
    ).start()
    try:
        for _ in range(300):
            report(db, "h0", "0", states.DEGRADED, alloc="vol-soak")
            report(db, "h0", "0", states.OK, alloc="vol-soak")
        time.sleep(0.2)
        assert db.lookup(states.eviction_key("vol-soak")) == ""
        timers = [
            t for t in threading.enumerate()
            if t.name == "fleet-grace-timer"
        ]
        assert len(timers) <= 1
    finally:
        monitor.close()
        db.close()
