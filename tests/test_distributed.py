"""Multi-host DCN tier: staged bootstraps → a REAL jax.distributed group.

tests/test_multihost.py proves the control-plane rendezvous (N NodeStages
converge on one coordinator assignment); this tier proves the thing the
rendezvous exists FOR: two separate worker processes read their staged
``tpu-bootstrap.json`` files, call ``coordinator.initialize()``, form one
``jax.distributed`` process group at the controller-allocated coordinator
address, build the global logical mesh, and run a cross-process
collective whose result every process agrees on.  CPU analog of the DCN
path (gloo collectives over a 2-process × 2-device global mesh) — the
reference's tier-3 discipline of driving the real runtime, not a fake
(reference test/test.make:1-16).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import subprocess
import sys
import time

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.controller import Controller
from oim_tpu.csi import OIMDriver
from oim_tpu.registry import Registry
from oim_tpu.spec import CSI_CONTROLLER, CSI_NODE, csi_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from oim_tpu.parallel import coordinator

mesh = coordinator.initialize({bootstrap!r})  # bind + join group + mesh

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

pid = jax.process_index()
# Each process contributes its own shard of a dp-sharded global array;
# the replicated sum forces a cross-process all-reduce over "DCN".
local = np.full((2, 4), pid + 1, np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, global_shape=(4, 4)
)
total = jax.jit(
    lambda x: x.sum(), out_shardings=NamedSharding(mesh, P())
)(x)
print(json.dumps({{
    "process": pid,
    "num_processes": jax.process_count(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "mesh_axes": {{k: int(v) for k, v in mesh.shape.items()}},
    "sum": float(total),
}}))
"""


def _worker_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    # 2 local CPU devices per process → 4 global over the 2-process group.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


def test_staged_bootstraps_form_real_process_group(tmp_path):
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop]
    channels = {}
    try:
        for host_id in ("host-a", "host-b"):
            store = ChipStore(
                mesh=(2, 1, 1), device_dir=str(tmp_path / host_id / "dev")
            )
            agent = FakeAgentServer(
                store, str(tmp_path / host_id / "agent.sock")
            ).start()
            cleanups.append(agent.stop)
            controller = Controller(
                host_id,
                agent.socket_path,
                registry_address=str(reg_srv.addr()),
                coordinator_host="127.0.0.1",
                registry_delay=30.0,
            )
            ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
            cleanups += [controller.close, ctrl_srv.stop]
            controller.start(str(ctrl_srv.addr()))
            driver = OIMDriver(
                csi_endpoint=f"unix://{tmp_path}/{host_id}-csi.sock",
                registry_address=str(reg_srv.addr()),
                controller_id=host_id,
            )
            csi_srv = driver.start_server()
            cleanups += [driver.close, csi_srv.stop]
            channel = grpc.insecure_channel(csi_srv.addr().grpc_target())
            cleanups.append(channel.close)
            channels[host_id] = channel

        deadline = time.time() + 10
        while any(
            registry.db.lookup(f"{h}/address") == "" for h in channels
        ):
            assert time.time() < deadline, "controllers never registered"
            time.sleep(0.02)

        cap = csi_pb2.VolumeCapability()
        cap.mount.SetInParent()
        cap.access_mode.mode = (
            csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_MULTI_WRITER
        )
        vol = CSI_CONTROLLER.stub(channels["host-a"]).CreateVolume(
            csi_pb2.CreateVolumeRequest(
                name="dist-vol",
                volume_capabilities=[cap],
                parameters={"chipCount": "2", "hosts": "host-a,host-b"},
            ),
            timeout=30,
        ).volume

        def stage(host_id: str) -> str:
            staging = str(tmp_path / host_id / "staging")
            target = str(tmp_path / host_id / "pod" / "tpu")
            node = CSI_NODE.stub(channels[host_id])
            node.NodeStageVolume(
                csi_pb2.NodeStageVolumeRequest(
                    volume_id="dist-vol",
                    staging_target_path=staging,
                    volume_capability=cap,
                    volume_context=dict(vol.volume_context),
                ),
                timeout=60,
            )
            node.NodePublishVolume(
                csi_pb2.NodePublishVolumeRequest(
                    volume_id="dist-vol",
                    staging_target_path=staging,
                    target_path=target,
                    volume_capability=cap,
                ),
                timeout=60,
            )
            return os.path.join(target, "tpu-bootstrap.json")

        # Concurrent: the rendezvous blocks until both hosts join.
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            paths = list(pool.map(stage, ["host-a", "host-b"]))

        boots = [json.load(open(p)) for p in paths]
        assert {b["process_id"] for b in boots} == {0, 1}
        assert all(b["num_processes"] == 2 for b in boots)
        assert len({b["coordinator_address"] for b in boots}) == 1

        # The workloads: one process per staged bootstrap, forming ONE
        # jax.distributed group and agreeing on a global collective.
        procs = []
        for p in paths:
            proc = subprocess.Popen(
                [sys.executable, "-c", WORKER.format(repo=REPO, bootstrap=p)],
                env=_worker_env(),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            procs.append(proc)
            # One worker failing must not leave its peer blocked in the
            # jax.distributed rendezvous: kill both on any exit path.
            cleanups.append(lambda proc=proc: (proc.kill(), proc.wait()))
        reports = []
        for proc in procs:
            out, err = proc.communicate(timeout=300)
            assert proc.returncode == 0, (
                f"worker failed\nhead: {err[:1200]}\n...\ntail: {err[-1200:]}"
            )
            reports.append(json.loads(out.strip().splitlines()[-1]))

        assert {r["process"] for r in reports} == {0, 1}
        for r in reports:
            assert r["num_processes"] == 2
            assert r["global_devices"] == 4
            assert r["local_devices"] == 2
            assert r["mesh_axes"] == {"dp": 4, "pp": 1, "sp": 1, "tp": 1,
                                      "ep": 1}
            # 8 elements of 1.0 (process 0) + 8 of 2.0 (process 1).
            assert r["sum"] == 24.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass


WORKER_N = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from oim_tpu.parallel import coordinator

mesh = coordinator.initialize({bootstrap!r})

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

pid = jax.process_index()
local = np.full((2, 4), pid + 1, np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")),
    local,
    global_shape=(2 * jax.process_count(), 4),
)
total = jax.jit(
    lambda x: x.sum(), out_shardings=NamedSharding(mesh, P())
)(x)
print(json.dumps({{
    "process": pid,
    "num_processes": jax.process_count(),
    "global_devices": len(jax.devices()),
    "mesh_axes": {{k: int(v) for k, v in mesh.shape.items()}},
    "sum": float(total),
}}))
"""


@pytest.mark.skipif(
    os.environ.get("TEST_MULTIHOST4") != "1",
    reason="4-process DCN tier is opt-in: TEST_MULTIHOST4=1 (heavy: 4 jax "
    "subprocesses; the 2-process tier above always runs)",
)
def test_four_hosts_etcd_registry_group(tmp_path):
    """VERDICT r3 #8: the 2-process tier, scaled to FOUR processes with
    the rendezvous through a registry backed by the REAL etcd wire
    (EtcdRegistryDB → in-process EtcdKVServer): 4 controllers register
    (leased), 4 NodeStages converge on one coordinator through etcd-backed
    state, and 4 worker processes form one jax.distributed group (2 CPU
    devices each → 8 global) agreeing on a cross-process collective."""
    from oim_tpu.registry import EtcdKVServer, EtcdRegistryDB

    kv = EtcdKVServer()
    kv_srv = kv.start_server("tcp://127.0.0.1:0")
    db = EtcdRegistryDB(str(kv_srv.addr()))
    registry = Registry(db=db)
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop, db.close, kv.close, kv_srv.stop]
    channels = {}
    hosts = [f"host-{i}" for i in range(4)]
    try:
        for host_id in hosts:
            store = ChipStore(
                mesh=(2, 1, 1), device_dir=str(tmp_path / host_id / "dev")
            )
            agent = FakeAgentServer(
                store, str(tmp_path / host_id / "agent.sock")
            ).start()
            cleanups.append(agent.stop)
            controller = Controller(
                host_id,
                agent.socket_path,
                registry_address=str(reg_srv.addr()),
                coordinator_host="127.0.0.1",
                registry_delay=30.0,
            )
            ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
            cleanups += [controller.close, ctrl_srv.stop]
            controller.start(str(ctrl_srv.addr()))
            driver = OIMDriver(
                csi_endpoint=f"unix://{tmp_path}/{host_id}-csi.sock",
                registry_address=str(reg_srv.addr()),
                controller_id=host_id,
            )
            csi_srv = driver.start_server()
            cleanups += [driver.close, csi_srv.stop]
            channel = grpc.insecure_channel(csi_srv.addr().grpc_target())
            cleanups.append(channel.close)
            channels[host_id] = channel

        deadline = time.time() + 15
        while any(
            registry.db.lookup(f"{h}/address") == "" for h in channels
        ):
            assert time.time() < deadline, "controllers never registered"
            time.sleep(0.02)

        cap = csi_pb2.VolumeCapability()
        cap.mount.SetInParent()
        cap.access_mode.mode = (
            csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_MULTI_WRITER
        )
        vol = CSI_CONTROLLER.stub(channels["host-0"]).CreateVolume(
            csi_pb2.CreateVolumeRequest(
                name="dist4-vol",
                volume_capabilities=[cap],
                parameters={"chipCount": "2", "hosts": ",".join(hosts)},
            ),
            timeout=30,
        ).volume

        def stage(host_id: str) -> str:
            staging = str(tmp_path / host_id / "staging")
            target = str(tmp_path / host_id / "pod" / "tpu")
            node = CSI_NODE.stub(channels[host_id])
            node.NodeStageVolume(
                csi_pb2.NodeStageVolumeRequest(
                    volume_id="dist4-vol",
                    staging_target_path=staging,
                    volume_capability=cap,
                    volume_context=dict(vol.volume_context),
                ),
                timeout=120,
            )
            node.NodePublishVolume(
                csi_pb2.NodePublishVolumeRequest(
                    volume_id="dist4-vol",
                    staging_target_path=staging,
                    target_path=target,
                    volume_capability=cap,
                ),
                timeout=120,
            )
            return os.path.join(target, "tpu-bootstrap.json")

        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            paths = list(pool.map(stage, hosts))

        boots = [json.load(open(p)) for p in paths]
        assert {b["process_id"] for b in boots} == {0, 1, 2, 3}
        assert all(b["num_processes"] == 4 for b in boots)
        assert len({b["coordinator_address"] for b in boots}) == 1

        procs = []
        for p in paths:
            proc = subprocess.Popen(
                [
                    sys.executable, "-c",
                    WORKER_N.format(repo=REPO, bootstrap=p),
                ],
                env=_worker_env(),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            procs.append(proc)
            cleanups.append(lambda proc=proc: (proc.kill(), proc.wait()))
        reports = []
        for proc in procs:
            out, err = proc.communicate(timeout=600)
            assert proc.returncode == 0, (
                f"worker failed\nhead: {err[:1200]}\n...\ntail: {err[-1200:]}"
            )
            reports.append(json.loads(out.strip().splitlines()[-1]))

        assert {r["process"] for r in reports} == {0, 1, 2, 3}
        for r in reports:
            assert r["num_processes"] == 4
            assert r["global_devices"] == 8
            # 8 rows of 4: (1+2+3+4) * 2 rows * 4 cols = 80.
            assert r["sum"] == 80.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass
