"""Multi-host DCN tier: staged bootstraps → a REAL jax.distributed group.

tests/test_multihost.py proves the control-plane rendezvous (N NodeStages
converge on one coordinator assignment); this tier proves the thing the
rendezvous exists FOR: N separate worker processes read their staged
``tpu-bootstrap.json`` files, call ``coordinator.initialize()``, form one
``jax.distributed`` process group at the controller-allocated coordinator
address, build the global logical mesh, and run a cross-process
collective whose result every process agrees on.  CPU analog of the DCN
path (gloo collectives over N processes × 2 devices each) — the
reference's tier-3 discipline of driving the real runtime, not a fake
(reference test/test.make:1-16).

The always-on case runs 2 processes on the in-memory registry; the
env-gated ``TEST_MULTIHOST4=1`` case runs 4 processes with the
rendezvous through an etcd-backed registry (EtcdRegistryDB → in-process
EtcdKVServer over the real v3 wire) — BASELINE config 5's shape.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import subprocess
import sys
import time

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.controller import Controller
from oim_tpu.csi import OIMDriver
from oim_tpu.registry import Registry
from oim_tpu.spec import CSI_CONTROLLER, CSI_NODE, csi_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from oim_tpu.parallel import coordinator

mesh = coordinator.initialize({bootstrap!r})  # bind + join group + mesh

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

pid = jax.process_index()
# Each process contributes its own shard of a dp-sharded global array;
# the replicated sum forces a cross-process all-reduce over "DCN".
local = np.full((2, 4), pid + 1, np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")),
    local,
    global_shape=(2 * jax.process_count(), 4),
)
total = jax.jit(
    lambda x: x.sum(), out_shardings=NamedSharding(mesh, P())
)(x)
print(json.dumps({{
    "process": pid,
    "num_processes": jax.process_count(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "mesh_axes": {{k: int(v) for k, v in mesh.shape.items()}},
    "sum": float(total),
}}))
"""


def _worker_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    # 2 local CPU devices per process → 2N global over the N-process group.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


def _build_hosts(tmp_path, hosts, registry, reg_addr, cleanups):
    """One fake agent + controller + remote CSI driver per host, all
    registered against one registry.  Returns host_id → CSI channel."""
    channels = {}
    for host_id in hosts:
        store = ChipStore(
            mesh=(2, 1, 1), device_dir=str(tmp_path / host_id / "dev")
        )
        agent = FakeAgentServer(
            store, str(tmp_path / host_id / "agent.sock")
        ).start()
        cleanups.append(agent.stop)
        controller = Controller(
            host_id,
            agent.socket_path,
            registry_address=reg_addr,
            coordinator_host="127.0.0.1",
            registry_delay=30.0,
        )
        ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
        cleanups += [controller.close, ctrl_srv.stop]
        controller.start(str(ctrl_srv.addr()))
        driver = OIMDriver(
            csi_endpoint=f"unix://{tmp_path}/{host_id}-csi.sock",
            registry_address=reg_addr,
            controller_id=host_id,
        )
        csi_srv = driver.start_server()
        cleanups += [driver.close, csi_srv.stop]
        channel = grpc.insecure_channel(csi_srv.addr().grpc_target())
        cleanups.append(channel.close)
        channels[host_id] = channel

    deadline = time.time() + 15
    while any(registry.db.lookup(f"{h}/address") == "" for h in channels):
        assert time.time() < deadline, "controllers never registered"
        time.sleep(0.02)
    return channels


def _stage_and_run_group(tmp_path, channels, volume, cleanups):
    """CreateVolume across all hosts, stage concurrently (the rendezvous
    blocks until every host joins), then run one worker process per
    staged bootstrap and return their reports."""
    hosts = list(channels)
    cap = csi_pb2.VolumeCapability()
    cap.mount.SetInParent()
    cap.access_mode.mode = (
        csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_MULTI_WRITER
    )
    vol = CSI_CONTROLLER.stub(channels[hosts[0]]).CreateVolume(
        csi_pb2.CreateVolumeRequest(
            name=volume,
            volume_capabilities=[cap],
            parameters={"chipCount": "2", "hosts": ",".join(hosts)},
        ),
        timeout=30,
    ).volume

    def stage(host_id: str) -> str:
        staging = str(tmp_path / host_id / "staging")
        target = str(tmp_path / host_id / "pod" / "tpu")
        node = CSI_NODE.stub(channels[host_id])
        node.NodeStageVolume(
            csi_pb2.NodeStageVolumeRequest(
                volume_id=volume,
                staging_target_path=staging,
                volume_capability=cap,
                volume_context=dict(vol.volume_context),
            ),
            timeout=120,
        )
        node.NodePublishVolume(
            csi_pb2.NodePublishVolumeRequest(
                volume_id=volume,
                staging_target_path=staging,
                target_path=target,
                volume_capability=cap,
            ),
            timeout=120,
        )
        return os.path.join(target, "tpu-bootstrap.json")

    with concurrent.futures.ThreadPoolExecutor(len(hosts)) as pool:
        paths = list(pool.map(stage, hosts))

    boots = [json.load(open(p)) for p in paths]
    assert {b["process_id"] for b in boots} == set(range(len(hosts)))
    assert all(b["num_processes"] == len(hosts) for b in boots)
    assert len({b["coordinator_address"] for b in boots}) == 1

    procs = []
    for p in paths:
        proc = subprocess.Popen(
            [sys.executable, "-c", WORKER.format(repo=REPO, bootstrap=p)],
            env=_worker_env(),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        procs.append(proc)
        # One worker failing must not leave its peers blocked in the
        # jax.distributed rendezvous: kill all on any exit path.
        cleanups.append(lambda proc=proc: (proc.kill(), proc.wait()))
    reports = []
    for proc in procs:
        out, err = proc.communicate(timeout=600)
        assert proc.returncode == 0, (
            f"worker failed\nhead: {err[:1200]}\n...\ntail: {err[-1200:]}"
        )
        reports.append(json.loads(out.strip().splitlines()[-1]))
    return reports


def test_staged_bootstraps_form_real_process_group(tmp_path):
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop]
    try:
        channels = _build_hosts(
            tmp_path, ["host-a", "host-b"], registry, str(reg_srv.addr()),
            cleanups,
        )
        reports = _stage_and_run_group(
            tmp_path, channels, "dist-vol", cleanups
        )
        assert {r["process"] for r in reports} == {0, 1}
        for r in reports:
            assert r["num_processes"] == 2
            assert r["global_devices"] == 4
            assert r["local_devices"] == 2
            assert r["mesh_axes"] == {"dp": 4, "pp": 1, "sp": 1, "tp": 1,
                                      "ep": 1}
            # 8 elements of 1.0 (process 0) + 8 of 2.0 (process 1).
            assert r["sum"] == 24.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass


@pytest.mark.skipif(
    os.environ.get("TEST_MULTIHOST4") != "1",
    reason="4-process DCN tier is opt-in: TEST_MULTIHOST4=1 (heavy: 4 jax "
    "subprocesses; the 2-process tier above always runs)",
)
def test_four_hosts_etcd_registry_group(tmp_path):
    """VERDICT r3 #8: the 2-process tier, scaled to FOUR processes with
    the rendezvous through a registry backed by the REAL etcd wire
    (EtcdRegistryDB → in-process EtcdKVServer): 4 controllers register,
    4 NodeStages converge on one coordinator through etcd-backed state,
    and 4 worker processes form one jax.distributed group (2 CPU devices
    each → 8 global) agreeing on a cross-process collective."""
    from oim_tpu.registry import EtcdKVServer, EtcdRegistryDB

    kv = EtcdKVServer()
    kv_srv = kv.start_server("tcp://127.0.0.1:0")
    db = EtcdRegistryDB(str(kv_srv.addr()))
    registry = Registry(db=db)
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop, db.close, kv.close, kv_srv.stop]
    try:
        channels = _build_hosts(
            tmp_path, [f"host-{i}" for i in range(4)], registry,
            str(reg_srv.addr()), cleanups,
        )
        reports = _stage_and_run_group(
            tmp_path, channels, "dist4-vol", cleanups
        )
        assert {r["process"] for r in reports} == {0, 1, 2, 3}
        for r in reports:
            assert r["num_processes"] == 4
            assert r["global_devices"] == 8
            # 2 rows per process of (pid+1): (1+2+3+4) * 2 rows * 4 cols.
            assert r["sum"] == 80.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass
