"""Multi-host DCN tier: staged bootstraps → a REAL jax.distributed group.

tests/test_multihost.py proves the control-plane rendezvous (N NodeStages
converge on one coordinator assignment); this tier proves the thing the
rendezvous exists FOR: N separate worker processes read their staged
``tpu-bootstrap.json`` files, call ``coordinator.initialize()``, form one
``jax.distributed`` process group at the controller-allocated coordinator
address, build the global logical mesh, and run a cross-process
collective whose result every process agrees on.  CPU analog of the DCN
path (gloo collectives over N processes × 2 devices each) — the
reference's tier-3 discipline of driving the real runtime, not a fake
(reference test/test.make:1-16).

The always-on case runs 2 processes on the in-memory registry; the
env-gated ``TEST_MULTIHOST4=1`` case runs 4 processes with the
rendezvous through an etcd-backed registry (EtcdRegistryDB → in-process
EtcdKVServer over the real v3 wire) — BASELINE config 5's shape.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import subprocess
import sys
import time

import grpc
import pytest

from oim_tpu.agent import ChipStore, FakeAgentServer
from oim_tpu.controller import Controller
from oim_tpu.csi import OIMDriver
from oim_tpu.registry import Registry
from oim_tpu.spec import CSI_CONTROLLER, CSI_NODE, csi_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from oim_tpu.parallel import coordinator

mesh = coordinator.initialize({bootstrap!r})  # bind + join group + mesh

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

pid = jax.process_index()
# Each process contributes its own shard of a dp-sharded global array;
# the replicated sum forces a cross-process all-reduce over "DCN".
local = np.full((2, 4), pid + 1, np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")),
    local,
    global_shape=(2 * jax.process_count(), 4),
)
total = jax.jit(
    lambda x: x.sum(), out_shardings=NamedSharding(mesh, P())
)(x)
print(json.dumps({{
    "process": pid,
    "num_processes": jax.process_count(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "mesh_axes": {{k: int(v) for k, v in mesh.shape.items()}},
    "sum": float(total),
}}))
"""


def _worker_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    # 2 local CPU devices per process → 2N global over the N-process group.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


def _build_hosts(tmp_path, hosts, registry, reg_addr, cleanups):
    """One fake agent + controller + remote CSI driver per host, all
    registered against one registry.  Returns host_id → CSI channel."""
    channels = {}
    for host_id in hosts:
        store = ChipStore(
            mesh=(2, 1, 1), device_dir=str(tmp_path / host_id / "dev")
        )
        agent = FakeAgentServer(
            store, str(tmp_path / host_id / "agent.sock")
        ).start()
        cleanups.append(agent.stop)
        controller = Controller(
            host_id,
            agent.socket_path,
            registry_address=reg_addr,
            coordinator_host="127.0.0.1",
            registry_delay=30.0,
        )
        ctrl_srv = controller.start_server("tcp://127.0.0.1:0")
        cleanups += [controller.close, ctrl_srv.stop]
        controller.start(str(ctrl_srv.addr()))
        driver = OIMDriver(
            csi_endpoint=f"unix://{tmp_path}/{host_id}-csi.sock",
            registry_address=reg_addr,
            controller_id=host_id,
        )
        csi_srv = driver.start_server()
        cleanups += [driver.close, csi_srv.stop]
        channel = grpc.insecure_channel(csi_srv.addr().grpc_target())
        cleanups.append(channel.close)
        channels[host_id] = channel

    deadline = time.time() + 15
    while any(registry.db.lookup(f"{h}/address") == "" for h in channels):
        assert time.time() < deadline, "controllers never registered"
        time.sleep(0.02)
    return channels


def _mk_cap():
    cap = csi_pb2.VolumeCapability()
    cap.mount.SetInParent()
    cap.access_mode.mode = (
        csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_MULTI_WRITER
    )
    return cap


def _create_volume(channels, volume):
    hosts = list(channels)
    vol = CSI_CONTROLLER.stub(channels[hosts[0]]).CreateVolume(
        csi_pb2.CreateVolumeRequest(
            name=volume,
            volume_capabilities=[_mk_cap()],
            parameters={"chipCount": "2", "hosts": ",".join(hosts)},
        ),
        timeout=30,
    ).volume
    return dict(vol.volume_context)


def _stage_group(tmp_path, channels, volume, context=None):
    """Stage + publish concurrently on every host (the rendezvous blocks
    until every host joins); creates the volume when no ``context`` is
    given.  Returns the per-host bootstrap paths, process-id-ordered."""
    hosts = list(channels)
    cap = _mk_cap()
    if context is None:
        context = _create_volume(channels, volume)

    def stage(host_id: str) -> str:
        staging = str(tmp_path / host_id / "staging")
        target = str(tmp_path / host_id / "pod" / "tpu")
        node = CSI_NODE.stub(channels[host_id])
        node.NodeStageVolume(
            csi_pb2.NodeStageVolumeRequest(
                volume_id=volume,
                staging_target_path=staging,
                volume_capability=cap,
                volume_context=context,
            ),
            timeout=120,
        )
        node.NodePublishVolume(
            csi_pb2.NodePublishVolumeRequest(
                volume_id=volume,
                staging_target_path=staging,
                target_path=target,
                volume_capability=cap,
            ),
            timeout=120,
        )
        return os.path.join(target, "tpu-bootstrap.json")

    with concurrent.futures.ThreadPoolExecutor(len(hosts)) as pool:
        paths = list(pool.map(stage, hosts))

    boots = [json.load(open(p)) for p in paths]
    assert {b["process_id"] for b in boots} == set(range(len(hosts)))
    assert all(b["num_processes"] == len(hosts) for b in boots)
    assert len({b["coordinator_address"] for b in boots}) == 1
    order = sorted(range(len(paths)), key=lambda i: boots[i]["process_id"])
    return [paths[i] for i in order]


def _unstage_group(tmp_path, channels, volume):
    """NodeUnpublish + NodeUnstage on every host — the last host out
    clears the volume's rendezvous record, so a later re-stage re-forms
    the coordinator from scratch."""
    for host_id, channel in channels.items():
        node = CSI_NODE.stub(channel)
        node.NodeUnpublishVolume(
            csi_pb2.NodeUnpublishVolumeRequest(
                volume_id=volume,
                target_path=str(tmp_path / host_id / "pod" / "tpu"),
            ),
            timeout=60,
        )
        node.NodeUnstageVolume(
            csi_pb2.NodeUnstageVolumeRequest(
                volume_id=volume,
                staging_target_path=str(tmp_path / host_id / "staging"),
            ),
            timeout=60,
        )


def _stage_and_run_group(tmp_path, channels, volume, cleanups):
    """Stage across all hosts, then run one worker process per staged
    bootstrap and return their reports."""
    paths = _stage_group(tmp_path, channels, volume)

    procs = []
    for p in paths:
        proc = subprocess.Popen(
            [sys.executable, "-c", WORKER.format(repo=REPO, bootstrap=p)],
            env=_worker_env(),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        procs.append(proc)
        # One worker failing must not leave its peers blocked in the
        # jax.distributed rendezvous: kill all on any exit path.
        cleanups.append(lambda proc=proc: (proc.kill(), proc.wait()))
    reports = []
    for proc in procs:
        out, err = proc.communicate(timeout=600)
        assert proc.returncode == 0, (
            f"worker failed\nhead: {err[:1200]}\n...\ntail: {err[-1200:]}"
        )
        reports.append(json.loads(out.strip().splitlines()[-1]))
    return reports


def test_staged_bootstraps_form_real_process_group(tmp_path):
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop]
    try:
        channels = _build_hosts(
            tmp_path, ["host-a", "host-b"], registry, str(reg_srv.addr()),
            cleanups,
        )
        reports = _stage_and_run_group(
            tmp_path, channels, "dist-vol", cleanups
        )
        assert {r["process"] for r in reports} == {0, 1}
        for r in reports:
            assert r["num_processes"] == 2
            assert r["global_devices"] == 4
            assert r["local_devices"] == 2
            assert r["mesh_axes"] == {"dp": 4, "pp": 1, "sp": 1, "tp": 1,
                                      "ep": 1}
            # 8 elements of 1.0 (process 0) + 8 of 2.0 (process 1).
            assert r["sum"] == 24.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass


TRAIN_FLAGS = [
    "--synthetic", "20000", "--batch-global", "4", "--seq", "32",
    "--vocab-size", "64", "--d-model", "32", "--n-layers", "2",
    "--n-heads", "4", "--d-ff", "64", "--dtype", "float32",
    "--log-every", "1", "--save-every", "1", "--seed", "3",
]


def _train_env() -> dict:
    # 2 CPU devices per process, matching the 2-chips-per-host slice so
    # mesh_from_bootstrap's dp inference (local × num_processes = 4) is
    # the device count.
    env = _worker_env()
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    return env


def _spawn_trainers(paths, ckpt_dir, steps, tag, tmp_path):
    """One oim-train process per bootstrap, logs to files (a SIGKILLed
    worker's partial log must survive for trajectory comparison)."""
    procs = []
    for i, p in enumerate(paths):
        logf = open(tmp_path / f"{tag}-w{i}.log", "w")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "oim_tpu.cli.train_main",
                "--bootstrap", p, "--checkpoint-dir", str(ckpt_dir),
                "--steps", str(steps), *TRAIN_FLAGS,
            ],
            env=_train_env(),
            stdout=logf,
            stderr=subprocess.STDOUT,
            text=True,
        )
        procs.append((proc, logf))
    return procs


def _parse_losses(log_path) -> dict[int, float]:
    import re

    out = {}
    with open(log_path) as f:
        for m in re.finditer(r"loss=([0-9.]+) step=(\d+)", f.read()):
            out[int(m.group(2))] = float(m.group(1))
    return out


def _finalized_steps(ckpt_dir) -> set[int]:
    try:
        return {int(d) for d in os.listdir(ckpt_dir) if d.isdigit()}
    except FileNotFoundError:
        return set()


def test_elastic_recovery_resumes_identical_trajectory(tmp_path):
    """Elastic recovery END TO END (round-4 VERDICT next #3): the pieces
    — heartbeat, leases, checkpoint, rendezvous — compose into the story
    they exist for.  A 2-process training gang is SIGKILLed mid-run
    (worker 1 first, then its orphaned peer — gang semantics), the
    volume is fully unstaged and re-staged so the CSI rendezvous
    re-forms the coordinator from scratch, and the restarted gang
    resumes from the checkpoint + data cursor.  The resumed loss
    trajectory must be IDENTICAL (same logged 4-decimal values) to an
    uninterrupted run's — fp32 CPU with deterministic data makes any
    resume drift (lost optimizer state, misaligned cursor) visible
    (≙ reference recovery stance, controller.go:425-443 +
    cmdmonitor.go:23-51)."""
    registry = Registry()
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop]
    steps = 8
    try:
        channels = _build_hosts(
            tmp_path, ["host-a", "host-b"], registry, str(reg_srv.addr()),
            cleanups,
        )
        context = _create_volume(channels, "elastic-vol")

        # --- Reference: uninterrupted 2-process run to `steps`.
        paths = _stage_group(tmp_path, channels, "elastic-vol", context)
        ref_procs = _spawn_trainers(
            paths, tmp_path / "ck-ref", steps, "ref", tmp_path
        )
        cleanups += [
            (lambda pr=pr: (pr.kill(), pr.wait())) for pr, _ in ref_procs
        ]
        for proc, logf in ref_procs:
            assert proc.wait(timeout=600) == 0, open(logf.name).read()[-1500:]
            logf.close()
        ref = _parse_losses(tmp_path / "ref-w0.log")
        assert set(ref) == set(range(1, steps + 1)), ref
        _unstage_group(tmp_path, channels, "elastic-vol")

        # --- Interrupted run: same seed/args, fresh checkpoint dir.
        paths = _stage_group(tmp_path, channels, "elastic-vol", context)
        ck = tmp_path / "ck-elastic"
        gang = _spawn_trainers(paths, ck, steps, "int", tmp_path)
        cleanups += [
            (lambda pr=pr: (pr.kill(), pr.wait())) for pr, _ in gang
        ]
        # Wait until a checkpoint at step >= 2 is durable, then SIGKILL
        # worker 1 mid-training; the peer dies with its gang.  Tight
        # 5 ms poll: the kill must land inside the remaining steps'
        # runway on a fast host (steps is sized to leave several
        # checkpoint round-trips of margin after the trigger).
        deadline = time.time() + 300
        while not any(s >= 2 for s in _finalized_steps(ck)):
            assert time.time() < deadline, "no checkpoint appeared"
            assert all(pr.poll() is None for pr, _ in gang), (
                "worker died before the kill: "
                + open(gang[0][1].name).read()[-800:]
                + open(gang[1][1].name).read()[-800:]
            )
            time.sleep(0.005)
        gang[1][0].kill()
        gang[0][0].kill()
        for proc, logf in gang:
            proc.wait(timeout=60)
            logf.close()
        interrupted = _parse_losses(tmp_path / "int-w0.log")
        saved = max(_finalized_steps(ck))
        assert saved < steps, "gang finished before the kill landed"

        # --- Recover: full unstage → re-stage (the rendezvous allocates
        # a fresh coordinator), restart the gang on the SAME checkpoint
        # dir; it must resume from the data cursor and finish.
        _unstage_group(tmp_path, channels, "elastic-vol")
        paths = _stage_group(tmp_path, channels, "elastic-vol", context)
        resumed_procs = _spawn_trainers(
            paths, ck, steps, "res", tmp_path
        )
        cleanups += [
            (lambda pr=pr: (pr.kill(), pr.wait()))
            for pr, _ in resumed_procs
        ]
        for proc, logf in resumed_procs:
            assert proc.wait(timeout=600) == 0, open(logf.name).read()[-1500:]
            logf.close()
        res_log = open(tmp_path / "res-w0.log").read()
        assert f"resumed step={saved}" in res_log, res_log[-800:]
        resumed = _parse_losses(tmp_path / "res-w0.log")

        # The composed trajectory equals the uninterrupted one: every
        # pre-kill step the interrupted gang logged, and every post-resume
        # step, matches the reference exactly.
        assert set(resumed) == set(range(saved + 1, steps + 1)), resumed
        for step, loss in {**interrupted, **resumed}.items():
            assert loss == ref[step], (
                f"step {step}: {loss} != reference {ref[step]} "
                f"(interrupted={interrupted}, resumed={resumed}, ref={ref})"
            )
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass


@pytest.mark.skipif(
    os.environ.get("TEST_MULTIHOST4") != "1",
    reason="4-process DCN tier is opt-in: TEST_MULTIHOST4=1 (heavy: 4 jax "
    "subprocesses; the 2-process tier above always runs)",
)
def test_four_hosts_etcd_registry_group(tmp_path):
    """VERDICT r3 #8: the 2-process tier, scaled to FOUR processes with
    the rendezvous through a registry backed by the REAL etcd wire
    (EtcdRegistryDB → in-process EtcdKVServer): 4 controllers register,
    4 NodeStages converge on one coordinator through etcd-backed state,
    and 4 worker processes form one jax.distributed group (2 CPU devices
    each → 8 global) agreeing on a cross-process collective."""
    from oim_tpu.registry import EtcdKVServer, EtcdRegistryDB

    kv = EtcdKVServer()
    kv_srv = kv.start_server("tcp://127.0.0.1:0")
    db = EtcdRegistryDB(str(kv_srv.addr()))
    registry = Registry(db=db)
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    cleanups = [registry.close, reg_srv.stop, db.close, kv.close, kv_srv.stop]
    try:
        channels = _build_hosts(
            tmp_path, [f"host-{i}" for i in range(4)], registry,
            str(reg_srv.addr()), cleanups,
        )
        reports = _stage_and_run_group(
            tmp_path, channels, "dist4-vol", cleanups
        )
        assert {r["process"] for r in reports} == {0, 1, 2, 3}
        for r in reports:
            assert r["num_processes"] == 4
            assert r["global_devices"] == 8
            # 2 rows per process of (pid+1): (1+2+3+4) * 2 rows * 4 cols.
            assert r["sum"] == 80.0
    finally:
        for cleanup in reversed(cleanups):
            try:
                cleanup()
            except Exception:
                pass
