"""Pipelined decode: dispatch-ahead double buffering must be invisible.

The load-bearing property (ISSUE 5): an engine at ``pipeline_depth=2``
— chunk N+1 dispatched before chunk N's readback — emits exactly the
tokens the serial (``pipeline_depth=1``) engine emits, for greedy,
sampled, speculative, and prefix-cache-injected requests, dense and
MoE, admissions mid-stream included.  ``set_pipeline_depth`` flips one
warm engine between the modes, so every A/B below compares the SAME
compiled programs and only the step loop's overlap differs.

Also here: drain/abort with a chunk in flight (the quiesce contract —
nothing emitted past EOS, no slot leaked), the readback-attribution
fix for embed/beam (they must hit ``readbacks``/``readback_seconds``,
not bypass the accumulator via raw device_get), and the overlap /
device-idle accounting the "Serving pipeline tuning" runbook reads.

Kept deliberately lean: engines are shared per model config and
prompts stay in one small bucket — this file backs ``make test-serve``
(<60 s cap).
"""

import jax
import numpy as np
import pytest

from oim_tpu.common import metrics as _metrics
from oim_tpu.models import TransformerConfig, init_params
from oim_tpu.models.decode import generate
from oim_tpu.serve import Engine, GenRequest

CFG = dict(
    vocab_size=101,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    dtype="float32",
    use_pallas=False,
)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, params = setup
    # One bucket (prompts stay <= 16) bounds the compile count; the
    # prefix cache is on so the matrix's injected-rows variant runs on
    # this same engine.
    return Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                  prompt_buckets=(16,), prefix_cache_size=2)


def _prompt(seed: int, n: int, vocab: int) -> list[int]:
    rng = np.random.RandomState(seed)
    return rng.randint(0, vocab, size=n).tolist()


def _echo_prompt(n: int, vocab: int) -> list[int]:
    pattern = [7, 21, 40, 3]
    return [t % vocab for t in (pattern * ((n // 4) + 1))[:n]]


def _oracle(params, cfg, tokens, max_new) -> list[int]:
    prompt = jax.numpy.asarray(tokens, jax.numpy.int32)[None]
    out = generate(params, prompt, cfg, max_new_tokens=max_new)
    return np.asarray(out)[0, len(tokens):].tolist()


def _matrix_workload(engine, vocab, system):
    """The exactness-matrix traffic shape on one engine: more requests
    than slots (queue pressure), greedy + sampled rows, a
    cache_prefix-marked system prompt plus a request sharing it (a
    prefix-cache hit once the entry exists), and a mid-stream admission
    wave landing while chunks are in flight."""
    specs = [
        # (tokens, max_new, temperature, seed, cache_prefix)
        (system, 8, 0.0, 0, True),
        (_prompt(21, 9, vocab), 10, 0.8, 7, False),
        (_prompt(22, 5, vocab), 6, 0.0, 0, False),
    ]
    rids = [
        engine.submit(GenRequest(
            tokens=t, max_new_tokens=m, temperature=temp, seed=s,
            cache_prefix=c,
        ))
        for t, m, temp, s, c in specs
    ]
    engine.step()
    engine.step()
    # Mid-stream: a prefix-cache candidate (shares the system prompt)
    # and one more sampled request join while slots are busy.
    late = [
        (system + _prompt(23, 4, vocab), 7, 0.0, 0, False),
        (_prompt(24, 6, vocab), 5, 0.5, 3, False),
    ]
    rids += [
        engine.submit(GenRequest(
            tokens=t, max_new_tokens=m, temperature=temp, seed=s,
            cache_prefix=c,
        ))
        for t, m, temp, s, c in late
    ]
    results = engine.run()
    return [results[r] for r in rids], [s[:2] for s in specs + late]


def test_exactness_matrix_dense(setup, dense_engine):
    """Pipelined == serial, token for token, on the dense engine across
    greedy / sampled / prefix-cache / mid-stream admission — and the
    greedy rows equal the solo oracle, so BOTH modes are exact, not
    merely identical."""
    cfg, params = setup
    engine = dense_engine
    system = _prompt(20, 10, cfg.vocab_size)

    engine.set_pipeline_depth(1)
    serial, shapes = _matrix_workload(engine, cfg.vocab_size, system)
    hits_before = engine.stats()["prefix_hits"]
    engine.set_pipeline_depth(2)
    pipelined, _ = _matrix_workload(engine, cfg.vocab_size, system)

    assert pipelined == serial
    # The pipelined pass really exercised the injection path (the
    # serial pass populated the cache).
    assert engine.stats()["prefix_hits"] > hits_before
    # Greedy rows against the solo oracle (rows 0 and 2 are temp=0).
    for idx in (0, 2):
        tokens, max_new = shapes[idx]
        assert serial[idx] == _oracle(params, cfg, tokens, max_new)


def test_exactness_matrix_moe(setup):
    """Same matrix on a MoE model: drop-free per-token routing keeps
    pipelining invisible there too (padding/batching independence is
    routing-exactness, ISSUE matrix × {dense, MoE})."""
    cfg = TransformerConfig(**{**CFG, "n_experts": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = Engine(params, cfg, n_slots=3, max_len=64, chunk=4,
                    prompt_buckets=(16,), prefix_cache_size=2)
    system = _prompt(40, 10, cfg.vocab_size)
    engine.set_pipeline_depth(1)
    serial, shapes = _matrix_workload(engine, cfg.vocab_size, system)
    engine.set_pipeline_depth(2)
    pipelined, _ = _matrix_workload(engine, cfg.vocab_size, system)
    assert pipelined == serial
    tokens, max_new = shapes[0]
    assert serial[0] == _oracle(params, cfg, tokens, max_new)


def test_exactness_spec_decode(setup):
    """Speculative engine (prompt-lookup drafting): pipelined == serial
    on echo prompts (high acceptance — multi-token emission rows) and a
    sampled request (the fold_in(base, counts+i) key-index chaining the
    pipelined dispatch must reproduce)."""
    cfg, params = setup
    engine = Engine(params, cfg, n_slots=2, max_len=64, chunk=4,
                    prompt_buckets=(16,), spec_decode=2)

    def workload():
        rids = [
            engine.submit(GenRequest(
                tokens=_echo_prompt(12, cfg.vocab_size), max_new_tokens=10,
            )),
            engine.submit(GenRequest(
                tokens=_prompt(50, 9, cfg.vocab_size), max_new_tokens=7,
                temperature=0.8, seed=11,
            )),
        ]
        engine.step()
        rids.append(engine.submit(GenRequest(
            tokens=_echo_prompt(8, cfg.vocab_size), max_new_tokens=6,
        )))
        results = engine.run()
        return [results[r] for r in rids]

    engine.set_pipeline_depth(1)
    serial = workload()
    engine.set_pipeline_depth(2)
    assert workload() == serial
    # Greedy echo row must equal the solo oracle through BOTH layers of
    # lag (speculative rejection + pipeline).
    assert serial[0] == _oracle(
        params, cfg, _echo_prompt(12, cfg.vocab_size), 10
    )


def test_exactness_spec_draft_model(setup):
    """Model-drafted speculation: the chained dispatch threads the
    draft cache's shared-lengths discipline too."""
    cfg, params = setup
    draft_cfg = TransformerConfig(**{**CFG, "d_model": 16, "n_layers": 1,
                                     "n_heads": 2, "d_ff": 32})
    draft_params = init_params(jax.random.PRNGKey(1), draft_cfg)
    engine = Engine(params, cfg, n_slots=2, max_len=64, chunk=2,
                    prompt_buckets=(16,), spec_decode=2,
                    draft_params=draft_params, draft_cfg=draft_cfg)
    req = dict(tokens=_prompt(60, 7, cfg.vocab_size), max_new_tokens=6)
    engine.set_pipeline_depth(1)
    rid0 = engine.submit(GenRequest(**req))
    serial = engine.run()[rid0]
    engine.set_pipeline_depth(2)
    rid = engine.submit(GenRequest(**req))
    assert engine.run()[rid] == serial == _oracle(
        params, cfg, req["tokens"], req["max_new_tokens"]
    )


def test_abort_quiesces_inflight_chunk(setup, dense_engine):
    """abort() with a chunk in flight: the in-flight handle is dropped,
    every request fails with the abort message, no slot leaks, and the
    engine keeps working afterwards (the donated-cache future stays
    consistent)."""
    cfg, params = setup
    engine = dense_engine
    rids = [
        engine.submit(GenRequest(
            tokens=_prompt(80 + i, 5, cfg.vocab_size), max_new_tokens=12,
        ))
        for i in range(2)
    ]
    engine.step()
    assert engine.stats()["inflight_dispatches"] == 1
    engine.abort("test abort")
    assert engine.stats()["inflight_dispatches"] == 0
    for rid in rids:
        with pytest.raises(RuntimeError, match="test abort"):
            engine.result(rid, timeout=0)
    assert engine.in_flight() == 0
    assert engine.stats()["free_slots"] == 3
    # Post-abort exactness: the engine is still serving correctly.
    tokens = _prompt(85, 6, cfg.vocab_size)
    rid = engine.submit(GenRequest(tokens=tokens, max_new_tokens=5))
    assert engine.run()[rid] == _oracle(params, cfg, tokens, 5)


def test_streaming_order_under_pipeline(setup, dense_engine):
    """Streaming callbacks stay ordered and complete under pipelining:
    per-token calls arrive in emission order, then exactly one
    (None, None) terminator, and the stream equals the stored result."""
    cfg, params = setup
    engine = dense_engine
    seen = []
    rid = engine.submit(
        GenRequest(tokens=_prompt(90, 7, cfg.vocab_size),
                   max_new_tokens=9),
        on_token=lambda t, lp: seen.append(t),
    )
    result = engine.run()[rid]
    assert seen == result + [None]
    engine.result(rid, timeout=0)  # consume


def test_embed_and_beam_hit_readback_accumulator(setup, dense_engine):
    """The attribution-leak fix: _embed_inner and _beam_inner route
    their readbacks through the accumulator, so a tunneled deployment's
    swing forensics see them in readbacks/readback_seconds."""
    cfg, params = setup
    engine = dense_engine
    before = engine.stats()["readbacks"]
    engine.embed(_prompt(91, 6, cfg.vocab_size))
    assert engine.stats()["readbacks"] == before + 1
    before_s = engine.stats()["readback_seconds"]
    engine.beam(_prompt(92, 5, cfg.vocab_size), max_new_tokens=3,
                beam_size=2)
    st = engine.stats()
    assert st["readbacks"] == before + 2
    assert st["readback_seconds"] >= before_s


def test_overlap_and_idle_accounting(setup, dense_engine):
    """The runbook's split, delta-measured on the shared (already warm,
    already used) engine: a serial phase accrues zero NEW overlap and
    positive device idle; flipped back to depth 2 the same engine
    accrues overlapped readback, the stats ratio stays positive, and
    the shared Prometheus gauges track the depth per engine."""
    cfg, params = setup
    engine = dense_engine
    label = engine._engine_label

    engine.set_pipeline_depth(1)
    before = engine.stats()
    rid = engine.submit(GenRequest(tokens=_prompt(95, 6, cfg.vocab_size),
                                   max_new_tokens=16))
    engine.run()
    st = engine.stats()
    assert st["overlap_seconds"] == before["overlap_seconds"]  # no new
    assert st["device_idle_seconds"] > before["device_idle_seconds"]
    assert st["pipeline_depth"] == 1
    assert _metrics.SERVE_PIPELINE_DEPTH.value(label) == 1.0
    assert st["dispatch_seconds"] > 0.0  # the dispatch-wait split exists
    assert st["readback_seconds"] > before["readback_seconds"]

    engine.set_pipeline_depth(2)
    rid2 = engine.submit(GenRequest(tokens=_prompt(96, 6, cfg.vocab_size),
                                    max_new_tokens=16))
    results = engine.run()
    st2 = engine.stats()
    assert st2["overlap_seconds"] > st["overlap_seconds"]
    assert st2["overlap_ratio"] > 0.0
    assert st2["pipeline_depth"] == 2
    assert _metrics.SERVE_PIPELINE_DEPTH.value(label) == 2.0
    assert _metrics.SERVE_OVERLAP_RATIO.value(label) > 0.0
    # Both runs' results intact (run() retains unfetched results).
    assert len(results[rid]) == 16 and len(results[rid2]) == 16


def test_no_admission_while_chunk_in_flight(setup, dense_engine):
    """The pipeline-boundary rule enforced inside _admit_wave: a
    submit() landing AFTER _step_inner's boundary check (empty queue
    seen, chunk left in flight) must wait one step rather than admit —
    the in-flight chunk still references every slot, and admitting
    into one would chain the new request onto the old occupant's token
    carry.  Simulated deterministically by calling _admit_wave directly
    with a chunk in flight, exactly the raced interleaving."""
    cfg, params = setup
    engine = dense_engine
    rid_a = engine.submit(GenRequest(tokens=_prompt(97, 6, cfg.vocab_size),
                                     max_new_tokens=12))
    engine.step()  # admit A, dispatch chunk 1, keep it in flight
    assert engine.in_flight() == 1
    rid_b = engine.submit(GenRequest(tokens=_prompt(98, 7, cfg.vocab_size),
                                     max_new_tokens=8))
    before = engine.stats()
    engine._admit_wave([0.0, 0.0])  # the raced post-boundary admit
    st = engine.stats()
    assert st["queued"] == before["queued"]  # B still queued
    assert st["active_slots"] == before["active_slots"]
    results = engine.run()  # next boundary admits B normally
    # Exactness vs the serial engine (same compiled programs — no
    # fresh oracle compile inside test-serve's 60 s budget).
    engine.set_pipeline_depth(1)
    rid_a2 = engine.submit(GenRequest(
        tokens=_prompt(97, 6, cfg.vocab_size), max_new_tokens=12))
    rid_b2 = engine.submit(GenRequest(
        tokens=_prompt(98, 7, cfg.vocab_size), max_new_tokens=8))
    sync = engine.run()
    engine.set_pipeline_depth(2)
    assert results[rid_a] == sync[rid_a2]
    assert results[rid_b] == sync[rid_b2]


def test_aux_readbacks_do_not_dilute_overlap_ratio(setup, dense_engine):
    """embed/beam fetch-wait lands in readback_seconds (the tunnel
    forensics) but NOT in overlap_ratio's denominator: an embed-heavy
    replica's ratio keeps reflecting its decode pipeline."""
    cfg, params = setup
    engine = dense_engine
    engine.submit(GenRequest(tokens=_prompt(99, 6, cfg.vocab_size),
                             max_new_tokens=12))
    engine.run()
    before = engine.stats()
    assert before["overlap_ratio"] > 0.0
    for i in range(3):
        engine.embed(_prompt(100 + i, 6, cfg.vocab_size))
    st = engine.stats()
    assert st["readback_seconds"] > before["readback_seconds"]
    assert st["overlap_ratio"] == before["overlap_ratio"]


def test_pipeline_depth_validation(setup, dense_engine):
    cfg, params = setup
    with pytest.raises(ValueError, match="pipeline_depth"):
        Engine(params, cfg, n_slots=1, max_len=16, pipeline_depth=3)
    with pytest.raises(ValueError, match="pipeline_depth"):
        dense_engine.set_pipeline_depth(0)
    assert dense_engine.info()["engine"]["pipeline_depth"] == 2


def test_tail_elision_skips_guaranteed_waste(setup, dense_engine):
    """When the chunk in flight already covers every active slot's
    remaining token budget, the chained dispatch would be 100%
    guaranteed waste (budget exhaustion is host-deterministic, unlike
    EOS) — the engine forces a boundary instead: same dispatch count
    as the serial engine, ``tail_elisions`` counts the skip, and the
    output is unchanged."""
    cfg, params = setup
    engine = dense_engine
    tokens = _prompt(110, 6, cfg.vocab_size)

    engine.set_pipeline_depth(1)
    before = engine.stats()
    rid_s = engine.submit(GenRequest(tokens=tokens, max_new_tokens=6))
    serial = engine.run()[rid_s]
    mid = engine.stats()
    assert mid["tail_elisions"] == before["tail_elisions"]  # serial: never
    steps_serial = mid["steps"] - before["steps"]

    engine.set_pipeline_depth(2)
    rid_p = engine.submit(GenRequest(tokens=tokens, max_new_tokens=6))
    pipelined = engine.run()[rid_p]
    st = engine.stats()
    assert pipelined == serial
    assert st["tail_elisions"] == mid["tail_elisions"] + 1
    # The elided dispatch is the whole point: without it the pipelined
    # run would cost one extra (wasted) chunk dispatch at the tail.
    assert st["steps"] - mid["steps"] == steps_serial


def test_drain_completes_inflight_chunk(setup, dense_engine):
    """drain() with a chunk in flight: the dispatch completes, nothing
    past EOS is emitted, and no slot leaks (in_flight() == 0, all slots
    free).  LAST in the module on purpose — draining is terminal, and
    reusing the shared engine here saves a compile set (make
    test-serve's 60 s budget)."""
    cfg, params = setup
    engine = dense_engine
    tokens = _prompt(70, 6, cfg.vocab_size)
    oracle = _oracle(params, cfg, tokens, 12)
    # EOS at the oracle's 5th token: lands mid-chunk, and with the
    # pipeline's one-chunk lag the engine decodes a full extra chunk
    # past it that must all be truncated.
    eos = oracle[4]
    rid = engine.submit(GenRequest(tokens=tokens, max_new_tokens=12,
                                   eos_id=eos))
    engine.step()  # admit + dispatch chunk 1; nothing processed yet
    assert engine.stats()["inflight_dispatches"] == 1
    engine.drain()
    with pytest.raises(Exception):  # DrainingError
        engine.submit(GenRequest(tokens=tokens, max_new_tokens=1))
    while engine.pending():
        engine.step()
    got = engine.result(rid, timeout=0)
    assert got == oracle[:5] and got[-1] == eos  # EOS included, nothing past
    assert engine.in_flight() == 0
    st = engine.stats()
    assert st["active_slots"] == 0
    assert st["free_slots"] == engine._cache.n_slots
    assert st["inflight_dispatches"] == 0
