"""Etcd-backed RegistryDB: the durable seam, filled.

≙ the etcd backend the reference planned behind RegistryDB but never
implemented (reference pkg/oim-registry/registry.go:31-41,
README.md:131-135).  EtcdRegistryDB speaks the real etcd v3 KV wire
subset; EtcdKVServer is the in-process etcd-compatible peer it is tested
against (BASELINE.json config 5: N controllers behind an etcd-backed
registry).
"""

from __future__ import annotations

import shutil
import socket
import subprocess
import time

import grpc
import pytest

from helpers import MockController

from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.registry import EtcdKVServer, EtcdRegistryDB, Registry
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2
from tests import procutil


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _RealEtcd:
    """A real ``etcd`` daemon when the binary exists (skip otherwise) —
    proving EtcdRegistryDB's v3 wire subset against the actual server,
    not just the in-process peer (≙ the reference's env-gated real-daemon
    tiers, test/test.make:1-16)."""

    def __init__(self, tmp_path):
        binary = shutil.which("etcd")
        if binary is None:
            pytest.skip("etcd binary not on PATH")
        port, peer = _free_port(), _free_port()
        self.target = f"127.0.0.1:{port}"
        self.proc = procutil.spawn(
            [
                binary,
                "--data-dir", str(tmp_path / "etcd-data"),
                "--listen-client-urls", f"http://{self.target}",
                "--advertise-client-urls", f"http://{self.target}",
                "--listen-peer-urls", f"http://127.0.0.1:{peer}",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        deadline = time.time() + 15
        while True:
            probe = socket.socket()
            try:
                probe.connect(("127.0.0.1", port))
                probe.close()
                break
            except OSError:
                probe.close()
                if self.proc.poll() is not None:
                    pytest.skip(
                        f"etcd exited rc={self.proc.returncode} at startup"
                    )
                if time.time() > deadline:
                    self.stop()
                    raise AssertionError("etcd never came up")
                time.sleep(0.1)

    def addr(self) -> str:
        # Duck-types NonBlockingGRPCServer.addr() for tests that re-dial.
        return f"tcp://{self.target}"

    def stop(self):
        procutil.stop(self.proc)


@pytest.fixture(params=["inprocess", "real"])
def etcd(request, tmp_path):
    if request.param == "real":
        daemon = _RealEtcd(tmp_path)
        try:
            db = EtcdRegistryDB(f"tcp://{daemon.target}")
        except BaseException:
            daemon.stop()
            raise
        yield None, daemon, db
        db.close()
        daemon.stop()
        return
    server = EtcdKVServer()
    srv = server.start_server("tcp://127.0.0.1:0")
    db = EtcdRegistryDB(str(srv.addr()))
    yield server, srv, db
    db.close()
    srv.stop()


def test_kv_roundtrip(etcd):
    _, _, db = etcd
    db.store("c1/address", "tcp://1.2.3.4:5")
    db.store("c1/pci", "0000:3f:")
    db.store("c2/address", "tcp://5.6.7.8:9")
    assert db.lookup("c1/address") == "tcp://1.2.3.4:5"
    assert db.lookup("missing") == ""
    assert db.keys("c1") == ["c1/address", "c1/pci"]
    assert db.items("c2") == [("c2/address", "tcp://5.6.7.8:9")]
    assert len(db.items("")) == 3
    db.store("c1/pci", "")  # empty value deletes
    assert db.lookup("c1/pci") == ""
    assert db.keys("c1") == ["c1/address"]


def test_prefix_is_segment_scoped(etcd):
    """Byte-prefix over-match must be filtered: "foo" ≠ "foo-bar"."""
    _, _, db = etcd
    db.store("foo/x", "1")
    db.store("foo-bar/y", "2")
    db.store("foo", "3")
    assert db.keys("foo") == ["foo", "foo/x"]


def test_survives_etcd_restart(etcd):
    """UNAVAILABLE triggers one redial, matching the per-operation
    resilience stance of the rest of the control plane."""
    server, srv, db = etcd
    if server is None:
        pytest.skip("same-port restart choreography needs the in-process peer")
    db.store("k", "v")
    addr = srv.addr()
    srv.stop()
    # Restart the KV service on the same port with the same store.
    srv2 = NonBlockingGRPCServer(str(addr))
    from oim_tpu.registry.etcd import ETCD_KV

    srv2.start(ETCD_KV.registrar(server))
    try:
        assert db.lookup("k") == "v"
        db.store("k2", "v2")
        assert db.lookup("k2") == "v2"
    finally:
        srv2.stop()


def test_registry_state_survives_registry_restart(etcd):
    """The registry process is stateless when etcd-backed: a replacement
    instance sees everything the old one stored."""
    _, srv, _ = etcd
    first = Registry(db=EtcdRegistryDB(str(srv.addr())))
    reg_srv = first.start_server("tcp://127.0.0.1:0")
    channel = grpc.insecure_channel(reg_srv.addr().grpc_target())
    REGISTRY.stub(channel).SetValue(
        oim_pb2.SetValueRequest(
            value=oim_pb2.Value(path="host-1/address", value="tcp://a:1")
        ),
        timeout=10,
    )
    channel.close()
    reg_srv.stop()
    first.db.close()

    second = Registry(db=EtcdRegistryDB(str(srv.addr())))
    reg_srv2 = second.start_server("tcp://127.0.0.1:0")
    channel = grpc.insecure_channel(reg_srv2.addr().grpc_target())
    try:
        reply = REGISTRY.stub(channel).GetValues(
            oim_pb2.GetValuesRequest(path="host-1"), timeout=10
        )
        assert [(v.path, v.value) for v in reply.values] == [
            ("host-1/address", "tcp://a:1")
        ]
    finally:
        channel.close()
        reg_srv2.stop()
        second.db.close()


def test_n_controllers_routed_through_etcd_backed_registry(etcd):
    """Config 5 shape: N controllers registered in the etcd-backed
    registry, proxy routing by controllerid metadata."""
    _, srv, db = etcd
    registry = Registry(db=db)
    reg_srv = registry.start_server("tcp://127.0.0.1:0")
    mocks = {}
    ctrl_srvs = []
    for cid in ["host-0", "host-1", "host-2"]:
        mock = MockController()
        ctrl_srv = NonBlockingGRPCServer("tcp://127.0.0.1:0")
        ctrl_srv.start(CONTROLLER.registrar(mock))
        db.store(f"{cid}/address", str(ctrl_srv.addr()))
        mocks[cid] = mock
        ctrl_srvs.append(ctrl_srv)
    channel = grpc.insecure_channel(reg_srv.addr().grpc_target())
    try:
        for cid in mocks:
            CONTROLLER.stub(channel).MapVolume(
                oim_pb2.MapVolumeRequest(volume_id=f"vol-{cid}"),
                metadata=(("controllerid", cid),),
                timeout=10,
            )
        for cid, mock in mocks.items():
            assert [r.volume_id for r in mock.requests] == [f"vol-{cid}"]
    finally:
        channel.close()
        reg_srv.stop()
        for s in ctrl_srvs:
            s.stop()


def test_registry_main_db_spec():
    from oim_tpu.cli.registry_main import make_db
    from oim_tpu.registry import MemRegistryDB

    assert isinstance(make_db(""), MemRegistryDB)
    db = make_db("etcd://127.0.0.1:2379")
    assert isinstance(db, EtcdRegistryDB)
    assert db.endpoint == "tcp://127.0.0.1:2379"


# ---------------------------------------------------------------------------
# Watch + Lease over the etcd v3 wire (the liveness layer; ≙ the etcd
# semantics the reference's RegistryDB seam was reserved for)


from helpers import wait_for as _wait_for


def test_watch_put_delete_events(etcd):
    _, _, db = etcd
    events: list[tuple[str, str]] = []
    cancel = db.watch("c1", lambda p, v: events.append((p, v)))
    try:
        db.store("c1/address", "tcp://a:1")
        db.store("c1-sibling/address", "tcp://b:2")  # byte-prefix overmatch
        assert _wait_for(lambda: ("c1/address", "tcp://a:1") in events)
        db.store("c1/address", "")
        assert _wait_for(lambda: ("c1/address", "") in events)
        # Segment scoping: the sibling key never arrives.
        assert all(p.startswith("c1/") for p, _ in events), events
    finally:
        cancel()
    n = len(events)
    db.store("c1/pci", "x")
    time.sleep(0.3)
    assert len(events) == n  # cancelled watch delivers nothing


def test_leased_key_expires_with_event(etcd):
    _, _, db = etcd
    events: list[tuple[str, str]] = []
    cancel = db.watch("c9", lambda p, v: events.append((p, v)))
    try:
        db.store("c9/address", "tcp://x:1", ttl=1)
        assert db.lookup("c9/address") == "tcp://x:1"
        # No refresh → the lease expires and etcd deletes the key,
        # emitting the DELETE watch event a crashed writer can't.
        assert _wait_for(lambda: db.lookup("c9/address") == "", timeout=15)
        assert _wait_for(lambda: ("c9/address", "") in events)
    finally:
        cancel()


def test_leased_key_survives_when_refreshed(etcd):
    _, _, db = etcd
    db.store("c8/address", "tcp://x:1", ttl=2)
    for _ in range(3):
        time.sleep(1.0)
        db.store("c8/address", "tcp://x:1", ttl=2)  # heartbeat refresh
    assert db.lookup("c8/address") == "tcp://x:1"
    db.store("c8/address", "")


def test_lease_grant_and_keepalive(etcd):
    _, _, db = etcd
    grant = db._grant(5)
    assert grant.ID != 0 and grant.TTL >= 5
    assert db.keepalive_once(grant.ID) >= 1
    # Unknown lease: keep-alive reports TTL 0 (etcd semantics).
    assert db.keepalive_once(987654321) == 0


def test_lease_revoke_deletes_attached_keys(etcd):
    from oim_tpu.registry.etcd import ETCD_LEASE
    from oim_tpu.spec.gen.etcd import rpc_pb2

    _, _, db = etcd
    grant = db._grant(60)
    from oim_tpu.registry.etcd import ETCD_KV

    db._call(
        lambda ch: ETCD_KV.stub(ch).Put(
            rpc_pb2.PutRequest(
                key=db._key("c7/address"), value=b"tcp://y:1", lease=grant.ID
            ),
            timeout=5,
        )
    )
    assert db.lookup("c7/address") == "tcp://y:1"
    events: list[tuple[str, str]] = []
    cancel = db.watch("c7", lambda p, v: events.append((p, v)))
    try:
        stub = ETCD_LEASE.stub(db._channel_get())
        stub.LeaseRevoke(rpc_pb2.LeaseRevokeRequest(ID=grant.ID), timeout=5)
        assert _wait_for(lambda: db.lookup("c7/address") == "")
        assert _wait_for(lambda: ("c7/address", "") in events)
    finally:
        cancel()


def test_duplicate_lease_grant_answers_without_deadlock(etcd):
    """Granting a lease ID that already exists must answer the
    duplicate error, not hang: the error response's header used to be
    built INSIDE the server's critical section, and ``_header()`` takes
    the same non-reentrant lock (concvet lock-order finding — the
    self-deadlock class).  The RPC timeout turns a regression into a
    DEADLINE_EXCEEDED failure instead of a wedged suite."""
    from oim_tpu.registry.etcd import ETCD_LEASE
    from oim_tpu.spec.gen.etcd import rpc_pb2

    _, _, db = etcd
    stub = ETCD_LEASE.stub(db._channel_get())
    first = stub.LeaseGrant(
        rpc_pb2.LeaseGrantRequest(ID=424242, TTL=60), timeout=5
    )
    assert first.ID == 424242 and not first.error
    dup = stub.LeaseGrant(
        rpc_pb2.LeaseGrantRequest(ID=424242, TTL=60), timeout=5
    )
    assert dup.error  # duplicate reported, server still answering
    assert db.keepalive_once(424242) >= 1  # lock released, lease intact


def test_put_with_unknown_lease_rejected(etcd):
    from oim_tpu.spec.gen.etcd import rpc_pb2

    _, _, db = etcd
    with pytest.raises(grpc.RpcError) as err:
        from oim_tpu.registry.etcd import ETCD_KV

        db._call(
            lambda ch: ETCD_KV.stub(ch).Put(
                rpc_pb2.PutRequest(
                    key=db._key("c6/x"), value=b"v", lease=123456789
                ),
                timeout=5,
            )
        )
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_two_registry_replicas_share_etcd_watch(etcd):
    """BASELINE config 5 (HA): two Registry replicas on ONE etcd — a
    SetValue through replica A reaches a WatchValues subscriber on
    replica B via the etcd Watch stream, and a leased key written
    through A expires for B's watchers too.  This is what makes the
    registry horizontally scalable: watchers may connect to any
    replica."""
    import threading

    from oim_tpu.registry import EtcdRegistryDB, Registry
    from oim_tpu.spec import REGISTRY, oim_pb2

    server, srv, _db = etcd
    endpoint = str(srv.addr())
    db_a, db_b = EtcdRegistryDB(endpoint), EtcdRegistryDB(endpoint)
    reg_a, reg_b = Registry(db=db_a), Registry(db=db_b)
    srv_a = reg_a.start_server("tcp://127.0.0.1:0")
    srv_b = reg_b.start_server("tcp://127.0.0.1:0")
    chan_a = grpc.insecure_channel(srv_a.addr().grpc_target())
    chan_b = grpc.insecure_channel(srv_b.addr().grpc_target())
    got: list[tuple[str, str]] = []
    try:
        call = REGISTRY.stub(chan_b).WatchValues(
            oim_pb2.WatchValuesRequest(path="ha", send_initial=True)
        )
        ready = threading.Event()

        def drain():
            try:
                for reply in call:
                    if reply.initial_done:
                        # The marker proves B's server-side subscription
                        # (and its etcd watch underneath) is LIVE — the
                        # only race-free "now write" signal.
                        ready.set()
                        continue
                    got.append((reply.value.path, reply.value.value))
            except grpc.RpcError:
                pass

        threading.Thread(target=drain, daemon=True).start()
        assert ready.wait(timeout=20), "B's watch stream never settled"
        REGISTRY.stub(chan_a).SetValue(
            oim_pb2.SetValueRequest(
                value=oim_pb2.Value(path="ha/x/address", value="tcp://x:1"),
                ttl_seconds=1,
            ),
            timeout=5,
        )
        assert _wait_for(lambda: ("ha/x/address", "tcp://x:1") in got), got
        # The lease (held in etcd, not in either replica) expires the
        # key; B's watcher sees the DELETE without A doing anything.
        assert _wait_for(
            lambda: ("ha/x/address", "") in got, timeout=15
        ), got
        # Reads through either replica agree.
        reply = REGISTRY.stub(chan_a).GetValues(
            oim_pb2.GetValuesRequest(path="ha"), timeout=5
        )
        assert len(reply.values) == 0
        call.cancel()
    finally:
        chan_a.close()
        chan_b.close()
        srv_a.stop()
        srv_b.stop()
        reg_a.close()
        reg_b.close()
        db_a.close()
        db_b.close()


def test_watch_storm_converges_over_wire(etcd):
    """The in-process storm (tests/test_registry.py), through the etcd
    v3 wire: 4 writer threads × stores/deletes/leases while a client
    watch replays events into a view that must converge to the final KV
    state.  Exercises the server-side event queue ordering AND the
    client watch delivery path under real concurrency."""
    import random
    import threading

    _, _, db = etcd
    view: dict[str, str] = {}
    view_lock = threading.Lock()

    def replay(path: str, value: str) -> None:
        with view_lock:
            if value == "":
                view.pop(path, None)
            else:
                view[path] = value

    cancel = db.watch("storm", replay)
    keys = [f"storm/k{i}/address" for i in range(4)]
    try:
        def worker(seed: int) -> None:
            rng = random.Random(seed)
            for n in range(40):
                key = rng.choice(keys)
                op = rng.random()
                if op < 0.55:
                    db.store(key, f"v{seed}-{n}")
                elif op < 0.8:
                    db.store(key, "")
                else:
                    db.store(key, f"leased{seed}-{n}", ttl=1)

        threads = [
            threading.Thread(target=worker, args=(s,)) for s in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        def converged() -> bool:
            state = dict(db.items("storm"))
            with view_lock:  # replay() still fires on lease expiries
                return state == view

        assert _wait_for(converged, timeout=20), (
            f"db={dict(db.items('storm'))}\nview={view}"
        )
    finally:
        cancel()
        for key in keys:
            db.store(key, "")
