"""Pallas-op tests (interpreter mode on CPU) against plain-JAX oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oim_tpu.ops import (
    apply_rope,
    flash_attention,
    reference_attention,
    reference_rmsnorm,
    rmsnorm,
)


class TestRMSNorm:
    @pytest.mark.parametrize("shape", [(4, 64), (2, 3, 128), (300, 64)])
    def test_matches_reference(self, shape):
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, shape)
        w = jax.random.normal(jax.random.PRNGKey(1), (shape[-1],)) + 1.0
        np.testing.assert_allclose(
            np.asarray(rmsnorm(x, w)),
            np.asarray(reference_rmsnorm(x, w)),
            rtol=1e-5,
            atol=1e-5,
        )

    def test_mixed_dtype_bf16_x_f32_w(self):
        """bf16 activations with f32 params — the training configuration;
        forward dtype and backward cotangent types must line up."""
        x = jax.random.normal(jax.random.PRNGKey(5), (8, 32)).astype(jnp.bfloat16)
        w = jnp.ones((32,), jnp.float32)
        out = rmsnorm(x, w)
        assert out.dtype == jnp.bfloat16
        grads = jax.grad(
            lambda x, w: jnp.sum(rmsnorm(x, w).astype(jnp.float32) ** 2), (0, 1)
        )(x, w)
        assert grads[0].dtype == jnp.bfloat16
        assert grads[1].dtype == jnp.float32

    def test_gradients(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 32))
        w = jnp.ones((32,))

        g_kernel = jax.grad(lambda x, w: jnp.sum(rmsnorm(x, w) ** 2), (0, 1))(x, w)
        g_ref = jax.grad(
            lambda x, w: jnp.sum(reference_rmsnorm(x, w) ** 2), (0, 1)
        )(x, w)
        for a, b in zip(g_kernel, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_blocked(self, causal):
        b, t, h, d = 2, 256, 2, 32
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(key, (b, t, h, d)) for key in keys)
        out = flash_attention(q, k, v, causal, 128, 128)
        expected = reference_attention(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_ragged_falls_back(self):
        b, t, h, d = 1, 48, 2, 16  # 48 not divisible by 128
        keys = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(key, (b, t, h, d)) for key in keys)
        out = flash_attention(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(reference_attention(q, k, v, True)),
            rtol=2e-5,
            atol=2e-5,
        )

    def test_gradients(self):
        b, t, h, d = 1, 128, 2, 16
        q = jax.random.normal(jax.random.PRNGKey(3), (b, t, h, d))

        def loss_flash(q):
            return jnp.sum(flash_attention(q, q, q, True, 64, 64) ** 2)

        def loss_ref(q):
            return jnp.sum(reference_attention(q, q, q, True) ** 2)

        np.testing.assert_allclose(
            np.asarray(jax.grad(loss_flash)(q)),
            np.asarray(jax.grad(loss_ref)(q)),
            rtol=1e-4,
            atol=1e-4,
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_fused_backward_distinct_qkv(self, causal):
        """The fused dq/dk/dv kernels against the reference VJP with three
        independent inputs (the self-attention test above cannot tell a
        dq↔dk mix-up apart)."""
        b, t, h, d = 2, 256, 2, 32
        keys = jax.random.split(jax.random.PRNGKey(7), 4)
        q, k, v = (jax.random.normal(key, (b, t, h, d)) for key in keys[:3])
        g = jax.random.normal(keys[3], (b, t, h, d))

        def run(attn):
            out, vjp = jax.vjp(lambda q, k, v: attn(q, k, v), q, k, v)
            return vjp(g)

        got = run(lambda q, k, v: flash_attention(q, k, v, causal, 128, 128))
        want = run(lambda q, k, v: reference_attention(q, k, v, causal))
        for name, a, b_ in zip("qkv", got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_gradients_auto_blocks(self):
        """Default (auto-tuned) block sizes through the fused backward —
        regression for the 0-sentinel reaching the bwd grid division."""
        b, t, h, d = 1, 256, 2, 16
        q = jax.random.normal(jax.random.PRNGKey(11), (b, t, h, d))
        got = jax.grad(lambda q: jnp.sum(flash_attention(q, q, q) ** 2))(q)
        want = jax.grad(
            lambda q: jnp.sum(reference_attention(q, q, q) ** 2)
        )(q)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_forward_lse_matches_logsumexp(self):
        """The saved logsumexp (what the backward recomputes p from) must
        equal the true row logsumexp of the scaled, masked scores."""
        from oim_tpu.ops.flash_attention import _forward

        b, t, h, d = 1, 256, 2, 32
        keys = jax.random.split(jax.random.PRNGKey(9), 3)
        q, k, v = (jax.random.normal(key, (b, t, h, d)) for key in keys)
        _, lse = _forward(q, k, v, True, 128, 128)
        assert lse is not None and lse.shape == (b * h, t, 8)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k
        ).astype(jnp.float32) / (d**0.5)
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask, scores, -1e30)
        want = jax.nn.logsumexp(scores, axis=-1).reshape(b * h, t)
        np.testing.assert_allclose(
            np.asarray(lse[..., 0]), np.asarray(want), rtol=1e-5, atol=1e-5
        )


class TestRope:
    def test_rotation_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 32))
        positions = jnp.broadcast_to(jnp.arange(16), (2, 16))
        rotated = apply_rope(x, positions)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(rotated), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            rtol=1e-5,
        )

    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 2, 16))
        rotated = apply_rope(x, jnp.zeros((1, 1), dtype=jnp.int32))
        np.testing.assert_allclose(np.asarray(rotated), np.asarray(x), rtol=1e-6)

    def test_relative_shift_invariance(self):
        """RoPE scores depend only on relative positions: q·k at (p, p+Δ) is
        the same for any p — the property ring attention relies on when
        passing global offsets."""
        d = 32
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, d))
        k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, d))

        def score(p):
            qr = apply_rope(q, jnp.array([[p]]))
            kr = apply_rope(k, jnp.array([[p + 5]]))
            return float(jnp.sum(qr * kr))

        assert abs(score(0) - score(117)) < 1e-3


class TestFlashAttentionGQA:
    """Grouped-query attention through the pallas kernels."""

    def _qkv(self, b=2, t=256, h=8, kvh=2, d=16, seed=7):
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        return (
            jax.random.normal(ks[0], (b, t, h, d)),
            jax.random.normal(ks[1], (b, t, kvh, d)),
            jax.random.normal(ks[2], (b, t, kvh, d)),
            jax.random.normal(ks[3], (b, t, h, d)),
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_oracle(self, causal):
        q, k, v, _ = self._qkv()
        out = flash_attention(q, k, v, causal, 128, 128)
        want = reference_attention(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_fused_backward_matches_oracle(self, causal):
        q, k, v, g = self._qkv()

        def run(attn):
            _, vjp = jax.vjp(lambda q, k, v: attn(q, k, v), q, k, v)
            return vjp(g)

        got = run(lambda q, k, v: flash_attention(q, k, v, causal, 128, 128))
        want = run(lambda q, k, v: reference_attention(q, k, v, causal))
        assert got[1].shape == k.shape  # dk stays kv-headed
        for name, a, b_ in zip("qkv", got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4,
                err_msg=f"d{name}",
            )

    def test_group_equals_repeated_kv(self):
        """GQA through the kernel ≡ MHA with explicitly repeated K/V."""
        q, k, v, _ = self._qkv()
        group = q.shape[2] // k.shape[2]
        gqa = flash_attention(q, k, v, True, 128, 128)
        mha = flash_attention(
            q,
            jnp.repeat(k, group, axis=2),
            jnp.repeat(v, group, axis=2),
            True, 128, 128,
        )
        np.testing.assert_array_equal(np.asarray(gqa), np.asarray(mha))

    def test_bad_group_rejected(self):
        q, k, v, _ = self._qkv(h=6, kvh=4)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, True, 128, 128)


class TestInt8Quant:
    """ops/quant.py: per-vector symmetric int8 for the KV cache."""

    def test_roundtrip_error_bounded(self):
        import jax
        import jax.numpy as jnp

        from oim_tpu.ops.quant import dequantize_int8, quantize_int8

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 64))
        q, scale = quantize_int8(x)
        assert q.dtype == jnp.int8
        assert scale.shape == (4, 16)
        err = jnp.abs(dequantize_int8(q, scale) - x)
        # Rounding error is at most half a quantization step per element.
        assert float(jnp.max(err - scale[..., None] / 2)) <= 1e-6

    def test_zero_vector_safe(self):
        import jax.numpy as jnp

        from oim_tpu.ops.quant import dequantize_int8, quantize_int8

        q, scale = quantize_int8(jnp.zeros((2, 8)))
        out = dequantize_int8(q, scale)
        assert not bool(jnp.any(jnp.isnan(out)))
        assert float(jnp.abs(out).max()) == 0.0

    def test_extreme_values_use_full_range(self):
        import jax.numpy as jnp

        from oim_tpu.ops.quant import quantize_int8

        q, _ = quantize_int8(jnp.asarray([[1000.0, -1000.0, 0.5]]))
        assert int(q[0, 0]) == 127 and int(q[0, 1]) == -127


class TestFusedLinearCE:
    """Vocab-tiled fused unembed+CE vs the materialized-logits oracle."""

    def _data(self, n=64, d=128, v=384, dtype=jnp.bfloat16, seed=0):
        from oim_tpu.ops import reference_linear_ce  # noqa: F401 (re-export)

        x = jax.random.normal(jax.random.PRNGKey(seed), (n, d), dtype)
        w = (
            jax.random.normal(jax.random.PRNGKey(seed + 1), (d, v)) * 0.05
        ).astype(jnp.float32)
        labels = jax.random.randint(jax.random.PRNGKey(seed + 2), (n,), 0, v)
        return x, w, labels

    @pytest.mark.parametrize("n,v", [(64, 384), (32, 128), (256, 640)])
    def test_forward_matches_oracle(self, n, v):
        from oim_tpu.ops import fused_linear_ce, reference_linear_ce

        x, w, labels = self._data(n=n, v=v)
        nll = fused_linear_ce(x, w, labels)
        ref = reference_linear_ce(x, w.astype(x.dtype), labels)
        assert nll.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(nll), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_gradients_match_oracle(self):
        from oim_tpu.ops import fused_linear_ce, reference_linear_ce

        x, w, labels = self._data()
        # Non-uniform per-token weights: the real loss masks invalid
        # positions, so the vjp must honor a per-row cotangent.
        rows = jax.random.uniform(jax.random.PRNGKey(9), (x.shape[0],))

        def loss(fn, x_, w_):
            return jnp.sum(fn(x_, w_, labels) * rows)

        dx, dw = jax.grad(
            lambda x_, w_: loss(fused_linear_ce, x_, w_), argnums=(0, 1)
        )(x, w)
        dxr, dwr = jax.grad(
            lambda x_, w_: loss(
                lambda a, b, l: reference_linear_ce(a, b.astype(a.dtype), l),
                x_,
                w_,
            ),
            argnums=(0, 1),
        )(x, w)
        assert dx.dtype == x.dtype and dw.dtype == w.dtype
        # dx/dw ride bf16 MXU operands inside the kernel; the oracle's
        # dlogits stay f32 — tolerance covers that rounding, nothing else.
        np.testing.assert_allclose(
            np.asarray(dx, np.float32),
            np.asarray(dxr, np.float32),
            atol=2e-3,
        )
        np.testing.assert_allclose(
            np.asarray(dw), np.asarray(dwr), atol=2e-3, rtol=1e-2
        )

    def test_ragged_falls_back(self):
        """Shapes the tiling can't cover (vocab not a multiple of 128,
        odd row counts) must still be exact via the XLA fallback."""
        from oim_tpu.ops import fused_linear_ce, reference_linear_ce

        x, w, labels = self._data(n=33, v=100)
        nll = fused_linear_ce(x, w, labels)
        ref = reference_linear_ce(x, w.astype(x.dtype), labels)
        np.testing.assert_allclose(
            np.asarray(nll), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
        dx, dw = jax.grad(
            lambda x_, w_: jnp.sum(fused_linear_ce(x_, w_, labels)),
            argnums=(0, 1),
        )(x, w)
        assert dx.shape == x.shape and dw.shape == w.shape

    def test_label_on_tile_boundary(self):
        """Labels at vocab-tile edges (0, block_v-1, block_v, V-1) hit the
        masked-sum target accumulation exactly once each."""
        from oim_tpu.ops import fused_linear_ce, reference_linear_ce

        x, w, _ = self._data(n=8, v=384)
        labels = jnp.asarray([0, 127, 128, 255, 256, 383, 1, 382])
        nll = fused_linear_ce(x, w, labels, 8, 128)
        ref = reference_linear_ce(x, w.astype(x.dtype), labels)
        np.testing.assert_allclose(
            np.asarray(nll), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_online_lse_extreme_scores(self):
        """Large-magnitude logits: the online max/denominator must stay
        finite where a naive sum-of-exp would overflow."""
        from oim_tpu.ops import fused_linear_ce, reference_linear_ce

        x, w, labels = self._data(n=16, v=256)
        w = w * 400.0  # logits in the hundreds
        nll = fused_linear_ce(x, w, labels, 16, 128)
        ref = reference_linear_ce(x, w.astype(x.dtype), labels)
        assert bool(jnp.all(jnp.isfinite(nll)))
        np.testing.assert_allclose(
            np.asarray(nll), np.asarray(ref), rtol=1e-4, atol=1e-3
        )

    def test_explicit_bad_blocks_rejected(self):
        """Explicit block sizes that cannot tile the array must raise —
        a silent grid truncation would skip rows/vocab columns."""
        from oim_tpu.ops import fused_linear_ce

        x, w, labels = self._data(n=33, v=384)
        with pytest.raises(ValueError, match="block_n"):
            fused_linear_ce(x, w, labels, 8, 128)  # 33 % 8 != 0
        x, w, labels = self._data(n=32, v=384)
        with pytest.raises(ValueError, match="block_v"):
            fused_linear_ce(x, w, labels, 8, 100)  # not lane-aligned
