"""Shared device-plane protocol suite, run against BOTH implementations:

- the in-process Python fake (oim_tpu/agent/fake.py), and
- the compiled C++ daemon (native/tpu-agent), spawned as a subprocess with a
  CmdMonitor watching it (≙ the reference spawning real SPDK for its tier-3
  tests, reference test/pkg/spdk/spdk.go:84-278).

This is the analog of the reference's SPDK client round-trip tests
(pkg/spdk/spdk_test.go) with the added guarantee that fake and native agree.
"""

import json
import random
import socket
import subprocess
import time

import pytest

from oim_tpu import agent as agent_mod
from oim_tpu.agent import Agent, AgentError, FakeAgentServer, ChipStore
from oim_tpu.common.cmdmonitor import CmdMonitor
from tests import procutil

NATIVE_BINARY = "native/tpu-agent/tpu-agent"


def _build_native():
    import os

    result = subprocess.run(
        ["make", "-C", "native/tpu-agent"], capture_output=True, text=True
    )
    return result.returncode == 0 and os.path.exists(NATIVE_BINARY)


@pytest.fixture(scope="session")
def native_built():
    return _build_native()


@pytest.fixture(params=["python", "native"])
def agent_socket(request, tmp_path, native_built):
    """Yields the socket path of a 2x2x2 v5p agent in fake-chip mode."""
    sock = str(tmp_path / "agent.sock")
    if request.param == "python":
        store = ChipStore(mesh=(2, 2, 2), device_dir=str(tmp_path))
        server = FakeAgentServer(store, sock).start()
        yield sock
        server.stop()
    else:
        if not native_built:
            pytest.skip("native tpu-agent not built")
        monitor = CmdMonitor()
        proc = procutil.spawn(
            [
                NATIVE_BINARY,
                "--socket", sock,
                "--fake-chips", "8",
                "--mesh", "2x2x2",
                "--state-dir", str(tmp_path),
            ],
            pass_fds=[monitor.child_fd],
            close_fds=True,
            stderr=subprocess.PIPE,
        )
        monitor.after_spawn()
        deadline = time.time() + 10
        while True:
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.connect(sock)
                probe.close()
                break
            except OSError:
                probe.close()
            assert not monitor.dead(0.05), proc.stderr.read().decode()
            assert time.time() < deadline, "agent socket never came up"
        yield sock
        procutil.stop(proc)


def test_topology_and_chips(agent_socket):
    with Agent(agent_socket) as a:
        topo = a.get_topology()
        assert topo["mesh"] == [2, 2, 2]
        assert topo["chip_count"] == 8
        assert topo["free_chips"] == 8
        assert topo["accel_type"] == "v5p"
        chips = a.get_chips()
        assert len(chips) == 8
        assert chips[0]["device_path"].endswith("accel0")
        assert chips[0]["phys_coord"] == [0, 0, 0]
        assert chips[7]["phys_coord"] == [1, 1, 1]
        assert all(c["allocation"] == "" for c in chips)


def test_allocation_lifecycle(agent_socket):
    with Agent(agent_socket) as a:
        alloc = a.create_allocation("vol-1", 4)
        # Compact deterministic placement: 1x2x2 box at the origin.
        assert alloc["mesh"] == [1, 2, 2]
        assert [c["chip_id"] for c in alloc["chips"]] == [0, 1, 2, 3]
        assert [c["coord"] for c in alloc["chips"]] == [
            [0, 0, 0], [0, 0, 1], [0, 1, 0], [0, 1, 1],
        ]
        assert alloc["attached"] is False

        # Idempotent re-create returns the same allocation.
        again = a.create_allocation("vol-1", 4)
        assert [c["chip_id"] for c in again["chips"]] == [0, 1, 2, 3]

        # Same name, different size → EEXIST.
        with pytest.raises(AgentError) as err:
            a.create_allocation("vol-1", 2)
        assert err.value.code == agent_mod.EEXIST

        # Free chips shrink; second allocation lands on the other half.
        assert a.get_topology()["free_chips"] == 4
        second = a.create_allocation("vol-2", 4)
        assert [c["chip_id"] for c in second["chips"]] == [4, 5, 6, 7]

        # Now the store is full.
        with pytest.raises(AgentError) as err:
            a.create_allocation("vol-3", 1)
        assert err.value.code == agent_mod.ENOSPC

        a.delete_allocation("vol-2")
        assert a.get_topology()["free_chips"] == 4
        assert [al["name"] for al in a.get_allocations()] == ["vol-1"]
        assert a.find_allocation("vol-2") is None

        with pytest.raises(AgentError) as err:
            a.delete_allocation("vol-2")
        assert err.value.code == agent_mod.ENODEV


def test_attach_detach(agent_socket):
    with Agent(agent_socket) as a:
        a.create_allocation("vol-1", 2)
        attached = a.attach_allocation("vol-1")
        assert attached["attached"] is True
        port = attached["coordinator_port"]
        assert port >= 8476

        # Idempotent attach keeps the port.
        assert a.attach_allocation("vol-1")["coordinator_port"] == port

        # A second attached allocation gets a different port.
        a.create_allocation("vol-2", 2)
        assert a.attach_allocation("vol-2")["coordinator_port"] != port

        # Attached allocations cannot be deleted (EBUSY), detach first.
        with pytest.raises(AgentError) as err:
            a.delete_allocation("vol-1")
        assert err.value.code == agent_mod.EBUSY
        a.detach_allocation("vol-1")
        a.delete_allocation("vol-1")

        with pytest.raises(AgentError) as err:
            a.attach_allocation("ghost")
        assert err.value.code == agent_mod.ENODEV


def test_provisioned_flag(agent_socket):
    with Agent(agent_socket) as a:
        pre = a.create_allocation("pre", 2, provisioned=True)
        assert pre["provisioned"] is True
        on_demand = a.create_allocation("od", 2)
        assert on_demand["provisioned"] is False
        # Idempotent re-create does not change the origin flag.
        assert a.create_allocation("pre", 2)["provisioned"] is True


def test_explicit_topology(agent_socket):
    with Agent(agent_socket) as a:
        alloc = a.create_allocation("vol-t", 4, topology=[2, 2, 1])
        assert alloc["mesh"] == [2, 2, 1]
        with pytest.raises(AgentError) as err:
            a.create_allocation("vol-bad", 4, topology=[3, 1, 1])
        assert err.value.code == -32602


def test_topology_rank_padding(agent_socket):
    """TPU topology convention: a lower-rank topology request is
    trailing-1-padded against the host mesh — "2x2" on a 2x2x2 host
    allocates a 2x2x1 sub-mesh (the gke-tpu dialect writes 2D
    topologies; ≙ chip_store.cc / fake.py padding)."""
    with Agent(agent_socket) as a:
        alloc = a.create_allocation("vol-2d", 4, topology=[2, 2])
        assert alloc["mesh"] == [2, 2, 1]
        assert len(alloc["chips"]) == 4
        # Still a real contiguity constraint: an impossible padded shape
        # ([3] → 3x1x1 does not fit a 2-wide axis) fails ENOSPC, not
        # silently linear.
        with pytest.raises(AgentError) as err:
            a.create_allocation("vol-3d-bad", 3, topology=[3])
        assert err.value.code == -28


def test_fragmentation_fallback(agent_socket):
    with Agent(agent_socket) as a:
        # Pin two chips so no 2x2x2-box-free region of 4 in one plane exists.
        a.create_allocation("pin-a", 1)  # chip 0
        a.create_allocation("pin-b", 1, topology=[1, 1, 1])  # chip 1
        alloc = a.create_allocation("vol-f", 4)
        # A 1x2x2 box still fits at x=1 → compact placement preferred.
        assert alloc["mesh"] == [1, 2, 2]
        assert [c["chip_id"] for c in alloc["chips"]] == [4, 5, 6, 7]
        # Now only chips 2,3 are free; a request for 2 fits a 1x1x2 box.
        assert a.create_allocation("vol-g", 2)["mesh"] == [1, 1, 2]


def test_linear_fallback_when_no_box_fits(agent_socket):
    with Agent(agent_socket) as a:
        # Occupy chips so the 3 remaining free ones never form a box.
        a.create_allocation("a", 1)  # chip 0
        a.create_allocation("b", 4, topology=[1, 2, 2])  # chips 4..7
        # Free: 1,2,3 — no 1x1x3 or 3-box exists in a 2x2x2 mesh.
        alloc = a.create_allocation("c", 3)
        assert alloc["mesh"] == [3]
        assert [c["chip_id"] for c in alloc["chips"]] == [1, 2, 3]


class TestFindChipsTopologyPadding:
    """Direct ChipStore coverage of the `_find_chips` trailing-1 padding
    (`padded = topology + (1,)*...`) — the placement arithmetic itself,
    below the wire (Python implementation; the shared socket suite above
    holds both daemons to the observable behavior)."""

    def test_2d_request_on_3d_host_pads_trailing_one(self):
        store = ChipStore(mesh=(2, 2, 2))
        ids, mesh = store._find_chips(4, (2, 2))
        assert mesh == (2, 2, 1)  # padded to host rank
        # The padded box is contiguous at the origin, in mesh order.
        assert ids == [0, 2, 4, 6]  # coords (x,y,0) for x,y in {0,1}
        coords = [store.chips[i].phys_coord for i in ids]
        assert coords == [(0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)]

    def test_1d_request_on_3d_host(self):
        store = ChipStore(mesh=(2, 2, 2))
        ids, mesh = store._find_chips(2, (2,))
        assert mesh == (2, 1, 1)
        assert [store.chips[i].phys_coord for i in ids] == [
            (0, 0, 0), (1, 0, 0),
        ]

    def test_padded_shape_exceeding_an_axis_is_enospc(self):
        store = ChipStore(mesh=(2, 2, 2))
        with pytest.raises(Exception) as err:
            store._find_chips(3, (3,))  # 3x1x1 cannot fit a 2-wide axis
        assert getattr(err.value, "code", None) == agent_mod.ENOSPC

    def test_fragmented_free_set_is_enospc_for_explicit_topology(self):
        """Diagonal fragmentation: two chips free but no contiguous
        padded sub-mesh.  An EXPLICIT topology must fail ENOSPC (the
        caller asked for that ICI shape — no silent linear fallback),
        while the same free set still satisfies a shapeless request via
        the fallback."""
        store = ChipStore(mesh=(2, 2, 1))
        # Occupy the (0,0,0)/(1,1,0) diagonal: free = chips 1,2 — every
        # x-pair (0-2, 1-3) and y-pair (0-1, 2-3) has one chip taken.
        store.chips[0].allocation = "pin"
        store.chips[3].allocation = "pin"
        for topo in ((2,), (1, 2), (2, 1)):
            with pytest.raises(Exception) as err:
                store._find_chips(2, topo)
            assert getattr(err.value, "code", None) == agent_mod.ENOSPC, topo
            assert "sub-mesh" in str(err.value)
        # Shapeless request, same free set: linear fallback succeeds.
        ids, mesh = store._find_chips(2, None)
        assert ids == [1, 2]
        assert mesh == (2,)

    def test_padding_noop_on_full_rank_and_oversized_rank(self):
        store = ChipStore(mesh=(2, 2, 1))
        ids, mesh = store._find_chips(4, (2, 2, 1))
        assert mesh == (2, 2, 1) and len(ids) == 4
        # A topology of HIGHER rank than the host mesh can never match a
        # candidate shape → ENOSPC (not a crash, not silent truncation).
        with pytest.raises(Exception) as err:
            store._find_chips(4, (2, 2, 1, 1))
        assert getattr(err.value, "code", None) == agent_mod.ENOSPC


def test_wire_errors(agent_socket):
    """Raw-socket probes of the framing layer."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(agent_socket)
    f = s.makefile("rb")

    def send(line: bytes) -> dict:
        s.sendall(line + b"\n")
        return json.loads(f.readline())

    # Parse error.
    resp = send(b"this is not json")
    assert resp["error"]["code"] == -32700

    # Valid JSON, not a JSON-RPC request.
    resp = send(b'{"id": 7, "jsonrpc": "1.0"}')
    assert resp["error"]["code"] == -32600
    assert resp["id"] == 7

    # Unknown method.
    resp = send(b'{"jsonrpc": "2.0", "id": 8, "method": "explode"}')
    assert resp["error"]["code"] == -32601

    # Non-object params.
    resp = send(b'{"jsonrpc": "2.0", "id": 9, "method": "get_chips", "params": [1]}')
    assert resp["error"]["code"] == -32602

    s.close()


def test_get_pjrt_info_always_served(agent_socket):
    """Both implementations serve get_pjrt_info; {} without a plugin."""
    with Agent(agent_socket) as agent:
        info = agent.get_pjrt_info()
        assert isinstance(info, dict)
        assert info == {}  # fixtures start without a PJRT plugin


def test_fuzz_storm_never_kills_daemon(agent_socket):
    """Fuzz hardening for the device-plane daemon: a storm of random
    bytes, truncated frames, abrupt disconnects, oversized garbage, and
    schema-violating JSON must never crash it — every well-formed line
    gets an error response, and a clean request still works afterwards
    (the reference's device daemon survives arbitrary socket abuse the
    same way; its control socket is a root-owned attack surface)."""
    rng = random.Random(20260730)

    corpus = [
        b"",                                   # empty line
        b"\x00\xff\xfe\x01" * 16,              # binary garbage
        b"{" * 512,                            # nested open braces
        b'{"jsonrpc": "2.0"',                  # truncated JSON
        b'{"jsonrpc": "2.0", "id": null, "method": 3}',
        b'{"jsonrpc": "2.0", "id": [1], "method": "get_chips"}',
        b'{"jsonrpc": "2.0", "id": 1, "method": "create_allocation", '
        b'"params": {"chip_count": -5}}',
        b'{"jsonrpc": "2.0", "id": 1, "method": "create_allocation", '
        b'"params": {"chip_count": 999999999999}}',
        b'{"jsonrpc": "2.0", "id": 1, "method": "attach_allocation", '
        b'"params": {"name": "' + b"A" * 4096 + b'"}}',
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": "get_chips",
                    "params": {"deep": [[[[[0] * 64]]]]}}).encode(),
    ]
    for _ in range(60):
        corpus.append(bytes(rng.randrange(32, 127) for _ in range(
            rng.randrange(1, 200))))

    probe = (
        b'{"jsonrpc": "2.0", "id": 777, "method": "get_chips"}\n'
    )
    for i, payload in enumerate(corpus):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(5)
        s.connect(agent_socket)
        s.sendall(payload + b"\n")
        if rng.random() < 0.3:
            s.close()  # abrupt disconnect mid-conversation
            continue
        # Liveness probe on the SAME connection: whatever the daemon did
        # with the garbage (error reply or blank-line skip), the
        # connection must stay up and answer a clean request — a daemon
        # that hangs up or goes silent on garbage fails.
        f = s.makefile("rb")
        answered = False
        try:
            s.sendall(probe)
            for line in f:  # garbage replies (if any), then the probe's
                resp = json.loads(line)
                assert "error" in resp or "result" in resp, (i, resp)
                if resp.get("id") == 777:
                    assert "result" in resp, (i, resp)
                    answered = True
                    break
        except (TimeoutError, ConnectionResetError, BrokenPipeError) as exc:
            raise AssertionError(
                f"payload {i} wedged the connection "
                f"({type(exc).__name__}): {payload[:60]!r}"
            )
        finally:
            f.close()  # the makefile dups the fd; leaking it would keep
            s.close()  # old connections alive server-side
        assert answered, (
            f"payload {i} made the daemon drop the connection without "
            f"answering the probe: {payload[:60]!r}"
        )

    # The daemon survived the storm: a clean request round-trips.
    with Agent(agent_socket) as agent:
        chips = agent.get_chips()
        assert len(chips) == 8


def test_stop_joins_accept_loop(tmp_path):
    """stop() joins the accept loop (oimlint resource-lifecycle harvest):
    returning while serve_forever is still winding down raced same-path
    restarts into two servers briefly owning one socket path."""
    store = ChipStore(mesh=(2, 1, 1), device_dir=str(tmp_path))
    server = FakeAgentServer(store, str(tmp_path / "join.sock")).start()
    thread = server._thread
    assert thread is not None and thread.is_alive()
    server.stop()
    assert server._thread is None
    assert not thread.is_alive()
