"""Spec pipeline sync + service descriptor round-trip.

The sync check is the pytest analog of the reference's CI diff enforcing
spec.md ↔ oim.proto consistency (reference Makefile:85-116).
"""

import subprocess
import sys

import grpc
import pytest

from oim_tpu import spec
from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.spec import oim_pb2


def test_spec_in_sync_with_proto():
    result = subprocess.run(
        [sys.executable, "tools/extract_proto.py", "--check"],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr


def test_method_paths_canonical():
    assert spec.REGISTRY.method_path("SetValue") == "/oim.v1.Registry/SetValue"
    assert spec.CSI_NODE.method_path("NodeStageVolume") == (
        "/csi.v1.Node/NodeStageVolume"
    )
    with pytest.raises(KeyError):
        spec.CONTROLLER.method_path("Nope")


class _EchoController:
    """Minimal servicer used to prove descriptor-driven client/server wiring."""

    def MapVolume(self, request, context):
        return oim_pb2.MapVolumeReply(
            chips=[
                oim_pb2.ChipAssignment(
                    chip_id=0,
                    device_path="/dev/accel0",
                    coord=oim_pb2.MeshCoord(coords=[0, 0, 0]),
                )
            ],
            mesh=oim_pb2.MeshShape(dims=[1, 1, 1]),
        )

    def UnmapVolume(self, request, context):
        return oim_pb2.UnmapVolumeReply()


def test_stub_and_registrar_roundtrip():
    srv = NonBlockingGRPCServer("tcp://127.0.0.1:0")
    srv.start(spec.CONTROLLER.registrar(_EchoController()))
    try:
        channel = grpc.insecure_channel(srv.addr().grpc_target())
        stub = spec.CONTROLLER.stub(channel)
        reply = stub.MapVolume(
            oim_pb2.MapVolumeRequest(
                volume_id="vol-1", slice=oim_pb2.SliceParams(chip_count=1)
            ),
            timeout=5,
        )
        assert reply.chips[0].device_path == "/dev/accel0"
        assert list(reply.mesh.dims) == [1, 1, 1]

        # Unimplemented-but-declared methods surface as UNIMPLEMENTED.
        with pytest.raises(grpc.RpcError) as err:
            stub.ProvisionSlice(oim_pb2.ProvisionSliceRequest(name="x"), timeout=5)
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
        channel.close()
    finally:
        srv.stop()


def test_oneof_params():
    req = oim_pb2.MapVolumeRequest(volume_id="v")
    assert req.WhichOneof("params") is None
    req.provisioned.SetInParent()
    assert req.WhichOneof("params") == "provisioned"
    req.slice.chip_count = 8
    assert req.WhichOneof("params") == "slice"
