// Minimal JSON value type for the tpu-agent's NDJSON JSON-RPC protocol.
//
// The image ships no C++ JSON library, so this is a small self-contained
// parser/serializer covering exactly what doc/agent-protocol.md needs:
// null/bool/number/string/array/object, strict parsing, compact output.

#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace oim {

class Json {
 public:
  enum Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Json() = default;
  static Json boolean(bool b);
  static Json number(double n);
  static Json integer(int64_t n);
  static Json str(std::string s);
  static Json array();
  static Json object();

  Type type() const { return type_; }
  bool is_null() const { return type_ == kNull; }

  // Accessors; behavior is defined only for the matching type.
  bool as_bool() const { return bool_; }
  double as_number() const { return number_; }
  int64_t as_int() const { return static_cast<int64_t>(number_); }
  const std::string& as_string() const { return string_; }
  const std::vector<Json>& items() const { return array_; }
  std::vector<Json>& items() { return array_; }

  // Object access. find() returns nullptr when the key is absent.
  const Json* find(const std::string& key) const;
  void set(const std::string& key, Json value);
  void push(Json value);

  std::string dump() const;

  // Parses exactly one JSON document from `text`; returns false and sets
  // `error` on malformed input or trailing garbage.
  static bool parse(const std::string& text, Json* out, std::string* error);

 private:
  Type type_ = kNull;
  bool bool_ = false;
  double number_ = 0;
  std::string string_;
  std::vector<Json> array_;
  std::vector<std::pair<std::string, Json>> object_;

  void dump_to(std::string* out) const;
};

}  // namespace oim
