// tpu-agent: the device-plane daemon owning one host's TPU chips.
//
// The role SPDK vhost plays in the reference (launched the way the
// reference's test fixture launches vhost, reference
// test/pkg/spdk/spdk.go:109-177): a native daemon serving a JSON-RPC control
// socket; the compute data plane (ICI/HBM) lives inside libtpu/PJRT and
// never passes through this process.
//
// Modes:
//   --fake-chips N [--mesh XxYxZ]   fabricate N chips, stub device files in
//                                   --state-dir (Malloc-BDev analog)
//   --devices GLOB                  real mode: chips = matching device files
//   --pjrt-plugin PATH              dlopen a PJRT C-API plugin: version
//                                   handshake + plugin attributes, served
//                                   via get_pjrt_info
//   --pjrt-create-client            also create a PJRT client and enumerate
//                                   real devices (released immediately)
//   --pjrt-option K=V               named create_options for the client
//                                   (repeatable; int64/bool auto-detected)
//   --chips-from-pjrt               chip inventory = PJRT device enumeration
//                                   (implies --pjrt-create-client)

#include <dlfcn.h>
#include <glob.h>
#include <signal.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "chip_store.h"
#include "pjrt_loader.h"
#include "rpc_server.h"

namespace {

oim::RpcServer* g_server = nullptr;

void HandleSignal(int) {
  if (g_server != nullptr) g_server->Shutdown();
}

std::vector<int> ParseMesh(const std::string& spec) {
  std::vector<int> mesh;
  size_t start = 0;
  while (start <= spec.size()) {
    size_t x = spec.find('x', start);
    std::string part =
        spec.substr(start, x == std::string::npos ? x : x - start);
    if (part.empty()) break;
    mesh.push_back(std::atoi(part.c_str()));
    if (x == std::string::npos) break;
    start = x + 1;
  }
  return mesh;
}

// Best-effort sysfs PCI BDF lookup for a device node like /dev/accel3:
// /sys/class/accel/accel3/device resolves to .../pci0000:00/0000:00:05.0.
std::string SysfsPci(const std::string& device_path) {
  size_t slash = device_path.rfind('/');
  std::string base =
      slash == std::string::npos ? device_path : device_path.substr(slash + 1);
  for (const char* cls : {"accel", "vfio"}) {
    std::string link = std::string("/sys/class/") + cls + "/" + base + "/device";
    char resolved[4096];
    ssize_t n = ::readlink(link.c_str(), resolved, sizeof(resolved) - 1);
    if (n <= 0) continue;
    resolved[n] = '\0';
    std::string target(resolved);
    size_t pos = target.rfind('/');
    std::string leaf = pos == std::string::npos ? target : target.substr(pos + 1);
    // A BDF looks like dddd:bb:dd.f.
    if (leaf.size() >= 12 && leaf[4] == ':' && leaf[7] == ':' &&
        leaf[10] == '.') {
      return leaf;
    }
  }
  return "";
}

void Usage(const char* argv0) {
  std::fprintf(
      stderr,
      "usage: %s --socket PATH (--fake-chips N [--mesh XxYxZ] "
      "--state-dir DIR | --devices GLOB [--mesh XxYxZ] | "
      "--chips-from-pjrt) [--accel-type TYPE] [--pjrt-plugin PATH] "
      "[--pjrt-create-client] [--pjrt-option K=V]...\n",
      argv0);
}

}  // namespace

int main(int argc, char** argv) {
  std::string socket_path;
  std::string state_dir = "/var/run/tpu-agent";
  std::string devices_glob;
  std::string accel_type = "v5p";
  std::string pjrt_plugin;
  std::string mesh_spec;
  std::vector<oim::PjrtOption> pjrt_options;
  bool pjrt_create_client = false;
  bool chips_from_pjrt = false;
  int fake_chips = 0;

  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    // Accept both "--flag value" and "--flag=value" (k8s manifests commonly
    // use the latter).
    std::string inline_value;
    bool has_inline = false;
    size_t eq = arg.find('=');
    if (arg.rfind("--", 0) == 0 && eq != std::string::npos) {
      inline_value = arg.substr(eq + 1);
      arg = arg.substr(0, eq);
      has_inline = true;
    }
    auto next = [&]() -> std::string {
      if (has_inline) return inline_value;
      if (i + 1 >= argc) {
        Usage(argv[0]);
        std::exit(2);
      }
      return argv[++i];
    };
    if (arg == "--socket") socket_path = next();
    else if (arg == "--fake-chips") fake_chips = std::atoi(next().c_str());
    else if (arg == "--mesh") mesh_spec = next();
    else if (arg == "--state-dir") state_dir = next();
    else if (arg == "--devices") devices_glob = next();
    else if (arg == "--accel-type") accel_type = next();
    else if (arg == "--pjrt-plugin") pjrt_plugin = next();
    else if (arg == "--pjrt-create-client") pjrt_create_client = true;
    else if (arg == "--chips-from-pjrt") chips_from_pjrt = true;
    else if (arg == "--pjrt-option") {
      std::string kv = next();
      size_t sep = kv.find('=');
      if (sep == std::string::npos) {
        std::fprintf(stderr, "--pjrt-option expects K=V, got %s\n", kv.c_str());
        return 2;
      }
      pjrt_options.push_back({kv.substr(0, sep), kv.substr(sep + 1)});
    }
    else if (arg == "--help" || arg == "-h") { Usage(argv[0]); return 0; }
    else {
      std::fprintf(stderr, "unknown flag %s\n", arg.c_str());
      Usage(argv[0]);
      return 2;
    }
  }
  if (socket_path.empty()) {
    Usage(argv[0]);
    return 2;
  }
  // The three inventory modes are mutually exclusive; silently letting one
  // win would hand an operator a different chip inventory than the flags
  // they passed describe.
  int inventory_modes = (fake_chips > 0 ? 1 : 0) +
                        (devices_glob.empty() ? 0 : 1) +
                        (chips_from_pjrt ? 1 : 0);
  if (inventory_modes > 1) {
    std::fprintf(stderr,
                 "--fake-chips, --devices and --chips-from-pjrt are mutually "
                 "exclusive inventory modes; pass exactly one\n");
    return 2;
  }
  // Real mode is the default: scan the standard TPU accel device nodes.
  if (inventory_modes == 0) {
    devices_glob = "/dev/accel*";
  }

  oim::Json pjrt_info;
  if (!pjrt_plugin.empty()) {
    pjrt_info = oim::LoadPjrtPlugin(
        pjrt_plugin, pjrt_create_client || chips_from_pjrt, pjrt_options);
    if (const oim::Json* err = pjrt_info.find("error")) {
      std::fprintf(stderr, "pjrt: %s\n", err->as_string().c_str());
    }
  } else if (chips_from_pjrt) {
    std::fprintf(stderr, "--chips-from-pjrt requires --pjrt-plugin\n");
    return 2;
  }

  std::vector<std::string> device_paths;
  std::vector<std::string> pci_addrs;
  if (chips_from_pjrt) {
    // Chip inventory = what the PJRT plugin enumerates.  Order devices
    // row-major by their torus coords so ChipStore's row-major coord
    // assignment reproduces the plugin's physical topology; the mesh is
    // the coords' bounding box when consistent, else linear.
    const oim::Json* client = pjrt_info.find("client");
    const oim::Json* devices =
        client != nullptr ? client->find("devices") : nullptr;
    if (devices == nullptr || devices->items().empty()) {
      std::fprintf(stderr, "pjrt plugin enumerated no devices\n");
      return 1;
    }
    struct PjrtDev {
      int id;
      std::vector<int> coords;
    };
    std::vector<PjrtDev> devs;
    bool have_coords = true;
    size_t coord_rank = 0;
    for (const oim::Json& d : devices->items()) {
      PjrtDev pd;
      const oim::Json* id = d.find("id");
      pd.id = id != nullptr ? static_cast<int>(id->as_int())
                            : static_cast<int>(devs.size());
      if (const oim::Json* coords = d.find("coords")) {
        for (const oim::Json& c : coords->items()) {
          pd.coords.push_back(static_cast<int>(c.as_int()));
        }
      }
      if (devs.empty()) coord_rank = pd.coords.size();
      if (pd.coords.empty() || pd.coords.size() != coord_rank) {
        have_coords = false;
      }
      devs.push_back(std::move(pd));
    }
    // An explicit --mesh wins: keep the operator's topology, linear id
    // order (the product check below still validates it).
    bool coords_ordered = false;
    if (have_coords && !mesh_spec.empty()) {
      std::fprintf(stderr,
                   "warning: --mesh overrides PJRT-reported torus coords; "
                   "devices are ordered by id, which may not match the "
                   "physical topology\n");
    }
    if (have_coords && mesh_spec.empty()) {
      std::vector<int> bounds(coord_rank, 0);
      for (const PjrtDev& d : devs) {
        for (size_t a = 0; a < coord_rank; a++) {
          if (d.coords[a] + 1 > bounds[a]) bounds[a] = d.coords[a] + 1;
        }
      }
      int product = 1;
      for (int b : bounds) product *= b;
      if (product == static_cast<int>(devs.size())) {
        std::sort(devs.begin(), devs.end(),
                  [](const PjrtDev& a, const PjrtDev& b) {
                    return a.coords < b.coords;
                  });
        // Duplicate coords would silently fabricate ICI adjacency that
        // does not exist; treat them as "no usable coords".
        for (size_t i = 1; i < devs.size() && have_coords; i++) {
          if (devs[i].coords == devs[i - 1].coords) {
            std::fprintf(stderr,
                         "warning: pjrt devices report duplicate coords; "
                         "falling back to a linear mesh\n");
            have_coords = false;
          }
        }
        if (have_coords) {
          coords_ordered = true;
          for (size_t a = 0; a < bounds.size(); a++) {
            mesh_spec += (a > 0 ? "x" : "") + std::to_string(bounds[a]);
          }
        }
      } else {
        have_coords = false;  // sparse slice: fall back to linear order
      }
    }
    if (!coords_ordered) {
      std::sort(devs.begin(), devs.end(),
                [](const PjrtDev& a, const PjrtDev& b) { return a.id < b.id; });
    }
    for (const PjrtDev& d : devs) {
      device_paths.push_back("pjrt:" + std::to_string(d.id));
      pci_addrs.push_back("");
    }
  } else if (fake_chips > 0) {
    ::mkdir(state_dir.c_str(), 0755);
    for (int i = 0; i < fake_chips; i++) {
      std::string path = state_dir + "/accel" + std::to_string(i);
      std::ofstream f(path);
      f << "fake-tpu-chip " << i << "\n";
      device_paths.push_back(path);
    }
  } else {
    glob_t results;
    if (::glob(devices_glob.c_str(), 0, nullptr, &results) == 0) {
      for (size_t i = 0; i < results.gl_pathc; i++) {
        device_paths.emplace_back(results.gl_pathv[i]);
      }
    }
    ::globfree(&results);
    if (device_paths.empty()) {
      std::fprintf(stderr, "no devices match %s\n", devices_glob.c_str());
      return 1;
    }
    for (const std::string& path : device_paths) {
      pci_addrs.push_back(SysfsPci(path));
    }
  }

  std::vector<int> mesh;
  if (!mesh_spec.empty()) {
    mesh = ParseMesh(mesh_spec);
    int product = 1;
    for (int d : mesh) product *= d;
    if (product != static_cast<int>(device_paths.size())) {
      std::fprintf(stderr, "mesh %s does not multiply to %zu chips\n",
                   mesh_spec.c_str(), device_paths.size());
      return 2;
    }
  } else {
    mesh = {static_cast<int>(device_paths.size())};
  }

  // Summary string surfaced by get_topology (full report via
  // get_pjrt_info): "pjrt-<maj>.<min>[ <platform_name> <version>]".
  std::string pjrt_version;
  if (!pjrt_info.is_null() && pjrt_info.find("error") == nullptr) {
    if (const oim::Json* v = pjrt_info.find("api_version")) {
      pjrt_version = "pjrt-" + std::to_string(v->find("major")->as_int()) +
                     "." + std::to_string(v->find("minor")->as_int());
    }
    if (const oim::Json* client = pjrt_info.find("client")) {
      if (const oim::Json* name = client->find("platform_name")) {
        pjrt_version += " " + name->as_string();
      }
      if (const oim::Json* ver = client->find("platform_version")) {
        pjrt_version += " " + ver->as_string();
      }
    }
  }

  oim::ChipStore store(mesh, accel_type, device_paths, pjrt_version,
                       pci_addrs);
  if (!pjrt_info.is_null()) store.SetPjrtInfo(std::move(pjrt_info));
  oim::RpcServer server(&store, socket_path);
  if (!server.Listen()) return 1;
  g_server = &server;
  ::signal(SIGINT, HandleSignal);
  ::signal(SIGTERM, HandleSignal);
  // A client that disconnects before reading its response must cost one
  // EPIPE write error on that connection, never the daemon: without this
  // the default SIGPIPE disposition kills the whole device plane (found
  // by tests/test_agent_protocol.py's fuzz storm).
  ::signal(SIGPIPE, SIG_IGN);
  std::fprintf(stderr, "tpu-agent serving %zu %s chips on %s\n",
               device_paths.size(), accel_type.c_str(), socket_path.c_str());
  server.Serve();
  return 0;
}
