// PJRT C API plugin loader: the agent's window into the compute stack.
//
// Where the reference's device daemon owns the hardware by linking SPDK's
// bdev/vhost libraries directly (reference vendor/github.com/spdk/spdk), a
// TPU is owned by whoever creates the PJRT client on it.  The agent
// therefore speaks the *PJRT C API* (third_party/pjrt/pjrt_c_api.h) via
// dlopen: handshake the API version, initialize the plugin, read plugin
// attributes, and — when asked — create a client and enumerate real
// devices (id, process index, coords, kind).  No XLA libraries are linked;
// any conforming plugin works (libtpu.so, CPU plugin, the in-tree test
// plugin).
//
// All failures are reported in-band (the "error" field) rather than
// thrown: a missing or broken plugin must never take the control-plane
// daemon down, matching the reference's stance that the control plane
// stays up when the device plane misbehaves.

#pragma once

#include <string>
#include <vector>

#include "json.h"

namespace oim {

struct PjrtOption {
  std::string name;
  std::string value;  // int64 is auto-detected from decimal strings
};

// Loads `plugin_path` and returns a JSON report:
//   {
//     "plugin_path": "...",
//     "api_version": {"major": N, "minor": N},
//     "attributes": {...},               // plugin attributes, if any
//     "client": {                        // present iff create_client
//       "platform_name": "...", "platform_version": "...",
//       "process_index": N,
//       "devices": [{"id": N, "process_index": N, "kind": "...",
//                    "coords": [x,y,z]?, "debug_string": "..."}]
//     },
//     "error": "..."                     // present iff something failed
//   }
// The client, when created, is destroyed again before returning: the agent
// probes and enumerates but must not hold the chips — workloads own them
// after NodeStage (same reason the reference daemon releases NBD disks,
// reference pkg/oim-csi-driver/local.go:136-139).
Json LoadPjrtPlugin(const std::string& plugin_path, bool create_client,
                    const std::vector<PjrtOption>& options);

}  // namespace oim
