// Chip inventory + allocation state: the daemon's source of truth.
//
// Semantics are specified by doc/agent-protocol.md and must stay identical
// to the Python reference implementation (oim_tpu/agent/fake.py) — the
// shared suite tests/test_agent_protocol.py runs against both.  This plays
// the role SPDK's bdev/vhost tables play in the reference architecture.

#pragma once

#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "json.h"

namespace oim {

// errno-style application error codes (doc/agent-protocol.md).
constexpr int kErrExist = -17;
constexpr int kErrNoDev = -19;
constexpr int kErrNoSpace = -28;
constexpr int kErrBusy = -16;
constexpr int kErrInvalidParams = -32602;
constexpr int kErrMethodNotFound = -32601;
constexpr int kErrParse = -32700;
constexpr int kErrInvalidRequest = -32600;

constexpr int kCoordinatorPortBase = 8476;

struct RpcError {
  int code;
  std::string message;
};

struct Chip {
  int chip_id;
  std::string device_path;
  std::string pci;
  std::string accel_type;
  std::vector<int> phys_coord;
  std::string allocation;  // owning allocation name, "" when free
};

struct Allocation {
  std::string name;
  std::vector<int> chip_ids;          // in mesh row-major order
  std::vector<int> mesh;
  bool attached = false;
  bool provisioned = false;  // created via ProvisionSlice (Malloc analog)
  int coordinator_port = 0;
  std::map<int, std::vector<int>> coords;  // chip_id -> coord within mesh
};

class ChipStore {
 public:
  // Fake mode: fabricate chips on a mesh, stub device files in state_dir.
  // Real mode: use the given device paths with a linear [n] mesh (or the
  // configured physical mesh when its product matches).  pci_addrs, when
  // non-empty, carries one BDF string per device (resolved from sysfs by
  // main); otherwise synthetic fake-mode addresses are fabricated.
  ChipStore(std::vector<int> mesh, std::string accel_type,
            std::vector<std::string> device_paths, std::string pjrt_version,
            std::vector<std::string> pci_addrs = {});

  // Dispatch one protocol method.  Throws RpcError on failure.
  Json Handle(const std::string& method, const Json& params);

  // Full PJRT plugin report (src/pjrt_loader.cc), served by get_pjrt_info.
  void SetPjrtInfo(Json info) { pjrt_info_ = std::move(info); }

 private:
  Json TopologyJson();
  Json ChipJson(const Chip& chip, const std::vector<int>* coord) const;
  Json AllocJson(const Allocation& alloc) const;

  Allocation& CreateAllocation(const std::string& name, int chip_count,
                               const std::vector<int>& topology,
                               bool provisioned);
  void DeleteAllocation(const std::string& name);
  Allocation& AttachAllocation(const std::string& name);
  void DetachAllocation(const std::string& name);

  // Deterministic compact sub-box allocator; see doc/agent-protocol.md.
  bool FindChips(int n, const std::vector<int>& topology,
                 std::vector<int>* ids, std::vector<int>* mesh);

  int CoordToId(const std::vector<int>& coord) const;

  std::vector<int> mesh_;
  std::string accel_type_;
  std::string pjrt_version_;
  Json pjrt_info_;
  std::vector<Chip> chips_;
  std::map<std::string, Allocation> allocations_;
  std::mutex mutex_;
};

// Enumerates all box shapes with product n fitting in dims, most compact
// first (longest edge, then perimeter, then lexicographic).
std::vector<std::vector<int>> SubBoxes(int n, const std::vector<int>& dims);

}  // namespace oim
