// NDJSON JSON-RPC 2.0 server over a Unix stream socket.
//
// The daemon-side counterpart of oim_tpu/agent/client.py: accepts
// connections, reads one JSON-RPC request per line, dispatches into the
// ChipStore, writes one response per line.  Thread-per-connection — the
// control plane is deliberately low-frequency (short-lived, infrequent
// connections; the data plane never passes through this socket).

#pragma once

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <set>
#include <string>

#include "chip_store.h"

namespace oim {

class RpcServer {
 public:
  RpcServer(ChipStore* store, std::string socket_path);
  ~RpcServer();

  // Binds the socket; returns false (with message on stderr) on failure.
  bool Listen();

  // Accept loop; returns when Shutdown() is called, after every connection
  // thread has been joined (so the ChipStore outlives all handlers).
  void Serve();

  void Shutdown();

 private:
  void HandleConnection(int fd);
  std::string DispatchLine(const std::string& line);

  ChipStore* store_;
  std::string socket_path_;
  int listen_fd_ = -1;
  std::atomic<bool> shutdown_{false};

  std::mutex conn_mutex_;
  std::condition_variable conn_done_;
  std::set<int> conn_fds_;
};

}  // namespace oim
