#include "json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace oim {

Json Json::boolean(bool b) {
  Json j;
  j.type_ = kBool;
  j.bool_ = b;
  return j;
}

Json Json::number(double n) {
  Json j;
  j.type_ = kNumber;
  j.number_ = n;
  return j;
}

Json Json::integer(int64_t n) { return number(static_cast<double>(n)); }

Json Json::str(std::string s) {
  Json j;
  j.type_ = kString;
  j.string_ = std::move(s);
  return j;
}

Json Json::array() {
  Json j;
  j.type_ = kArray;
  return j;
}

Json Json::object() {
  Json j;
  j.type_ = kObject;
  return j;
}

const Json* Json::find(const std::string& key) const {
  for (const auto& kv : object_) {
    if (kv.first == key) return &kv.second;
  }
  return nullptr;
}

void Json::set(const std::string& key, Json value) {
  for (auto& kv : object_) {
    if (kv.first == key) {
      kv.second = std::move(value);
      return;
    }
  }
  object_.emplace_back(key, std::move(value));
}

void Json::push(Json value) { array_.push_back(std::move(value)); }

static void escape_to(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

void Json::dump_to(std::string* out) const {
  switch (type_) {
    case kNull: *out += "null"; break;
    case kBool: *out += bool_ ? "true" : "false"; break;
    case kNumber: {
      double intpart;
      if (std::modf(number_, &intpart) == 0.0 && std::fabs(number_) < 1e15) {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%lld",
                      static_cast<long long>(number_));
        *out += buf;
      } else {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%.17g", number_);
        *out += buf;
      }
      break;
    }
    case kString: escape_to(string_, out); break;
    case kArray: {
      out->push_back('[');
      for (size_t i = 0; i < array_.size(); i++) {
        if (i) out->push_back(',');
        array_[i].dump_to(out);
      }
      out->push_back(']');
      break;
    }
    case kObject: {
      out->push_back('{');
      for (size_t i = 0; i < object_.size(); i++) {
        if (i) out->push_back(',');
        escape_to(object_[i].first, out);
        out->push_back(':');
        object_[i].second.dump_to(out);
      }
      out->push_back('}');
      break;
    }
  }
}

std::string Json::dump() const {
  std::string out;
  dump_to(&out);
  return out;
}

// ---------------------------------------------------------------------------
// Parser

namespace {

struct Parser {
  const char* p;
  const char* end;
  std::string* error;

  bool fail(const std::string& msg) {
    *error = msg;
    return false;
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }

  bool parse_value(Json* out) {
    skip_ws();
    if (p >= end) return fail("unexpected end of input");
    switch (*p) {
      case '{': return parse_object(out);
      case '[': return parse_array(out);
      case '"': {
        std::string s;
        if (!parse_string(&s)) return false;
        *out = Json::str(std::move(s));
        return true;
      }
      case 't':
        if (end - p >= 4 && std::strncmp(p, "true", 4) == 0) {
          p += 4;
          *out = Json::boolean(true);
          return true;
        }
        return fail("bad literal");
      case 'f':
        if (end - p >= 5 && std::strncmp(p, "false", 5) == 0) {
          p += 5;
          *out = Json::boolean(false);
          return true;
        }
        return fail("bad literal");
      case 'n':
        if (end - p >= 4 && std::strncmp(p, "null", 4) == 0) {
          p += 4;
          *out = Json();
          return true;
        }
        return fail("bad literal");
      default: return parse_number(out);
    }
  }

  bool parse_string(std::string* out) {
    p++;  // opening quote
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        p++;
        if (p >= end) return fail("bad escape");
        switch (*p) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            if (end - p < 5) return fail("bad \\u escape");
            unsigned code = 0;
            for (int i = 1; i <= 4; i++) {
              char c = p[i];
              code <<= 4;
              if (c >= '0' && c <= '9') code |= c - '0';
              else if (c >= 'a' && c <= 'f') code |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F') code |= c - 'A' + 10;
              else return fail("bad \\u escape");
            }
            p += 4;
            // UTF-8 encode the BMP code point (surrogate pairs unsupported;
            // the protocol never uses them).
            if (code < 0x80) {
              out->push_back(static_cast<char>(code));
            } else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default: return fail("bad escape");
        }
        p++;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return fail("unterminated string");
    p++;  // closing quote
    return true;
  }

  bool parse_number(Json* out) {
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) p++;
    bool digits = false;
    while (p < end && (std::isdigit(static_cast<unsigned char>(*p)) ||
                       *p == '.' || *p == 'e' || *p == 'E' || *p == '-' ||
                       *p == '+')) {
      if (std::isdigit(static_cast<unsigned char>(*p))) digits = true;
      p++;
    }
    if (!digits) return fail("bad number");
    *out = Json::number(std::strtod(std::string(start, p).c_str(), nullptr));
    return true;
  }

  bool parse_array(Json* out) {
    p++;  // [
    *out = Json::array();
    skip_ws();
    if (p < end && *p == ']') {
      p++;
      return true;
    }
    while (true) {
      Json item;
      if (!parse_value(&item)) return false;
      out->push(std::move(item));
      skip_ws();
      if (p < end && *p == ',') {
        p++;
        continue;
      }
      if (p < end && *p == ']') {
        p++;
        return true;
      }
      return fail("expected , or ] in array");
    }
  }

  bool parse_object(Json* out) {
    p++;  // {
    *out = Json::object();
    skip_ws();
    if (p < end && *p == '}') {
      p++;
      return true;
    }
    while (true) {
      skip_ws();
      if (p >= end || *p != '"') return fail("expected object key");
      std::string key;
      if (!parse_string(&key)) return false;
      skip_ws();
      if (p >= end || *p != ':') return fail("expected : in object");
      p++;
      Json value;
      if (!parse_value(&value)) return false;
      out->set(key, std::move(value));
      skip_ws();
      if (p < end && *p == ',') {
        p++;
        continue;
      }
      if (p < end && *p == '}') {
        p++;
        return true;
      }
      return fail("expected , or } in object");
    }
  }
};

}  // namespace

bool Json::parse(const std::string& text, Json* out, std::string* error) {
  Parser parser{text.data(), text.data() + text.size(), error};
  if (!parser.parse_value(out)) return false;
  parser.skip_ws();
  if (parser.p != parser.end) {
    *error = "trailing garbage";
    return false;
  }
  return true;
}

}  // namespace oim
