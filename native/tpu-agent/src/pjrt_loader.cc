#include "pjrt_loader.h"

#include <dlfcn.h>

#include <cstddef>
#include <cstring>

#include "pjrt/pjrt_c_api.h"

namespace oim {
namespace {

// The PJRT_Api table grows over releases; a plugin built against an older
// header ships a smaller table.  Every entry must be bounds-checked against
// the plugin's own struct_size AND null-checked before the call — the
// header's versioning contract (pjrt_c_api.h: "Callers can implement
// forwards compatibility by using PJRT_Api_Version").
#define PJRT_HAS(api, member)                                          \
  (offsetof(PJRT_Api, member) + sizeof((api)->member) <=               \
       (api)->struct_size &&                                           \
   (api)->member != nullptr)

std::string TakeErrorMessage(const PJRT_Api* api, PJRT_Error* error) {
  std::string text = "(unreadable PJRT error)";
  if (PJRT_HAS(api, PJRT_Error_Message)) {
    PJRT_Error_Message_Args msg{};
    msg.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
    msg.error = error;
    api->PJRT_Error_Message(&msg);
    text.assign(msg.message, msg.message_size);
  }
  if (PJRT_HAS(api, PJRT_Error_Destroy)) {
    PJRT_Error_Destroy_Args destroy{};
    destroy.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
    destroy.error = error;
    api->PJRT_Error_Destroy(&destroy);
  }
  return text;
}

// For calls whose failure is non-fatal to the report: destroys the error
// (the PJRT contract makes the caller responsible) and returns success.
bool CheckOk(const PJRT_Api* api, PJRT_Error* error) {
  if (error == nullptr) return true;
  TakeErrorMessage(api, error);
  return false;
}

Json NamedValueJson(const PJRT_NamedValue& nv) {
  switch (nv.type) {
    case PJRT_NamedValue_kString:
      return Json::str(std::string(nv.string_value, nv.value_size));
    case PJRT_NamedValue_kInt64:
      return Json::integer(nv.int64_value);
    case PJRT_NamedValue_kInt64List: {
      Json list = Json::array();
      for (size_t i = 0; i < nv.value_size; i++) {
        list.push(Json::integer(nv.int64_array_value[i]));
      }
      return list;
    }
    case PJRT_NamedValue_kFloat:
      return Json::number(nv.float_value);
    case PJRT_NamedValue_kBool:
      return Json::boolean(nv.bool_value);
    default:
      return Json();
  }
}

Json NamedValuesJson(const PJRT_NamedValue* values, size_t count) {
  Json out = Json::object();
  for (size_t i = 0; i < count; i++) {
    out.set(std::string(values[i].name, values[i].name_size),
            NamedValueJson(values[i]));
  }
  return out;
}

// Owns the PJRT_NamedValue array built from --pjrt-option flags; the
// strings must outlive the PJRT_Client_Create call.
struct CreateOptions {
  explicit CreateOptions(const std::vector<PjrtOption>& options) {
    for (const PjrtOption& opt : options) {
      PJRT_NamedValue nv{};
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = opt.name.c_str();
      nv.name_size = opt.name.size();
      char* end = nullptr;
      long long as_int = std::strtoll(opt.value.c_str(), &end, 10);
      if (end != nullptr && *end == '\0' && !opt.value.empty()) {
        nv.type = PJRT_NamedValue_kInt64;
        nv.int64_value = as_int;
        nv.value_size = 1;
      } else if (opt.value == "true" || opt.value == "false") {
        nv.type = PJRT_NamedValue_kBool;
        nv.bool_value = opt.value == "true";
        nv.value_size = 1;
      } else {
        nv.type = PJRT_NamedValue_kString;
        nv.string_value = opt.value.c_str();
        nv.value_size = opt.value.size();
      }
      values.push_back(nv);
    }
  }
  std::vector<PJRT_NamedValue> values;
};

Json DeviceJson(const PJRT_Api* api, PJRT_Device* device) {
  Json out = Json::object();
  if (!PJRT_HAS(api, PJRT_Device_GetDescription)) {
    out.set("error", Json::str("plugin lacks PJRT_Device_GetDescription"));
    return out;
  }
  PJRT_Device_GetDescription_Args desc{};
  desc.struct_size = PJRT_Device_GetDescription_Args_STRUCT_SIZE;
  desc.device = device;
  if (PJRT_Error* err = api->PJRT_Device_GetDescription(&desc)) {
    out.set("error", Json::str(TakeErrorMessage(api, err)));
    return out;
  }
  PJRT_DeviceDescription* dd = desc.device_description;

  if (PJRT_HAS(api, PJRT_DeviceDescription_Id)) {
    PJRT_DeviceDescription_Id_Args id{};
    id.struct_size = PJRT_DeviceDescription_Id_Args_STRUCT_SIZE;
    id.device_description = dd;
    if (CheckOk(api, api->PJRT_DeviceDescription_Id(&id))) {
      out.set("id", Json::integer(id.id));
    }
  }

  if (PJRT_HAS(api, PJRT_DeviceDescription_ProcessIndex)) {
    PJRT_DeviceDescription_ProcessIndex_Args pi{};
    pi.struct_size = PJRT_DeviceDescription_ProcessIndex_Args_STRUCT_SIZE;
    pi.device_description = dd;
    if (CheckOk(api, api->PJRT_DeviceDescription_ProcessIndex(&pi))) {
      out.set("process_index", Json::integer(pi.process_index));
    }
  }

  if (PJRT_HAS(api, PJRT_DeviceDescription_Kind)) {
    PJRT_DeviceDescription_Kind_Args kind{};
    kind.struct_size = PJRT_DeviceDescription_Kind_Args_STRUCT_SIZE;
    kind.device_description = dd;
    if (CheckOk(api, api->PJRT_DeviceDescription_Kind(&kind))) {
      out.set("kind", Json::str(std::string(kind.device_kind,
                                            kind.device_kind_size)));
    }
  }

  if (PJRT_HAS(api, PJRT_DeviceDescription_Attributes)) {
    PJRT_DeviceDescription_Attributes_Args attrs{};
    attrs.struct_size = PJRT_DeviceDescription_Attributes_Args_STRUCT_SIZE;
    attrs.device_description = dd;
    if (CheckOk(api, api->PJRT_DeviceDescription_Attributes(&attrs))) {
      Json attr_json = NamedValuesJson(attrs.attributes, attrs.num_attributes);
      // TPU plugins expose the chip's physical torus position as "coords";
      // surface it at top level — it is the ICI analog of the PCI BDF the
      // reference reads from sysfs (reference pkg/oim-csi-driver/
      // remote.go:324-373).
      if (const Json* coords = attr_json.find("coords")) {
        out.set("coords", *coords);
      }
      out.set("attributes", std::move(attr_json));
    }
  }

  if (PJRT_HAS(api, PJRT_DeviceDescription_DebugString)) {
    PJRT_DeviceDescription_DebugString_Args dbg{};
    dbg.struct_size = PJRT_DeviceDescription_DebugString_Args_STRUCT_SIZE;
    dbg.device_description = dd;
    if (CheckOk(api, api->PJRT_DeviceDescription_DebugString(&dbg))) {
      out.set("debug_string",
              Json::str(std::string(dbg.debug_string, dbg.debug_string_size)));
    }
  }
  return out;
}

Json ClientJson(const PJRT_Api* api,
                const std::vector<PjrtOption>& options, std::string* error) {
  if (!PJRT_HAS(api, PJRT_Client_Create)) {
    *error = "plugin lacks PJRT_Client_Create";
    return Json();
  }
  CreateOptions create_options(options);
  PJRT_Client_Create_Args create{};
  create.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  create.create_options = create_options.values.data();
  create.num_options = create_options.values.size();
  if (PJRT_Error* err = api->PJRT_Client_Create(&create)) {
    *error = "client_create: " + TakeErrorMessage(api, err);
    return Json();
  }
  PJRT_Client* client = create.client;
  Json out = Json::object();

  if (PJRT_HAS(api, PJRT_Client_PlatformName)) {
    PJRT_Client_PlatformName_Args name{};
    name.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
    name.client = client;
    if (CheckOk(api, api->PJRT_Client_PlatformName(&name))) {
      out.set("platform_name", Json::str(std::string(
                                   name.platform_name,
                                   name.platform_name_size)));
    }
  }

  if (PJRT_HAS(api, PJRT_Client_PlatformVersion)) {
    PJRT_Client_PlatformVersion_Args version{};
    version.struct_size = PJRT_Client_PlatformVersion_Args_STRUCT_SIZE;
    version.client = client;
    if (CheckOk(api, api->PJRT_Client_PlatformVersion(&version))) {
      out.set("platform_version",
              Json::str(std::string(version.platform_version,
                                    version.platform_version_size)));
    }
  }

  if (PJRT_HAS(api, PJRT_Client_ProcessIndex)) {
    PJRT_Client_ProcessIndex_Args process{};
    process.struct_size = PJRT_Client_ProcessIndex_Args_STRUCT_SIZE;
    process.client = client;
    if (CheckOk(api, api->PJRT_Client_ProcessIndex(&process))) {
      out.set("process_index", Json::integer(process.process_index));
    }
  }

  // Global device count for visibility; the enumerated "devices" list below
  // is the *addressable* set only — a per-host agent must never inventory
  // chips that physically live on other hosts of the slice.
  if (PJRT_HAS(api, PJRT_Client_Devices)) {
    PJRT_Client_Devices_Args all{};
    all.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
    all.client = client;
    if (CheckOk(api, api->PJRT_Client_Devices(&all))) {
      out.set("num_global_devices", Json::integer(all.num_devices));
    }
  }

  if (!PJRT_HAS(api, PJRT_Client_AddressableDevices)) {
    *error = "plugin lacks PJRT_Client_AddressableDevices";
  } else {
    PJRT_Client_AddressableDevices_Args devices{};
    devices.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    devices.client = client;
    if (PJRT_Error* err = api->PJRT_Client_AddressableDevices(&devices)) {
      *error = "addressable_devices: " + TakeErrorMessage(api, err);
    } else {
      Json device_list = Json::array();
      for (size_t i = 0; i < devices.num_addressable_devices; i++) {
        device_list.push(DeviceJson(api, devices.addressable_devices[i]));
      }
      out.set("devices", std::move(device_list));
    }
  }

  if (PJRT_HAS(api, PJRT_Client_Destroy)) {
    PJRT_Client_Destroy_Args destroy{};
    destroy.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
    destroy.client = client;
    CheckOk(api, api->PJRT_Client_Destroy(&destroy));
  }
  return out;
}

}  // namespace

Json LoadPjrtPlugin(const std::string& plugin_path, bool create_client,
                    const std::vector<PjrtOption>& options) {
  Json out = Json::object();
  out.set("plugin_path", Json::str(plugin_path));

  // RTLD_GLOBAL: libtpu-style plugins expect their own symbols visible to
  // dependent dlopens.  The handle is deliberately never dlclosed — PJRT
  // plugins do not support unloading.
  void* handle = dlopen(plugin_path.c_str(), RTLD_NOW | RTLD_GLOBAL);
  if (handle == nullptr) {
    out.set("error", Json::str(std::string("dlopen: ") + dlerror()));
    return out;
  }
  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetPjrtApiFn>(dlsym(handle, "GetPjrtApi"));
  if (get_api == nullptr) {
    out.set("error", Json::str("plugin lacks GetPjrtApi"));
    return out;
  }
  const PJRT_Api* api = get_api();
  if (api == nullptr) {
    out.set("error", Json::str("GetPjrtApi returned null"));
    return out;
  }

  Json version = Json::object();
  version.set("major", Json::integer(api->pjrt_api_version.major_version));
  version.set("minor", Json::integer(api->pjrt_api_version.minor_version));
  out.set("api_version", std::move(version));

  if (!PJRT_HAS(api, PJRT_Plugin_Initialize)) {
    out.set("error", Json::str("plugin lacks PJRT_Plugin_Initialize"));
    return out;
  }
  PJRT_Plugin_Initialize_Args init{};
  init.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  if (PJRT_Error* err = api->PJRT_Plugin_Initialize(&init)) {
    out.set("error",
            Json::str("plugin_initialize: " + TakeErrorMessage(api, err)));
    return out;
  }

  if (PJRT_HAS(api, PJRT_Plugin_Attributes)) {
    PJRT_Plugin_Attributes_Args attrs{};
    attrs.struct_size = PJRT_Plugin_Attributes_Args_STRUCT_SIZE;
    if (CheckOk(api, api->PJRT_Plugin_Attributes(&attrs))) {
      out.set("attributes",
              NamedValuesJson(attrs.attributes, attrs.num_attributes));
    }
  }

  if (create_client) {
    std::string error;
    Json client = ClientJson(api, options, &error);
    if (!error.empty()) out.set("error", Json::str(error));
    if (!client.is_null()) out.set("client", std::move(client));
  }
  return out;
}

}  // namespace oim
