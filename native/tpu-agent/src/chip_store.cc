#include "chip_store.h"

#include <algorithm>
#include <cstdio>
#include <functional>
#include <numeric>
#include <set>

namespace oim {

namespace {

// Row-major enumeration of all coordinates inside `dims`.
std::vector<std::vector<int>> AllCoords(const std::vector<int>& dims) {
  std::vector<std::vector<int>> out;
  std::vector<int> cur(dims.size(), 0);
  std::function<void(size_t)> rec = [&](size_t axis) {
    if (axis == dims.size()) {
      out.push_back(cur);
      return;
    }
    for (int i = 0; i < dims[axis]; i++) {
      cur[axis] = i;
      rec(axis + 1);
    }
  };
  rec(0);
  if (dims.empty()) out.push_back({});
  return out;
}

int Product(const std::vector<int>& dims) {
  int p = 1;
  for (int d : dims) p *= d;
  return p;
}

}  // namespace

std::vector<std::vector<int>> SubBoxes(int n, const std::vector<int>& dims) {
  std::set<std::vector<int>> shapes;
  std::vector<int> prefix;
  std::function<void(int, size_t)> rec = [&](int remaining, size_t axis) {
    if (axis == dims.size()) {
      if (remaining == 1) shapes.insert(prefix);
      return;
    }
    int limit = std::min(dims[axis], remaining);
    for (int d = 1; d <= limit; d++) {
      if (remaining % d == 0) {
        prefix.push_back(d);
        rec(remaining / d, axis + 1);
        prefix.pop_back();
      }
    }
  };
  rec(n, 0);
  std::vector<std::vector<int>> out(shapes.begin(), shapes.end());
  std::sort(out.begin(), out.end(),
            [](const std::vector<int>& a, const std::vector<int>& b) {
              int ma = *std::max_element(a.begin(), a.end());
              int mb = *std::max_element(b.begin(), b.end());
              if (ma != mb) return ma < mb;
              int sa = std::accumulate(a.begin(), a.end(), 0);
              int sb = std::accumulate(b.begin(), b.end(), 0);
              if (sa != sb) return sa < sb;
              return a < b;
            });
  return out;
}

ChipStore::ChipStore(std::vector<int> mesh, std::string accel_type,
                     std::vector<std::string> device_paths,
                     std::string pjrt_version,
                     std::vector<std::string> pci_addrs)
    : mesh_(std::move(mesh)),
      accel_type_(std::move(accel_type)),
      pjrt_version_(std::move(pjrt_version)) {
  auto coords = AllCoords(mesh_);
  chips_.reserve(device_paths.size());
  for (size_t i = 0; i < device_paths.size(); i++) {
    Chip chip;
    chip.chip_id = static_cast<int>(i);
    chip.device_path = device_paths[i];
    if (i < pci_addrs.size() && !pci_addrs[i].empty()) {
      chip.pci = pci_addrs[i];
    } else {
      char pci[32];
      std::snprintf(pci, sizeof(pci), "0000:%02zx:05.0", i);
      chip.pci = pci;
    }
    chip.accel_type = accel_type_;
    chip.phys_coord = coords[i];
    chips_.push_back(std::move(chip));
  }
}

int ChipStore::CoordToId(const std::vector<int>& coord) const {
  // Row-major index within mesh_.
  int idx = 0;
  for (size_t a = 0; a < mesh_.size(); a++) {
    idx = idx * mesh_[a] + coord[a];
  }
  return idx;
}

bool ChipStore::FindChips(int n, const std::vector<int>& topology,
                          std::vector<int>* ids, std::vector<int>* mesh) {
  std::set<int> free;
  for (const Chip& c : chips_) {
    if (c.allocation.empty()) free.insert(c.chip_id);
  }
  if (n > static_cast<int>(free.size())) {
    throw RpcError{kErrNoSpace, "need " + std::to_string(n) + " chips, " +
                                    std::to_string(free.size()) + " free"};
  }
  std::vector<std::vector<int>> shapes;
  if (!topology.empty()) {
    // TPU topology convention: a lower-rank request is implicitly
    // trailing-1-padded ("2x2" on a 2x2x1 host means 2x2x1) — the
    // gke-tpu dialect writes 2D topologies against 3D host meshes.
    std::vector<int> padded = topology;
    while (padded.size() < mesh_.size()) padded.push_back(1);
    shapes.push_back(padded);
  } else {
    shapes = SubBoxes(n, mesh_);
  }
  for (const auto& shape : shapes) {
    if (shape.size() != mesh_.size()) continue;
    // Slide the box over every origin in deterministic (row-major) order.
    std::vector<int> origin_dims;
    bool fits = true;
    for (size_t a = 0; a < shape.size(); a++) {
      int range = mesh_[a] - shape[a] + 1;
      if (range <= 0) fits = false;
      origin_dims.push_back(range);
    }
    if (!fits) continue;
    for (const auto& origin : AllCoords(origin_dims)) {
      std::vector<int> candidate;
      bool ok = true;
      for (const auto& offset : AllCoords(shape)) {
        std::vector<int> coord(shape.size());
        for (size_t a = 0; a < shape.size(); a++) {
          coord[a] = origin[a] + offset[a];
        }
        int cid = CoordToId(coord);
        if (!free.count(cid)) {
          ok = false;
          break;
        }
        candidate.push_back(cid);
      }
      if (ok) {
        *ids = candidate;
        *mesh = shape;
        return true;
      }
    }
  }
  if (!topology.empty()) {
    std::string shape_str;
    for (size_t i = 0; i < topology.size(); i++) {
      if (i) shape_str += "x";
      shape_str += std::to_string(topology[i]);
    }
    throw RpcError{kErrNoSpace, "no free " + shape_str + " sub-mesh"};
  }
  // Fragmented: linear mesh over the lowest-id free chips.
  ids->assign(free.begin(), free.end());
  ids->resize(n);
  *mesh = {n};
  return true;
}

Allocation& ChipStore::CreateAllocation(const std::string& name,
                                        int chip_count,
                                        const std::vector<int>& topology,
                                        bool provisioned) {
  if (name.empty() || chip_count <= 0) {
    throw RpcError{kErrInvalidParams, "name and chip_count>0 required"};
  }
  if (!topology.empty() && Product(topology) != chip_count) {
    throw RpcError{kErrInvalidParams,
                   "topology does not multiply to chip_count"};
  }
  auto it = allocations_.find(name);
  if (it != allocations_.end()) {
    if (static_cast<int>(it->second.chip_ids.size()) != chip_count) {
      throw RpcError{kErrExist, "allocation '" + name + "' exists with " +
                                    std::to_string(it->second.chip_ids.size()) +
                                    " chips"};
    }
    return it->second;
  }
  Allocation alloc;
  alloc.name = name;
  alloc.provisioned = provisioned;
  FindChips(chip_count, topology, &alloc.chip_ids, &alloc.mesh);
  auto offsets = AllCoords(alloc.mesh);
  for (size_t i = 0; i < alloc.chip_ids.size(); i++) {
    alloc.coords[alloc.chip_ids[i]] = offsets[i];
    chips_[alloc.chip_ids[i]].allocation = name;
  }
  return allocations_.emplace(name, std::move(alloc)).first->second;
}

void ChipStore::DeleteAllocation(const std::string& name) {
  auto it = allocations_.find(name);
  if (it == allocations_.end()) {
    throw RpcError{kErrNoDev, "no allocation '" + name + "'"};
  }
  if (it->second.attached) {
    throw RpcError{kErrBusy, "allocation '" + name + "' is attached"};
  }
  for (int cid : it->second.chip_ids) chips_[cid].allocation.clear();
  allocations_.erase(it);
}

Allocation& ChipStore::AttachAllocation(const std::string& name) {
  auto it = allocations_.find(name);
  if (it == allocations_.end()) {
    throw RpcError{kErrNoDev, "no allocation '" + name + "'"};
  }
  Allocation& alloc = it->second;
  if (!alloc.attached) {
    std::set<int> used;
    for (const auto& kv : allocations_) {
      if (kv.second.attached) used.insert(kv.second.coordinator_port);
    }
    int port = kCoordinatorPortBase;
    while (used.count(port)) port++;
    alloc.coordinator_port = port;
    alloc.attached = true;
  }
  return alloc;
}

void ChipStore::DetachAllocation(const std::string& name) {
  auto it = allocations_.find(name);
  if (it == allocations_.end()) {
    throw RpcError{kErrNoDev, "no allocation '" + name + "'"};
  }
  it->second.attached = false;
  it->second.coordinator_port = 0;
}

// ---------------------------------------------------------------------------
// JSON views

namespace {

Json IntArray(const std::vector<int>& values) {
  Json arr = Json::array();
  for (int v : values) arr.push(Json::integer(v));
  return arr;
}

std::vector<int> ParseIntArray(const Json& j) {
  std::vector<int> out;
  for (const Json& item : j.items()) {
    out.push_back(static_cast<int>(item.as_int()));
  }
  return out;
}

}  // namespace

Json ChipStore::ChipJson(const Chip& chip,
                         const std::vector<int>* coord) const {
  Json j = Json::object();
  j.set("chip_id", Json::integer(chip.chip_id));
  j.set("device_path", Json::str(chip.device_path));
  j.set("pci", Json::str(chip.pci));
  j.set("accel_type", Json::str(chip.accel_type));
  j.set("phys_coord", IntArray(chip.phys_coord));
  j.set("allocation", Json::str(chip.allocation));
  if (coord != nullptr) j.set("coord", IntArray(*coord));
  return j;
}

Json ChipStore::AllocJson(const Allocation& alloc) const {
  Json j = Json::object();
  j.set("name", Json::str(alloc.name));
  j.set("chip_count", Json::integer(alloc.chip_ids.size()));
  j.set("mesh", IntArray(alloc.mesh));
  j.set("attached", Json::boolean(alloc.attached));
  j.set("provisioned", Json::boolean(alloc.provisioned));
  j.set("coordinator_port", Json::integer(alloc.coordinator_port));
  Json chips = Json::array();
  for (int cid : alloc.chip_ids) {
    chips.push(ChipJson(chips_[cid], &alloc.coords.at(cid)));
  }
  j.set("chips", std::move(chips));
  return j;
}

Json ChipStore::TopologyJson() {
  int free = 0;
  for (const Chip& c : chips_) {
    if (c.allocation.empty()) free++;
  }
  Json j = Json::object();
  j.set("accel_type", Json::str(accel_type_));
  j.set("mesh", IntArray(mesh_));
  j.set("chip_count", Json::integer(chips_.size()));
  j.set("free_chips", Json::integer(free));
  if (!pjrt_version_.empty()) j.set("pjrt_version", Json::str(pjrt_version_));
  return j;
}

Json ChipStore::Handle(const std::string& method, const Json& params) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto name_param = [&]() -> std::string {
    const Json* name = params.find("name");
    if (name == nullptr || name->as_string().empty()) {
      throw RpcError{kErrInvalidParams, "name required"};
    }
    return name->as_string();
  };

  if (method == "get_topology") return TopologyJson();
  if (method == "get_pjrt_info") {
    // Implementation-specific compute-stack report; {} when the daemon
    // was started without a PJRT plugin (doc/agent-protocol.md).
    return pjrt_info_.is_null() ? Json::object() : pjrt_info_;
  }
  if (method == "get_chips") {
    Json arr = Json::array();
    for (const Chip& c : chips_) arr.push(ChipJson(c, nullptr));
    return arr;
  }
  if (method == "get_allocations") {
    Json arr = Json::array();
    const Json* name = params.find("name");
    if (name != nullptr && !name->as_string().empty()) {
      auto it = allocations_.find(name->as_string());
      if (it != allocations_.end()) arr.push(AllocJson(it->second));
    } else {
      for (const auto& kv : allocations_) arr.push(AllocJson(kv.second));
    }
    return arr;
  }
  if (method == "create_allocation") {
    const Json* name = params.find("name");
    const Json* count = params.find("chip_count");
    std::vector<int> topology;
    if (const Json* topo = params.find("topology")) {
      topology = ParseIntArray(*topo);
    }
    const Json* provisioned = params.find("provisioned");
    return AllocJson(CreateAllocation(
        name != nullptr ? name->as_string() : "",
        count != nullptr ? static_cast<int>(count->as_int()) : 0, topology,
        provisioned != nullptr && provisioned->as_bool()));
  }
  if (method == "delete_allocation") {
    DeleteAllocation(name_param());
    return Json::boolean(true);
  }
  if (method == "attach_allocation") {
    return AllocJson(AttachAllocation(name_param()));
  }
  if (method == "detach_allocation") {
    DetachAllocation(name_param());
    return Json::boolean(true);
  }
  throw RpcError{kErrMethodNotFound, "method '" + method + "' not found"};
}

}  // namespace oim
