#include "rpc_server.h"

#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace oim {

RpcServer::RpcServer(ChipStore* store, std::string socket_path)
    : store_(store), socket_path_(std::move(socket_path)) {}

RpcServer::~RpcServer() { Shutdown(); }

bool RpcServer::Listen() {
  listen_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (listen_fd_ < 0) {
    std::perror("socket");
    return false;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (socket_path_.size() >= sizeof(addr.sun_path)) {
    std::fprintf(stderr, "socket path too long: %s\n", socket_path_.c_str());
    return false;
  }
  std::strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);

  // Refuse to steal a live socket; remove a stale one.
  int probe = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (probe >= 0) {
    if (::connect(probe, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
        0) {
      std::fprintf(stderr, "%s is already in use\n", socket_path_.c_str());
      ::close(probe);
      return false;
    }
    ::close(probe);
  }
  ::unlink(socket_path_.c_str());

  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
      0) {
    std::perror("bind");
    return false;
  }
  if (::listen(listen_fd_, 16) < 0) {
    std::perror("listen");
    return false;
  }
  return true;
}

void RpcServer::Serve() {
  while (!shutdown_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR || errno == EBADF || shutdown_.load()) break;
      std::perror("accept");
      continue;
    }
    {
      std::lock_guard<std::mutex> lock(conn_mutex_);
      if (shutdown_.load()) {
        ::close(fd);
        break;
      }
      conn_fds_.insert(fd);
    }
    std::thread(&RpcServer::HandleConnection, this, fd).detach();
  }
  // Drain: Shutdown() has already shut down every open connection fd, which
  // makes the handlers' read() return 0; wait for them all to finish before
  // the caller tears down the ChipStore.
  std::unique_lock<std::mutex> lock(conn_mutex_);
  conn_done_.wait(lock, [this] { return conn_fds_.empty(); });
}

void RpcServer::Shutdown() {
  if (shutdown_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  {
    std::lock_guard<std::mutex> lock(conn_mutex_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  ::unlink(socket_path_.c_str());
}

void RpcServer::HandleConnection(int fd) {
  std::string buffer;
  char chunk[4096];
  while (true) {
    ssize_t n = ::read(fd, chunk, sizeof(chunk));
    if (n <= 0) break;
    buffer.append(chunk, static_cast<size_t>(n));
    size_t pos;
    bool closed = false;
    while ((pos = buffer.find('\n')) != std::string::npos) {
      std::string line = buffer.substr(0, pos);
      buffer.erase(0, pos + 1);
      if (line.empty()) continue;
      std::string response = DispatchLine(line) + "\n";
      size_t written = 0;
      while (written < response.size()) {
        ssize_t w =
            ::write(fd, response.data() + written, response.size() - written);
        if (w <= 0) {
          closed = true;
          break;
        }
        written += static_cast<size_t>(w);
      }
      if (closed) break;
    }
    if (closed) break;
  }
  {
    std::lock_guard<std::mutex> lock(conn_mutex_);
    conn_fds_.erase(fd);
  }
  conn_done_.notify_all();
  ::close(fd);
}

std::string RpcServer::DispatchLine(const std::string& line) {
  Json response = Json::object();
  response.set("jsonrpc", Json::str("2.0"));
  Json request;
  std::string parse_error;
  if (!Json::parse(line, &request, &parse_error)) {
    response.set("id", Json());
    Json err = Json::object();
    err.set("code", Json::integer(kErrParse));
    err.set("message", Json::str(parse_error));
    response.set("error", std::move(err));
    return response.dump();
  }
  const Json* id = request.find("id");
  response.set("id", id != nullptr ? *id : Json());
  try {
    const Json* version = request.find("jsonrpc");
    const Json* method = request.find("method");
    if (version == nullptr || version->as_string() != "2.0" ||
        method == nullptr) {
      throw RpcError{kErrInvalidRequest, "not a JSON-RPC 2.0 request"};
    }
    const Json* params = request.find("params");
    Json empty = Json::object();
    if (params != nullptr && params->type() != Json::kObject) {
      throw RpcError{kErrInvalidParams, "params must be an object"};
    }
    Json result =
        store_->Handle(method->as_string(), params != nullptr ? *params : empty);
    response.set("result", std::move(result));
  } catch (const RpcError& rpc_error) {
    Json err = Json::object();
    err.set("code", Json::integer(rpc_error.code));
    err.set("message", Json::str(rpc_error.message));
    response.set("error", std::move(err));
  }
  return response.dump();
}

}  // namespace oim
