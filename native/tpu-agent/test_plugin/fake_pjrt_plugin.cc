// A minimal in-tree PJRT plugin: 8 fake TPU devices on a 2x2x2 torus.
//
// The CI analog of a real libtpu.so — it lets the agent's PJRT C API
// loader (src/pjrt_loader.cc) be exercised end-to-end (dlopen → version
// handshake → plugin init → client create with named options → device
// enumeration with coords) on machines with no TPU, the same way the
// reference tests its device plane against Malloc BDevs instead of real
// disks (reference spec.md:119-122).  Implements exactly the API subset
// the loader calls; everything else in the PJRT_Api table stays null.
//
// Build: make -C native/tpu-agent test-plugin  → test_plugin/fake_pjrt.so

#include <cstring>
#include <string>
#include <vector>

#include "pjrt/pjrt_c_api.h"

namespace {

constexpr int kNumDevices = 8;
constexpr int kMesh[3] = {2, 2, 2};

std::string* g_last_error_storage = nullptr;

struct FakeDevice {
  int id;
  int64_t coords[3];
  std::string kind;
  std::string debug;
  PJRT_NamedValue attrs[2];
};

FakeDevice g_devices[kNumDevices];
PJRT_Device* g_device_ptrs[kNumDevices];
bool g_client_alive = false;
std::string g_platform_name = "fake_tpu";
std::string g_platform_version = "fake-pjrt 1.0";

void InitDevices() {
  static bool done = false;
  if (done) return;
  done = true;
  for (int i = 0; i < kNumDevices; i++) {
    FakeDevice& d = g_devices[i];
    d.id = i;
    d.coords[0] = (i / (kMesh[1] * kMesh[2])) % kMesh[0];
    d.coords[1] = (i / kMesh[2]) % kMesh[1];
    d.coords[2] = i % kMesh[2];
    d.kind = "Fake TPU v5";
    d.debug = "FakeTpu(id=" + std::to_string(i) + ")";
    d.attrs[0] = PJRT_NamedValue{};
    d.attrs[0].struct_size = PJRT_NamedValue_STRUCT_SIZE;
    d.attrs[0].name = "coords";
    d.attrs[0].name_size = 6;
    d.attrs[0].type = PJRT_NamedValue_kInt64List;
    d.attrs[0].int64_array_value = d.coords;
    d.attrs[0].value_size = 3;
    d.attrs[1] = PJRT_NamedValue{};
    d.attrs[1].struct_size = PJRT_NamedValue_STRUCT_SIZE;
    d.attrs[1].name = "core_count";
    d.attrs[1].name_size = 10;
    d.attrs[1].type = PJRT_NamedValue_kInt64;
    d.attrs[1].int64_value = 1;
    d.attrs[1].value_size = 1;
    // PJRT_Device/PJRT_DeviceDescription are opaque to callers: hand out
    // the FakeDevice address under both types and cast back on entry.
    g_device_ptrs[i] = reinterpret_cast<PJRT_Device*>(&d);
  }
}

PJRT_Error* MakeError(const std::string& message) {
  // One error live at a time is enough for the loader's call pattern.
  if (g_last_error_storage == nullptr) g_last_error_storage = new std::string;
  *g_last_error_storage = message;
  return reinterpret_cast<PJRT_Error*>(g_last_error_storage);
}

void ErrorDestroy(PJRT_Error_Destroy_Args*) {}

void ErrorMessage(PJRT_Error_Message_Args* args) {
  const auto* storage = reinterpret_cast<const std::string*>(args->error);
  args->message = storage->c_str();
  args->message_size = storage->size();
}

PJRT_Error* ErrorGetCode(PJRT_Error_GetCode_Args* args) {
  args->code = PJRT_Error_Code_INTERNAL;
  return nullptr;
}

PJRT_Error* PluginInitialize(PJRT_Plugin_Initialize_Args*) {
  InitDevices();
  return nullptr;
}

PJRT_Error* PluginAttributes(PJRT_Plugin_Attributes_Args* args) {
  static PJRT_NamedValue attrs[1];
  static std::string mesh_name = "fake_mesh";
  static int64_t mesh[3] = {kMesh[0], kMesh[1], kMesh[2]};
  attrs[0] = PJRT_NamedValue{};
  attrs[0].struct_size = PJRT_NamedValue_STRUCT_SIZE;
  attrs[0].name = mesh_name.c_str();
  attrs[0].name_size = mesh_name.size();
  attrs[0].type = PJRT_NamedValue_kInt64List;
  attrs[0].int64_array_value = mesh;
  attrs[0].value_size = 3;
  args->attributes = attrs;
  args->num_attributes = 1;
  return nullptr;
}

PJRT_Error* ClientCreate(PJRT_Client_Create_Args* args) {
  InitDevices();
  // Honor a "fail" option so tests can exercise the loader's error path.
  for (size_t i = 0; i < args->num_options; i++) {
    const PJRT_NamedValue& nv = args->create_options[i];
    if (std::string(nv.name, nv.name_size) == "fail" &&
        nv.type == PJRT_NamedValue_kBool && nv.bool_value) {
      return MakeError("client creation failed by request");
    }
  }
  g_client_alive = true;
  args->client = reinterpret_cast<PJRT_Client*>(&g_client_alive);
  return nullptr;
}

PJRT_Error* ClientDestroy(PJRT_Client_Destroy_Args*) {
  g_client_alive = false;
  return nullptr;
}

PJRT_Error* ClientPlatformName(PJRT_Client_PlatformName_Args* args) {
  args->platform_name = g_platform_name.c_str();
  args->platform_name_size = g_platform_name.size();
  return nullptr;
}

PJRT_Error* ClientPlatformVersion(PJRT_Client_PlatformVersion_Args* args) {
  args->platform_version = g_platform_version.c_str();
  args->platform_version_size = g_platform_version.size();
  return nullptr;
}

PJRT_Error* ClientProcessIndex(PJRT_Client_ProcessIndex_Args* args) {
  args->process_index = 0;
  return nullptr;
}

PJRT_Error* ClientDevices(PJRT_Client_Devices_Args* args) {
  args->devices = g_device_ptrs;
  args->num_devices = kNumDevices;
  return nullptr;
}

PJRT_Error* ClientAddressableDevices(
    PJRT_Client_AddressableDevices_Args* args) {
  // Single-process fake: every device is addressable.
  args->addressable_devices = g_device_ptrs;
  args->num_addressable_devices = kNumDevices;
  return nullptr;
}

PJRT_Error* DeviceGetDescription(PJRT_Device_GetDescription_Args* args) {
  args->device_description =
      reinterpret_cast<PJRT_DeviceDescription*>(args->device);
  return nullptr;
}

PJRT_Error* DescriptionId(PJRT_DeviceDescription_Id_Args* args) {
  args->id = reinterpret_cast<FakeDevice*>(args->device_description)->id;
  return nullptr;
}

PJRT_Error* DescriptionProcessIndex(
    PJRT_DeviceDescription_ProcessIndex_Args* args) {
  args->process_index = 0;
  return nullptr;
}

PJRT_Error* DescriptionAttributes(
    PJRT_DeviceDescription_Attributes_Args* args) {
  auto* d = reinterpret_cast<FakeDevice*>(args->device_description);
  args->attributes = d->attrs;
  args->num_attributes = 2;
  return nullptr;
}

PJRT_Error* DescriptionKind(PJRT_DeviceDescription_Kind_Args* args) {
  auto* d = reinterpret_cast<FakeDevice*>(args->device_description);
  args->device_kind = d->kind.c_str();
  args->device_kind_size = d->kind.size();
  return nullptr;
}

PJRT_Error* DescriptionDebugString(
    PJRT_DeviceDescription_DebugString_Args* args) {
  auto* d = reinterpret_cast<FakeDevice*>(args->device_description);
  args->debug_string = d->debug.c_str();
  args->debug_string_size = d->debug.size();
  return nullptr;
}

PJRT_Error* DescriptionToString(PJRT_DeviceDescription_ToString_Args* args) {
  auto* d = reinterpret_cast<FakeDevice*>(args->device_description);
  args->to_string = d->debug.c_str();
  args->to_string_size = d->debug.size();
  return nullptr;
}

}  // namespace

extern "C" const PJRT_Api* GetPjrtApi() {
  static PJRT_Api api = [] {
    PJRT_Api a{};
    a.struct_size = PJRT_Api_STRUCT_SIZE;
    a.pjrt_api_version.struct_size = PJRT_Api_Version_STRUCT_SIZE;
    a.pjrt_api_version.major_version = PJRT_API_MAJOR;
    a.pjrt_api_version.minor_version = PJRT_API_MINOR;
    a.PJRT_Error_Destroy = ErrorDestroy;
    a.PJRT_Error_Message = ErrorMessage;
    a.PJRT_Error_GetCode = ErrorGetCode;
    a.PJRT_Plugin_Initialize = PluginInitialize;
    a.PJRT_Plugin_Attributes = PluginAttributes;
    a.PJRT_Client_Create = ClientCreate;
    a.PJRT_Client_Destroy = ClientDestroy;
    a.PJRT_Client_PlatformName = ClientPlatformName;
    a.PJRT_Client_PlatformVersion = ClientPlatformVersion;
    a.PJRT_Client_ProcessIndex = ClientProcessIndex;
    a.PJRT_Client_Devices = ClientDevices;
    a.PJRT_Client_AddressableDevices = ClientAddressableDevices;
    a.PJRT_Device_GetDescription = DeviceGetDescription;
    a.PJRT_DeviceDescription_Id = DescriptionId;
    a.PJRT_DeviceDescription_ProcessIndex = DescriptionProcessIndex;
    a.PJRT_DeviceDescription_Attributes = DescriptionAttributes;
    a.PJRT_DeviceDescription_Kind = DescriptionKind;
    a.PJRT_DeviceDescription_DebugString = DescriptionDebugString;
    a.PJRT_DeviceDescription_ToString = DescriptionToString;
    return a;
  }();
  return &api;
}
