"""Checkpoint / resume for training state.

The reference keeps no durable state of its own — its registry DB is
reconstructible from controller heartbeats and device state lives in SPDK
(/root/reference/README.md:131-135, SURVEY.md §5).  The TPU build's
workloads *do* carry durable state: model parameters, optimizer moments and
the data-pipeline cursor.  This package is the durable-store seam for that
state, playing the role the planned etcd backend played for the registry —
except here the store is orbax over a filesystem, sharding-aware and
async so saves overlap the next train step.
"""

from oim_tpu.checkpoint.manager import (
    Checkpointer,
    CheckpointerOptions,
    load_params,
    load_params_from_peer,
)

__all__ = [
    "Checkpointer",
    "CheckpointerOptions",
    "load_params",
    "load_params_from_peer",
]
