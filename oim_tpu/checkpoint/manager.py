"""Sharding-aware orbax checkpointing of TrainState (+ data cursor).

Design points, TPU-first:

- **Async by default.**  ``save`` hands device buffers to orbax's async
  checkpointer and returns; the transfer to host and the filesystem write
  overlap subsequent train steps (the train step donates its buffers, so
  orbax snapshots before returning control).
- **Restore is sharded.**  The restore target is an abstract TrainState
  (``jax.eval_shape`` over the init) annotated with the same NamedShardings
  training uses (``oim_tpu.models.train.state_shardings``), so each host
  reads only the shards it owns and arrays come back already placed on the
  mesh — no host-memory spike, no resharding transfer.
- **Preemption resume.**  ``restore_or_init`` makes the train loop entry
  idempotent: fresh start and post-preemption restart are the same call,
  mirroring how every reference control RPC is specified idempotent so any
  caller can blindly retry (/root/reference/spec.md:80-87).
- The data-pipeline cursor rides along as a JSON item so resume continues
  the token stream exactly where it stopped (no repeated/skipped batches).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

import jax
import orbax.checkpoint as ocp

from oim_tpu import log
from oim_tpu.common import metrics
from oim_tpu.models.train import (
    TrainState,
    params_shardings,
    shard_state,
    state_shardings,
)

# Checkpoint observability (the manager touched metrics nowhere): save
# latency here is the *enqueue + device snapshot* for async saves — the
# part that blocks the train loop — not the filesystem write.
_CKPT_SECONDS = metrics.registry().histogram(
    "oim_checkpoint_seconds",
    "Checkpoint operation latency by op (save = async enqueue + device "
    "snapshot, i.e. the train-loop stall; restore = full read).",
    ("op",),
)
_CKPT_BYTES = metrics.registry().counter(
    "oim_checkpoint_bytes_total",
    "Array bytes moved through the checkpoint manager, by op.",
    ("op",),
)


def _tree_bytes(tree) -> float:
    try:
        return float(
            sum(getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(tree))
        )
    except Exception:
        return 0.0  # observability must never break a save/restore


@dataclass(frozen=True)
class CheckpointerOptions:
    max_to_keep: int = 3
    save_interval_steps: int = 1
    async_save: bool = True
    # False = read-only open (serving): never mkdir the directory, so a
    # typo'd path cannot leave a plausible-looking empty checkpoint dir
    # (and read-only filesystems don't hit a confusing mkdir error).
    create: bool = True


def _attach_shardings(abstract, cfg, mesh):
    """ShapeDtypeStructs with NamedShardings attached — the one
    definition of a sharding-annotated restore target."""
    shardings = params_shardings(abstract, cfg, mesh)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract,
        shardings,
    )


class Checkpointer:
    """Save/restore TrainState on a mesh, with an optional JSON side-car
    for data-iterator state."""

    STATE = "state"
    DATA = "data"

    def __init__(
        self,
        directory,
        cfg,
        mesh,
        options: CheckpointerOptions | None = None,
        zero1: bool = False,
    ):
        self._cfg = cfg
        self._mesh = mesh
        # ZeRO-1 restore target: moments restore dp-sharded so a resumed
        # run keeps the sharded-optimizer placement (values are placement-
        # independent — a zero1 checkpoint restores fine either way).
        self._zero1 = zero1
        self._options = options or CheckpointerOptions()
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._options.max_to_keep,
                save_interval_steps=self._options.save_interval_steps,
                enable_async_checkpointing=self._options.async_save,
                create=self._options.create,
            ),
        )

    # -- save ---------------------------------------------------------------

    def save(
        self,
        state: TrainState,
        data_state: dict | None = None,
        force: bool = False,
    ) -> bool:
        """Queue an async save at ``state.step``.  Returns False when the
        save-interval policy skips this step."""
        step = int(jax.device_get(state.step))
        items = {
            self.STATE: ocp.args.StandardSave(state),
            # Always present so restore can unconditionally ask for it.
            self.DATA: ocp.args.JsonSave(data_state or {}),
        }
        t0 = time.perf_counter()
        saved = self._mgr.save(
            step, args=ocp.args.Composite(**items), force=force
        )
        if saved:
            _CKPT_SECONDS.observe(time.perf_counter() - t0, "save")
            _CKPT_BYTES.inc("save", by=_tree_bytes(state))
            log.current().debug("checkpoint queued", step=step)
        return saved

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def _abstract_state(self, init_fn: Callable[[], TrainState]) -> TrainState:
        shape = jax.eval_shape(init_fn)
        shardings = state_shardings(
            shape, self._cfg, self._mesh, zero1=self._zero1
        )
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shape,
            shardings,
        )

    def restore(
        self,
        init_fn: Callable[[], TrainState],
        step: int | None = None,
    ) -> tuple[TrainState, dict | None]:
        """Restore ``step`` (default: latest) directly onto the mesh.
        ``init_fn`` is only traced (``eval_shape``) for the restore target —
        it never materializes arrays."""
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError("no checkpoint to restore")
        t0 = time.perf_counter()
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                **{
                    self.STATE: ocp.args.StandardRestore(
                        self._abstract_state(init_fn)
                    ),
                    self.DATA: ocp.args.JsonRestore(),
                }
            ),
        )
        _CKPT_SECONDS.observe(time.perf_counter() - t0, "restore")
        _CKPT_BYTES.inc("restore", by=_tree_bytes(restored[self.STATE]))
        data = restored.get(self.DATA)
        log.current().info("checkpoint restored", step=step)
        return restored[self.STATE], data

    def restore_or_init(
        self,
        init_fn: Callable[[], TrainState],
    ) -> tuple[TrainState, dict | None, bool]:
        """The idempotent train-loop entry: resume from the latest
        checkpoint when one exists, otherwise materialize ``init_fn``
        sharded.  Returns ``(state, data_state, resumed)``."""
        step = self._mgr.latest_step()
        if step is not None:
            state, data = self.restore(init_fn, step)
            return state, data, True
        state = shard_state(
            init_fn(), self._cfg, self._mesh, zero1=self._zero1
        )
        return state, None, False

    def restore_params(
        self, init_params_fn: Callable[[], dict], step: int | None = None
    ) -> dict:
        """Restore just the ``params`` subtree of a training checkpoint.

        Serving needs the weights but neither has nor wants the optimizer
        state — whose tree shape depends on the trainer's optimizer flags
        (schedule, grad-clip chain), so a stand-in optimizer cannot
        reconstruct it.  A partial PyTree restore sidesteps that whole
        coupling.  ``init_params_fn`` is only traced for shapes/dtypes.
        """
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError("no checkpoint to restore")
        abstract = {"params": self._abstract_params(init_params_fn)}
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                **{
                    self.STATE: ocp.args.PyTreeRestore(
                        item=abstract,
                        # PyTreeRestore (unlike StandardRestore) does not
                        # read ShapeDtypeStruct.sharding — without these
                        # it falls back to the training topology's
                        # sharding file.
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            abstract
                        ),
                        partial_restore=True,
                    )
                }
            ),
        )
        log.current().info("checkpoint params restored", step=step)
        return restored[self.STATE]["params"]

    def _abstract_params(self, init_params_fn: Callable[[], dict]) -> dict:
        """ShapeDtypeStructs with THIS mesh's shardings attached — without
        them orbax falls back to the sharding file saved by the *training*
        topology, which is unsafe when restoring elsewhere."""
        return _attach_shardings(
            jax.eval_shape(init_params_fn), self._cfg, self._mesh
        )

    # -- params-only export (serving) ---------------------------------------

    def export_params(self, state: TrainState, directory) -> None:
        """One-shot params-only export for serving.

        The training checkpoint carries the optimizer state — for adamw,
        2 extra copies of every parameter — which an inference server
        never reads.  This writes just ``state.params`` (a standalone
        orbax StandardSave, restored by ``load_params``), synchronously.
        Params are passed as-is so orbax performs the sharded/collective
        save on multi-host meshes (no host gather).  Refuses to overwrite
        an existing export.
        """
        import os

        if os.path.exists(os.fspath(directory)):
            raise FileExistsError(
                f"params export target exists: {directory}"
            )
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(directory, state.params)
        log.current().info("params exported", dir=str(directory))

    # -- lifecycle ----------------------------------------------------------

    def wait(self) -> None:
        """Block until queued async saves hit the filesystem."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _read_exact(resp, n: int) -> bytes:
    """Read exactly ``n`` bytes from an HTTP response stream (short
    reads mean the peer died mid-stream — fail loudly, never restore a
    truncated tensor)."""
    chunks = []
    remaining = n
    while remaining > 0:
        chunk = resp.read(min(remaining, 8 << 20))
        if not chunk:
            raise IOError(
                f"peer weight stream truncated: wanted {n} bytes, "
                f"short by {remaining}"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _np_dtype(name: str):
    """numpy dtype for a manifest dtype name, including the ml_dtypes
    extension types (bfloat16 & friends) numpy itself cannot parse."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def load_params_from_peer(
    url: str,
    abstract_params=None,
    cfg=None,
    mesh=None,
    *,
    ssl_context=None,
    timeout: float = 600.0,
) -> dict:
    """Restore a params tree from a serving sibling's streamed
    ``GET /v1/weights`` endpoint (serve/server.py) — the scale-out
    fast path: a new replica pulls weights over the pod network from
    an instance that already holds them instead of re-reading blob
    storage, so bring-up is bounded by network bandwidth, not
    checkpoint cold-start (ISSUE 8 tentpole, ROADMAP item 3).

    ``abstract_params`` (a flat name → ShapeDtypeStruct dict, e.g.
    ``jax.eval_shape(lambda: init_params(key, cfg))``) validates the
    peer's manifest against THIS replica's expected geometry — a peer
    serving a different model fails with a clear error, never a shape
    error mid-decode.  Pass ``cfg`` and ``mesh`` to place leaves
    sharded exactly like ``load_params`` would; without them leaves
    land on the default device.

    Quantized serving params round-trip too: the manifest carries raw
    dtypes (int8 payloads + their ``*_wscale`` scale leaves), so a
    ``--weights-int8`` sibling hands over its quantized form directly.
    """
    import json as _json
    import struct
    import urllib.request

    import numpy as np

    if (cfg is None) != (mesh is None):
        raise ValueError("pass both cfg and mesh, or neither")
    request = urllib.request.Request(url.rstrip("/") + "/v1/weights")
    kwargs = {"context": ssl_context} if ssl_context is not None else {}
    t0 = time.perf_counter()
    leaves: dict = {}
    with urllib.request.urlopen(request, timeout=timeout, **kwargs) as resp:
        (manifest_len,) = struct.unpack(">Q", _read_exact(resp, 8))
        manifest = _json.loads(_read_exact(resp, manifest_len))
        if abstract_params is not None:
            # Validate on the MANIFEST, before a byte of payload moves:
            # a mismatched peer (wrong geometry, quantized vs not) must
            # fail in milliseconds, not after a multi-GB transfer.
            want = {
                name: (tuple(leaf.shape), str(leaf.dtype))
                for name, leaf in abstract_params.items()
            }
            got = {
                entry["name"]: (
                    tuple(int(d) for d in entry["shape"]),
                    entry["dtype"],
                )
                for entry in manifest
            }
            if want != got:
                diff = sorted(
                    set(want.items()) ^ set(got.items()),
                    key=lambda item: item[0],
                )
                raise ValueError(
                    f"peer {url} serves a different model geometry; "
                    f"first mismatches: {diff[:4]}"
                )
        for entry in manifest:
            dtype = _np_dtype(entry["dtype"])
            shape = tuple(int(d) for d in entry["shape"])
            count = 1
            for dim in shape:
                count *= dim
            raw = _read_exact(resp, count * dtype.itemsize)
            leaves[entry["name"]] = np.frombuffer(raw, dtype=dtype).reshape(
                shape
            )
    if cfg is not None:
        placed = _attach_shardings(
            jax.tree.map(
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
                leaves,
            ),
            cfg,
            mesh,
        )
        restored = {
            name: jax.device_put(leaf, placed[name].sharding)
            for name, leaf in leaves.items()
        }
    else:
        restored = {name: jax.device_put(leaf) for name, leaf in leaves.items()}
    _CKPT_SECONDS.observe(time.perf_counter() - t0, "restore-peer")
    _CKPT_BYTES.inc("restore-peer", by=_tree_bytes(restored))
    log.current().info(
        "params restored from peer",
        peer=url,
        leaves=len(restored),
        seconds=round(time.perf_counter() - t0, 2),
    )
    return restored


def load_params(directory, abstract_params, cfg=None, mesh=None) -> dict:
    """Restore a params-only export (``Checkpointer.export_params``).

    ``abstract_params`` is the target pytree of ShapeDtypeStructs (e.g.
    ``jax.eval_shape(lambda: init_params(key, cfg))``) or a concrete
    pytree of the same structure.  Pass ``cfg`` and ``mesh`` to attach
    this host's shardings to the target — without them orbax falls back
    to the sharding file written by the exporting topology, which is
    unsafe when restoring on a different one.
    """
    if (cfg is None) != (mesh is None):
        raise ValueError("pass both cfg and mesh, or neither")
    if cfg is not None:
        abstract_params = _attach_shardings(abstract_params, cfg, mesh)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(directory, target=abstract_params)
    log.current().info("params restored", dir=str(directory))
    return restored
