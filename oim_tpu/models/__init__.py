"""Model zoo: the flagship transformer LM + training utilities.

These are the workloads that run ON control-plane-provisioned slices
(BASELINE.json configs 2/3/5).  The flagship model demonstrates every
parallelism axis the framework supports: dp (batch), pp (GPipe stages),
sp (ring attention), tp (heads/mlp/vocab via GSPMD), ep (switch-MoE experts).
"""

from oim_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    logical_axes,
    forward_hidden,
    forward_local,
    param_pspecs,
)
from oim_tpu.models.beam import make_beam_search_fn
from oim_tpu.models.speculative import make_speculative_fn
from oim_tpu.models.train import (
    TrainState,
    data_pspec,
    make_eval_step,
    make_train_loop,
    make_train_step,
)
from oim_tpu.models.decode import (
    KVCache,
    decode_step,
    generate,
    make_generate_fn,
    prefill,
)

__all__ = [
    "KVCache",
    "decode_step",
    "generate",
    "make_generate_fn",
    "prefill",
    "TransformerConfig",
    "init_params",
    "logical_axes",
    "forward_hidden",
    "forward_local",
    "param_pspecs",
    "TrainState",
    "make_beam_search_fn",
    "make_eval_step",
    "make_speculative_fn",
    "make_train_loop",
    "make_train_step",
    "data_pspec",
]
