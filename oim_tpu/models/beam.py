"""Beam search decoding (batch 1, static shapes).

The remaining decoding mode next to greedy/temperature/top-k/top-p
(``models.decode``) and speculation (``models.speculative``): keep the
``beam_size`` highest-scoring hypotheses, expanding all of them in one
batched forward per step — TPU-friendly: the beams ARE the batch, the
per-step reorder is a gather on the cache's batch axis, and the whole
search is one ``lax.scan`` (one compile).

EOS-aware: a beam that emits ``eos_id`` freezes (its score stops
accumulating; it keeps competing in the running top-k), and the final
pick applies GNMT length normalization ``score / ((5+len)/6)**alpha``
so longer finished hypotheses aren't unfairly penalized.

New work for the TPU build (SURVEY.md §2.3).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from oim_tpu.models.decode import (
    _NEG_BIG,
    KVCache,
    _forward_cached,
    prefill,
)
from oim_tpu.models.transformer import TransformerConfig


def _gather_cache(cache: KVCache, parents) -> KVCache:
    """Reorder the beam (batch) axis by ``parents`` [k]."""
    take = lambda a: None if a is None else jnp.take(a, parents, axis=1)
    return KVCache(
        k=take(cache.k),
        v=take(cache.v),
        length=cache.length,
        k_scale=take(cache.k_scale),
        v_scale=take(cache.v_scale),
    )


def _beam(
    params,
    prompt,
    cfg: TransformerConfig,
    max_new_tokens: int,
    beam_size: int,
    alpha: float,
    eos_id: int | None,
):
    b, t = prompt.shape
    if b != 1:
        raise ValueError("beam search is batch-1 (the beams are the batch)")
    k = beam_size
    max_len = t + max_new_tokens
    vocab = cfg.vocab_size

    logits, cache = prefill(params, prompt, cfg, max_len)
    logp0 = jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
    # Seed: top-k first tokens of the single prompt hypothesis.
    scores, first = jax.lax.top_k(logp0, k)  # [k], [k]
    # Replicate the prompt's cache across the beam axis.
    cache = _gather_cache(cache, jnp.zeros((k,), jnp.int32))
    seqs = jnp.zeros((k, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, 0].set(first)
    finished = (
        first == eos_id if eos_id is not None
        else jnp.zeros((k,), bool)
    )
    lengths = jnp.ones((k,), jnp.int32)  # generated tokens per beam

    def step(carry, i):
        cache, seqs, scores, finished, lengths = carry
        last = jnp.take_along_axis(seqs, (i - 1)[None, None], axis=1)  # [k,1]
        logits, cache = _forward_cached(params, last, cache, cfg)
        logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [k,V]
        if eos_id is not None:
            # Frozen beams propose exactly one continuation (token 0) at
            # no cost, so they keep competing without growing.  Static
            # branch: without an eos nothing ever freezes and the mask
            # would be a provable no-op XLA cannot fold (scan carry).
            pad_row = jnp.full((vocab,), _NEG_BIG).at[0].set(0.0)
            logp = jnp.where(finished[:, None], pad_row[None, :], logp)
        total = scores[:, None] + logp  # [k, V]
        scores, flat = jax.lax.top_k(total.reshape(-1), k)
        parents = flat // vocab
        tokens = flat % vocab
        cache = _gather_cache(cache, parents)
        seqs = jnp.take(seqs, parents, axis=0)
        if eos_id is not None:
            finished = jnp.take(finished, parents)
            lengths = jnp.take(lengths, parents)
            tokens = jnp.where(finished, 0, tokens)
            lengths = lengths + (~finished).astype(jnp.int32)
        else:
            lengths = lengths + 1
        seqs = seqs.at[:, i].set(tokens)
        if eos_id is not None:
            finished = finished | (tokens == eos_id)
        return (cache, seqs, scores, finished, lengths), None

    if max_new_tokens > 1:
        (cache, seqs, scores, finished, lengths), _ = jax.lax.scan(
            step,
            (cache, seqs, scores, finished, lengths),
            jnp.arange(1, max_new_tokens),
        )
    # GNMT length normalization over generated length.
    norm = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** alpha
    best = jnp.argmax(scores / norm)
    out = jnp.concatenate([prompt[0], seqs[best]])[None]
    return out, {
        "score": scores[best],
        "normalized_score": (scores / norm)[best],
        "length": lengths[best],
    }


def make_beam_search_fn(
    cfg: TransformerConfig,
    beam_size: int = 4,
    alpha: float = 0.6,
    eos_id: int | None = None,
):
    """Jitted ``(params, prompt [1, t], max_new_tokens) ->
    (tokens [1, t + max_new], stats)``.  ``stats['score']`` is the best
    hypothesis's total logprob; with ``eos_id`` set, tokens after a
    beam's EOS are 0-padding and ``stats['length']`` bounds the real
    generation."""
    if not 1 <= beam_size <= cfg.vocab_size:
        raise ValueError(
            f"beam_size must be in [1, vocab_size={cfg.vocab_size}], "
            f"got {beam_size}"
        )
    return jax.jit(
        partial(
            _beam, cfg=cfg, beam_size=beam_size, alpha=alpha, eos_id=eos_id
        ),
        static_argnames=("max_new_tokens",),
    )
