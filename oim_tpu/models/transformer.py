"""Flagship decoder-only transformer LM, TPU-first.

Architecture: pre-RMSNorm, rotary positions, SwiGLU MLP (or switch-routed
MoE when ``n_experts > 0``), tied nothing, f32 logits.  Layers are *stacked*
and iterated with ``lax.scan`` (one compiled layer body regardless of depth
— XLA-friendly, constant compile time), stages stacked again on a leading
``pp`` dimension.

Parallelism split (see oim_tpu/parallel):
  manual (shard_map): dp (batch), sp (sequence → ring attention),
                      pp (GPipe schedule)
  automatic (GSPMD):  tp (heads / mlp hidden / vocab),
                      ep (MoE experts; the dispatch einsums reshard
                      token-major → expert-major, which XLA lowers to
                      all-to-all on ICI)

``forward_local`` is per-device SPMD code and must run inside
``shard_map(axis_names={'dp','sp','pp'})``; ``oim_tpu.models.train`` wraps
it.  All matmuls are einsums on stacked weights → MXU; accumulation dtypes
are f32 with bf16 params/activations by default.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from oim_tpu.ops import (
    apply_rope,
    flash_attention,
    reference_attention,
    reference_rmsnorm,
    rmsnorm,
)
from oim_tpu.parallel.pipeline import gpipe_spmd
from oim_tpu.parallel.ring_attention import ring_attention
from oim_tpu.parallel.ulysses import ulysses_attention


# Weight on the MoE auxiliary channel (load-balance + router z-loss) in
# the train objective — the switch-transformer value.  Lives here so the
# layer code (which folds per-layer terms into the channel) and the
# objective (which scales it once) can't disagree.
AUX_LOSS_WEIGHT = 0.01


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    # Grouped-query attention: kv heads shared by groups of query heads
    # (0 → n_heads, classic MHA).  Shrinks the decode KV cache and its
    # bandwidth by n_heads/n_kv_heads; the flash kernel reads grouped K/V
    # natively.
    n_kv_heads: int = 0
    # Biases on the q/k/v projections (the Qwen2 family; Llama has
    # none).  o/MLP biases stay unsupported — no target family uses
    # them.
    attn_bias: bool = False
    # MLP gate activation: "silu" (Llama/Mistral/Qwen/Mixtral) or
    # "gelu_tanh" (Gemma's GeGLU — torch's tanh-approximated gelu).
    mlp_act: str = "silu"
    # Gemma-family numerics: RMSNorm scales by (1 + weight) and the
    # token embedding is multiplied by sqrt(d_model) after lookup.
    norm_offset: bool = False
    embed_scale: bool = False
    d_ff: int = 0  # 0 → 4 * d_model
    n_experts: int = 0  # 0 → dense SwiGLU
    # Experts chosen per token: 1 = switch routing (gate = router prob,
    # per the switch transformer), >=2 = GShard-style top-k (gates
    # normalized over the chosen experts).
    moe_top_k: int = 1
    expert_capacity_factor: float = 1.25
    # Router z-loss coefficient (ST-MoE, arXiv:2202.08906 §2.2):
    # penalizes mean(logsumexp(router_logits)^2), keeping router logits
    # small so the f32 softmax stays in its well-conditioned range —
    # the standard stabilizer for large-scale MoE training.  0 = off
    # (bit-identical to before); the paper's value is 1e-3.
    # Trade-off: the term shares the single aux channel with the
    # load-balance loss (pre-divided so the objective scale is exact),
    # so with z-loss ON, (loss - ce)/AUX_LOSS_WEIGHT reads balance PLUS
    # the scaled z term — expert-imbalance monitoring should compare
    # against a z-only baseline, or run with coef 0.  A second channel
    # through both pipeline schedules wasn't worth that diagnostic.
    router_z_loss: float = 0.0
    rope_theta: float = 10000.0
    # Llama-3.1 long-context RoPE frequency remap as (factor,
    # low_freq_factor, high_freq_factor, original_max_position) — empty
    # = plain RoPE.  A tuple (not a dict) so the config stays hashable
    # for jit static args; ops/rope.py applies the piecewise rule.
    rope_scaling: tuple = ()
    # RMSNorm epsilon — configurable so imported checkpoints (HF Llama
    # uses 1e-5) reproduce their source numerics exactly
    # (models/hf.py); 1e-6 is this framework's native default.
    norm_eps: float = 1e-6
    n_stages: int = 1  # pipeline stages; must divide n_layers
    n_microbatches: int = 1
    # Gradient accumulation: the per-device batch is split into this many
    # sequential microbatches whose grads are averaged before the single
    # optimizer update — same math as the full batch (equal splits, equal
    # per-microbatch label counts), peak activation memory divided by N.
    # Orthogonal to pp's n_microbatches (which pipelines within one
    # forward/backward).
    grad_accum: int = 1
    dtype: str = "bfloat16"  # activation/compute dtype
    param_dtype: str = "float32"
    remat: bool = True
    # Pallas (Mosaic) kernels cannot sit inside GSPMD-auto-partitioned
    # regions; the train step enables them only when every mesh axis is
    # manual (tp == ep == 1) and falls back to XLA-fused reference ops
    # otherwise.
    use_pallas: bool = True
    # Fused unembed+CE (ops/fused_ce.py): the train loss streams vocab
    # tiles through VMEM instead of materializing [b, t, V] logits in
    # HBM.  Rides the use_pallas gate (off inside GSPMD-auto regions —
    # under tp the vocab axis is sharded and the global logsumexp would
    # need a cross-shard combine); decode/serving keep real logits.
    fused_ce: bool = True
    # Sequence-parallel attention over sp>1: "ring" rotates K/V blocks via
    # ppermute (O(T/sp) memory, any head count); "ulysses" trades sequence
    # for head shards with one all_to_all each way (fewer collective hops,
    # needs n_heads % sp == 0).  See oim_tpu/parallel/ulysses.py.
    attn_impl: str = "ring"
    # Pipeline schedule over pp>1: "gpipe" (autodiff transpose, simple) or
    # "1f1b" (interleaved fwd/bwd, min(M, 2S-1) in-flight activations and
    # per-microbatch loss head — see parallel/pipeline.py).
    pp_schedule: str = "gpipe"
    # Sliding-window attention (Mistral-style): each query attends the
    # last `sliding_window` positions (0 = full causal attention).
    # Train: flash and the sp ring both skip fully-masked blocks
    # (O(T·W)); ulysses masks over its full-sequence view.  Decode/
    # serving mask the full-length
    # cache by position arithmetic (rows are 1:1 with global positions)
    # — exact today; a W-row ring buffer is the later memory win.
    sliding_window: int = 0
    # Sequence packing: >= 0 marks this token id as a document separator
    # (BOS-style: the separator belongs to the document it opens).
    # Attention is masked to same-document pairs (flash/ring/ulysses all
    # carry segment ids) and labels crossing a boundary drop out of the
    # loss, so a packed batch trains identically to per-document batches.
    doc_sep_id: int = -1

    @property
    def gemma_numerics(self) -> bool:
        """All three Gemma-family numerics on (GeGLU + (1+w) RMSNorm +
        sqrt(d) embed scale) — THE exportable-as-Gemma predicate shared
        by hf.py and the export CLI (GemmaModel applies all three
        unconditionally, so partial combos have no HF analog)."""
        return (
            self.mlp_act == "gelu_tanh"
            and self.norm_offset
            and self.embed_scale
        )

    def __post_init__(self):
        if self.mlp_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"unknown mlp_act {self.mlp_act!r}; "
                "expected 'silu' or 'gelu_tanh'"
            )
        if self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; "
                "expected 'ring' or 'ulysses'"
            )
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pp_schedule {self.pp_schedule!r}; "
                "expected 'gpipe' or '1f1b'"
            )
        if self.n_kv_heads and (
            self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads
        ):
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must be a positive divisor "
                f"of n_heads={self.n_heads}"
            )
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum={self.grad_accum} must be >= 1")
        if self.rope_scaling:
            if len(self.rope_scaling) != 4:
                raise ValueError(
                    "rope_scaling must be empty or (factor, low_freq_factor, "
                    f"high_freq_factor, original_max_position); "
                    f"got {self.rope_scaling!r}"
                )
            factor, low, high, orig = self.rope_scaling
            # Degenerate values produce inf frequencies / divide-by-zero
            # smoothing — NaN logits with no error, the silent-wrong-
            # numerics failure this validation exists to prevent.
            if factor <= 0 or low <= 0 or orig <= 0 or low >= high:
                raise ValueError(
                    "rope_scaling needs factor>0, 0<low_freq_factor"
                    f"<high_freq_factor, original_max>0; got "
                    f"{self.rope_scaling!r}"
                )
        if self.moe_top_k < 1 or (
            self.n_experts and self.moe_top_k > self.n_experts
        ):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in "
                f"[1, n_experts={self.n_experts}]"
            )
        if self.sliding_window < 0:
            raise ValueError(
                f"sliding_window={self.sliding_window} must be >= 0"
            )
        if self.doc_sep_id >= 0:
            if self.doc_sep_id >= self.vocab_size:
                raise ValueError(
                    f"doc_sep_id={self.doc_sep_id} outside vocab "
                    f"{self.vocab_size}"
                )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def layers_per_stage(self) -> int:
        if self.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers={self.n_layers} not divisible by "
                f"n_stages={self.n_stages}"
            )
        return self.n_layers // self.n_stages

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


# ---------------------------------------------------------------------------
# Parameters


def init_params(key: jax.Array, cfg: TransformerConfig) -> dict:
    """Truncated-normal init, stacked [n_stages, layers_per_stage, ...]."""
    pdt = jnp.dtype(cfg.param_dtype)
    d, n = cfg.d_model, cfg.n_heads * cfg.head_dim
    kvn = cfg.kv_heads * cfg.head_dim
    f, s, l = cfg.ff_dim, cfg.n_stages, cfg.layers_per_stage
    keys = iter(jax.random.split(key, 16))

    def dense(key, *shape, fan_in):
        return (
            jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            / math.sqrt(fan_in)
        ).astype(pdt)

    params = {
        "wte": dense(next(keys), cfg.vocab_size, d, fan_in=d),
        "attn_norm": jnp.ones((s, l, d), pdt),
        "wq": dense(next(keys), s, l, d, n, fan_in=d),
        "wk": dense(next(keys), s, l, d, kvn, fan_in=d),
        "wv": dense(next(keys), s, l, d, kvn, fan_in=d),
        "wo": dense(next(keys), s, l, n, d, fan_in=n),
        "mlp_norm": jnp.ones((s, l, d), pdt),
        "final_norm": jnp.ones((d,), pdt),
        "wlm": dense(next(keys), d, cfg.vocab_size, fan_in=d),
    }
    if cfg.attn_bias:
        params.update(
            {
                "bq": jnp.zeros((s, l, n), pdt),
                "bk": jnp.zeros((s, l, kvn), pdt),
                "bv": jnp.zeros((s, l, kvn), pdt),
            }
        )
    if cfg.n_experts:
        e = cfg.n_experts
        params.update(
            {
                "router": dense(next(keys), s, l, d, e, fan_in=d),
                "w_gate": dense(next(keys), s, l, e, d, f, fan_in=d),
                "w_in": dense(next(keys), s, l, e, d, f, fan_in=d),
                "w_out": dense(next(keys), s, l, e, f, d, fan_in=f),
            }
        )
    else:
        params.update(
            {
                "w_gate": dense(next(keys), s, l, d, f, fan_in=d),
                "w_in": dense(next(keys), s, l, d, f, fan_in=d),
                "w_out": dense(next(keys), s, l, f, d, fan_in=f),
            }
        )
    return params


def logical_axes(cfg: TransformerConfig) -> dict:
    """Logical dim names per parameter (see parallel.sharding rules)."""
    axes = {
        "wte": ("vocab", "model"),
        "attn_norm": ("stages", None, None),
        "wq": ("stages", None, "model", "heads"),
        "wk": ("stages", None, "model", "heads"),
        "wv": ("stages", None, "model", "heads"),
        "wo": ("stages", None, "heads", "model"),
        "mlp_norm": ("stages", None, None),
        "final_norm": (None,),
        "wlm": ("model", "vocab"),
    }
    if cfg.attn_bias:
        axes.update(
            {
                "bq": ("stages", None, "heads"),
                "bk": ("stages", None, "heads"),
                "bv": ("stages", None, "heads"),
            }
        )
    if cfg.n_experts:
        axes.update(
            {
                "router": ("stages", None, "model", None),
                "w_gate": ("stages", None, "experts", "model", "mlp"),
                "w_in": ("stages", None, "experts", "model", "mlp"),
                "w_out": ("stages", None, "experts", "mlp", "model"),
            }
        )
    else:
        axes.update(
            {
                "w_gate": ("stages", None, "model", "mlp"),
                "w_in": ("stages", None, "model", "mlp"),
                "w_out": ("stages", None, "mlp", "model"),
            }
        )
    return axes


def param_pspecs(cfg: TransformerConfig, rules=None) -> dict:
    """Full PartitionSpecs (manual + auto axes) per parameter."""
    from oim_tpu.parallel.sharding import DEFAULT_RULES, partition_spec

    rules = rules or DEFAULT_RULES
    return {
        name: partition_spec(dims, rules)
        for name, dims in logical_axes(cfg).items()
    }


def manual_pspecs(cfg: TransformerConfig) -> dict:
    """PartitionSpecs restricted to the manual axes (what shard_map sees):
    only the stacked ``stages`` dimension is manual (pp)."""
    specs = {}
    for name, dims in logical_axes(cfg).items():
        specs[name] = P(*("pp" if dim == "stages" else None for dim in dims))
    return specs


# ---------------------------------------------------------------------------
# Forward (per-device SPMD)


def _rmsnorm(x, w, cfg: TransformerConfig):
    if cfg.norm_offset:
        # Gemma convention: the learned scale is a residual around 1 —
        # formed and KEPT in f32 (both norm impls compute in f32; a
        # round back to bf16 would shave the learned scale's precision
        # where HF's GemmaRMSNorm keeps it).
        w = 1.0 + w.astype(jnp.float32)
    if cfg.use_pallas:
        return rmsnorm(x, w, cfg.norm_eps)
    return reference_rmsnorm(x, w, cfg.norm_eps)


def embed_lookup(wte, tokens, cfg: TransformerConfig):
    """THE token-embedding lookup (train, solo decode, and the serving
    engine all route here so Gemma's sqrt(d_model) scale cannot be
    applied in some paths and missed in others — the scale rounds
    through the compute dtype, matching HF)."""
    dt = cfg.compute_dtype
    x = wte.astype(dt)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dt)
    return x


def _mlp_act(x, cfg: TransformerConfig):
    """The gate activation: silu (Llama family) or Gemma's GeGLU
    (torch gelu(approximate="tanh"))."""
    if cfg.mlp_act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _attention(x, lp, positions, cfg: TransformerConfig, sp_size,
               segments=None):
    b, t, d = x.shape
    h, hd, kvh = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    normed = _rmsnorm(x, lp["attn_norm"], cfg)
    q = jnp.einsum("btd,dn->btn", normed, lp["wq"])
    k = jnp.einsum("btd,dn->btn", normed, lp["wk"])
    v = jnp.einsum("btd,dn->btn", normed, lp["wv"])
    if "bq" in lp:  # Qwen-style qkv biases (cfg.attn_bias)
        # Cast to the activation dtype: an f32 bias against bf16
        # activations would promote everything downstream.
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(b, t, h, hd)
    k = k.reshape(b, t, kvh, hd)
    v = v.reshape(b, t, kvh, hd)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    if segments is not None and segments.shape[0] != b:
        # Microbatched pipeline stages see a slice of the batch; segments
        # were built for the full local batch and broadcast over it.
        raise ValueError(
            f"segments batch {segments.shape[0]} != activation batch {b}"
        )
    if sp_size > 1:
        if cfg.attn_impl == "ulysses":
            # Ulysses trades sequence shards for HEAD shards via
            # all_to_all; broadcast the kv groups so every shard gets a
            # full head set (grouped head-sharding is future work).
            if kvh != h:
                k = jnp.repeat(k, h // kvh, axis=2)
                v = jnp.repeat(v, h // kvh, axis=2)
            out = ulysses_attention(
                q, k, v, "sp", causal=True, use_flash=cfg.use_pallas,
                segments=segments, window=cfg.sliding_window,
            )
        else:  # "ring" (validated in __post_init__)
            # The ring carries kv-sized blocks natively: GQA divides the
            # rotation traffic by n_heads/n_kv_heads.
            out = ring_attention(q, k, v, "sp", causal=True,
                                 segments=segments,
                                 window=cfg.sliding_window)
    elif cfg.use_pallas:
        out = flash_attention(q, k, v, True, window=cfg.sliding_window,
                              segments=segments)
    else:
        out = reference_attention(
            q, k, v, True, segments, cfg.sliding_window
        )
    out = out.reshape(b, t, h * hd)
    return x + jnp.einsum("btn,nd->btd", out, lp["wo"]).astype(x.dtype)


def _dense_mlp(x, lp, cfg: TransformerConfig):
    normed = _rmsnorm(x, lp["mlp_norm"], cfg)
    gate = _mlp_act(jnp.einsum("btd,df->btf", normed, lp["w_gate"]), cfg)
    up = jnp.einsum("btd,df->btf", normed, lp["w_in"])
    down = jnp.einsum("btf,fd->btd", gate * up, lp["w_out"])
    return x + down.astype(x.dtype), jnp.zeros((), jnp.float32)


def _router_gates(probs, top_k: int):
    """(top-k probs [G, K], indices [G, K], gates [G, K]).

    k=1: the gate is the raw router prob (switch transformer — keeps the
    router differentiable through the scale of its own choice);
    k>=2: gates renormalized over the chosen experts (GShard)."""
    top_probs, top_idx = jax.lax.top_k(probs, top_k)
    if top_k == 1:
        return top_probs, top_idx, top_probs
    return top_probs, top_idx, top_probs / jnp.sum(
        top_probs, axis=-1, keepdims=True
    )


def _capacity_dispatch(top_idx, gates, e: int, capacity: int):
    """Queue tokens into expert slots with choice-rank priority.

    top_idx/gates: [G, K].  Returns (dispatch, combine), both
    [G, E, capacity]: dispatch is the 0/1 slot assignment, combine is
    dispatch scaled by the choice's gate.  Rank r tokens take positions
    after every rank < r assignment to the same expert (first choices
    never lose a slot to second choices); overflow rows are all-zero, so
    dropped assignments fall back to the residual.  Pure function of the
    routing — unit-tested directly in tests/test_model.py.
    """
    g, k = top_idx.shape
    dispatch = jnp.zeros((g, e, capacity), jnp.float32)
    combine = jnp.zeros((g, e, capacity), jnp.float32)
    prior = jnp.zeros((e,), jnp.float32)  # per-expert count so far
    for rank in range(k):
        assign = jax.nn.one_hot(top_idx[:, rank], e, dtype=jnp.float32)
        position = (jnp.cumsum(assign, axis=0) - 1.0 + prior[None, :]) * assign
        position = jnp.where(assign > 0, position, -1.0)
        prior = prior + jnp.sum(assign, axis=0)
        keep = (position >= 0) & (position < capacity)
        d_rank = jax.nn.one_hot(
            jnp.where(keep, position, -1).astype(jnp.int32),
            capacity,
            dtype=jnp.float32,
        )  # [G, E, C]
        dispatch = dispatch + d_rank
        combine = combine + d_rank * gates[:, rank, None, None]
    return dispatch, combine


def _switch_moe(x, lp, cfg: TransformerConfig):
    """Top-k expert routing with capacity, Mesh-TensorFlow style dispatch:
    the one-hot dispatch/combine einsums ride the MXU and GSPMD turns the
    token→expert resharding into all-to-all over ``ep``.

    k=1 is switch-transformer routing; k>=2 is GShard-style with
    choice-rank priority (every token's first choice queues before any
    token's second choice, so drops hit the lower-gate assignments
    first)."""
    b, t, d = x.shape
    e, k = cfg.n_experts, cfg.moe_top_k
    g = b * t
    capacity = max(int(cfg.expert_capacity_factor * k * g / e), 1)
    normed = _rmsnorm(x, lp["mlp_norm"], cfg).reshape(g, d)

    router_logits = jnp.einsum(
        "gd,de->ge", normed.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [G, E]
    _, top_idx, gates = _router_gates(probs, k)  # [G, K] each
    dispatch, combine = _capacity_dispatch(top_idx, gates, e, capacity)

    expert_in = jnp.einsum("gec,gd->ecd", dispatch, normed.astype(jnp.float32))
    gate = _mlp_act(
        jnp.einsum("ecd,edf->ecf", expert_in, lp["w_gate"]), cfg
    )
    up = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_in"])
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, lp["w_out"])
    out = jnp.einsum("gec,ecd->gd", combine, expert_out).reshape(b, t, d)

    # Load-balancing auxiliary loss over first choices (switch/GShard).
    first_assign = jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32)
    density = jnp.mean(first_assign, axis=0)  # fraction routed per expert
    density_proxy = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(density * density_proxy)
    if cfg.router_z_loss:
        # ST-MoE router z-loss: mean squared logsumexp of the router
        # logits, folded into the shared aux channel.  The train
        # objective scales aux by AUX_LOSS_WEIGHT, so the coefficient
        # is pre-divided — the effective term is exactly
        # router_z_loss * mean(z²).
        z = jax.nn.logsumexp(router_logits, axis=-1)  # [G]
        aux = aux + (cfg.router_z_loss / AUX_LOSS_WEIGHT) * jnp.mean(z * z)
    return x + out.astype(x.dtype), aux


def _cast_matmul_weights(lp: dict, cfg: TransformerConfig) -> dict:
    """Matmul operands in compute dtype: f32 master weights mixed with
    bf16 activations would promote every einsum to an f32 matmul — HALF
    the MXU rate — for no accuracy the f32 accumulator doesn't already
    give.  Norm scales stay f32 (elementwise, VPU), the MoE router keeps
    its deliberate f32 math, and under MoE the expert weights stay f32
    too: ``_switch_moe`` feeds them f32 ``expert_in`` so a bf16 cast would
    promote right back (quantizing the weights for zero speedup)."""
    dt = cfg.compute_dtype
    keep = {"attn_norm", "mlp_norm", "router"}
    if cfg.n_experts:
        keep |= {"w_gate", "w_in", "w_out"}
    return {k: v if k in keep else v.astype(dt) for k, v in lp.items()}


def _layer(carry, lp, cfg: TransformerConfig, sp_size, segments=None):
    x, positions, aux = carry
    lp = _cast_matmul_weights(lp, cfg)
    x = _attention(x, lp, positions, cfg, sp_size, segments)
    if cfg.n_experts:
        x, layer_aux = _switch_moe(x, lp, cfg)
    else:
        x, layer_aux = _dense_mlp(x, lp, cfg)
    return (x, positions, aux + layer_aux), None


def _stage_layer_params(params: dict, cfg: TransformerConfig) -> dict:
    """This pp-rank's stacked layer weights (leading dim layers_per_stage).
    Under shard_map the ``stages`` dim arrived pre-sliced to size 1."""
    layer_names = {"attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                   "router", "w_gate", "w_in", "w_out", "bq", "bk", "bv"}
    return {
        name: value[0]
        for name, value in params.items()
        if name in layer_names
    }


def make_stage_fn(cfg: TransformerConfig, positions: jax.Array, sp_size: int,
                  segments: jax.Array | None = None):
    """One pipeline stage's layer stack as ``(stage_params, act,
    mb_idx=None) -> (act, aux)`` — the unit both pipeline schedules and
    the single-stage path run.  ``positions`` broadcast over any
    (micro)batch size.  ``segments`` (sequence packing) ride the closure
    like cfg: [b_local, t_local] on the single-stage path, or
    [n_micro, mb, t_local] under pipelining — the schedules pass their
    current microbatch index and the stage slices its row (bubble steps
    pass clipped indices; their garbage output is masked downstream
    like every other bubble product)."""
    base_layer_fn = partial(_layer, cfg=cfg, sp_size=sp_size)

    def stage_fn(stage_params, activation, mb_idx=None):
        seg = segments
        if segments is not None and segments.ndim == 3:
            if mb_idx is None:
                raise ValueError(
                    "microbatched segments need the schedule's mb_idx"
                )
            seg = jax.lax.dynamic_index_in_dim(
                segments,
                jnp.clip(mb_idx, 0, segments.shape[0] - 1),
                0,
                keepdims=False,
            )
        layer_fn = partial(base_layer_fn, segments=seg)
        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn)
        (out, _, aux), _ = jax.lax.scan(
            lambda carry, lw: layer_fn(carry, lw),
            (activation, positions, jnp.zeros((), jnp.float32)),
            stage_params,
        )
        return out, aux

    return stage_fn


def _doc_segments(tokens, cfg: TransformerConfig) -> jax.Array:
    """Global document ids for a packed [b, t_local] token shard.

    A separator opens a new document (BOS-style), so the id is the
    inclusive running count of separators in GLOBAL sequence order:
    local cumsum plus the preceding shards' totals (one ``all_gather``
    of a [b]-vector over ``sp`` — negligible next to the ring's k/v
    rotation).  Must run inside shard_map with the ``sp`` axis.
    """
    sep = (tokens == cfg.doc_sep_id).astype(jnp.int32)
    local = jnp.cumsum(sep, axis=1)  # [b, t_local]
    totals = jax.lax.all_gather(local[:, -1], "sp")  # [sp, b]
    before = (
        jnp.arange(totals.shape[0]) < jax.lax.axis_index("sp")
    )[:, None]
    offset = jnp.sum(jnp.where(before, totals, 0), axis=0)  # [b]
    return local + offset[:, None]


def forward_local(
    params: dict, tokens: jax.Array, cfg: TransformerConfig
) -> tuple[jax.Array, jax.Array]:
    """Per-device forward: local token shard → local f32 logits + aux loss.

    tokens: [batch_local, seq_local].  Must run inside shard_map with
    manual axes {'dp', 'sp', 'pp'}.  The returned aux is PER-DEVICE (this
    pipeline stage's own layers only) — psum over ``pp`` for the global
    value; keeping collectives out of it lets the train step differentiate
    a purely local objective (models/train.py ``_local_objective``).
    """
    x, aux = forward_hidden(params, tokens, cfg)
    return _unembed(x, params["wlm"], cfg), aux


def forward_hidden(
    params: dict, tokens: jax.Array, cfg: TransformerConfig
) -> tuple[jax.Array, jax.Array]:
    """``forward_local`` up to (and including) the final rmsnorm — the
    [b, t, D] hidden the unembed consumes.  Split out so the fused
    unembed+CE path (ops/fused_ce.py) can take the hidden directly and
    never materialize the [b, t, V] logits; same shard_map contract."""
    sp_size = jax.lax.axis_size("sp")
    sp_index = jax.lax.axis_index("sp")
    pp_size = jax.lax.axis_size("pp")
    b, t_local = tokens.shape
    dt = cfg.compute_dtype

    x = embed_lookup(params["wte"], tokens, cfg)  # [b, t, D]
    # 1-D positions broadcast over any (micro)batch size.
    positions = sp_index * t_local + jnp.arange(t_local)

    segments = (
        _doc_segments(tokens, cfg) if cfg.doc_sep_id >= 0 else None
    )
    stage_params = _stage_layer_params(params, cfg)

    if pp_size > 1:
        n_micro = max(cfg.n_microbatches, 1)
        if b % n_micro:
            raise ValueError(
                f"local batch {b} not divisible by n_microbatches={n_micro}"
            )
        mb = b // n_micro
        x_micro = x.reshape(n_micro, mb, t_local, cfg.d_model)
        if segments is not None:
            # Stage functions slice their current microbatch's row by
            # the schedule-provided index (make_stage_fn).
            segments = segments.reshape(n_micro, mb, t_local)
        run_stage = make_stage_fn(cfg, positions, sp_size, segments)
        # Outputs are real only on the LAST stage (zeros elsewhere); the
        # loss in models/train.py masks to the last stage, so the garbage
        # logits other stages compute below are never counted.  The MoE
        # aux loss is collected per (stage, microbatch) with bubble steps
        # masked out inside the schedule.
        x, aux = gpipe_spmd(
            run_stage, stage_params, x_micro, "pp", stage_remat=cfg.remat
        )
        x = x.reshape(b, t_local, cfg.d_model)
    else:
        run_stage = make_stage_fn(cfg, positions, sp_size, segments)
        x, aux = run_stage(stage_params, x)

    x = _rmsnorm(x, params["final_norm"], cfg)
    return x, aux


def _unembed(x, wlm, cfg: TransformerConfig):
    """f32 logits from compute-dtype inputs with f32 MXU accumulation.

    bf16 operands at the MXU's full rate, f32 accumulator/output — the
    unembed is ~20% of step FLOPs at vocab 32k, and f32 operands would run
    it at half throughput for no accuracy the f32 accumulator doesn't
    already provide."""
    return jnp.einsum(
        "btd,dv->btv",
        x.astype(cfg.compute_dtype),
        wlm.astype(cfg.compute_dtype),
        preferred_element_type=jnp.float32,
    )
