"""Speculative decoding with prompt-lookup drafting (greedy, batch 1).

Sequential greedy decode runs one bandwidth-bound forward per token.
Speculation verifies ``draft_len`` guessed tokens in ONE forward over
``draft_len + 1`` positions — accepted guesses cost a fraction of a
step each; the worst case degrades to exactly sequential decode (one
real token per forward), never to wrong output:

- **Drafting is assistant-free** (prompt lookup): the draft for the next
  tokens is whatever followed the most recent earlier occurrence of the
  last ``ngram`` generated/prompt tokens.  Free to compute, surprisingly
  effective on extraction/summarization/code where outputs echo inputs;
  useless-but-harmless on novel text.
- **TPU-friendly shapes.**  Every iteration runs the same static
  ``[1, draft_len + 1]`` verify forward inside a ``lax.while_loop``;
  the history ring, cache, and n-gram search are all fixed-size with
  masking — one compile total.
- **Exactly greedy.**  Accepted tokens are provably the tokens
  sequential greedy would emit (each is argmax given a fully-verified
  prefix); rejected drafts roll the cache length back, and the stale
  rows past it are masked until overwritten (the same overshoot argument
  the serving engine's slot cache uses).  Asserted token-for-token
  against ``generate`` in tests/test_decode.py.

New work for the TPU build (SURVEY.md §2.3; the reference is a storage
control plane).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from oim_tpu.models.decode import KVCache, _forward_cached
from oim_tpu.models.transformer import TransformerConfig


def _draft_ngram(history, length, draft_len: int, ngram: int):
    """Prompt-lookup draft: the ``draft_len`` tokens that followed the
    most recent earlier occurrence of the last ``ngram`` tokens.

    history [T] int32 ring (first ``length`` valid).  Returns
    (draft [draft_len], found bool).  No match → zeros drafts (they
    simply fail verification; one real token still decodes).
    """
    t = history.shape[0]
    query = jax.lax.dynamic_slice(history, (length - ngram,), (ngram,))
    # windows[p] = history[p : p + ngram] (clipped gather; out-of-range
    # rows are masked below).
    idx = jnp.arange(t)[:, None] + jnp.arange(ngram)[None, :]
    windows = history[jnp.clip(idx, 0, t - 1)]
    matches = jnp.all(windows == query[None, :], axis=1)
    # Prefer the most recent match whose continuation lies fully inside
    # the decided region [0, length): rows at/past ``length`` are zeros
    # (undecided), and a match ending near the edge drafts them —
    # wasting the draft budget in exactly the self-repetition regime
    # where lookup should accept everything.  Fall back to the freshest
    # edge match (continuation clipped by the zero rows) when no
    # fully-decided match exists yet.
    positions = jnp.arange(t)
    ok = matches & (positions + ngram < length - ngram + 1)
    best_full = jnp.max(
        jnp.where(ok & (positions + ngram + draft_len <= length),
                  positions, -1)
    )
    best_edge = jnp.max(jnp.where(ok, positions, -1))
    best = jnp.where(best_full >= 0, best_full, best_edge)
    found = best_edge >= 0
    start = jnp.clip(best + ngram, 0, t - draft_len)
    draft = jax.lax.dynamic_slice(history, (start,), (draft_len,))
    return jnp.where(found, draft, jnp.zeros_like(draft)), found


def _speculative(
    params,
    prompt,
    cfg: TransformerConfig,
    max_new_tokens: int,
    draft_len: int,
    ngram: int,
):
    b, t = prompt.shape
    if b != 1:
        raise ValueError("speculative decoding is batch-1 (latency mode)")
    # History ring: prompt + generated (+ headroom for the final
    # overshoot of up to draft_len extra accepted tokens).
    t_buf = t + max_new_tokens + draft_len + 1
    cache = KVCache.create(cfg, 1, t_buf)
    history = jnp.zeros((t_buf,), jnp.int32)
    history = jax.lax.dynamic_update_slice(history, prompt[0], (0,))

    # Prefill: cache holds the prompt; the first greedy token is decided
    # but not yet fed (the invariant: cache.length == length - 1, i.e.
    # every decided token except the newest has K/V rows).
    logits, cache = _forward_cached(params, prompt, cache, cfg)
    first = jnp.argmax(logits[0, -1]).astype(jnp.int32)
    history = jax.lax.dynamic_update_slice(history, first[None], (t,))
    length = jnp.int32(t + 1)

    def cond(carry):
        _, _, length, _, _ = carry
        return length - t < max_new_tokens

    def body(carry):
        cache, history, length, iters, accepted_total = carry
        draft, _ = _draft_ngram(history, length, draft_len, ngram)
        # Verify forward over [newest token, draft...] at the cache
        # frontier: logits_i = distribution AFTER consuming input i.
        last = jax.lax.dynamic_slice(history, (length - 1,), (1,))
        inputs = jnp.concatenate([last, draft])[None]
        logits, cache = _forward_cached(params, inputs, cache, cfg)
        greedy = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
        # draft[i] survives iff every earlier draft matched too.
        match = jnp.cumprod(
            (draft == greedy[:draft_len]).astype(jnp.int32)
        )
        accepted = jnp.sum(match)  # 0..draft_len
        # Emit greedy[0..accepted]: accepted+1 real tokens.
        emitted = accepted + 1
        keep = jnp.arange(draft_len + 1) < emitted
        patch = jnp.where(
            keep, greedy, jax.lax.dynamic_slice(
                history, (length,), (draft_len + 1,)
            )
        )
        history = jax.lax.dynamic_update_slice(history, patch, (length,))
        length = length + emitted
        # Roll back the cache past the verified prefix: rows for rejected
        # draft inputs are stale garbage, masked until overwritten.
        cache = KVCache(
            k=cache.k, v=cache.v, length=length - 1,
            k_scale=cache.k_scale, v_scale=cache.v_scale,
        )
        return cache, history, length, iters + 1, accepted_total + accepted

    carry = (cache, history, length, jnp.int32(0), jnp.int32(0))
    _, history, length, iters, accepted_total = jax.lax.while_loop(
        cond, body, carry
    )
    out = jax.lax.dynamic_slice(history, (0,), (t + max_new_tokens,))
    return out[None], {
        "iterations": iters,
        "drafts_accepted": accepted_total,
        "tokens": jnp.int32(max_new_tokens),
    }


def make_speculative_fn(
    cfg: TransformerConfig, draft_len: int = 4, ngram: int = 2
):
    """Jitted greedy ``(params, prompt [1, t], max_new_tokens) ->
    (tokens [1, t + max_new], stats)`` with prompt-lookup speculation.
    ``stats['iterations']`` counts verify forwards — sequential decode
    would use ``max_new_tokens - 1`` of them (prefill already decides
    the first token); fewer means speculation paid.
    """
    if draft_len < 1 or ngram < 1:
        raise ValueError(
            f"need draft_len>=1, ngram>=1; got {draft_len}, {ngram}"
        )
    return jax.jit(
        partial(_speculative, cfg=cfg, draft_len=draft_len, ngram=ngram),
        static_argnames=("max_new_tokens",),
    )
