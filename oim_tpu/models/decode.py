"""Autoregressive inference: prefill + KV-cache decode + sampling.

The serving-side counterpart of ``models.train`` (the reference framework
is a control plane and has no model inventory — this is new work grounded
in SURVEY.md §2.3's TPU-build column).  TPU-first design decisions:

- **Static shapes everywhere.**  The cache is pre-allocated at
  ``max_len``; the decode loop is a ``lax.scan`` over a fixed number of
  steps with masking doing the work of "length" — nothing reshapes, so
  XLA compiles one program for the whole generation.
- **Prefill and decode share one cached-attention primitive.**  Prefill
  writes the prompt's K/V into the cache in one shot (big MXU-friendly
  einsums over the whole prompt); each decode step appends one position
  via ``dynamic_update_slice``.  MoE routes drop-free per token on both
  (``_moe_exact``) — inference results must not depend on batch packing
  or padding, so capacity routing stays a train-path-only construct.
- **GSPMD, not shard_map.**  Decode has no sequence axis to parallelize
  (t=1), so inference relies on sharding *propagation*: shard the params
  (and the prompt's batch over ``dp``) before calling and XLA propagates
  head/tensor sharding through the cache and inserts the collectives —
  the train-path manual axes (sp ring, pp pipeline) don't apply.
- bf16 activations with f32 logits/softmax, matching the train path.

Weights are the training checkpoints unchanged (same stacked
``[n_stages, layers_per_stage, ...]`` pytree from ``init_params``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp

from oim_tpu.models.transformer import (
    TransformerConfig,
    _dense_mlp,
    _mlp_act,
    embed_lookup,
    _rmsnorm,
    _router_gates,
    _unembed,
)
from oim_tpu.ops.quant import (
    WEIGHT_QUANT_TARGETS,
    dequantize_int8,
    dequantize_named,
    make_kv_buffers,
    maybe_dequantize_weights,
    quantize_int8,
)
from oim_tpu.ops.rope import apply_rope

_NEG_BIG = -1e30


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class KVCache:
    """Per-layer key/value cache: ``k``, ``v`` are
    ``[n_layers, batch, max_len, heads, head_dim]``; ``length`` is the
    number of valid positions (scalar int32, same on every layer).

    With ``quantized=True`` the k/v values are int8 with per-(token,
    head) f32 scales ``k_scale``/``v_scale`` [n_layers, batch, max_len,
    heads] (``ops/quant.py``) — half the cache bytes, which is the
    decode bottleneck; scales are None in the full-precision cache."""

    k: jax.Array
    v: jax.Array
    length: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @classmethod
    def create(
        cls,
        cfg: TransformerConfig,
        batch: int,
        max_len: int,
        quantized: bool = False,
    ) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
        k, v, ks, vs = make_kv_buffers(shape, cfg.compute_dtype, quantized)
        return cls(
            k=k, v=v, length=jnp.zeros((), jnp.int32), k_scale=ks, v_scale=vs
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def _flat_layer_params(params: dict, cfg: TransformerConfig) -> dict:
    """Collapse the stacked [n_stages, layers_per_stage, ...] layer weights
    to [n_layers, ...] — decode scans plain layers; pipeline staging is a
    training-throughput construct with no benefit at t=1."""
    layer_names = {"attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                   "router", "w_gate", "w_in", "w_out", "bq", "bk", "bv"}
    # Weight-only int8 scale companions (only quantizable names get one).
    layer_names |= {
        f"{n}_wscale" for n in layer_names if n in WEIGHT_QUANT_TARGETS
    }
    out = {}
    for name, value in params.items():
        if name in layer_names:
            out[name] = value.reshape(cfg.n_layers, *value.shape[2:])
    return out


def _store_kv(cache, scale, new, start):
    """Write ``new`` [B, t, KVH, hd] into the cache at position ``start``
    — quantizing when the cache is int8 (scale is not None)."""
    if scale is None:
        cache = jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype), (0, start, 0, 0)
        )
        return cache, None
    q, s = quantize_int8(new)
    cache = jax.lax.dynamic_update_slice(cache, q, (0, start, 0, 0))
    scale = jax.lax.dynamic_update_slice(scale, s, (0, start, 0))
    return cache, scale


def _load_kv(cache, scale):
    """Cache rows as f32 — dequantizing when int8.  XLA fuses the
    convert+multiply into the consuming matmul's operand read, so the
    HBM traffic is the int8 bytes (the point)."""
    if scale is None:
        return cache.astype(jnp.float32)
    return dequantize_int8(cache, scale)


def _cached_attention(
    x, lp, k_cache, v_cache, k_scale, v_scale, start, cfg: TransformerConfig
):
    """Attend x's tokens (global positions start..start+t) against the
    cache prefix plus themselves; returns
    (x_out, (k_cache, v_cache, k_scale, v_scale)).

    x: [B, t, D]; k_cache/v_cache: [B, max_len, KVH, hd] (kv heads — GQA
    keeps the cache kv-sized); scales [B, max_len, KVH] or None
    (int8 vs full-precision cache); start: scalar.
    """
    b, t, _ = x.shape
    h, hd, kvh = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    group = h // kvh
    max_len = k_cache.shape[1]

    normed = _rmsnorm(x, lp["attn_norm"], cfg)
    q = jnp.einsum("btd,dn->btn", normed, lp["wq"])
    k = jnp.einsum("btd,dn->btn", normed, lp["wk"])
    v = jnp.einsum("btd,dn->btn", normed, lp["wv"])
    if "bq" in lp:  # Qwen-style qkv biases (cfg.attn_bias)
        # Cast to the activation dtype: an f32 bias against bf16
        # activations would promote everything downstream.
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(b, t, h, hd)
    k = k.reshape(b, t, kvh, hd)
    v = v.reshape(b, t, kvh, hd)
    positions = start + jnp.arange(t)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

    k_cache, k_scale = _store_kv(k_cache, k_scale, k, start)
    v_cache, v_scale = _store_kv(v_cache, v_scale, v, start)

    # GQA: group query heads per kv head; the cache stays kv-sized (the
    # whole point — decode is cache-bandwidth-bound).
    q_g = q.reshape(b, t, kvh, group, hd)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk",
        q_g.astype(jnp.float32),
        _load_kv(k_cache, k_scale),
    ) / (hd**0.5)
    # Causal over global positions; cache slots past start+t are invalid.
    # Cache rows map 1:1 to global positions, so sliding-window masking
    # is position arithmetic — no rolling buffer needed for exactness
    # (a W-row ring buffer is the later memory optimization).
    q_pos = start + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    keep = k_pos <= q_pos
    if cfg.sliding_window:
        keep &= q_pos - k_pos < cfg.sliding_window
    scores = jnp.where(keep, scores, _NEG_BIG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs, _load_kv(v_cache, v_scale)
    ).astype(x.dtype)
    out = out.reshape(b, t, h * hd)
    return x + jnp.einsum("btn,nd->btd", out, lp["wo"]).astype(x.dtype), (
        k_cache,
        v_cache,
        k_scale,
        v_scale,
    )


def _moe_exact(x, lp, cfg: TransformerConfig):
    """Drop-free MoE for the ENTIRE inference path (prefill and decode):
    every token runs through its top-k experts (k = ``cfg.moe_top_k``;
    gates per ``transformer._router_gates``, matching the train path)
    with no capacity bookkeeping.  Routing is per-token, so results are
    independent of batch packing, padding, and prompt length — the
    property the serving engine's exactness invariant needs (capacity
    routing would count pad tokens against expert capacity, making
    results depend on the prompt bucket).  Capacity drops are a
    train-time load-balancing artifact; inference never drops.  Cost:
    dense grouping computes all E experts per token (E/k× the routed
    FLOPs) — fine at decode scale and acceptable at serving-prefill
    scale for small E; a top-k gather dispatch is the optimization seam
    if E grows."""
    b, t, d = x.shape
    normed = _rmsnorm(x, lp["mlp_norm"], cfg).reshape(b * t, d)
    router_logits = jnp.einsum(
        "gd,de->ge", normed.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [G, E]
    _, top_idx, gates = _router_gates(probs, cfg.moe_top_k)  # [G, K]
    # Per-expert weight = the gate of whichever choice picked it.
    assign = jax.nn.one_hot(top_idx, cfg.n_experts)  # [G, K, E]
    weights = jnp.einsum("gke,gk->ge", assign, gates)
    normed_f = normed.astype(jnp.float32)
    up_gate = _mlp_act(
        jnp.einsum("gd,edf->gef", normed_f, lp["w_gate"]), cfg
    )
    up = jnp.einsum("gd,edf->gef", normed_f, lp["w_in"])
    expert_out = jnp.einsum("gef,efd->ged", up_gate * up, lp["w_out"])
    out = jnp.einsum("ged,ge->gd", expert_out, weights)
    return x + out.reshape(b, t, d).astype(x.dtype)


def _hidden_cached(
    params,
    tokens,
    cache: KVCache,
    cfg: TransformerConfig,
):
    """Run ``tokens`` (global positions cache.length..+t) through all
    layers, reading and extending the cache.  Returns the final-norm
    hidden states ``(x [b, t, d], cache)`` (no unembedding).

    MoE uses drop-free per-token routing everywhere (``_moe_exact``) —
    inference results must not depend on batch packing or padding, which
    capacity routing would reintroduce (it counts pad tokens against
    expert capacity).  Agreement with the *training* forward therefore
    holds exactly when the train-path capacity drops nothing (ample
    ``expert_capacity_factor``)."""
    # Inference runs under GSPMD auto-partitioning where pallas (Mosaic)
    # kernels cannot sit (same constraint train.py gates on); XLA fuses
    # the reference rmsnorm anyway at t=1.
    cfg = replace(cfg, use_pallas=False)
    # Overflow guard: jit traces can't check the traced length, but eager
    # misuse (decode_step past capacity) fails loudly instead of letting
    # dynamic_update_slice clamp-corrupt the last cache slot.
    if not isinstance(cache.length, jax.core.Tracer):
        if int(cache.length) + tokens.shape[1] > cache.max_len:
            raise ValueError(
                f"cache overflow: length {int(cache.length)} + "
                f"{tokens.shape[1]} new tokens > max_len {cache.max_len}"
            )
    x = embed_lookup(params["wte"], tokens, cfg)
    start = cache.length
    flat = _flat_layer_params(params, cfg)

    quantized = cache.k_scale is not None

    def layer_step(carry, scanned):
        x, k_all, v_all, ks_all, vs_all = carry
        lp, layer = scanned
        lp = maybe_dequantize_weights(lp, cfg.compute_dtype)  # weight-int8
        # Slice THIS layer's cache out of the stacked carry and write the
        # update back with dynamic_update_index_in_dim.  The stacked
        # buffers ride the scan CARRY (not xs/ys): ys concatenation
        # allocated a fresh [L, ...] cache stack and copied every layer's
        # buffer on every decode step, which made per-step cost scale
        # with the cache ALLOCATION (measured 1.32 -> 1.87 ms/step going
        # max_len 96 -> 160); carried buffers update in place.
        idx = lambda a: jax.lax.dynamic_index_in_dim(  # noqa: E731
            a, layer, 0, keepdims=False
        )
        put = lambda a, u: jax.lax.dynamic_update_index_in_dim(  # noqa: E731
            a, u, layer, 0
        )
        x, (k_l, v_l, ks_l, vs_l) = _cached_attention(
            x, lp, idx(k_all), idx(v_all),
            idx(ks_all) if quantized else None,
            idx(vs_all) if quantized else None,
            start, cfg,
        )
        k_all, v_all = put(k_all, k_l), put(v_all, v_l)
        if quantized:
            ks_all, vs_all = put(ks_all, ks_l), put(vs_all, vs_l)
        if cfg.n_experts:
            x = _moe_exact(x, lp, cfg)
        else:
            x, _ = _dense_mlp(x, lp, cfg)
        return (x, k_all, v_all, ks_all, vs_all), None

    (x, new_k, new_v, new_ks, new_vs), _ = jax.lax.scan(
        layer_step,
        (x, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (flat, jnp.arange(cfg.n_layers)),
    )
    x = _rmsnorm(x, params["final_norm"], cfg)
    new_cache = KVCache(
        k=new_k, v=new_v, length=start + tokens.shape[1],
        k_scale=new_ks, v_scale=new_vs,
    )
    return x, new_cache


def _forward_cached(
    params,
    tokens,
    cache: KVCache,
    cfg: TransformerConfig,
):
    """``_hidden_cached`` + the unembedding: (logits, cache)."""
    x, new_cache = _hidden_cached(params, tokens, cache, cfg)
    return _unembed(x, dequantize_named(params, "wlm"), cfg), new_cache


def embed_tokens(params, tokens, true_lens, cfg: TransformerConfig):
    """Mean-pooled, L2-normalized final hidden states: an embeddings
    surface over the causal LM (standard last-layer mean pooling).

    tokens [b, t] (right-padded); true_lens [b] valid lengths — pads sit
    AFTER the valid positions, so causal attention keeps every valid
    hidden state pad-independent and the masked mean is exact at any
    padding bucket.  Returns f32 [b, d_model], unit-norm rows.
    """
    b, t = tokens.shape
    cache = KVCache.create(cfg, b, t)
    x, _ = _hidden_cached(params, tokens, cache, cfg)
    mask = (
        jnp.arange(t)[None, :] < true_lens[:, None]
    ).astype(jnp.float32)[..., None]
    pooled = jnp.sum(x.astype(jnp.float32) * mask, axis=1) / jnp.maximum(
        jnp.sum(mask, axis=1), 1.0
    )
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )


def prefill(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    max_len: int,
    kv_int8: bool = False,
) -> tuple[jax.Array, KVCache]:
    """Process the whole prompt in one pass.

    tokens: [batch, prompt_len] (all positions valid).  Returns the
    full-prompt logits ``[batch, prompt_len, vocab]`` and a cache of
    capacity ``max_len`` holding the prompt's K/V (int8-quantized per
    token/head when ``kv_int8`` — half the cache bandwidth decode pays).
    """
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(f"prompt length {t} exceeds max_len {max_len}")
    cache = KVCache.create(cfg, b, max_len, quantized=kv_int8)
    return _forward_cached(params, tokens, cache, cfg)


def decode_step(
    params: dict, cache: KVCache, tokens: jax.Array, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """One autoregressive step: tokens [batch, 1] → logits [batch, vocab]."""
    logits, cache = _forward_cached(params, tokens, cache, cfg)
    return logits[:, -1, :], cache


def _validate_truncation(
    top_k: int, top_p: float, vocab: int, min_p: float = 0.0
) -> None:
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k must be in [0, vocab={vocab}], got {top_k}")
    if not 0.0 <= min_p < 1.0:
        raise ValueError(f"min_p must be in [0, 1), got {min_p}")


def nucleus_min_p_mask(logits, top_p, min_p) -> jax.Array:
    """Top-p (nucleus) + min-p masking with PER-ROW ``top_p``/``min_p``
    (scalars or arrays broadcast over the leading axes) — jit-friendly:
    dynamic VALUES, static shapes.  min-p keeps tokens whose probability
    is at least ``min_p`` times the max probability (the modern
    truncation that adapts to distribution peakedness); the argmax token
    always survives both masks, so the set is never empty."""
    rows = logits.shape[:-1]
    top_p = jnp.broadcast_to(
        jnp.asarray(top_p, jnp.float32), rows
    )[..., None]
    min_p = jnp.broadcast_to(
        jnp.asarray(min_p, jnp.float32), rows
    )[..., None]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    sp = jax.nn.softmax(sorted_desc, axis=-1)
    # Exclusive cumulative mass: a token is cut iff the mass BEFORE it
    # already reaches top_p (so the boundary token is kept and the set
    # is never empty).
    exclusive = jnp.cumsum(sp, axis=-1) - sp
    cut = exclusive >= top_p
    threshold = jnp.min(
        jnp.where(cut, jnp.inf, sorted_desc), axis=-1, keepdims=True
    )
    probs = jax.nn.softmax(logits, axis=-1)
    keep = (logits >= threshold) & (
        probs >= min_p * jnp.max(probs, axis=-1, keepdims=True)
    )
    return jnp.where(keep, logits, _NEG_BIG)


def truncate_logits(
    logits, top_k: int = 0, top_p: float = 1.0, min_p: float = 0.0
) -> jax.Array:
    """Mask logits outside the top-k tokens and/or the top-p (nucleus)
    mass and/or below min-p.  All three are static here (the solo path;
    the serving engine routes per-request values through
    ``nucleus_min_p_mask``); truncation is a mask, not a gather."""
    _validate_truncation(top_k, top_p, logits.shape[-1], min_p)
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # [b, 1]
        logits = jnp.where(logits < kth, _NEG_BIG, logits)
    if top_p < 1.0 or min_p > 0.0:
        logits = nucleus_min_p_mask(logits, top_p, min_p)
    return logits


def apply_penalties(
    logits,
    tok_counts,
    gen_counts,
    repetition_penalty=1.0,
    presence_penalty=0.0,
    frequency_penalty=0.0,
):
    """Sampling penalties over [..., V] logits.

    - repetition (HF convention): logits of tokens that appeared in the
      PROMPT OR the generation divide by the penalty when positive,
      multiply when negative (> 1 discourages reuse).
    - presence / frequency (OpenAI convention): flat / per-occurrence
      subtraction for tokens already GENERATED.

    ``tok_counts`` counts prompt+generated occurrences, ``gen_counts``
    generated only (both [..., V] ints).  Penalty params broadcast over
    the leading axes (scalar or per-row).  Neutral values (1, 0, 0)
    return the logits bit-for-bit unchanged — the serving engine applies
    this unconditionally and the existing exactness matrix relies on it.
    """
    rows = logits.shape[:-1]
    rep = jnp.broadcast_to(
        jnp.asarray(repetition_penalty, logits.dtype), rows
    )[..., None]
    pres = jnp.broadcast_to(
        jnp.asarray(presence_penalty, logits.dtype), rows
    )[..., None]
    freq = jnp.broadcast_to(
        jnp.asarray(frequency_penalty, logits.dtype), rows
    )[..., None]
    adjusted = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(tok_counts > 0, adjusted, logits)
    return (
        logits
        - pres * (gen_counts > 0).astype(logits.dtype)
        - freq * gen_counts.astype(logits.dtype)
    )


def token_counts(tokens, vocab: int) -> jax.Array:
    """Occurrence counts per vocab id: [..., T] int tokens → [..., V].
    Scatter-add, O(V) memory — a one_hot formulation would materialize
    a [..., T, V] intermediate (gigabytes at long-prompt × big-vocab)."""
    lead = tokens.shape[:-1]
    flat = tokens.reshape(-1, tokens.shape[-1])
    counts = jax.vmap(
        lambda row: jnp.zeros((vocab,), jnp.int32).at[row].add(1)
    )(flat)
    return counts.reshape(*lead, vocab)


def sample_token(
    logits, temperature: float, key, top_k: int = 0, top_p: float = 1.0,
    min_p: float = 0.0,
) -> jax.Array:
    """Greedy at temperature 0 (or no key); else categorical over the
    temperature-scaled logits truncated by ``truncate_logits``."""
    if temperature == 0.0 or key is None:
        # Validate the static args even though greedy ignores them.
        _validate_truncation(top_k, top_p, logits.shape[-1], min_p)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = truncate_logits(logits / temperature, top_k, top_p, min_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    kv_int8: bool = False,
    min_p: float = 0.0,
    repetition_penalty: float = 1.0,
    presence_penalty: float = 0.0,
    frequency_penalty: float = 0.0,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    prompt: [batch, prompt_len] int32.  Returns
    ``[batch, prompt_len + max_new_tokens]``.  Jit-friendly: one prefill,
    then a ``lax.scan`` of single-token steps over static length.
    Penalty params apply ``apply_penalties`` before each sampling step
    (occurrence counts ride the scan carry); neutral defaults change
    nothing — this is the serving engine's exactness oracle.
    """
    b, t = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    if temperature != 0.0 and key is None:
        raise ValueError(
            "temperature > 0 requires an explicit PRNG key; a silent "
            "default would make every call return identical samples"
        )
    max_len = t + max_new_tokens
    logits, cache = prefill(params, prompt, cfg, max_len, kv_int8=kv_int8)
    if key is None:
        key = jax.random.PRNGKey(0)  # greedy path: key is never consumed
    first_key, key = jax.random.split(key)  # never reuse a consumed key
    tok_counts = token_counts(prompt, cfg.vocab_size)  # [b, V]
    gen_counts = jnp.zeros_like(tok_counts)
    penals = (repetition_penalty, presence_penalty, frequency_penalty)

    def counted(counts, token):
        return counts + jax.nn.one_hot(token, cfg.vocab_size, dtype=jnp.int32)

    first = sample_token(
        apply_penalties(logits[:, -1, :], tok_counts, gen_counts, *penals),
        temperature, first_key, top_k, top_p, min_p,
    )
    tok_counts = counted(tok_counts, first)
    gen_counts = counted(gen_counts, first)

    def step(carry, step_key):
        cache, token, tok_counts, gen_counts = carry
        logits, cache = decode_step(params, cache, token[:, None], cfg)
        next_token = sample_token(
            apply_penalties(logits, tok_counts, gen_counts, *penals),
            temperature, step_key, top_k, top_p, min_p,
        )
        return (
            cache,
            next_token,
            counted(tok_counts, next_token),
            counted(gen_counts, next_token),
        ), token

    # `first` is generated token 1; the scan produces the remaining n-1.
    step_keys = jax.random.split(key, max_new_tokens - 1)
    (_, last, _, _), generated = jax.lax.scan(
        step, (cache, first, tok_counts, gen_counts), step_keys
    )
    # ys hold each step's *input* (tokens 1..n-1); the final carry is n.
    out = jnp.concatenate(
        [generated.swapaxes(0, 1), last[:, None]], axis=1
    )
    return jnp.concatenate([prompt, out], axis=1)


def make_generate_fn(cfg: TransformerConfig):
    """``generate`` jitted once per (prompt-shape, max_new_tokens,
    temperature); shard params/prompt before calling (batch over ``dp``)
    and GSPMD propagates head/tensor sharding from the param shardings."""
    return jax.jit(
        partial(generate, cfg=cfg),
        static_argnames=(
            "max_new_tokens", "temperature", "top_k", "top_p", "kv_int8",
            "min_p",
            "repetition_penalty", "presence_penalty", "frequency_penalty",
        ),
    )
