"""HF Llama-family checkpoint import: external weights, native layout.

The flagship transformer is architecture-compatible with the Llama
family — including Mistral-style sliding-window variants, Qwen2's
q/k/v projection biases, Mixtral's block-sparse MoE, Gemma v1's
GeGLU/norm-offset/embed-scale numerics, and Phi-3's fused projections
(RMSNorm, RoPE, SwiGLU, GQA, untied or tied unembed), so a user
can bring real open weights instead of training from scratch — the
interchange surface the reference left to its storage backends
(volumes carry whatever bytes the workload expects) becomes, for a
compute framework, checkpoint compatibility with the de-facto public
format (new work; SURVEY.md §2.3).

Two deliberate conversion points, both proven by the parity tests
(tests/test_hf_import.py runs ``transformers``' reference
implementation on CPU and matches logits):

- **Layout.** HF ``nn.Linear`` stores [out, in]; this framework stores
  [in, out] (right-multiplication einsums) — every projection
  transposes.  Per-layer tensors stack into the pipeline layout
  [n_stages, layers_per_stage, ...].
- **RoPE convention.** HF rotates (x[i], x[i + hd/2]) pairs
  (rotate_half); ops/rope.py rotates interleaved (x[2i], x[2i+1])
  pairs with the same frequency set.  The two are a fixed permutation
  of head-dim coordinates, folded into the q/k projection COLUMNS at
  import time (``_rope_perm``) — zero runtime cost, and v/o are
  untouched because the permutation is internal to the q·k rotation.
"""

from __future__ import annotations

import numpy as np

from oim_tpu.models.transformer import TransformerConfig



# Tokenizer artifacts a complete HF checkpoint carries — the whitelist
# both CLI directions copy (import: checkpoint → sibling dir next to the
# orbax tree; export: back into the HF directory).  A whitelist, not a
# dir copy: pointing at a full checkpoint must never drag model files.
TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "special_tokens_map.json",
    "tokenizer.model",
    "vocab.json",
    "merges.txt",
)

def llama_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig mirroring an HF ``LlamaConfig``-shaped object
    (attribute access; a plain dict also works).  ``overrides`` pass
    through to the dataclass (e.g. ``dtype=\"float32\"`` for parity
    tests, ``n_stages`` for pipeline serving)."""
    get = (
        hf_config.get if isinstance(hf_config, dict)
        else lambda k, d=None: getattr(hf_config, k, d)
    )
    # Gemma (v1) differs from the Llama family in three numerics —
    # GeGLU (tanh gelu), (1 + weight) RMSNorm, sqrt(d) embedding scale —
    # all carried as config flags so the one forward serves both.
    model_type = get("model_type", "") or ""
    gemma = model_type == "gemma"
    if model_type.startswith("gemma") and not gemma:
        # Gemma 2/3 add pre/post-FFN norms and logit soft-capping this
        # forward does not model; importing would silently produce wrong
        # logits (their act check alone would pass).
        raise ValueError(
            f"unsupported model_type {model_type!r} (gemma v1 only)"
        )
    act = get("hidden_act", "silu") or "silu"
    if get("hidden_activation", None):  # GemmaConfig's preferred field
        act = get("hidden_activation")
    if act in ("silu", "swish"):
        mlp_act = "silu"
    elif act == "gelu_pytorch_tanh" or (act == "gelu" and gemma):
        # HF Gemma's historical "gelu" configs are RUN as tanh-gelu by
        # transformers (the well-known config mislabel) — Gemma only; a
        # non-Gemma "gelu" really is erf-gelu there and stays rejected.
        mlp_act = "gelu_tanh"
    else:
        raise ValueError(f"unsupported hidden_act {act!r}")
    if get("mlp_bias", False):
        raise ValueError("MLP biases are not supported")

    scaling = get("rope_scaling", None)
    rope_scaling = ()
    if scaling:
        # Llama-3.1 frequency remap maps onto ops/rope.py's piecewise
        # rule; other rope_types (linear, dynamic, yarn) have different
        # numerics and are rejected rather than silently misconverted.
        kind = scaling.get("rope_type", scaling.get("type", ""))
        if kind != "llama3":
            raise ValueError(
                f"unsupported rope_scaling type {kind!r} (llama3 only)"
            )
        try:
            rope_scaling = (
                float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                float(scaling["original_max_position_embeddings"]),
            )
        except KeyError as exc:
            raise ValueError(
                f"llama3 rope_scaling is missing {exc.args[0]!r}: {scaling!r}"
            ) from exc
    d = int(get("hidden_size"))
    h = int(get("num_attention_heads"))
    partial = float(get("partial_rotary_factor", 1.0) or 1.0)
    if partial != 1.0:
        # Phi-4-mini-style partial rotary: transformers rotates only a
        # fraction of the head dim; the native RoPE rotates all of it —
        # importing would be silently wrong on every token.
        raise ValueError(
            f"partial_rotary_factor={partial} is not supported "
            "(full-head-dim RoPE only)"
        )
    explicit_hd = get("head_dim", None)
    if explicit_hd and int(explicit_hd) != d // h:
        raise ValueError(
            f"head_dim {explicit_hd} != hidden_size/heads {d // h}"
        )
    # Mixtral: block-sparse MoE layers.  The native drop-free top-k
    # inference routing IS Mixtral's rule (softmax over all router
    # logits, keep top-k, renormalize — transformer._router_gates k>=2).
    n_experts = int(get("num_local_experts", 0) or 0)
    moe_top_k = int(get("num_experts_per_tok", 1) or 1)
    if n_experts and moe_top_k < 2:
        # The native k=1 gate is the switch rule (raw router prob);
        # HF Mixtral renormalizes over the chosen experts (gate 1.0 at
        # k=1) — importing would silently scale every MoE layer wrong.
        raise ValueError(
            f"Mixtral import needs num_experts_per_tok >= 2 "
            f"(renormalized-gate rule); got {moe_top_k}"
        )
    kwargs = dict(
        vocab_size=int(get("vocab_size")),
        d_model=d,
        n_layers=int(get("num_hidden_layers")),
        n_heads=h,
        n_kv_heads=int(get("num_key_value_heads", h) or h),
        d_ff=int(get("intermediate_size")),
        rope_theta=float(get("rope_theta", 10000.0) or 10000.0),
        rope_scaling=rope_scaling,
        # Mistral-family sliding window: masked identically in train,
        # solo decode, and the serving engine (cache rows are 1:1 with
        # global positions); parity-tested vs transformers' reference.
        # Qwen-style configs carry a window value but gate it off with
        # use_sliding_window=false — honor the gate or full-attention-
        # trained weights get silently windowed numerics.
        sliding_window=(
            int(get("sliding_window", 0) or 0)
            if get("use_sliding_window", True)
            else 0
        ),
        norm_eps=float(get("rms_norm_eps", 1e-6) or 1e-6),
        n_experts=n_experts,
        moe_top_k=moe_top_k if n_experts else 1,
        mlp_act=mlp_act,
        norm_offset=gemma,
        embed_scale=gemma,
        # Qwen2-style q/k/v biases: Qwen2Config carries no
        # attention_bias attribute (its implementation hardwires qkv
        # biases on, o bias off), so the model_type decides; Llama-like
        # configs say it explicitly.  Llama's attention_bias=True also
        # biases o_proj, which no target family uses — from_hf_llama
        # rejects such checkpoints loudly.
        attn_bias=bool(get("attention_bias", False))
        or get("model_type", "") == "qwen2",
    )
    kwargs.update(overrides)
    return TransformerConfig(**kwargs)


def _to_np(t) -> np.ndarray:
    """Array-like → float32 numpy (torch tensors included, without
    importing torch)."""
    if hasattr(t, "detach"):  # torch.Tensor
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, dtype=np.float32)


def _rope_perm(head_dim: int) -> np.ndarray:
    """Column permutation turning rotate_half coordinates into the
    interleaved pairs ops/rope.py rotates: out[2i] = hf[i],
    out[2i+1] = hf[i + hd/2]."""
    half = head_dim // 2
    perm = np.empty(head_dim, dtype=np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half) + half
    return perm


def _proj(weight, heads: int, head_dim: int, permute: bool) -> np.ndarray:
    """HF [heads·hd, d] projection → native [d, heads·hd], with the RoPE
    coordinate permutation applied per head when ``permute``."""
    w = _to_np(weight).T  # [d, heads*hd]
    if not permute:
        return w
    d = w.shape[0]
    w = w.reshape(d, heads, head_dim)[:, :, _rope_perm(head_dim)]
    return w.reshape(d, heads * head_dim)


def _bias(vec, heads: int, head_dim: int, permute: bool) -> np.ndarray:
    """HF [heads·hd] projection bias → native layout, with the same
    per-head RoPE coordinate permutation ``_proj`` applies to the
    weight columns (the bias adds BEFORE rotation, so its coordinates
    must move with the weight's)."""
    b = _to_np(vec)
    if not permute:
        return b
    return b.reshape(heads, head_dim)[:, _rope_perm(head_dim)].reshape(-1)


def from_hf_llama(state_dict, cfg: TransformerConfig) -> dict:
    """Native params pytree from an HF Llama ``state_dict``.

    ``state_dict`` maps HF parameter names to array-likes (torch tensors
    straight from ``model.state_dict()``, numpy arrays, or anything
    ``np.asarray`` accepts).  Tied embeddings (no ``lm_head.weight``)
    reuse the token embedding transposed.  ``cfg.n_experts > 0`` reads
    the Mixtral layout (``block_sparse_moe.gate`` + per-expert
    ``w1``/``w2``/``w3`` SwiGLU experts) into the native stacked MoE
    weights.  Raises KeyError naming the first missing tensor and
    ValueError on shape mismatches.
    """
    sd = dict(state_dict)
    qkv_bias_names = {"q_proj.bias", "k_proj.bias", "v_proj.bias"}
    bias = [
        k for k in sd
        if k.endswith(".bias")
        and k.rsplit("self_attn.", 1)[-1] not in qkv_bias_names
    ]
    if bias:
        raise ValueError(f"unsupported projection biases: {bias[:3]}")
    if not cfg.attn_bias and any(k.endswith(".bias") for k in sd):
        raise ValueError(
            "checkpoint carries q/k/v biases but cfg.attn_bias is off "
            "(llama_config reads attention_bias from the HF config)"
        )

    def take(name):
        if name not in sd:
            raise KeyError(f"HF checkpoint is missing {name!r}")
        return sd[name]

    h, kvh, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    per_layer = {
        "attn_norm": [], "wq": [], "wk": [], "wv": [], "wo": [],
        "mlp_norm": [], "w_gate": [], "w_in": [], "w_out": [],
    }
    if cfg.attn_bias:
        per_layer.update({"bq": [], "bk": [], "bv": []})
    if cfg.n_experts:
        per_layer["router"] = []
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        per_layer["attn_norm"].append(_to_np(take(p + "input_layernorm.weight")))
        if p + "self_attn.qkv_proj.weight" in sd:
            # Phi-3 fuses q/k/v into one projection, rows ordered
            # [q (h·hd), k (kvh·hd), v (kvh·hd)] (Phi3Attention's
            # split); unfuse to the native per-projection layout.
            qkv = _to_np(take(p + "self_attn.qkv_proj.weight"))
            q_rows, kv_rows = h * hd, kvh * hd
            q_w = qkv[:q_rows]
            k_w = qkv[q_rows:q_rows + kv_rows]
            v_w = qkv[q_rows + kv_rows:]
            if v_w.shape[0] != kv_rows:
                raise ValueError(
                    f"qkv_proj rows {qkv.shape[0]} != q {q_rows} + "
                    f"2x kv {kv_rows}"
                )
        else:
            q_w = take(p + "self_attn.q_proj.weight")
            k_w = take(p + "self_attn.k_proj.weight")
            v_w = take(p + "self_attn.v_proj.weight")
        per_layer["wq"].append(_proj(q_w, h, hd, True))
        per_layer["wk"].append(_proj(k_w, kvh, hd, True))
        per_layer["wv"].append(_proj(v_w, kvh, hd, False))
        if cfg.attn_bias:
            per_layer["bq"].append(
                _bias(take(p + "self_attn.q_proj.bias"), h, hd, True)
            )
            per_layer["bk"].append(
                _bias(take(p + "self_attn.k_proj.bias"), kvh, hd, True)
            )
            per_layer["bv"].append(
                _bias(take(p + "self_attn.v_proj.bias"), kvh, hd, False)
            )
        per_layer["wo"].append(_to_np(take(p + "self_attn.o_proj.weight")).T)
        per_layer["mlp_norm"].append(
            _to_np(take(p + "post_attention_layernorm.weight"))
        )
        if cfg.n_experts:
            # Mixtral experts are SwiGLU with w1=gate, w3=up, w2=down;
            # stacked over the expert axis for the native layout.
            per_layer["router"].append(
                _to_np(take(p + "block_sparse_moe.gate.weight")).T
            )
            per_layer["w_gate"].append(np.stack([
                _to_np(take(p + f"block_sparse_moe.experts.{e}.w1.weight")).T
                for e in range(cfg.n_experts)
            ]))
            per_layer["w_in"].append(np.stack([
                _to_np(take(p + f"block_sparse_moe.experts.{e}.w3.weight")).T
                for e in range(cfg.n_experts)
            ]))
            per_layer["w_out"].append(np.stack([
                _to_np(take(p + f"block_sparse_moe.experts.{e}.w2.weight")).T
                for e in range(cfg.n_experts)
            ]))
        elif p + "mlp.gate_up_proj.weight" in sd:
            # Phi-3 fuses gate/up: rows [gate (f), up (f)] (Phi3MLP's
            # chunk(2) split).
            gu = _to_np(take(p + "mlp.gate_up_proj.weight"))
            if gu.shape[0] != 2 * cfg.ff_dim:
                raise ValueError(
                    f"gate_up_proj rows {gu.shape[0]} != 2x d_ff "
                    f"{cfg.ff_dim}"
                )
            per_layer["w_gate"].append(gu[: cfg.ff_dim].T)
            per_layer["w_in"].append(gu[cfg.ff_dim:].T)
            per_layer["w_out"].append(
                _to_np(take(p + "mlp.down_proj.weight")).T
            )
        else:
            per_layer["w_gate"].append(
                _to_np(take(p + "mlp.gate_proj.weight")).T
            )
            per_layer["w_in"].append(_to_np(take(p + "mlp.up_proj.weight")).T)
            per_layer["w_out"].append(
                _to_np(take(p + "mlp.down_proj.weight")).T
            )

    wte = _to_np(take("model.embed_tokens.weight"))
    wlm = (
        _to_np(sd["lm_head.weight"]).T
        if "lm_head.weight" in sd
        else wte.T.copy()  # tied embeddings
    )

    import jax.numpy as jnp

    pdt = jnp.dtype(cfg.param_dtype)
    s, l = cfg.n_stages, cfg.layers_per_stage

    def stack(name):
        arr = np.stack(per_layer[name])  # [L, ...]
        return jnp.asarray(
            arr.reshape(s, l, *arr.shape[1:]), dtype=pdt
        )

    params = {name: stack(name) for name in per_layer}
    params["wte"] = jnp.asarray(wte, dtype=pdt)
    params["final_norm"] = jnp.asarray(
        _to_np(take("model.norm.weight")), dtype=pdt
    )
    params["wlm"] = jnp.asarray(wlm, dtype=pdt)

    expect = {
        "wte": (cfg.vocab_size, cfg.d_model),
        "wq": (s, l, cfg.d_model, h * hd),
        "wk": (s, l, cfg.d_model, kvh * hd),
        "wlm": (cfg.d_model, cfg.vocab_size),
        "w_gate": (
            (s, l, cfg.n_experts, cfg.d_model, cfg.ff_dim)
            if cfg.n_experts
            else (s, l, cfg.d_model, cfg.ff_dim)
        ),
    }
    if cfg.n_experts:
        expect["router"] = (s, l, cfg.d_model, cfg.n_experts)
    for name, shape in expect.items():
        if params[name].shape != shape:
            raise ValueError(
                f"{name}: checkpoint shape {params[name].shape} != "
                f"config shape {shape} — config/checkpoint mismatch"
            )
    return params


def _inv_proj(weight, heads: int, head_dim: int, permute: bool) -> np.ndarray:
    """Native [d, heads·hd] projection → HF [heads·hd, d], inverting the
    RoPE coordinate permutation where ``_proj`` applied it."""
    w = np.asarray(weight, dtype=np.float32)
    if permute:
        d = w.shape[0]
        inv = np.argsort(_rope_perm(head_dim))
        w = w.reshape(d, heads, head_dim)[:, :, inv].reshape(d, -1)
    return w.T


def to_hf_llama(params: dict, cfg: TransformerConfig) -> dict:
    """HF Llama ``state_dict`` (numpy float32) from a native params
    pytree — the exact inverse of ``from_hf_llama``: projections
    transpose back to [out, in], the interleaved-RoPE q/k column
    permutation inverts, and the [n_stages, layers_per_stage, ...]
    stacking flattens to per-layer tensors.  Exports an untied
    ``lm_head`` — except Gemma-numerics models, which HF always ties:
    those export WITHOUT lm_head and require wlm == wte.T (true for any
    imported-then-fine-tuned-tied checkpoint; an untied-trained wlm has
    no Gemma analog and is rejected).  MoE models (k >= 2) export in
    the Mixtral block-sparse layout; switch-routed (k=1) models are
    rejected — their raw-prob gate has no HF analog.
    Roundtrip and logit parity are pinned by tests/test_hf_import.py.
    """
    any_gemma = (
        cfg.norm_offset or cfg.embed_scale or cfg.mlp_act != "silu"
    )
    gemma = cfg.gemma_numerics
    if any_gemma and not gemma:
        # GemmaModel applies ALL THREE numerics unconditionally; a
        # partial combination would export to a model that silently
        # applies numerics this checkpoint never trained with.
        raise ValueError(
            "partial Gemma numerics (mlp_act/norm_offset/embed_scale "
            "not all set) have no HF analog; export needs all three "
            "or none"
        )
    if gemma and cfg.n_experts:
        raise ValueError(
            "Gemma-numerics MoE export has no HF analog (Mixtral runs "
            "silu experts without Gemma numerics)"
        )
    if gemma and cfg.attn_bias:
        raise ValueError(
            "Gemma export with attn_bias has no HF analog"
        )
    if gemma and not np.allclose(
        np.asarray(params["wlm"], np.float32),
        np.asarray(params["wte"], np.float32).T,
    ):
        raise ValueError(
            "Gemma export requires tied embeddings (wlm == wte.T); "
            "this model's unembedding diverged from the embedding "
            "and GemmaForCausalLM cannot represent that"
        )
    if cfg.n_experts and cfg.attn_bias:
        # Mixtral's layout has no projection biases; a Qwen2-MoE-style
        # geometry has no exportable HF analog here.
        raise ValueError(
            "MoE export with attn_bias has no HF Mixtral analog"
        )
    if cfg.n_experts and cfg.moe_top_k < 2:
        # Mixtral's layout requires the renormalized-top-k rule shared
        # with _router_gates k>=2; a switch-routed (k=1) model has no
        # HF analog with matching numerics.
        raise ValueError(
            "MoE export needs moe_top_k >= 2 (Mixtral layout); "
            f"got {cfg.moe_top_k}"
        )
    if cfg.sliding_window:
        # Mirror of the import guard: the exported config would claim
        # full attention over windowed-trained weights.
        raise ValueError(
            "sliding-window models are not exportable yet (the HF "
            "config would misdescribe the attention pattern)"
        )
    h, kvh, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["wte"], dtype=np.float32
        ),
        "model.norm.weight": np.asarray(
            params["final_norm"], dtype=np.float32
        ),
    }
    if not gemma:
        sd["lm_head.weight"] = np.asarray(
            params["wlm"], dtype=np.float32
        ).T

    def layer(name, i):
        s, l = divmod(i, cfg.layers_per_stage)
        return params[name][s, l]

    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.asarray(
            layer("attn_norm", i), dtype=np.float32
        )
        sd[p + "self_attn.q_proj.weight"] = _inv_proj(
            layer("wq", i), h, hd, True
        )
        sd[p + "self_attn.k_proj.weight"] = _inv_proj(
            layer("wk", i), kvh, hd, True
        )
        sd[p + "self_attn.v_proj.weight"] = _inv_proj(
            layer("wv", i), kvh, hd, False
        )
        if cfg.attn_bias:
            inv = np.argsort(_rope_perm(hd))
            bq = np.asarray(layer("bq", i), np.float32).reshape(h, hd)
            bk = np.asarray(layer("bk", i), np.float32).reshape(kvh, hd)
            sd[p + "self_attn.q_proj.bias"] = bq[:, inv].reshape(-1)
            sd[p + "self_attn.k_proj.bias"] = bk[:, inv].reshape(-1)
            sd[p + "self_attn.v_proj.bias"] = np.asarray(
                layer("bv", i), np.float32
            )
        sd[p + "self_attn.o_proj.weight"] = np.asarray(
            layer("wo", i), dtype=np.float32
        ).T
        sd[p + "post_attention_layernorm.weight"] = np.asarray(
            layer("mlp_norm", i), dtype=np.float32
        )
        if cfg.n_experts:
            sd[p + "block_sparse_moe.gate.weight"] = np.asarray(
                layer("router", i), dtype=np.float32
            ).T
            for e in range(cfg.n_experts):
                q = f"{p}block_sparse_moe.experts.{e}."
                sd[q + "w1.weight"] = np.asarray(
                    layer("w_gate", i)[e], dtype=np.float32
                ).T
                sd[q + "w3.weight"] = np.asarray(
                    layer("w_in", i)[e], dtype=np.float32
                ).T
                sd[q + "w2.weight"] = np.asarray(
                    layer("w_out", i)[e], dtype=np.float32
                ).T
        else:
            sd[p + "mlp.gate_proj.weight"] = np.asarray(
                layer("w_gate", i), dtype=np.float32
            ).T
            sd[p + "mlp.up_proj.weight"] = np.asarray(
                layer("w_in", i), dtype=np.float32
            ).T
            sd[p + "mlp.down_proj.weight"] = np.asarray(
                layer("w_out", i), dtype=np.float32
            ).T
    return sd


def hf_llama_config_kwargs(
    cfg: TransformerConfig, max_position_embeddings: int | None = None
) -> dict:
    """Kwargs for ``transformers.LlamaConfig`` mirroring ``cfg`` — the
    inverse of ``llama_config`` (rope_scaling tuple → HF dict).
    ``max_position_embeddings`` should be the context the model was
    trained/served at; when omitted it derives from rope_scaling
    (factor × original) or falls back to transformers' default."""
    kwargs = dict(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.kv_heads,
        intermediate_size=cfg.ff_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.norm_eps,
        tie_word_embeddings=False,
        attention_bias=cfg.attn_bias,
        mlp_bias=False,
    )
    if cfg.n_experts:
        # Mixtral keys; the consumer (oim-export-hf) builds a
        # MixtralConfig, whose ctor takes neither bias flag.
        kwargs.pop("attention_bias")
        kwargs.pop("mlp_bias")
        kwargs["num_local_experts"] = cfg.n_experts
        kwargs["num_experts_per_tok"] = cfg.moe_top_k
    if cfg.gemma_numerics:
        # Gemma keys: always-tied embeddings, explicit head_dim, and
        # the activation under its canonical name.
        kwargs.pop("attention_bias", None)
        kwargs.pop("mlp_bias", None)
        kwargs["tie_word_embeddings"] = True
        kwargs["head_dim"] = cfg.head_dim
        kwargs["hidden_activation"] = (
            "gelu_pytorch_tanh" if cfg.mlp_act == "gelu_tanh" else "silu"
        )
    if cfg.rope_scaling:
        factor, low, high, orig = cfg.rope_scaling
        kwargs["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": factor,
            "low_freq_factor": low,
            "high_freq_factor": high,
            "original_max_position_embeddings": int(orig),
        }
        if max_position_embeddings is None:
            # Without this, the exported config.json inherits
            # transformers' 2048 default and downstream consumers cap
            # context there despite the scaling dict implying
            # factor x orig.
            max_position_embeddings = int(factor * orig)
    if max_position_embeddings is not None:
        kwargs["max_position_embeddings"] = int(max_position_embeddings)
    return kwargs
