"""LoRA fine-tuning: low-rank adapters over the attention projections.

Parameter-efficient fine-tune for the flagship transformer (new work —
the reference is a storage control plane; SURVEY.md §2.3).  Design:

- **Adapters, not forks.**  For each target weight ``W [.., din, dout]``
  the trainable state is ``A [.., din, r]`` (truncated-normal) and
  ``B [.., r, dout]`` (zeros — the adapted model starts exactly at the
  base model).  The effective weight is ``W + (alpha/r)·A@B``.
- **Merge-then-chain-rule.**  The train step materializes the merged
  weights and reuses the UNCHANGED full train machinery (shard_map,
  GPipe/1F1B, ring/ulysses, MoE — everything composes for free), then
  converts the merged-weight grads to adapter grads analytically:
  ``dA = s·dW@Bᵀ``, ``dB = s·Aᵀ@dW``.  The merge is one rank-r matmul
  + add per target per step — negligible next to the forward — and the
  real LoRA win is kept: the optimizer state (2 extra copies of every
  weight for adamw) exists only for the adapters.
- **Tiny checkpoints.**  The training state holds adapters only; the
  frozen base rides outside.  ``merge_lora`` produces standard params
  for ``oim-serve`` / ``export_params`` — serving needs no LoRA support.

Targets are the attention projections (wq/wk/wv/wo) — the standard LoRA
recipe; the mlp/expert weights stay frozen.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import optax

from oim_tpu.models.train import TrainState, _build_value_and_grad
from oim_tpu.models.transformer import TransformerConfig, init_params

LORA_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora(key: jax.Array, cfg: TransformerConfig, rank: int) -> dict:
    """Adapter pytree: ``{<target>_a, <target>_b}`` per LoRA target,
    stacked like the base weights ([n_stages, layers_per_stage, ...]).
    B starts at zero so step 0 reproduces the base model exactly."""
    if rank < 1:
        raise ValueError(f"lora rank must be >= 1, got {rank}")
    shapes = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    adapters = {}
    keys = iter(jax.random.split(key, len(LORA_TARGETS)))
    for name in LORA_TARGETS:
        *lead, din, dout = shapes[name].shape
        adapters[f"{name}_a"] = (
            jax.random.truncated_normal(
                next(keys), -2, 2, (*lead, din, rank), jnp.float32
            )
            / math.sqrt(din)
        )
        adapters[f"{name}_b"] = jnp.zeros((*lead, rank, dout), jnp.float32)
    return adapters


def merge_lora(params: dict, adapters: dict, alpha: float, rank: int) -> dict:
    """Standard params with the adapters folded in:
    ``W + (alpha/rank)·A@B`` per target (everything else passes through).
    The output serves/exports like any other params pytree."""
    scale = alpha / rank
    merged = dict(params)
    for name in LORA_TARGETS:
        delta = jnp.einsum(
            "...dr,...rn->...dn", adapters[f"{name}_a"], adapters[f"{name}_b"]
        )
        merged[name] = (params[name] + scale * delta).astype(
            params[name].dtype
        )
    return merged


def _adapter_grads(grads_w: dict, adapters: dict, alpha: float, rank: int):
    """Chain rule from merged-weight grads to adapter grads:
    W = Wb + s·A@B  ⇒  dL/dA = s·(dL/dW)@Bᵀ, dL/dB = s·Aᵀ@(dL/dW)."""
    scale = alpha / rank
    out = {}
    for name in LORA_TARGETS:
        dw = grads_w[name].astype(jnp.float32)
        out[f"{name}_a"] = scale * jnp.einsum(
            "...dn,...rn->...dr", dw, adapters[f"{name}_b"]
        )
        out[f"{name}_b"] = scale * jnp.einsum(
            "...dr,...dn->...rn", adapters[f"{name}_a"], dw
        )
    return out


def make_lora_train_step(
    cfg: TransformerConfig,
    mesh,
    optimizer,
    alpha: float,
    rank: int,
):
    """Jitted ``(state, base_params, tokens) -> (state, metrics)``.

    ``state.params`` are the adapters (the only thing optimized or
    checkpointed); ``base_params`` stay frozen and undonated.  Internally
    the full train step runs on the merged weights — every parallelism
    mix works unchanged — and its weight grads are converted to adapter
    grads analytically (module docstring).
    """
    sharded_vag = _build_value_and_grad(cfg, mesh)

    def lora_step(state: TrainState, base_params, tokens):
        merged = merge_lora(base_params, state.params, alpha, rank)
        loss, ce, grads_w = sharded_vag(merged, tokens)
        grads = _adapter_grads(grads_w, state.params, alpha, rank)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_adapters = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_adapters,
                opt_state=new_opt_state,
                step=state.step + 1,
            ),
            {"loss": loss, "ce": ce},
        )

    return jax.jit(lora_step, donate_argnums=(0,))
