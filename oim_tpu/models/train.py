"""Training: loss, gradients, optimizer step over the full mesh.

The train step wraps ``forward_local`` in ``shard_map`` (manual dp/sp/pp,
auto tp/ep) and differentiates a purely LOCAL objective with static
normalizers (``_local_objective`` — no collective touches the loss
scalar, because the psum transpose inside shard_map re-sums cotangents
and would inflate per-device grads by the mesh size); the explicit
per-axis psums in ``spmd_value_and_grad`` then reduce the per-device
grads to the exact global gradient.  Next-token loss handles sequence-
shard boundaries exactly (the label for a shard's last token is fetched
from the next shard with a one-hop ppermute).  Optax updates apply
outside, where GSPMD keeps parameter math sharded.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from oim_tpu.models.transformer import (
    AUX_LOSS_WEIGHT,
    TransformerConfig,
    _doc_segments,
    _rmsnorm,
    _stage_layer_params,
    _unembed,
    forward_hidden,
    forward_local,
    make_stage_fn,
    manual_pspecs,
    param_pspecs,
)



@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer) -> "TrainState":
        return cls(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )


def data_pspec() -> P:
    """Tokens are sharded batch × sequence."""
    return P("dp", "sp")


def _shifted_labels(tokens, doc_sep_id: int = -1):
    """Next-token labels + validity mask for a [b, t_local] sequence shard.

    The last local position's label is the first token of the *next*
    sequence shard (one neighbor ppermute hop over ``sp``); the global
    final position of each sequence is masked out.  With sequence packing
    (``doc_sep_id`` >= 0) labels that ARE a separator drop out too: the
    separator opens the next document (BOS-style), so predicting it would
    cross the same boundary the attention mask isolates.  Returns
    ``(labels [b, t], valid [b, t] bool, positions [t])`` — the one
    definition of shard-boundary labeling, shared by the autodiff loss and
    the 1F1B per-microbatch head.
    """
    sp_size = jax.lax.axis_size("sp")
    sp_index = jax.lax.axis_index("sp")
    b, t_local = tokens.shape
    perm = [(i, (i - 1) % sp_size) for i in range(sp_size)]
    next_first = jax.lax.ppermute(tokens[:, :1], "sp", perm)  # [b, 1]
    labels = jnp.concatenate([tokens[:, 1:], next_first], axis=1)
    positions = sp_index * t_local + jnp.arange(t_local)  # [t]
    t_global = t_local * sp_size
    valid = jnp.broadcast_to(positions < t_global - 1, (b, t_local))
    if doc_sep_id >= 0:
        valid = valid & (labels != doc_sep_id)
    return labels, valid, positions


def _masked_ce_sum(logits, labels, valid):
    """Σ of valid-position next-token NLL (no normalization).

    Gather-then-logsumexp instead of materializing the full [b, t, V]
    log-softmax: NLL = logsumexp(logits) - logits[label], which reads the
    logits once for the reduction and once for the gather rather than
    writing a second vocab-sized tensor (the logits are the biggest
    activation in the model at vocab 32k)."""
    lse = jax.nn.logsumexp(logits, axis=-1)  # [b, t]
    target = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - target
    return jnp.sum(nll * valid), jnp.sum(valid.astype(jnp.float32))


def _fused_ce_sum(hidden, wlm, labels, valid, cfg: TransformerConfig):
    """``_masked_ce_sum`` over the fused unembed+CE kernel: takes the
    final-norm hidden [b, t, D] instead of logits, so the [b, t, V]
    logits never reach HBM in either pass (ops/fused_ce.py)."""
    from oim_tpu.ops import fused_linear_ce

    b, t, d = hidden.shape
    nll = fused_linear_ce(
        hidden.astype(cfg.compute_dtype).reshape(b * t, d),
        wlm,
        labels.reshape(b * t),
    ).reshape(b, t)
    return jnp.sum(nll * valid), jnp.sum(valid.astype(jnp.float32))


def _global_metrics(obj, ce_sum, ce_count):
    """Forward-only psums turning ``_local_objective``'s per-device terms
    into the replicated (loss, ce) metrics.  Σ_mesh obj is the global
    objective by construction (static normalizers)."""
    loss = jax.lax.psum(obj, ("dp", "sp", "pp"))
    ce = jax.lax.psum(ce_sum, ("dp", "sp", "pp")) / jax.lax.psum(
        ce_count, ("dp", "sp", "pp")
    )
    return loss, ce


def _local_loss(params, tokens, cfg: TransformerConfig):
    """Globally-reduced (loss, ce) over the local [b, t] token shard —
    the METRIC path (eval).  One definition with the grad path: the same
    ``_local_objective`` terms, reduced by ``_global_metrics``."""
    obj, (ce_sum, ce_count) = _local_objective(params, tokens, cfg)
    return _global_metrics(obj, ce_sum, ce_count)


def _local_objective(params, tokens, cfg: TransformerConfig):
    """Per-device slice of the global training objective — what autodiff
    differentiates.

    NO collective touches the returned scalar, deliberately: inside
    shard_map the transpose of ``psum`` re-sums cotangents across devices
    (every device's backward seed becomes the sum of all devices'), so
    differentiating an already-psum'd loss inflates the per-device
    gradients by the psum'd axes' total size — and by *inconsistent*
    factors when the CE psums over dp·sp·pp while the aux pmeans over
    dp·sp, bending the gradient direction on MoE models.  Instead the
    objective is purely local with STATIC normalizers: summing it over
    the whole mesh equals ``_local_loss``'s value, and the per-device
    autodiff grads psum to the exact global gradient
    (finite-difference-checked in tests/test_parallel.py).

    Returns ``(obj, (ce_sum, ce_count))`` with the CE terms masked to the
    last pipeline stage (the one whose logits are real) so the caller can
    reconstruct the ce metric with forward-only psums.
    """
    labels, valid, _ = _shifted_labels(tokens, cfg.doc_sep_id)
    if cfg.use_pallas and cfg.fused_ce:
        hidden, aux = forward_hidden(params, tokens, cfg)
        ce_sum, ce_count = _fused_ce_sum(
            hidden, params["wlm"], labels, valid, cfg
        )
    else:
        logits, aux = forward_local(params, tokens, cfg)
        ce_sum, ce_count = _masked_ce_sum(logits, labels, valid)
    is_last_stage = (
        jax.lax.axis_index("pp") == jax.lax.axis_size("pp") - 1
    ).astype(jnp.float32)
    ce_sum = ce_sum * is_last_stage
    ce_count = ce_count * is_last_stage
    b, t_local = tokens.shape
    dp_size = jax.lax.axis_size("dp")
    sp_size = jax.lax.axis_size("sp")
    # Every label position except each sequence's global last is valid, on
    # every data shard — a static count (== psum(ce_count) over the mesh,
    # except under sequence packing where separator labels drop out and
    # the objective deliberately keeps the FIXED denominator: per-token
    # weights must not depend on how many documents a batch packed).
    c_global = float(b * dp_size * (t_local * sp_size - 1))
    obj = ce_sum / c_global + AUX_LOSS_WEIGHT * aux / (dp_size * sp_size)
    return obj, (ce_sum, ce_count)


def make_train_step(
    cfg: TransformerConfig,
    mesh,
    optimizer=None,
    learning_rate: float = 3e-4,
):
    """Build the jitted ``(state, tokens) -> (state, metrics)`` step.

    Donates the state buffers (in-place update on HBM) and pins shardings:
    params by their logical axes, tokens by (dp, sp).
    """
    return jax.jit(
        _build_train_step(cfg, mesh, optimizer, learning_rate),
        donate_argnums=(0,),
    )


def make_train_loop(
    cfg: TransformerConfig,
    mesh,
    optimizer=None,
    learning_rate: float = 3e-4,
):
    """Build a jitted ``(state, token_batches[n, b, t]) -> (state, metrics)``
    N-step training loop — one dispatch, ``lax.scan`` over the batches.

    One host→device dispatch per N steps instead of per step: device-side
    scan removes the per-step dispatch/transfer overhead entirely (on the
    tunneled single-chip setup that overhead is larger than the step itself)
    and is the idiomatic way to drive TPUs from a remote host.  Metrics come
    back stacked per step.
    """
    step = _build_train_step(cfg, mesh, optimizer, learning_rate)

    def loop(state: TrainState, token_batches: jax.Array):
        return jax.lax.scan(step, state, token_batches)

    return jax.jit(loop, donate_argnums=(0,))


def _manual_setup(cfg: TransformerConfig, mesh):
    """(cfg, manual_axes) for a shard_mapped step on ``mesh`` — THE one
    definition of the manual/auto axis split and the pallas gating, shared
    by the train and eval builders so they can never compile differently.

    Mosaic (pallas) kernels cannot run inside GSPMD-auto regions: when
    tp == ep == 1 there is nothing to auto-partition, so every axis goes
    manual and pallas stays on; with real tp/ep the model falls back to
    XLA-fused reference ops and tp/ep stay automatic.
    """
    from dataclasses import replace as dc_replace

    if mesh.shape["pp"] != cfg.n_stages:
        raise ValueError(
            f"mesh pp={mesh.shape['pp']} must equal cfg.n_stages="
            f"{cfg.n_stages}; otherwise stages would be silently dropped"
        )
    fully_manual = mesh.shape["tp"] == 1 and mesh.shape["ep"] == 1
    cfg = dc_replace(cfg, use_pallas=cfg.use_pallas and fully_manual)
    manual_axes = (
        {"dp", "sp", "pp", "tp", "ep"} if fully_manual else {"dp", "sp", "pp"}
    )
    return cfg, manual_axes


def make_eval_step(cfg: TransformerConfig, mesh):
    """Jitted forward-only ``(params, tokens) -> ce`` for held-out eval.

    Shares ``_local_loss`` (and therefore the exact masking/normalization
    the train step optimizes) but takes no grads, updates nothing, and
    does NOT donate params — the same state is evaluated across batches.
    Under pp>1 the forward runs the GPipe schedule regardless of
    ``pp_schedule``: 1F1B exists to overlap the backward, which eval does
    not have.  Returns the aux-free cross entropy (perplexity = exp(ce)).
    """
    cfg, manual_axes = _manual_setup(cfg, mesh)

    def local_eval(params, tokens):
        _, ce = _local_loss(params, tokens, cfg)
        return ce

    return jax.jit(
        jax.shard_map(
            local_eval,
            mesh=mesh,
            in_specs=(manual_pspecs(cfg), data_pspec()),
            out_specs=P(),
            axis_names=manual_axes,
            check_vma=False,
        )
    )


def _build_value_and_grad(cfg: TransformerConfig, mesh):
    """``(params, tokens) -> (loss, ce, grads)`` — the sharded forward +
    backward (GPipe or 1F1B, with gradient accumulation), no optimizer.
    The seam shared by the standard and LoRA train steps."""
    cfg, manual_axes = _manual_setup(cfg, mesh)
    manual_specs = manual_pspecs(cfg)

    use_1f1b = cfg.pp_schedule == "1f1b" and cfg.n_stages > 1

    def autodiff_value_and_grad(params, tokens):
        (obj, (ce_sum, ce_count)), grads = jax.value_and_grad(
            partial(_local_objective, cfg=cfg), has_aux=True
        )(params, tokens)
        # Metric reductions happen OUTSIDE the differentiated scalar (see
        # _local_objective on why a psum'd loss breaks the gradients).
        loss, ce = _global_metrics(obj, ce_sum, ce_count)
        return loss, ce, grads

    def spmd_value_and_grad(params, tokens):
        vag = _1f1b_value_and_grad if use_1f1b else autodiff_value_and_grad
        loss, ce, grads = vag(params, tokens)
        # Per-device grads are only each rank's local contribution — the
        # psum in the loss broadcasts cotangents, it does not sum parameter
        # gradients.  Reduce explicitly: stage-sharded params over data
        # axes; replicated params additionally over pp (their contribution
        # lives on exactly one stage thanks to the loss mask / pipeline
        # routing, so the psum reconstructs the full gradient everywhere).
        def reduce_grad(name, g):
            if manual_specs[name] and manual_specs[name][0] == "pp":
                return jax.lax.psum(g, ("dp", "sp"))
            return jax.lax.psum(g, ("dp", "sp", "pp"))

        grads = {name: reduce_grad(name, g) for name, g in grads.items()}
        return loss, ce, grads

    def _1f1b_value_and_grad(params, tokens):
        """Manual pipeline fwd+bwd (parallel/pipeline.py 1F1B schedule):
        embedding and loss head are differentiated here, the layer stack's
        gradients come back from the schedule itself."""
        from oim_tpu.parallel.pipeline import pipeline_1f1b_value_and_grad

        sp_size = jax.lax.axis_size("sp")
        dp_size = jax.lax.axis_size("dp")
        b, t_local = tokens.shape
        dt = cfg.compute_dtype
        n_micro = max(cfg.n_microbatches, 1)
        if b % n_micro:
            raise ValueError(
                f"local batch {b} not divisible by n_microbatches={n_micro}"
            )
        mb = b // n_micro

        labels, valid, positions = _shifted_labels(tokens, cfg.doc_sep_id)
        labels_m = labels.reshape(n_micro, mb, t_local)
        valid_m = valid.reshape(n_micro, mb, t_local)
        # Static normalizer: every label position except each sequence's
        # global last is counted, on every data shard.
        c_global = float(b * dp_size * (t_local * sp_size - 1))

        def embed(wte):
            from oim_tpu.models.transformer import embed_lookup

            return embed_lookup(wte, tokens, cfg).reshape(
                n_micro, mb, t_local, cfg.d_model
            )

        x_micro, embed_vjp = jax.vjp(embed, params["wte"])
        segments = None
        if cfg.doc_sep_id >= 0:
            segments = _doc_segments(tokens, cfg).reshape(
                n_micro, mb, t_local
            )
        stage_fn = make_stage_fn(cfg, positions, sp_size, segments)
        stage_params = _stage_layer_params(params, cfg)
        head_params = {
            "final_norm": params["final_norm"],
            "wlm": params["wlm"],
        }

        def loss_fn(hp, y, m):
            normed = _rmsnorm(y, hp["final_norm"], cfg)
            lbl = jax.lax.dynamic_index_in_dim(labels_m, m, 0, keepdims=False)
            val = jax.lax.dynamic_index_in_dim(valid_m, m, 0, keepdims=False)
            if cfg.use_pallas and cfg.fused_ce:
                ce_sum, _ = _fused_ce_sum(normed, hp["wlm"], lbl, val, cfg)
            else:
                logits = _unembed(normed, hp["wlm"], cfg)
                ce_sum, _ = _masked_ce_sum(logits, lbl, val)
            ce = ce_sum / c_global
            return ce, ce

        # d(total objective)/d(aux_{stage,m}): the aux term is
        # AUX_LOSS_WEIGHT * pmean_{dp,sp}(psum_pp(Σ_m aux)/M).
        aux_seed = AUX_LOSS_WEIGHT / (n_micro * dp_size * sp_size)
        loss, ce, aux, d_sp, d_hp, dx = pipeline_1f1b_value_and_grad(
            stage_fn,
            loss_fn,
            stage_params,
            head_params,
            x_micro,
            aux_seed=aux_seed,
            axis_name="pp",
        )
        (d_wte,) = embed_vjp(dx)
        # Totals: ce is real on the last stage only; aux sums per stage.
        obj_ce = jax.lax.psum(ce, ("dp", "sp", "pp"))  # Σ ce_sum/c_global
        aux_total = jax.lax.psum(aux, "pp") / n_micro
        aux_total = jax.lax.pmean(aux_total, ("dp", "sp"))
        loss_total = obj_ce + AUX_LOSS_WEIGHT * aux_total
        # The CE METRIC divides by the DYNAMIC valid count (the autodiff
        # path's psum(ce_sum)/psum(ce_count) contract): with sequence
        # packing, separator labels drop out and the static c_global in
        # the objective deliberately over-counts — the metric must not.
        is_last = (
            jax.lax.axis_index("pp") == jax.lax.axis_size("pp") - 1
        ).astype(jnp.float32)
        count = jnp.sum(valid.astype(jnp.float32)) * is_last
        ce_total = (
            obj_ce * c_global
            / jax.lax.psum(count, ("dp", "sp", "pp"))
        )
        grads = {name: g[None] for name, g in d_sp.items()}  # restore pp dim
        grads["wte"] = d_wte
        grads["final_norm"] = d_hp["final_norm"]
        grads["wlm"] = d_hp["wlm"]
        return loss_total, ce_total, grads

    # NOTE: partial-manual shard_map (manual dp/sp/pp, auto tp/ep) with an
    # explicit mesh= only traces under jit — make_train_step returns the
    # jitted step, never call the raw python function.
    sharded_vag = jax.shard_map(
        spmd_value_and_grad,
        mesh=mesh,
        in_specs=(manual_specs, data_pspec()),
        out_specs=(P(), P(), manual_specs),
        axis_names=manual_axes,
        check_vma=False,
    )

    def value_and_grad_accum(params, tokens):
        """Split the batch into ``grad_accum`` sequential microbatches and
        average their grads — same math as the full batch (equal splits ⇒
        equal per-microbatch label counts), peak activation memory ÷ N.
        The scan re-runs the whole sharded fwd+bwd per microbatch, so the
        only extra live memory is one grads-sized accumulator."""
        accum = cfg.grad_accum
        if accum == 1:
            return sharded_vag(params, tokens)
        b = tokens.shape[0]
        if b % accum:
            raise ValueError(
                f"global batch {b} not divisible by grad_accum={accum}"
            )
        micro = tokens.reshape(accum, b // accum, *tokens.shape[1:])

        def acc(carry, mtok):
            loss_a, ce_a, grads_a = carry
            loss, ce, grads = sharded_vag(params, mtok)
            return (
                loss_a + loss,
                ce_a + ce,
                jax.tree.map(jnp.add, grads_a, grads),
            ), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss, ce, grads), _ = jax.lax.scan(
            acc, (jnp.zeros(()), jnp.zeros(()), zeros), micro
        )
        scale = 1.0 / accum
        return (
            loss * scale,
            ce * scale,
            jax.tree.map(lambda g: g * scale, grads),
        )

    return value_and_grad_accum


def _build_train_step(
    cfg: TransformerConfig,
    mesh,
    optimizer=None,
    learning_rate: float = 3e-4,
):
    optimizer = optimizer or optax.adamw(learning_rate)
    value_and_grad = _build_value_and_grad(cfg, mesh)

    def train_step(state: TrainState, tokens: jax.Array):
        loss, ce, grads = value_and_grad(state.params, tokens)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, {"loss": loss, "ce": ce}

    return train_step


def params_shardings(params: dict, cfg: TransformerConfig, mesh) -> dict:
    """NamedShardings for a params dict by its logical axes — usable as a
    restore target annotation (``params`` may be concrete or abstract).
    LoRA adapter names (``*_a``/``*_b`` over a known target) replicate —
    they are rank-r small by construction; any OTHER unknown name stays a
    loud KeyError (a weight added to init_params but forgotten in
    logical_axes must not silently replicate across the mesh)."""
    pspecs = param_pspecs(cfg)

    def spec(name):
        if name not in pspecs and name[-2:] in ("_a", "_b") and (
            name[:-2] in pspecs
        ):
            return P()
        return pspecs[name]

    return {name: NamedSharding(mesh, spec(name)) for name in params}


def state_shardings(
    state, cfg: TransformerConfig, mesh, zero1: bool = False
) -> TrainState:
    """A TrainState-shaped pytree of NamedShardings: params by their logical
    axes, optimizer moments mirroring the params (optax states are nested
    namedtuples whose moment pytrees share the params' dict structure, so the
    same specs apply), everything else replicated.  ``state`` may be concrete
    or a ``jax.eval_shape`` pytree of ShapeDtypeStructs — only the tree
    structure is inspected.

    ``zero1=True`` additionally shards the optimizer MOMENTS over the
    ``dp`` axis (ZeRO stage 1): each moment leaf takes its param's spec
    plus ``dp`` on the first still-unsharded dimension the axis
    divides.  Because the optax update runs OUTSIDE the manual
    shard_map region (at GSPMD level, ``_build_train_step``), this is
    purely a placement change — XLA computes each dp shard's slice of
    the elementwise update and all-gathers the new params, the ZeRO-1
    exchange — and adamw's m+v (2x params in f32, the largest state in
    training) shrink per-device by the dp degree.  Math unchanged
    (elementwise; proven by trajectory-equality tests)."""
    param_names = set(state.params.keys())
    replicated = NamedSharding(mesh, P())
    dp_size = mesh.shape.get("dp", 1)

    def spec_params(tree: dict) -> dict:
        return params_shardings(tree, cfg, mesh)

    def zero1_specs(tree: dict) -> dict:
        base = params_shardings(tree, cfg, mesh)
        out = {}
        for name, sharding in base.items():
            spec = list(sharding.spec) if sharding.spec else []
            shape = tree[name].shape
            spec += [None] * (len(shape) - len(spec))
            for i, (axis, dim) in enumerate(zip(spec, shape)):
                if axis is None and dp_size > 1 and dim % dp_size == 0:
                    spec[i] = "dp"
                    break
            out[name] = NamedSharding(mesh, P(*spec))
        return out

    def mirror(node):
        if isinstance(node, dict) and set(node.keys()) == param_names:
            return zero1_specs(node) if zero1 else spec_params(node)
        if hasattr(node, "_fields"):  # optax namedtuple states
            return type(node)(*(mirror(getattr(node, f)) for f in node._fields))
        if isinstance(node, (list, tuple)):
            return type(node)(mirror(x) for x in node)
        if hasattr(node, "shape"):
            return replicated
        return node

    return TrainState(
        params=spec_params(state.params),
        opt_state=mirror(state.opt_state),
        step=replicated,
    )


def shard_state(
    state: TrainState, cfg: TransformerConfig, mesh, zero1: bool = False
) -> TrainState:
    """Place params — and the optimizer state mirroring them — onto the mesh
    by logical axes (see ``state_shardings``)."""
    return jax.device_put(state, state_shardings(state, cfg, mesh, zero1))
