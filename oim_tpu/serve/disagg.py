"""Disaggregated prefill/decode serving: the paged-KV block-transfer
protocol and its router-side ship client.

The serving-plane analogue of the control plane's registry-routed
resource handoff (SURVEY §1, §7): a request's KV state — which the
paged cache (ISSUE 10) already makes an enumerable set of refcounted
fixed-size blocks — ships between TPU backends over HTTP, so a fleet
can split into a **prefill pool** (long-prompt admission, TTFT-bound)
and a **decode pool** (steady token streaming, bandwidth-bound) that
scale independently.  The flow (doc/serving.md "Disaggregated
prefill/decode"):

1. The router admits a long prompt to a prefill backend with
   ``max_new_tokens`` clamped to the first chunk and ``hold_kv`` set —
   on completion the engine RETAINS the request's blocks (one incref
   each) instead of freeing them, keyed by rid with a TTL.
2. ``GET /v1/kv?rid=N`` on the prefill backend streams the held state:
   an 8-byte big-endian manifest length, a JSON manifest (geometry,
   valid rows, prompt + emitted tokens, sampling state, leaf table),
   then each leaf's raw bytes in manifest order — byte-for-byte the
   ``GET /v1/weights`` framing (PR 7), applied to KV blocks.
3. The router POSTs the same bytes to a decode backend's
   ``PUT /v1/kv`` ingest, which geometry-validates the manifest,
   reserves fresh pool blocks (all-or-nothing: exhaustion answers 429
   — capacity backpressure, never a partial import), and stages the
   payload host-side; the continuation request (``kv_import``)
   scatter-writes the blocks on the driver thread and resumes decode
   at the shipped frontier — no recompute of the prefill.
4. Any failure — dense (non-paged) backend, geometry mismatch, ship
   killed mid-body, ingest capacity — falls back to the router's
   splice-recompute continuation (PR 6): token-identical greedy, the
   same exactness contract, just paying the prefill again.

Exactness: both backends serve the same checkpoint, so shipped KV rows
are bit-identical to what the decode backend would have computed — the
continuation is token-identical to the same request on one mixed
backend (tests/test_serve_disagg.py pins the matrix).

This module owns the WIRE protocol (manifest codec + framing), the
error taxonomy, the hold/import bookkeeping records, and the
router-side ship client; engine-side state (refcounts, block tables,
the staged-import write) lives in ``serve/engine.py``.
"""

from __future__ import annotations

import json
import struct
import time
import urllib.request
from dataclasses import dataclass, field

import numpy as np

# Pool roles (oim-serve --pool): "prefill" backends take long-prompt
# admissions and serve /v1/kv exports; "decode" backends ingest shipped
# KV and stream the continuation; "mixed" (the default) does both and
# never participates in a ship.
POOLS = ("prefill", "decode", "mixed")

# Hold/import bounds: a KV hold (prefill side) or staged import (decode
# side) pins pool blocks, so both are TTL'd and count-capped — an
# orchestrator that died mid-ship leaks nothing past the TTL, and a
# flood of ingests cannot pin the pool shut (oldest evicted first).
KV_HOLD_TTL_S = 60.0
KV_HOLD_MAX = 8
KV_IMPORT_TTL_S = 60.0
KV_IMPORT_MAX = 8

MANIFEST_KIND = "oim-kv"
MANIFEST_VERSION = 1


class KvTransferError(RuntimeError):
    """Base: this backend cannot serve/accept the requested transfer."""


class KvIneligibleError(KvTransferError):
    """Dense (non-paged) engine, or no such hold — the dense-ineligible
    guard: the router falls back to splice recompute (HTTP 409/404)."""


class KvGeometryError(KvTransferError):
    """Manifest geometry does not match this engine (layer count, KV
    heads, head dim, block size, quantization, dtype) — shipping
    between heterogeneous replicas is refused, never coerced (HTTP
    409)."""


class KvCapacityError(KvTransferError):
    """The ingest pool cannot reserve the shipped blocks right now —
    capacity backpressure (HTTP 429 + Retry-After), the admission
    planner's OOM-of-blocks stance applied to imports."""


@dataclass
class KvHold:
    """Prefill-side retained KV: the completed request's block ids
    (one extra ref each, taken at finish), the valid row frontier, and
    the full token record (prompt + emitted) the continuation must
    extend.  Host bookkeeping only — block contents live in the pool,
    kept alive by the refs."""

    rid: int
    blocks: tuple[int, ...]
    rows: int
    prompt_tokens: list[int]
    tokens: list[int]  # emitted
    sampling: dict
    t_created: float = field(default_factory=time.monotonic)


@dataclass
class KvImport:
    """Decode-side staged ingest: freshly reserved block ids (ref 1
    each), the shipped frontier, the token record the continuation
    request must match, and the host-side leaf payload the driver
    thread scatter-writes at admission."""

    import_id: int
    blocks: tuple[int, ...]
    rows: int
    tokens: list[int]  # prompt + emitted, the continuation's prompt
    data: dict  # leaf name → np array [n_layers, n_ship, bs, kvh, hd]
    t_created: float = field(default_factory=time.monotonic)


def _np_dtype(name: str):
    """numpy dtype for a manifest dtype name, including the ml_dtypes
    names (bfloat16) numpy itself does not know — the checkpoint
    manifest convention (checkpoint/manager.py).  An unknown name is a
    malformed manifest (:class:`KvGeometryError`), never an escaping
    AttributeError — the PUT handler must answer a clean 4xx."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError) as exc:
        raise KvGeometryError(f"unknown leaf dtype {name!r}") from exc


def build_manifest(
    *,
    geometry: dict,
    rows: int,
    prompt_tokens: list[int],
    tokens: list[int],
    sampling: dict,
    leaves: list[dict],
) -> dict:
    return {
        "kind": MANIFEST_KIND,
        "version": MANIFEST_VERSION,
        "geometry": geometry,
        "rows": rows,
        "prompt_tokens": list(prompt_tokens),
        "tokens": list(tokens),
        "sampling": dict(sampling),
        "leaves": leaves,
    }


def pack_transfer(manifest: dict, arrays: list[np.ndarray]) -> bytes:
    """One transfer as bytes: 8-byte big-endian manifest length, the
    JSON manifest, each leaf's raw bytes in manifest order (the
    /v1/weights framing).  Small transfers only ride this helper
    (tests, the ingest response path); the export endpoint streams
    leaf-by-leaf instead of materializing the whole body."""
    mb = json.dumps(manifest, separators=(",", ":")).encode()
    parts = [struct.pack(">Q", len(mb)), mb]
    for arr in arrays:
        parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def unpack_transfer(body: bytes) -> tuple[dict, dict]:
    """Parse one transfer body → (manifest, {leaf name: np array}).
    Raises KvGeometryError on any framing/shape problem — a torn or
    foreign body must refuse cleanly, never ingest garbage."""
    try:
        if len(body) < 8:
            raise ValueError("short header")
        (mlen,) = struct.unpack(">Q", body[:8])
        if mlen > len(body) - 8:
            raise ValueError("manifest length exceeds body")
        manifest = json.loads(body[8:8 + mlen])
        if (
            not isinstance(manifest, dict)
            or manifest.get("kind") != MANIFEST_KIND
        ):
            raise ValueError(f"not a {MANIFEST_KIND} manifest")
        off = 8 + mlen
        data: dict[str, np.ndarray] = {}
        for leaf in manifest["leaves"]:
            dtype = _np_dtype(leaf["dtype"])
            shape = tuple(int(d) for d in leaf["shape"])
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * dtype.itemsize
            if off + nbytes > len(body):
                raise ValueError(f"leaf {leaf['name']} truncated")
            data[leaf["name"]] = np.frombuffer(
                body, dtype=dtype, count=count, offset=off
            ).reshape(shape)
            off += nbytes
        if off != len(body):
            raise ValueError(f"{len(body) - off} trailing bytes")
        return manifest, data
    except KvGeometryError:
        raise
    except (KeyError, TypeError, ValueError, struct.error) as exc:
        raise KvGeometryError(f"malformed KV transfer: {exc}") from exc


def validate_geometry(manifest: dict, geometry: dict) -> None:
    """Refuse a manifest whose geometry does not match this engine's
    (``geometry`` = the engine's own dict, same keys).  Checked on the
    MANIFEST before any payload is staged — the weight-fetch
    discipline (PR 7 review)."""
    theirs = manifest.get("geometry")
    if not isinstance(theirs, dict):
        raise KvGeometryError("manifest carries no geometry")
    for key, want in geometry.items():
        got = theirs.get(key)
        if got != want:
            raise KvGeometryError(
                f"geometry mismatch on {key}: peer has {got!r}, "
                f"this engine has {want!r}"
            )
    rows = manifest.get("rows")
    n_tok = len(manifest.get("prompt_tokens", ())) + len(
        manifest.get("tokens", ())
    )
    if not isinstance(rows, int) or rows < 1 or rows != n_tok - 1:
        raise KvGeometryError(
            f"rows {rows!r} inconsistent with {n_tok} tokens "
            f"(valid rows must be tokens - 1)"
        )


# ---------------------------------------------------------------------------
# Router-side ship client


def ship_kv(
    opener,
    prefill_url: str,
    rid: int,
    decode_url: str,
    timeout: float = 30.0,
) -> tuple[int, int, int]:
    """Move one held KV state: GET it off the prefill backend, PUT it
    into the decode backend's ingest.  Returns (import_id, rows,
    bytes shipped).  Raises on ANY failure — short read (a backend
    killed mid-ship), HTTP error, unparseable ingest reply — and the
    caller falls back to splice recompute; this function performs no
    cleanup (the caller releases the hold either way).

    The body is relayed verbatim (the decode backend validates the
    manifest itself); the router never parses leaves."""
    with opener(
        f"{prefill_url}/v1/kv?rid={int(rid)}", timeout=timeout
    ) as resp:
        clen = int(resp.headers.get("Content-Length", "0"))
        body = resp.read()
    if clen and len(body) != clen:
        raise OSError(
            f"KV fetch truncated: {len(body)} of {clen} bytes "
            f"(prefill backend died mid-ship)"
        )
    req = urllib.request.Request(
        f"{decode_url}/v1/kv",
        data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="PUT",
    )
    with opener(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    return int(reply["import_id"]), int(reply["rows"]), len(body)


def release_kv(
    opener, url: str, *, rid: int | None = None,
    import_id: int | None = None, timeout: float = 5.0,
) -> None:
    """Best-effort DELETE of a hold (prefill side) or a staged import
    (decode side): the TTL expires either anyway, this just returns
    the blocks at the ship's own cadence instead of seconds later."""
    query = (
        f"rid={int(rid)}" if rid is not None
        else f"import={int(import_id)}"
    )
    req = urllib.request.Request(
        f"{url}/v1/kv?{query}", method="DELETE"
    )
    try:
        with opener(req, timeout=timeout):
            pass
    except Exception:
        pass  # the TTL sweep owns the backstop
