"""Disaggregated prefill/decode serving: the paged-KV block-transfer
protocol and its router-side ship client.

The serving-plane analogue of the control plane's registry-routed
resource handoff (SURVEY §1, §7): a request's KV state — which the
paged cache (ISSUE 10) already makes an enumerable set of refcounted
fixed-size blocks — ships between TPU backends over HTTP, so a fleet
can split into a **prefill pool** (long-prompt admission, TTFT-bound)
and a **decode pool** (steady token streaming, bandwidth-bound) that
scale independently.  The flow (doc/serving.md "Disaggregated
prefill/decode"):

1. The router admits a long prompt to a prefill backend with
   ``max_new_tokens`` clamped to the first chunk and ``hold_kv`` set —
   on completion the engine RETAINS the request's blocks (one incref
   each) instead of freeing them, keyed by rid with a TTL.
2. ``GET /v1/kv?rid=N`` on the prefill backend streams the held state:
   an 8-byte big-endian manifest length, a JSON manifest (geometry,
   valid rows, prompt + emitted tokens, sampling state, leaf table),
   then each leaf's raw bytes in manifest order — byte-for-byte the
   ``GET /v1/weights`` framing (PR 7), applied to KV blocks.
3. The router POSTs the same bytes to a decode backend's
   ``PUT /v1/kv`` ingest, which geometry-validates the manifest,
   reserves fresh pool blocks (all-or-nothing: exhaustion answers 429
   — capacity backpressure, never a partial import), and stages the
   payload host-side; the continuation request (``kv_import``)
   scatter-writes the blocks on the driver thread and resumes decode
   at the shipped frontier — no recompute of the prefill.
4. Any failure — dense (non-paged) backend, geometry mismatch, ship
   killed mid-body, ingest capacity — falls back to the router's
   splice-recompute continuation (PR 6): token-identical greedy, the
   same exactness contract, just paying the prefill again.

Exactness: both backends serve the same checkpoint, so shipped KV rows
are bit-identical to what the decode backend would have computed — the
continuation is token-identical to the same request on one mixed
backend (tests/test_serve_disagg.py pins the matrix).

This module owns the WIRE protocol (manifest codec + framing), the
error taxonomy, the hold/import bookkeeping records, and the
router-side ship client; engine-side state (refcounts, block tables,
the staged-import write) lives in ``serve/engine.py``.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
import urllib.request
from dataclasses import dataclass, field

import numpy as np

# Pool roles (oim-serve --pool): "prefill" backends take long-prompt
# admissions and serve /v1/kv exports; "decode" backends ingest shipped
# KV and stream the continuation; "mixed" (the default) does both and
# never participates in a ship.
POOLS = ("prefill", "decode", "mixed")

# Hold/import bounds: a KV hold (prefill side) or staged import (decode
# side) pins pool blocks, so both are TTL'd and count-capped — an
# orchestrator that died mid-ship leaks nothing past the TTL, and a
# flood of ingests cannot pin the pool shut (oldest evicted first).
KV_HOLD_TTL_S = 60.0
KV_HOLD_MAX = 8
KV_IMPORT_TTL_S = 60.0
KV_IMPORT_MAX = 8

# Fleet prefix residency (ISSUE 14): staged prefix installs pin pool
# blocks exactly like staged imports, same TTL/cap stance; the digest
# summary published in load/serve.<id> is truncated to the hottest
# PREFIX_DIGEST_CAP entries so the leased registry value stays small
# (a 4k-entry cache must not ship 4k digests every heartbeat).
PREFIX_IMPORT_TTL_S = 60.0
PREFIX_IMPORT_MAX = 8
PREFIX_DIGEST_CAP = 32

# Live slot migration (ISSUE 17): a draining backend's suspended-slot
# records pin their captured blocks like holds do, so they carry the
# same TTL — but no count cap: records are only ever minted from live
# slots and parked requests, so engine capacity already bounds them.
MIGRATE_TTL_S = 60.0

MANIFEST_KIND = "oim-kv"
MANIFEST_VERSION = 1


def prefix_digest(tokens) -> str:
    """Stable content digest of a prefix-cache entry: the hash of the
    token ids it covers.  THE fleet-wide identity of a resident prefix
    — the engine stamps it on every entry, load/serve.<id> publishes
    the summary, and the router recomputes it over a request's leading
    tokens to find which backend already holds that prefill.  16 hex
    chars: collision-safe at fleet scale (2^64) and short enough for
    registry values and log lines."""
    payload = ",".join(str(int(t)) for t in tokens).encode()
    return hashlib.sha256(b"oim-pfx:" + payload).hexdigest()[:16]


class KvTransferError(RuntimeError):
    """Base: this backend cannot serve/accept the requested transfer."""


class KvIneligibleError(KvTransferError):
    """Dense (non-paged) engine, or no such hold — the dense-ineligible
    guard: the router falls back to splice recompute (HTTP 409/404)."""


class KvGeometryError(KvTransferError):
    """Manifest geometry does not match this engine (layer count, KV
    heads, head dim, block size, quantization, dtype) — shipping
    between heterogeneous replicas is refused, never coerced (HTTP
    409)."""


class KvCapacityError(KvTransferError):
    """The ingest pool cannot reserve the shipped blocks right now —
    capacity backpressure (HTTP 429 + Retry-After), the admission
    planner's OOM-of-blocks stance applied to imports."""


@dataclass
class KvHold:
    """Prefill-side retained KV: the completed request's block ids
    (one extra ref each, taken at finish), the valid row frontier, and
    the full token record (prompt + emitted) the continuation must
    extend.  Host bookkeeping only — block contents live in the pool,
    kept alive by the refs."""

    rid: int
    blocks: tuple[int, ...]
    rows: int
    prompt_tokens: list[int]
    tokens: list[int]  # emitted
    sampling: dict
    t_created: float = field(default_factory=time.monotonic)


@dataclass
class KvImport:
    """Decode-side staged ingest: freshly reserved block ids (ref 1
    each), the shipped frontier, the token record the continuation
    request must match, and the host-side leaf payload the driver
    thread scatter-writes at admission."""

    import_id: int
    blocks: tuple[int, ...]
    rows: int
    tokens: list[int]  # prompt + emitted, the continuation's prompt
    data: dict  # leaf name → np array [n_layers, n_ship, bs, kvh, hd]
    t_created: float = field(default_factory=time.monotonic)


@dataclass
class SlotRecord:
    """Draining-side suspended live slot (ISSUE 17): everything a
    sibling needs to resume the request exactly.  EITHER ``blocks``
    (device ids, one extra ref each — an active slot captured
    hold-style at the migrate wave) OR ``host_blocks`` (a parked
    request's host-tier payload, ownership transferred from the parked
    record) is set, never both.  ``meta`` becomes the manifest's
    ``"slot"`` branch: the position-indexed sampling offset
    (``sample_base``), deadline remainder, tenant/tier, and trace
    context."""

    rid: int
    blocks: tuple[int, ...]
    host_blocks: tuple[int, ...]
    rows: int
    prompt_tokens: list[int]
    tokens: list[int]  # emitted on this backend
    sampling: dict
    meta: dict
    t_created: float = field(default_factory=time.monotonic)


def _np_dtype(name: str):
    """numpy dtype for a manifest dtype name, including the ml_dtypes
    names (bfloat16) numpy itself does not know — the checkpoint
    manifest convention (checkpoint/manager.py).  An unknown name is a
    malformed manifest (:class:`KvGeometryError`), never an escaping
    AttributeError — the PUT handler must answer a clean 4xx."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError) as exc:
        raise KvGeometryError(f"unknown leaf dtype {name!r}") from exc


def build_manifest(
    *,
    geometry: dict,
    rows: int,
    prompt_tokens: list[int],
    tokens: list[int],
    sampling: dict,
    leaves: list[dict],
) -> dict:
    return {
        "kind": MANIFEST_KIND,
        "version": MANIFEST_VERSION,
        "geometry": geometry,
        "rows": rows,
        "prompt_tokens": list(prompt_tokens),
        "tokens": list(tokens),
        "sampling": dict(sampling),
        "leaves": leaves,
    }


def pack_transfer(manifest: dict, arrays: list[np.ndarray]) -> bytes:
    """One transfer as bytes: 8-byte big-endian manifest length, the
    JSON manifest, each leaf's raw bytes in manifest order (the
    /v1/weights framing).  Small transfers only ride this helper
    (tests, the ingest response path); the export endpoint streams
    leaf-by-leaf instead of materializing the whole body."""
    mb = json.dumps(manifest, separators=(",", ":")).encode()
    parts = [struct.pack(">Q", len(mb)), mb]
    for arr in arrays:
        parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def unpack_transfer(body: bytes) -> tuple[dict, dict]:
    """Parse one transfer body → (manifest, {leaf name: np array}).
    Raises KvGeometryError on any framing/shape problem — a torn or
    foreign body must refuse cleanly, never ingest garbage."""
    try:
        if len(body) < 8:
            raise ValueError("short header")
        (mlen,) = struct.unpack(">Q", body[:8])
        if mlen > len(body) - 8:
            raise ValueError("manifest length exceeds body")
        manifest = json.loads(body[8:8 + mlen])
        if (
            not isinstance(manifest, dict)
            or manifest.get("kind") != MANIFEST_KIND
        ):
            raise ValueError(f"not a {MANIFEST_KIND} manifest")
        off = 8 + mlen
        data: dict[str, np.ndarray] = {}
        for leaf in manifest["leaves"]:
            dtype = _np_dtype(leaf["dtype"])
            shape = tuple(int(d) for d in leaf["shape"])
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * dtype.itemsize
            if off + nbytes > len(body):
                raise ValueError(f"leaf {leaf['name']} truncated")
            data[leaf["name"]] = np.frombuffer(
                body, dtype=dtype, count=count, offset=off
            ).reshape(shape)
            off += nbytes
        if off != len(body):
            raise ValueError(f"{len(body) - off} trailing bytes")
        return manifest, data
    except KvGeometryError:
        raise
    except (KeyError, TypeError, ValueError, struct.error) as exc:
        raise KvGeometryError(f"malformed KV transfer: {exc}") from exc


def validate_geometry(manifest: dict, geometry: dict) -> None:
    """Refuse a manifest whose geometry does not match this engine's
    (``geometry`` = the engine's own dict, same keys).  Checked on the
    MANIFEST before any payload is staged — the weight-fetch
    discipline (PR 7 review)."""
    theirs = manifest.get("geometry")
    if not isinstance(theirs, dict):
        raise KvGeometryError("manifest carries no geometry")
    for key, want in geometry.items():
        got = theirs.get(key)
        if got != want:
            raise KvGeometryError(
                f"geometry mismatch on {key}: peer has {got!r}, "
                f"this engine has {want!r}"
            )
    rows = manifest.get("rows")
    n_tok = len(manifest.get("prompt_tokens", ())) + len(
        manifest.get("tokens", ())
    )
    if manifest.get("prefix"):
        # A prefix-entry transfer (GET /v1/kv?prefix=<digest>) ships a
        # block-aligned prompt-KV entry: every covered token has a row
        # (there is no pending emitted token), and the digest must be
        # the hash of exactly those tokens — a manifest whose digest
        # and token record disagree is torn or forged, refuse it.
        if manifest.get("tokens"):
            # Conforming exporters always ship tokens=[]: a nonempty
            # emitted record would let rows exceed what the digest
            # hashes (it covers prompt_tokens only) — an entry keyed
            # by fewer tokens than the rows it pins.
            raise KvGeometryError(
                "a prefix transfer must not carry emitted tokens"
            )
        if not isinstance(rows, int) or rows < 1 or rows != n_tok:
            raise KvGeometryError(
                f"prefix rows {rows!r} inconsistent with {n_tok} "
                f"tokens (a prefix entry has one row per covered token)"
            )
        want = prefix_digest(manifest.get("prompt_tokens", ()))
        if manifest["prefix"] != want:
            raise KvGeometryError(
                f"prefix digest {manifest['prefix']!r} does not match "
                f"the shipped token record ({want})"
            )
    elif not isinstance(rows, int) or rows < 1 or rows != n_tok - 1:
        raise KvGeometryError(
            f"rows {rows!r} inconsistent with {n_tok} tokens "
            f"(valid rows must be tokens - 1)"
        )
    slot = manifest.get("slot")
    if slot is not None:
        # A live-slot transfer (GET /v1/slot) is hold-shaped — it rode
        # the rows == tokens - 1 check above — plus a "slot" branch
        # whose sampling offset the continuation depends on for
        # sampled exactness: refuse a torn/forged branch here, before
        # anything is staged.
        if manifest.get("prefix"):
            raise KvGeometryError(
                "a transfer cannot be both a prefix entry and a slot"
            )
        base = slot.get("sample_base") if isinstance(slot, dict) else None
        if not isinstance(base, int) or base < len(
            manifest.get("tokens", ())
        ):
            raise KvGeometryError(
                f"slot sample_base {base!r} inconsistent with "
                f"{len(manifest.get('tokens', ()))} emitted tokens"
            )


# ---------------------------------------------------------------------------
# Router-side ship client


def ship_kv(
    opener,
    prefill_url: str,
    rid: int,
    decode_url: str,
    timeout: float = 30.0,
) -> tuple[int, int, int]:
    """Move one held KV state: GET it off the prefill backend, PUT it
    into the decode backend's ingest.  Returns (import_id, rows,
    bytes shipped).  Raises on ANY failure — short read (a backend
    killed mid-ship), HTTP error, unparseable ingest reply — and the
    caller falls back to splice recompute; this function performs no
    cleanup (the caller releases the hold either way).

    The body is relayed verbatim (the decode backend validates the
    manifest itself); the router never parses leaves."""
    with opener(
        f"{prefill_url}/v1/kv?rid={int(rid)}", timeout=timeout
    ) as resp:
        clen = int(resp.headers.get("Content-Length", "0"))
        body = resp.read()
    if clen and len(body) != clen:
        raise OSError(
            f"KV fetch truncated: {len(body)} of {clen} bytes "
            f"(prefill backend died mid-ship)"
        )
    req = urllib.request.Request(
        f"{decode_url}/v1/kv",
        data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="PUT",
    )
    with opener(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    return int(reply["import_id"]), int(reply["rows"]), len(body)


def ship_slot(
    opener,
    src_url: str,
    rid: int,
    dst_url: str,
    timeout: float = 30.0,
) -> tuple[int, int, dict, int]:
    """Move one suspended live slot (ISSUE 17): GET it off the
    draining backend, PUT it into the migration target's staging
    ingest.  Returns (import_id, rows, slot branch, bytes shipped).
    Raises on ANY failure — short read (the source died mid-ship),
    HTTP error (404 record expired, 409 geometry, 429 capacity),
    unparseable reply — and the caller falls back to the
    splice-recompute continuation; like :func:`ship_kv` this performs
    no cleanup (the caller releases the source record either way, and
    a staged-but-never-consumed target side TTL-expires)."""
    with opener(
        f"{src_url}/v1/slot?rid={int(rid)}", timeout=timeout
    ) as resp:
        clen = int(resp.headers.get("Content-Length", "0"))
        body = resp.read()
    if clen and len(body) != clen:
        raise OSError(
            f"slot fetch truncated: {len(body)} of {clen} bytes "
            f"(draining backend died mid-ship)"
        )
    req = urllib.request.Request(
        f"{dst_url}/v1/slot",
        data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="PUT",
    )
    with opener(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    slot = reply.get("slot")
    return (
        int(reply["import_id"]),
        int(reply["rows"]),
        slot if isinstance(slot, dict) else {},
        len(body),
    )


def release_slot(
    opener, url: str, rid: int, timeout: float = 5.0
) -> None:
    """Best-effort DELETE of a suspended-slot record on the draining
    source — same stance as :func:`release_kv` (the TTL sweep owns the
    backstop; a torn-down source needs nothing released at all)."""
    req = urllib.request.Request(
        f"{url}/v1/slot?rid={int(rid)}", method="DELETE"
    )
    try:
        with opener(req, timeout=timeout):
            pass
    except Exception:
        pass  # the TTL sweep (or the teardown itself) owns the backstop


def ship_prefix(
    opener,
    src_url: str,
    digest: str,
    dst_url: str,
    timeout: float = 30.0,
) -> tuple[int, int]:
    """Move one resident prefix entry between backends: GET it off the
    backend whose cache holds ``digest``, PUT it into the target's
    ingest, which installs it as a refcounted prefix-cache entry.
    Returns (rows, bytes shipped).  Raises on ANY failure — the caller
    (the router's residency-aware miss path, the autoscaler's bring-up
    pre-warm) falls back to recompute prefill, which is always
    token-identical; like :func:`ship_kv` this performs no cleanup
    (nothing is held on the source — entries are cache-managed — and a
    staged-but-never-installed target side TTL-expires)."""
    with opener(
        f"{src_url}/v1/kv?prefix={digest}", timeout=timeout
    ) as resp:
        clen = int(resp.headers.get("Content-Length", "0"))
        body = resp.read()
    if clen and len(body) != clen:
        raise OSError(
            f"prefix fetch truncated: {len(body)} of {clen} bytes "
            f"(source backend died mid-ship)"
        )
    req = urllib.request.Request(
        f"{dst_url}/v1/kv",
        data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="PUT",
    )
    with opener(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    return int(reply["rows"]), len(body)


def prewarm_from_peer(
    engine,
    peer_url: str,
    top_k: int,
    opener=None,
    timeout: float = 30.0,
) -> int:
    """The ``--params-peer`` bring-up path's prefix leg (ISSUE 14): pull
    the weight-donor sibling's ``top_k`` hottest resident prefixes and
    install them locally, so a scale-out replica joins the fleet with
    the system prompts its cohort shares already resident — its first
    requests hit instead of re-prefilling what the whole fleet already
    computed.  Returns the number of entries installed.

    Strictly best-effort, by contract: ANY failure (peer gone, dense
    peer, geometry mismatch, capacity) degrades to normal bring-up —
    pre-warming must never block replica readiness, the same stance as
    a failed KV ship falling back to recompute.  The caller owns the
    driver-thread discipline: call BEFORE the serve loop starts (the
    install writes pool blocks through the engine's jitted ingest)."""
    if top_k <= 0 or not getattr(engine, "paged", False):
        return 0
    if opener is None:
        opener = urllib.request.urlopen
    try:
        with opener(f"{peer_url}/v1/info", timeout=timeout) as resp:
            info = json.loads(resp.read())
    except Exception:
        return 0  # peer gone/unreadable: serve cold, never block
    digests = (info.get("load") or {}).get("prefix_digests") or []
    installed = 0
    for entry in digests[: max(0, int(top_k))]:
        digest = entry.get("digest") if isinstance(entry, dict) else None
        if not digest:
            continue
        try:
            with opener(
                f"{peer_url}/v1/kv?prefix={digest}", timeout=timeout
            ) as resp:
                body = resp.read()
            engine.import_kv_prefix(*unpack_transfer(body))
            installed += 1
        except Exception:
            continue  # best-effort per entry; the rest may still land
    if installed:
        # Land the staged payloads in the pool now — no driver thread
        # runs yet, so the caller's thread IS the device writer.
        engine.install_prefix_imports()
    return installed


def release_kv(
    opener, url: str, *, rid: int | None = None,
    import_id: int | None = None, timeout: float = 5.0,
) -> None:
    """Best-effort DELETE of a hold (prefill side) or a staged import
    (decode side): the TTL expires either anyway, this just returns
    the blocks at the ship's own cadence instead of seconds later."""
    query = (
        f"rid={int(rid)}" if rid is not None
        else f"import={int(import_id)}"
    )
    req = urllib.request.Request(
        f"{url}/v1/kv?{query}", method="DELETE"
    )
    try:
        with opener(req, timeout=timeout):
            pass
    except Exception:
        pass  # the TTL sweep owns the backstop
