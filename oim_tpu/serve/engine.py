"""Continuous-batching inference engine over a slot-based KV cache.

TPU-first design:

- **Slots — dense or paged.**  The dense cache is ``[n_layers,
  n_slots, max_len, kv_heads, head_dim]`` — one contiguous region per
  request slot, with a per-slot ``lengths`` vector doing the work of a
  page table.  Static shapes mean XLA compiles exactly one decode
  program; admission and completion never reshape anything.
  ``kv_block > 0`` switches to a **paged** cache (``PagedCache``): a
  global pool of fixed-size blocks plus a host-side refcounted
  allocator and per-slot block table — same static shapes, same
  attention math on a gathered view, token-identical output — so HBM
  is reserved per request instead of per slot × max_len and
  prefix-cache entries alias their blocks copy-free across concurrent
  requests (copy-on-write on the first divergent write).  The capacity
  lever: more live slots per chip at the same cache budget.
- **Continuous batching.**  New requests are admitted into free slots
  while other slots keep decoding: ``admit_batch`` prefills every
  admission sharing a prompt bucket in ONE dispatch (buckets bound the
  compile count; one combined readback covers all of a step's
  admissions), ``decode_chunk`` advances every active slot.  The [B]
  ``starts`` vector generalizes ``models/decode.py``'s scalar cache
  length — each slot attends only to its own prefix.
- **Chunked decode.**  ``decode_chunk`` runs ``chunk`` steps in one
  ``lax.scan`` dispatch and returns ``[n_slots, chunk]`` tokens — one
  host↔device round trip per chunk, not per token.  On a tunneled or
  remote-host deployment (this box: ~70 ms/readback) that is the
  difference between 14 tok/s and line rate; EOS detection lags by at
  most one chunk, which costs bounded wasted compute, never correctness
  (the host truncates at EOS before emitting).
- **Exactness.**  A request decoded via the engine produces exactly the
  tokens ``models.decode.generate`` produces for the same prompt (greedy;
  verified in tests/test_serve.py) — batching composition cannot change
  results because every slot's attention is masked to its own length and
  MoE routing is drop-free per-token (``decode._moe_exact``) on prefill
  and decode alike, so padding and bucket choice are invisible at every
  prompt length, dense and MoE.
- **Per-request sampling streams.**  Every sampled token's PRNG key is
  ``fold_in(PRNGKey(request.seed), token_index)`` — a function of the
  request alone, so temperature>0 results are reproducible across runs
  and invariant to slot assignment, batching composition, and chunk
  size, the same property greedy gets for free.

The engine itself is host-side Python (the analog of the reference's
control-plane daemons); everything that touches the accelerator is a
handful of jitted functions with donated cache buffers.

- **Tensor-parallel serving.**  Pass ``mesh=`` (the canonical 5-axis
  ``parallel.build_mesh`` mesh; tp>1, optionally ep>1 for MoE) and the
  engine shards params by their logical axes and the KV cache over
  kv-heads, then lets GSPMD propagate through the same jitted
  admit/decode functions — models larger than one chip serve across
  the slice the control plane's ``MapVolume`` hands out.  Slot
  machinery stays host-side and identical; results are token-for-token
  the single-device engine's (tests/test_serve.py).

Also here: per-token logprobs (``result_full`` / the streaming
callback), an LRU prompt-KV **prefix cache** for system prompts
(``prefix_cache_size`` + ``GenRequest.cache_prefix`` — injected rows
are exact, dense and MoE alike), ``stop_ids``, slot-free ``embed`` and
latency-mode ``beam`` surfaces (beam-k runs as its own jitted program
beside the slot engine; beam-1 == greedy exactly), in-engine
speculative decoding (``spec_decode`` — prompt-lookup drafting, or a
trained draft model via ``draft_params``/``draft_cfg`` for workloads
whose continuations are not in the prompt; exactness preserved either
way), int8 KV (``kv_int8``) and weight-only int8
params (both preserve the exactness invariant), int4 KV (``kv_int4``,
paged-only — per-block scale arrays ride the pool), the paged
flash-decode kernel (``paged_kernel`` — decode attention reads K/V
straight from the block pool, ``ops/paged_attention.py``; auto-on for
TPU paged engines, token-identical to the gather path), Prometheus
instrumentation, and ``warmup``/``abort``/``forget`` lifecycle
discipline for daemon use.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oim_tpu.common import events as _events
from oim_tpu.common import locksan
from oim_tpu.common import metrics as _metrics
from oim_tpu.common import tracing as _tracing
from oim_tpu.serve import sentinel as _sentinel
from oim_tpu.qos.policy import (
    DEFAULT_POLICY as _QOS_DEFAULT,
    TIER_PRIORITY as _QOS_TIER_PRIORITY,
)

from oim_tpu.models.decode import (
    _dense_mlp,
    _flat_layer_params,
    _load_kv,
    _moe_exact,
    apply_penalties,
    embed_tokens,
    nucleus_min_p_mask,
    truncate_logits,
)
from oim_tpu.ops.paged import (
    copy_block,
    paged_store,
    paged_view,
    read_block,
    write_block,
)
from oim_tpu.ops.paged_attention import paged_flash_decode, paged_flash_prefill
from oim_tpu.serve.disagg import (
    KV_HOLD_MAX,
    KV_HOLD_TTL_S,
    KV_IMPORT_MAX,
    KV_IMPORT_TTL_S,
    MIGRATE_TTL_S,
    PREFIX_DIGEST_CAP,
    PREFIX_IMPORT_MAX,
    PREFIX_IMPORT_TTL_S,
    KvCapacityError,
    KvGeometryError,
    KvHold,
    KvImport,
    KvIneligibleError,
    SlotRecord,
    build_manifest,
    prefix_digest,
    validate_geometry,
)
from oim_tpu.ops.quant import (
    dequantize_named,
    make_kv_buffers,
    maybe_dequantize_weights,
    quantize_int8,
    weight_quant_mode,
)
from oim_tpu.models.transformer import (
    TransformerConfig,
    _rmsnorm,
    _unembed,
    embed_lookup,
    param_pspecs,
)
from oim_tpu.ops.rope import apply_rope

_NEG_BIG = -1e30

# Engine.beam server-side policy: beam-k replicates the KV cache k-fold,
# each distinct (beam_size, alpha, eos_id) is a fresh XLA program, and
# each distinct (prompt_len, max_new) is a fresh trace inside one — all
# client-controlled on a public endpoint, all bounded here.
_MAX_BEAM_SIZE = 32
_MAX_BEAM_PROGRAMS = 8
_MAX_BEAM_TRACES = 64

# Per-tenant QoS accounting rows are client-controlled cardinality
# (one per distinct CN / x-oim-tenant value): bound them like the beam
# caps above.  Evicted rows lose stats() history only — the shared
# Prometheus counters keep theirs.
_MAX_TENANT_ROWS = 256


def serve_param_shardings(params: dict, cfg: TransformerConfig, mesh):
    """NamedShardings for inference params by their logical axes
    (heads/mlp/vocab → ``tp``, experts → ``ep`` per
    ``parallel.sharding.DEFAULT_RULES``; the mesh's pp/dp/sp axes are
    size-1 in a serving mesh, making those entries no-ops).  Extends
    the training-side rule set with the inference-only names: a
    ``<w>_wscale`` int8 companion is its weight's shape minus the
    reduction (second-to-last) axis, so it drops that entry from the
    weight's spec; LoRA ``_a``/``_b`` adapters replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    pspecs = param_pspecs(cfg)

    def spec(name, value):
        if name.endswith("_wscale") and name[: -len("_wscale")] in pspecs:
            base = pspecs[name[: -len("_wscale")]]
            if len(value.shape) == len(base):
                # int4 group-wise scale: a groups axis replaces the
                # reduction axis (replicated); the output axis keeps the
                # weight's sharding.
                return P(*base[:-2], None, base[-1])
            return P(*base[:-2], base[-1])
        if name not in pspecs and name[-2:] in ("_a", "_b") and (
            name[:-2] in pspecs
        ):
            return P()
        return pspecs[name]

    def fitted(value, sp):
        # device_put shards exactly (no GSPMD padding): drop an axis from
        # any dimension it doesn't divide (e.g. an odd vocab replicates
        # wte/wlm while heads and mlp still shard).
        return P(*(
            a if a is not None and value.shape[i] % mesh.shape[a] == 0
            else None
            for i, a in enumerate(sp)
        ))

    return {
        name: NamedSharding(mesh, fitted(value, spec(name, value)))
        for name, value in params.items()
    }


def cache_shardings(cache, mesh):
    """Cache-shaped NamedShardings: k/v (and their int8 scales)
    sharded over ``tp`` on the kv-heads axis — attention is fully
    head-parallel, so each tp shard owns its heads' cache rows and the
    only tp collective in the decode path is the psum GSPMD inserts for
    the wo/w_out contractions.  The kv-heads axis sits at index 3 in
    both layouts ([L, slots, max_len, KVH, hd] dense, [L, blocks,
    block_size, KVH, hd] paged), so one spec serves either; only the
    wrapper type differs."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    kv = NamedSharding(mesh, P(None, None, None, "tp", None))
    scale = NamedSharding(mesh, P(None, None, None, "tp"))
    cls = type(cache)
    return cls(
        k=kv,
        v=kv,
        lengths=NamedSharding(mesh, P()),
        k_scale=None if cache.k_scale is None else scale,
        v_scale=None if cache.v_scale is None else scale,
    )


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class SlotCache:
    """KV cache with one region per request slot.

    ``k``/``v``: [n_layers, n_slots, max_len, kv_heads, head_dim];
    ``lengths``: [n_slots] int32 — valid positions per slot (the engine's
    "page table": a slot attends to rows < its own length only).
    ``k_scale``/``v_scale``: per-(token, head) f32 scales
    [n_layers, n_slots, max_len, kv_heads] when the cache is int8
    (``ops/quant.py`` — half the cache bandwidth decode pays), else None.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @classmethod
    def create(
        cls,
        cfg: TransformerConfig,
        n_slots: int,
        max_len: int,
        quantized: bool = False,
    ) -> "SlotCache":
        shape = (cfg.n_layers, n_slots, max_len, cfg.kv_heads, cfg.head_dim)
        k, v, ks, vs = make_kv_buffers(shape, cfg.compute_dtype, quantized)
        return cls(
            k=k, v=v, lengths=jnp.zeros((n_slots,), jnp.int32),
            k_scale=ks, v_scale=vs,
        )

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class PagedCache:
    """Paged KV cache: a global pool of fixed-size blocks shared by
    every slot (the vLLM PagedAttention layout, ISSUE 10).

    ``k``/``v``: [n_layers, n_blocks, block_size, kv_heads, head_dim];
    ``lengths``: [n_slots] int32 — valid positions per slot, exactly
    the dense cache's frontier semantics.  ``k_scale``/``v_scale``:
    per-(token, head) f32 scales [n_layers, n_blocks, block_size,
    kv_heads] when quantized (int8 or int4 payloads — the pool dtype
    selects the scheme), else None.  Which pool blocks belong to which
    slot lives OUTSIDE this pytree: the engine's host-side
    ``BlockAllocator`` + block table, pushed to the device as a
    [n_slots, n_tables] int32 array each dispatch (sentinel entry
    ``n_blocks`` = unallocated).  Memory is therefore reserved per
    REQUEST (rounded up to blocks), not per slot × max_len — the
    capacity lever: a pool sized like a 4-slot dense cache admits as
    many concurrent slots as actually fit, and prefix-cache entries
    alias their blocks into every concurrent reader copy-free.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @classmethod
    def create(
        cls,
        cfg: TransformerConfig,
        n_slots: int,
        n_blocks: int,
        block_size: int,
        quantized: bool | str = False,
    ) -> "PagedCache":
        shape = (
            cfg.n_layers, n_blocks, block_size, cfg.kv_heads, cfg.head_dim
        )
        k, v, ks, vs = make_kv_buffers(shape, cfg.compute_dtype, quantized)
        return cls(
            k=k, v=v, lengths=jnp.zeros((n_slots,), jnp.int32),
            k_scale=ks, v_scale=vs,
        )

    @property
    def n_slots(self) -> int:
        return self.lengths.shape[0]

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]


class BlockAllocator:
    """Host-side refcounted allocator over the paged pool's block ids.

    Pure bookkeeping (never traced): the engine calls it under its own
    lock, so there is no lock here.  ``alloc`` is all-or-nothing — an
    admission either gets every block its worst case needs or stays
    queued (OOM-of-blocks is queue backpressure, never a crash or a
    partially-allocated slot).  Refcounts implement copy-free sharing:
    a prefix-cache entry and every slot aliasing it each hold one ref
    on the shared blocks, and the last ``decref`` returns a block to
    the free list.  Copy-on-write is the engine's job (pick a fresh
    block, device-copy, repoint the table); the allocator only
    guarantees a shared block (ref > 1) is never on the free list.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 1:
            raise ValueError(f"need n_blocks >= 1, got {n_blocks}")
        self.n_blocks = n_blocks
        self._free: list[int] = list(range(n_blocks - 1, -1, -1))
        self._refs = np.zeros((n_blocks,), np.int64)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def shared_blocks(self) -> int:
        """Blocks aliased by more than one owner (ref > 1) — each is
        HBM the fleet would otherwise hold in duplicate."""
        return int(np.sum(self._refs > 1))

    def alloc(self, n: int) -> list[int] | None:
        """``n`` fresh blocks at ref 1, or None (all-or-nothing)."""
        if n < 0:
            raise ValueError(f"need n >= 0, got {n}")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._refs[ids] += 1
        return ids

    def exclusive(self, ids) -> int:
        """How many of ``ids`` have exactly one owner — the blocks a
        single decref would actually return to the pool (the eviction
        policy's is-it-worth-dropping test)."""
        return int(sum(1 for b in ids if self._refs[b] == 1))

    def incref(self, ids) -> None:
        for b in ids:
            if self._refs[b] <= 0:
                raise ValueError(f"incref of free block {b}")
            self._refs[b] += 1

    def decref(self, ids) -> int:
        """Drop one ref per id; blocks hitting zero return to the free
        list.  Returns how many were freed."""
        freed = 0
        for b in ids:
            if self._refs[b] <= 0:
                raise ValueError(f"decref of free block {b}")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(int(b))
                freed += 1
        return freed


class HostBlockPool:
    """Host-RAM overflow tier for the paged pool (ISSUE 15): the same
    ``[n_layers, block, block_size, kv_heads, head_dim]`` geometry as
    the device pool (including the int8/int4 scale planes) in plain
    numpy, plus its own refcounted ``BlockAllocator``.  Warm prefix
    entries and parked slot tables live here instead of being
    destroyed when HBM runs short — a later hit PROMOTES the blocks
    back through the warmup-precompiled ingest program instead of
    recomputing the prefill.

    Pure host state: every byte that lands here arrived via a
    stream-ordered ``read_block`` fetch, and every byte that leaves
    goes back up through ``write_block`` — the pool itself is never
    traced.  Mutated only under the engine lock (allocator) or by the
    completion path that owns the pending write (array rows), so there
    is no lock here — the ``BlockAllocator`` single-owner contract."""

    def __init__(self, cache: "PagedCache", n_blocks: int):
        if n_blocks < 1:
            raise ValueError(f"need n_blocks >= 1, got {n_blocks}")
        shape = (cache.k.shape[0], n_blocks) + cache.k.shape[2:]
        # np.zeros accepts the device pool's dtype directly (bfloat16 /
        # int4 are ml_dtypes-registered numpy dtypes) — the host copy
        # is bit-identical to the device block, quantized payloads and
        # all, which is what makes demote→promote exact by
        # construction.
        self.k = np.zeros(shape, cache.k.dtype)
        self.v = np.zeros(shape, cache.v.dtype)
        if cache.k_scale is not None:
            sshape = (cache.k_scale.shape[0], n_blocks) + (
                cache.k_scale.shape[2:]
            )
            self.k_scale = np.zeros(sshape, cache.k_scale.dtype)
            self.v_scale = np.zeros(sshape, cache.v_scale.dtype)
        else:
            self.k_scale = self.v_scale = None
        self.alloc = BlockAllocator(n_blocks)

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    def pools(self) -> list[tuple[str, "np.ndarray"]]:
        """(leaf name, host array) pairs, scale planes included when
        quantized — the one leaf-name order demote writes and promote
        reads share."""
        out = [("k", self.k), ("v", self.v)]
        if self.k_scale is not None:
            out += [("k_scale", self.k_scale), ("v_scale", self.v_scale)]
        return out


@dataclass
class _HostWrite:
    """One dispatched-but-unfetched tier demotion: the device-side
    ``read_block`` futures for each moved block plus where their bytes
    land in the host pool.  ``kind`` "prefix" registers a host prefix
    entry on completion; "park" marks the parked slot restorable.  The
    device futures were dispatched BEFORE the source blocks were
    decref'd, so the single device stream guarantees they carry the
    pre-reuse contents no matter who reallocates the blocks next."""

    kind: str  # "prefix" | "park"
    host_blocks: tuple[int, ...]
    # One entry per moved block: list of per-leaf device arrays in
    # HostBlockPool.pools() order.
    dev: list
    key: tuple = ()  # prefix: the covered-token entry key
    rows: int = 0  # prefix: covered rows
    meta: dict | None = None  # prefix: residency record to carry over
    rid: int = -1  # park: the parked request


@dataclass
class _ParkedSlot:
    """A mid-stream request swapped out to the host tier: its full
    host slot state plus the host blocks holding its KV through the
    frontier.  ``ready`` flips when the demote fetch lands;
    ``n_live`` is the original reservation's block count, so restore
    re-reserves exactly what admission planned."""

    state: "_SlotState"
    host_blocks: tuple[int, ...]
    n_cov: int  # leading blocks that carry live rows (the payload)
    n_live: int  # total blocks the original plan reserved
    rows: int  # valid KV rows (len(prompt) + len(emitted) - 1)
    ready: bool = False
    # True while _unpark_wave holds the lock released for the restore's
    # device writes: the record stays in _parked (visible to cancel/
    # reap/abort/in_flight the whole time) and whoever POPS it owns the
    # host-block decref — the restore's commit detects a concurrent
    # abort by the pop coming back empty.
    restoring: bool = False
    t_parked: float = field(default_factory=time.monotonic)


def _restore_slot(
    cache: PagedCache, history, tok_counts, gen_counts,
    slot, length, hist_row, tok_row, gen_row,
    *, track_history: bool, penalize: bool,
):
    """Device half of un-parking: put one slot's per-slot device state
    back — the cache frontier (``lengths[slot]``), the spec-decode
    token history row, and the sampling-penalty occurrence rows — all
    reconstructed from HOST truth (prompt + emitted tokens), so the
    restored slot is indistinguishable from one that never left.
    ``slot``/``length`` are traced: ONE compile covers every restore
    (the demote/promote steady state stays recompile-free)."""
    lengths = cache.lengths.at[slot].set(length)
    cache = PagedCache(
        cache.k, cache.v, lengths, cache.k_scale, cache.v_scale
    )
    if track_history:
        history = history.at[slot].set(hist_row)
    if penalize:
        tok_counts = tok_counts.at[slot].set(tok_row)
        gen_counts = gen_counts.at[slot].set(gen_row)
    return cache, history, tok_counts, gen_counts


def _cow_block(cache: PagedCache, src, dst):
    """Device half of copy-on-write: duplicate block ``src`` into the
    freshly-allocated ``dst`` across every pool (k/v and, when int8,
    their scales).  The host repoints the diverging slot's table row
    at ``dst`` and the shared ``src`` — still referenced by the prefix
    cache and any concurrent readers — is never written again."""
    cp = lambda pool: (  # noqa: E731
        None if pool is None else copy_block(pool, src, dst)
    )
    return PagedCache(
        cp(cache.k), cp(cache.v), cache.lengths,
        cp(cache.k_scale), cp(cache.v_scale),
    )


def _ingest_block(cache: PagedCache, kb, vb, ksb, vsb, dst):
    """Device half of a KV-ship ingest (serve/disagg.py): write one
    shipped block's rows into every pool at block ``dst`` — k/v and,
    when int8, their scales (the scale args are unused [1] dummies on
    a full-precision cache; the branch is trace-time static on the
    pytree).  ``dst`` is traced, so ONE compile covers every
    destination block; the engine chains these through ``self._cache``
    before the continuation's prefill dispatch, device-stream-ordered
    like copy-on-write."""
    put = lambda pool, row: (  # noqa: E731
        None if pool is None else write_block(pool, row, dst)
    )
    return PagedCache(
        put(cache.k, kb), put(cache.v, vb), cache.lengths,
        put(cache.k_scale, ksb), put(cache.v_scale, vsb),
    )


def _slot_store(cache, scale, new, starts):
    """Per-slot write of ``new`` [B, t, KVH, hd] at ``starts`` [B] —
    quantizing when the cache is int8 (scale is not None)."""
    write = lambda c, u, s: jax.lax.dynamic_update_slice(  # noqa: E731
        c, u, (s, 0, 0)
    )
    if scale is None:
        return jax.vmap(write)(cache, new.astype(cache.dtype), starts), None
    q, s = quantize_int8(new)
    cache = jax.vmap(write)(cache, q, starts)
    scale = jax.vmap(
        lambda c, u, st: jax.lax.dynamic_update_slice(c, u, (st, 0))
    )(scale, s, starts)
    return cache, scale


def _slot_attention(
    x, lp, k_cache, v_cache, k_scale, v_scale, starts,
    cfg: TransformerConfig, tables=None, paged_kernel: bool = False,
    prefill_kernel: bool = False,
):
    """Cached attention with per-slot start positions.

    x: [B, t, D]; k_cache/v_cache: [B, max_len, KVH, hd]; scales
    [B, max_len, KVH] (int8 cache) or None; starts: [B].  Generalizes
    ``decode._cached_attention`` (scalar start) to a vector — the one
    primitive continuous batching needs.

    With ``tables`` [B, n_tables] (the paged layout), k_cache/v_cache
    are instead the ONE-LAYER POOL [n_blocks, block_size, KVH, hd]
    (scales [n_blocks, block_size, KVH]): the store scatters through
    the table (sentinel entries drop — padding rows and freed slots
    write nowhere) and attention runs on the gathered per-row view,
    which has exactly the dense region shape because the engine pins
    ``n_tables * block_size == max_len``.  Score math, masking, and
    softmax are shared code on either layout — the paged engine's
    token-identical-to-dense property is by construction, not by a
    parallel implementation.

    ``paged_kernel`` (trace-time static, paged only) swaps the
    gather-then-attend lower half for the Pallas flash-decode kernel
    (``ops/paged_attention.py``): attention reads K/V straight from
    the pool through the block table — no dense intermediate, one HBM
    pass over the cache bytes, sentinel entries contributing nothing
    and int8/int4 dequant fused at the operand read.  The engine
    enables it on decode chunks only (prefill keeps the gather); the
    store half and the qkv/rope/wo math above and below are shared
    either way, so the kernel path's output is pinned token-identical
    to the gather path's by tests/test_serve_paged.py.

    ``prefill_kernel`` (trace-time static, paged only, admission legs
    only) goes one further for prompt segments: the flash-PREFILL
    kernel both writes the segment's K/V straight into the slot's
    blocks (fused quant, no dense intermediate) and attends off the
    pool — ``paged_store`` + gather + dense attention collapse into
    one pass over the cache bytes.  Token-identical to the gather leg
    by tests/test_serve_prefill_kernel.py.
    """
    b, t, _ = x.shape
    h, hd, kvh = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    group = h // kvh

    normed = _rmsnorm(x, lp["attn_norm"], cfg)
    q = jnp.einsum("btd,dn->btn", normed, lp["wq"])
    k = jnp.einsum("btd,dn->btn", normed, lp["wk"])
    v = jnp.einsum("btd,dn->btn", normed, lp["wv"])
    if "bq" in lp:  # Qwen-style qkv biases (cfg.attn_bias)
        # Cast to the activation dtype: an f32 bias against bf16
        # activations would promote everything downstream.
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(b, t, h, hd)
    k = k.reshape(b, t, kvh, hd)
    v = v.reshape(b, t, kvh, hd)
    positions = starts[:, None] + jnp.arange(t)  # [B, t] global positions
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

    if tables is None:
        k_cache, k_scale = _slot_store(k_cache, k_scale, k, starts)
        v_cache, v_scale = _slot_store(v_cache, v_scale, v, starts)
        k_view, ks_view = k_cache, k_scale
        v_view, vs_view = v_cache, v_scale
    else:
        if prefill_kernel:
            # Flash-prefill path: store and attend fused — the staged
            # blocks land through the sentinel-dropping block scatter
            # (bytes identical to paged_store's), then the flash
            # kernel attends off the updated pool.
            out, k_cache, v_cache, k_scale, v_scale = paged_flash_prefill(
                q, k, v, k_cache, v_cache, k_scale, v_scale, tables,
                starts, window=cfg.sliding_window,
            )
            out = out.astype(x.dtype).reshape(b, t, h * hd)
            return x + jnp.einsum(
                "btn,nd->btd", out, lp["wo"]
            ).astype(x.dtype), (k_cache, v_cache, k_scale, v_scale)
        k_cache, k_scale = paged_store(k_cache, k_scale, k, tables, starts)
        v_cache, v_scale = paged_store(v_cache, v_scale, v, tables, starts)
        if paged_kernel:
            # Flash-decode path: no gather, no dense view — the kernel
            # walks the block table itself.  Output matches the shared
            # math below position for position (pinned token-identical
            # by the exactness matrix), so the wo projection and the
            # residual are common code again immediately after.
            out = paged_flash_decode(
                q, k_cache, v_cache, k_scale, v_scale, tables, starts,
                window=cfg.sliding_window,
            ).astype(x.dtype)
            out = out.reshape(b, t, h * hd)
            return x + jnp.einsum(
                "btn,nd->btd", out, lp["wo"]
            ).astype(x.dtype), (k_cache, v_cache, k_scale, v_scale)
        k_view, ks_view = paged_view(k_cache, k_scale, tables)
        v_view, vs_view = paged_view(v_cache, v_scale, tables)
    max_len = k_view.shape[1]

    q_g = q.reshape(b, t, kvh, group, hd)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk",
        q_g.astype(jnp.float32),
        _load_kv(k_view, ks_view),
    ) / (hd**0.5)
    # Causal per slot: query at global position p attends to rows <= p of
    # its own region; rows past the slot's frontier are invalid.  Rows
    # map 1:1 to global positions, so the sliding window is the same
    # position arithmetic as decode._cached_attention.
    q_pos = positions[:, None, None, :, None]  # [B, 1, 1, t, 1]
    k_pos = jnp.arange(max_len)[None, None, None, None, :]
    keep = k_pos <= q_pos
    if cfg.sliding_window:
        keep &= q_pos - k_pos < cfg.sliding_window
    scores = jnp.where(keep, scores, _NEG_BIG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs, _load_kv(v_view, vs_view)
    ).astype(x.dtype)
    out = out.reshape(b, t, h * hd)
    return x + jnp.einsum("btn,nd->btd", out, lp["wo"]).astype(x.dtype), (
        k_cache,
        v_cache,
        k_scale,
        v_scale,
    )


def _hidden_slots(
    params, tokens, kv, starts, cfg, paged_kernel=False,
    prefill_kernel=False,
):
    """tokens [B, t] at per-slot positions ``starts`` → (final-norm
    hidden states [B, t, D], kv) — no unembedding, so prefill callers
    can unembed only the one position they sample from (the unembed is
    ~20% of step FLOPs at vocab 32k and all-position prefill logits are
    the largest activation there is).

    ``kv`` = (k, v, k_scale, v_scale): [n_layers, B, max_len, KVH, hd]
    values with per-(token, head) scales (or None when full-precision).
    A FIVE-tuple (k, v, k_scale, v_scale, tables) is the paged layout:
    pools [n_layers, n_blocks, block_size, KVH, hd] plus the per-row
    block table [B, n_tables], threaded through the scan untouched —
    ``_slot_attention`` scatters/gathers through it per layer
    (``paged_kernel`` — trace-time static — flips that layer read to
    the flash-decode kernel; ``prefill_kernel`` flips the whole
    store+attend to the flash-prefill kernel on admission legs; both
    ignored on the dense layout).
    MoE routing follows ``models/decode.py``: drop-free per-token top-k
    (``_moe_exact``) on prefill AND incremental steps — per-token routing
    is what makes engine results independent of padding, batch packing,
    and prompt length.
    """
    cfg = replace(cfg, use_pallas=False)
    x = embed_lookup(params["wte"], tokens, cfg)
    flat = _flat_layer_params(params, cfg)
    paged = len(kv) == 5
    quantized = kv[2] is not None

    def layer_step(carry, scanned):
        x, k_all, v_all, ks_all, vs_all = carry[:5]
        tables = carry[5] if paged else None
        lp, layer = scanned
        lp = maybe_dequantize_weights(lp, cfg.compute_dtype)  # weight-int8
        # Stacked cache rides the CARRY with per-layer dynamic slicing —
        # an xs/ys cache made lax.scan concatenate (allocate + copy) the
        # whole stack every decode step, scaling per-step cost with the
        # cache allocation (see models/decode.py:_hidden_cached).
        idx = lambda a: jax.lax.dynamic_index_in_dim(  # noqa: E731
            a, layer, 0, keepdims=False
        )
        put = lambda a, u: jax.lax.dynamic_update_index_in_dim(  # noqa: E731
            a, u, layer, 0
        )
        x, (k_l, v_l, ks_l, vs_l) = _slot_attention(
            x, lp, idx(k_all), idx(v_all),
            idx(ks_all) if quantized else None,
            idx(vs_all) if quantized else None,
            starts, cfg, tables=tables, paged_kernel=paged_kernel,
            prefill_kernel=prefill_kernel,
        )
        k_all, v_all = put(k_all, k_l), put(v_all, v_l)
        if quantized:
            ks_all, vs_all = put(ks_all, ks_l), put(vs_all, vs_l)
        if cfg.n_experts:
            x = _moe_exact(x, lp, cfg)
        else:
            x, _ = _dense_mlp(x, lp, cfg)
        out = (x, k_all, v_all, ks_all, vs_all)
        return (out + (tables,) if paged else out), None

    (x, *kv), _ = jax.lax.scan(
        layer_step, (x, *kv), (flat, jnp.arange(cfg.n_layers))
    )
    return _rmsnorm(x, params["final_norm"], cfg), tuple(kv)


def _sample_batched(
    logits, temps, keys, top_k, top_ps, min_ps, penalties=None
):
    """Per-slot temperature sampling with per-slot PRNG keys: greedy
    where temp == 0, else categorical over temperature-scaled logits
    truncated by the engine-static top-k plus PER-SLOT top-p / min-p
    ([S] arrays — dynamic values, static shapes;
    ``nucleus_min_p_mask``).  The nucleus/min-p sort only runs when some
    slot actually truncates (``lax.cond`` — default traffic never pays
    the [S, V] sort on the decode hot path).  ``penalties`` = (rep [S],
    pres [S], freq [S], tok_counts [S, V], gen_counts [S, V])
    pre-adjusts the logits (``apply_penalties``; neutral rows are
    bit-exact no-ops).  Returns ``(tokens [S], logprobs [S])`` — the
    logprob is the chosen token's log-softmax under the
    (penalty-adjusted) temperature-1 untruncated distribution, the
    standard scoring convention."""
    if penalties is not None:
        rep, pres, freq, tok_counts, gen_counts = penalties
        logits = apply_penalties(
            logits, tok_counts, gen_counts, rep, pres, freq
        )
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Static top-k through the solo path's truncate_logits (ONE mask
    # definition); the dynamic per-slot masks follow.
    scaled = truncate_logits(
        logits / jnp.maximum(temps, 1e-6)[:, None], top_k
    )
    scaled = jax.lax.cond(
        jnp.any((top_ps < 1.0) | (min_ps > 0.0)),
        lambda x: nucleus_min_p_mask(x, top_ps, min_ps),
        lambda x: x,
        scaled,
    )
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(keys, scaled).astype(jnp.int32)
    tokens = jnp.where(temps > 0, sampled, greedy)
    # log_softmax[token] without materializing an [S, V] fp32 array:
    # logit[token] - logsumexp(logits), fp32 only on the [S] outputs.
    chosen = jnp.take_along_axis(logits, tokens[:, None], axis=-1)[:, 0]
    logprobs = chosen.astype(jnp.float32) - jax.nn.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
    return tokens, logprobs


# oimlint: hotpath
def _admit_batch(
    params, cache, row_tables, history, tok_counts, gen_counts,
    prompt_counts, full_rows, prompts, slots, starts,
    true_tails, temps, top_ps, min_ps, reps, press, freqs, keys,
    *, cfg, top_k, track_history, penalize, prefill_kernel=False,
):
    """Prefill a whole GROUP of admissions in one dispatch and sample
    each one's first generated token.  Returns
    (cache, first_tokens [S], first_logprobs [S]).

    ``cache`` is a SlotCache or a PagedCache (different pytree
    structures → separate traces; the branch below is trace-time
    static).  ``row_tables`` [S, n_tables] is the paged layout's
    per-admission block table — the target slot's freshly-built row
    for live admissions, all-sentinel for padding rows (their writes
    drop at the pool edge, the paged twin of the dense scatter's
    out-of-bounds slot index) — and an unused [1, 1] dummy on dense
    engines.

    ``history`` [n_slots, max_len] is the engine's device-side token
    record (speculative decoding's draft source); ``full_rows``
    [S, max_len] holds each admission's FULL prompt (prefix-injected
    tokens included) zero-padded, overwriting the admitted slots' rows.
    With ``track_history=False`` (non-speculative engines — nothing
    consumes the record) both pass through untouched and the caller
    hands in dummies, skipping the per-admission host→device transfer.
    ``tok_counts``/``gen_counts`` [n_slots, V] are the engine's sampling-
    penalty occurrence state; ``prompt_counts`` [S, V] (host-side
    bincounts of each admission's FULL prompt) resets the admitted
    slots' rows, and the first sampled token joins both counts.
    ``reps``/``press``/``freqs`` [S] are the per-row penalty params
    (neutral on padding rows).
    prompts [S, Lb]: each row's uncached prompt tail, padded to the
    group's shared bucket; slots [S]: row → slot index, with the
    OUT-OF-BOUNDS value ``n_slots`` marking inert padding rows (S is
    always n_slots, so there is exactly one compile per prompt bucket);
    starts [S]: first uncached position (> 0 after a prefix-cache
    injection — the causal mask attends the tail to the injected rows
    exactly as a full prefill would); true_tails [S]: valid tail
    lengths; temps [S]; keys [S] per-request PRNG keys.

    Padding rows gather the LAST slot's region, compute on garbage, and
    vanish at the scatter (``mode="drop"`` on the out-of-bounds index) —
    their FLOPs are the price of one static shape per bucket.  Pad positions
    past ``start + true_tail`` are written but masked forever: the
    slot's length stops there and decode overwrites them one by one.
    """
    n_slots = cache.n_slots
    if track_history:
        history = history.at[slots].set(full_rows, mode="drop")
    if isinstance(cache, PagedCache):
        # No per-slot row extraction: every row reads and writes the
        # GLOBAL pool through its own table (aliased prefix blocks are
        # read copy-free by however many rows share them; writes land
        # only in each row's freshly-allocated blocks — the host
        # allocator never hands a shared block to a writer).
        kv = (cache.k, cache.v, cache.k_scale, cache.v_scale, row_tables)
        x, kv = _hidden_slots(
            params, prompts, kv, starts, cfg,
            prefill_kernel=prefill_kernel,
        )
        k_all, v_all, ks_all, vs_all = kv[:4]
        lengths = cache.lengths.at[slots].set(
            starts + true_tails, mode="drop"
        )
        new_cache = PagedCache(k_all, v_all, lengths, ks_all, vs_all)
    else:
        kv_full = (cache.k, cache.v, cache.k_scale, cache.v_scale)
        # padding rows read slot-(-1)
        row_src = jnp.minimum(slots, n_slots - 1)
        kv_rows = jax.tree.map(
            lambda c: jnp.take(c, row_src, axis=1), kv_full
        )
        x, kv_rows = _hidden_slots(params, prompts, kv_rows, starts, cfg)
        k_all, v_all, ks_all, vs_all = jax.tree.map(
            lambda c, u: c.at[:, slots].set(u, mode="drop"),
            kv_full, kv_rows,
        )
        lengths = cache.lengths.at[slots].set(
            starts + true_tails, mode="drop"
        )
        new_cache = SlotCache(k_all, v_all, lengths, ks_all, vs_all)
    last_h = jax.vmap(
        lambda row, t: jax.lax.dynamic_index_in_dim(
            row, t - 1, axis=0, keepdims=False
        )
    )(x, true_tails)
    logits = _unembed(
        last_h[:, None], dequantize_named(params, "wlm"), cfg
    )[:, 0]
    if penalize:
        gen_zero = jnp.zeros_like(prompt_counts)
        first, first_lp = _sample_batched(
            logits, temps, keys, top_k, top_ps, min_ps,
            penalties=(reps, press, freqs, prompt_counts, gen_zero),
        )
        onehot = jax.nn.one_hot(
            first, prompt_counts.shape[1], dtype=jnp.int32
        )
        tok_counts = tok_counts.at[slots].set(
            prompt_counts + onehot, mode="drop"
        )
        gen_counts = gen_counts.at[slots].set(onehot, mode="drop")
    else:
        first, first_lp = _sample_batched(
            logits, temps, keys, top_k, top_ps, min_ps
        )
    return (
        new_cache,
        history,
        tok_counts,
        gen_counts,
        first,
        first_lp,
    )


def _extract_prefix(cache: SlotCache, slot, *, rows: int):
    """Copy the first ``rows`` KV rows of ``slot`` out (a prefix-cache
    entry): pytree (k, v, k_scale, v_scale) with the slot axis dropped."""
    def cut(c):
        sizes = (c.shape[0], 1, rows, *c.shape[3:])
        start = (0, slot) + (0,) * (c.ndim - 2)
        return jax.lax.dynamic_slice(c, start, sizes)[:, 0]

    return jax.tree.map(
        cut, (cache.k, cache.v, cache.k_scale, cache.v_scale)
    )


def _inject_prefix(cache: SlotCache, entry, slot):
    """Write a prefix-cache entry's rows into the head of ``slot``'s
    region (admit then continues at ``start`` = the usable prefix length;
    rows past it are garbage until overwritten, and masked until then)."""
    def put(c, u):
        start = (0, slot) + (0,) * (c.ndim - 2)
        return jax.lax.dynamic_update_slice(c, u[:, None], start)

    kv_full = (cache.k, cache.v, cache.k_scale, cache.v_scale)
    k, v, ks, vs = jax.tree.map(put, kv_full, entry)
    return SlotCache(k, v, cache.lengths, ks, vs)


# oimlint: hotpath
def _decode_chunk(
    params, cache, tables, tok_counts, gen_counts, tokens, temps,
    top_ps, min_ps, reps, press, freqs, active, bases, counts,
    *, cfg, chunk, top_k, penalize, max_len, paged_kernel=False,
):
    """Advance every active slot by ``chunk`` tokens in one dispatch.

    tokens [S] (each slot's latest token), temps [S], active [S] bool,
    bases [S] per-request PRNG base keys, counts [S] tokens already
    generated per request; tok_counts/gen_counts [S, V] +
    reps/press/freqs [S] drive the sampling penalties (neutral rows are
    exact no-ops).  Returns (cache, tok_counts, gen_counts, out, lps).
    ``cache`` is a SlotCache or PagedCache; ``tables`` [n_slots,
    n_tables] is the paged per-slot block table (a freed slot's
    all-sentinel row drops its post-EOS garbage writes at the pool
    edge, the paged twin of dense garbage staying confined to its own
    region) and an unused dummy on dense engines.  ``max_len`` is the
    logical per-slot capacity — a static partial kwarg because the
    paged pool's shape no longer encodes it.

    Step ``i`` samples slot ``s`` with ``fold_in(bases[s], counts[s]+i)``
    — the key is a function of (request seed, absolute token index), so
    chunking and batching are invisible to sampling.  Inactive or
    budget-exhausted slots keep computing (the host truncates overshoot;
    bounded waste, never a per-token readback) and their lengths clamp at
    the cache edge — masking beats dynamic batch shapes on TPU.
    """
    paged = isinstance(cache, PagedCache)

    def one(carry, i):
        kv, lengths, tok, tok_c, gen_c = carry
        x, kv = _hidden_slots(
            params, tok[:, None], kv, lengths, cfg,
            paged_kernel=paged_kernel, prefill_kernel=False,
        )
        logits = _unembed(x, dequantize_named(params, "wlm"), cfg)
        keys = jax.vmap(jax.random.fold_in)(bases, counts + i)
        if penalize:
            nxt, lp = _sample_batched(
                logits[:, -1], temps, keys, top_k, top_ps, min_ps,
                penalties=(reps, press, freqs, tok_c, gen_c),
            )
            nxt = jnp.where(active, nxt, tok)
            upd = active.astype(jnp.int32)[:, None] * jax.nn.one_hot(
                nxt, tok_c.shape[1], dtype=jnp.int32
            )
            tok_c, gen_c = tok_c + upd, gen_c + upd
        else:
            nxt, lp = _sample_batched(
                logits[:, -1], temps, keys, top_k, top_ps, min_ps
            )
            nxt = jnp.where(active, nxt, tok)
        # Clamp: a slot decoding past its budget inside a chunk (host
        # truncates after) must not index past the cache edge.
        lengths = jnp.minimum(
            lengths + active.astype(jnp.int32), max_len - 1
        )
        return (kv, lengths, nxt, tok_c, gen_c), (nxt, lp)

    kv0 = (cache.k, cache.v, cache.k_scale, cache.v_scale)
    if paged:
        kv0 = kv0 + (tables,)
    (
        kv_out, lengths, last_tok, tok_counts,
        gen_counts,
    ), (out, lps) = jax.lax.scan(
        one,
        (kv0, cache.lengths, tokens, tok_counts, gen_counts),
        jnp.arange(chunk),
    )
    k_all, v_all, ks_all, vs_all = kv_out[:4]
    cls = PagedCache if paged else SlotCache
    # ``last_tok`` [S] (each slot's post-chunk latest token) stays on
    # device: the pipelined engine feeds it straight into the NEXT
    # dispatch so chunk N+1 never waits on chunk N's readback.
    return (
        cls(k_all, v_all, lengths, ks_all, vs_all),
        tok_counts,
        gen_counts,
        out.T,
        lps.T,
        last_tok,
    )


def _draft_lookup(hist, length, draft_len: int, ngram: int, max_len: int):
    """Prompt-lookup drafting for one slot: find the most recent earlier
    occurrence of the last ``ngram`` known tokens (ending at position
    ``length``, where the newest decided token was just written) and
    return the ``draft_len`` tokens that followed it.  No match → zeros;
    a wrong draft is rejection-safe (verification emits the true token),
    so garbage never affects results, only the acceptance rate.

    Candidate selection prefers the most recent match whose whole
    continuation lies inside the decided region ``[0, length]`` — rows
    past ``length`` hold the previous sub-step's rejected drafts (stale
    garbage), and a match ending right at the edge drafts from them.
    Without the preference, a slot in a repetition cycle always matched
    at the edge and drafted ``[real, stale, stale, ...]``, capping
    acceptance near ``1/draft_len`` in exactly the regime where prompt
    lookup should accept everything.  When no fully-decided match
    exists (early in a short history), fall back to the freshest edge
    match with its undecided positions masked to 0 — a partial draft
    still beats none."""
    query_start = length - ngram + 1
    query = hist[jnp.clip(query_start + jnp.arange(ngram), 0, max_len - 1)]
    idx = jnp.arange(max_len)[:, None] + jnp.arange(ngram)[None, :]
    windows = hist[jnp.clip(idx, 0, max_len - 1)]  # [max_len, ngram]
    eq = jnp.all(windows == query[None, :], axis=1)
    window_end = jnp.arange(max_len) + ngram - 1
    positions = jnp.arange(max_len)
    ok = eq & (query_start >= 0)
    w_full = jnp.max(
        jnp.where(ok & (window_end + draft_len <= length), positions, -1)
    )
    w_edge = jnp.max(jnp.where(ok & (window_end < length), positions, -1))
    w = jnp.where(w_full >= 0, w_full, w_edge)
    cont = w + ngram + jnp.arange(draft_len)
    drafts = jnp.where(
        cont <= length, hist[jnp.clip(cont, 0, max_len - 1)], 0
    )
    return jnp.where(w >= 0, drafts, 0)


def _verify_emit(
    params, kv, lengths, tok, drafts, temps, top_ps, min_ps, active,
    bases, counts, i, *, cfg, top_k, max_len, n_drafts,
    paged_kernel=False,
):
    """The exactness-critical verify+emit core shared by BOTH drafting
    sources (prompt lookup and draft model): one (L+1)-position target
    forward over [tok, drafts], longest-accepted-prefix emission with
    the non-speculative path's per-sub-step ``fold_in(base, counts+i)``
    sampling keys, and the headroom-clamped length update.  Returns
    (kv, lengths, tok_next, emitted, lps, n_emit)."""
    inputs = jnp.concatenate([tok[:, None], drafts], axis=1)
    x, kv = _hidden_slots(
        params, inputs, kv, lengths, cfg, paged_kernel=paged_kernel,
        prefill_kernel=False,
    )
    logits = _unembed(x, dequantize_named(params, "wlm"), cfg)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, L+1]
    accepted = jnp.sum(
        jnp.cumprod(
            (drafts == greedy[:, :n_drafts]).astype(jnp.int32), axis=1
        ),
        axis=1,
    )
    keys = jax.vmap(jax.random.fold_in)(bases, counts + i)
    samp, samp_lp = _sample_batched(
        logits[:, 0], temps, keys, top_k, top_ps, min_ps
    )
    is_greedy = temps <= 0.0
    emitted = greedy.at[:, 0].set(
        jnp.where(is_greedy, greedy[:, 0], samp)
    )
    chosen = jnp.take_along_axis(
        logits, emitted[..., None], axis=-1
    )[..., 0]
    lps = chosen.astype(jnp.float32) - jax.nn.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
    lps = lps.at[:, 0].set(jnp.where(is_greedy, lps[:, 0], samp_lp))
    n_emit = jnp.where(
        active, jnp.where(is_greedy, accepted + 1, 1), 0
    ).astype(jnp.int32)
    tok_next = jnp.where(
        active,
        jnp.take_along_axis(
            emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
        )[:, 0],
        tok,
    )
    lengths = jnp.minimum(lengths + n_emit, max_len - 1 - n_drafts)
    return kv, lengths, tok_next, emitted, lps, n_emit


# oimlint: hotpath
def _decode_chunk_spec(
    params, cache, tables, history, tokens, temps, top_ps, min_ps,
    active, bases, counts,
    *, cfg, chunk, draft_len, ngram, top_k, max_len, paged_kernel=False,
):
    """``_decode_chunk`` with in-engine speculative decoding: each of the
    ``chunk`` sub-steps drafts ``draft_len`` tokens per slot by prompt
    lookup over the slot's device-side token ``history`` [S, max_len],
    verifies all ``draft_len + 1`` positions in ONE forward, and emits
    the longest accepted prefix plus the bonus token — decode is
    KV-bandwidth-bound, so the (L+1)-token forward costs about one
    step's wall time while emitting up to L+1 tokens.

    Exactness: greedy emission is unchanged by construction — position
    j's logits are conditioned on the draft prefix, which is only
    consumed when verified equal to the true greedy continuation.
    Sampled slots (temp > 0) take the position-0 logits and emit exactly
    one token per sub-step with the same ``fold_in(base, counts + i)``
    keys as the non-speculative path, so sampling results are identical
    too.  Rejected draft rows (KV and history alike) sit past the slot's
    length — dead until overwritten, exactly like admission pads.  The
    engine reserves ``draft_len + 1`` rows of cache headroom so clamped
    writes can never land on live rows.

    Returns (cache, history, out [S, chunk, L+1], lps [S, chunk, L+1],
    n_emit [S, chunk]) — the host consumes ``n_emit[s, i]`` tokens of
    sub-step i's row.  ``tables``/``max_len`` follow the
    ``_decode_chunk`` contract (paged block table / static logical
    capacity).
    """
    paged = isinstance(cache, PagedCache)
    n_drafts = draft_len

    def one(carry, i):
        kv, lengths, tok, hist = carry
        # Newest decided token enters the history at its position.
        hist = jax.vmap(
            lambda h, n, t: h.at[jnp.minimum(n, max_len - 1)].set(t)
        )(hist, lengths, tok)
        drafts = jax.vmap(
            partial(_draft_lookup, draft_len=n_drafts, ngram=ngram,
                    max_len=max_len)
        )(hist, lengths)  # [S, L]
        hist = jax.vmap(
            lambda h, n, d: jax.lax.dynamic_update_slice(
                h, d, (jnp.minimum(n + 1, max_len - n_drafts),)
            )
        )(hist, lengths, drafts)
        kv, lengths, tok_next, emitted, lps, n_emit = _verify_emit(
            params, kv, lengths, tok, drafts, temps, top_ps, min_ps,
            active, bases, counts, i, cfg=cfg, top_k=top_k,
            max_len=max_len, n_drafts=n_drafts, paged_kernel=paged_kernel,
        )
        return (kv, lengths, tok_next, hist), (emitted, lps, n_emit)

    kv0 = (cache.k, cache.v, cache.k_scale, cache.v_scale)
    if paged:
        kv0 = kv0 + (tables,)
    (kv_out, lengths, last_tok, history), (
        out, lps, n_emit
    ) = jax.lax.scan(
        one, (kv0, cache.lengths, tokens, history), jnp.arange(chunk)
    )
    k_all, v_all, ks_all, vs_all = kv_out[:4]
    cls = PagedCache if paged else SlotCache
    return (
        cls(k_all, v_all, lengths, ks_all, vs_all),
        history,
        out.transpose(1, 0, 2),
        lps.transpose(1, 0, 2),
        n_emit.T,
        last_tok,
    )


# oimlint: hotpath
def _admit_draft(
    draft_params, dcache: SlotCache, full_rows, slots, new_lengths,
    *, dcfg,
):
    """Prefill the DRAFT model's slot cache for a batch of admissions.

    ``full_rows`` [S, bucket] is each admission's FULL prompt padded to
    the group's full-prompt bucket (one compile per bucket, like the
    target's admit), so the draft cache is exact from position 0
    regardless of any target-side prefix-cache injection (the prompt-KV
    cache stores TARGET rows only).  ``new_lengths`` [S] is the
    target's post-admission length per row; both caches track ONE
    shared length (``_decode_chunk_spec_model``'s invariant).  Padding
    rows (slot index ``n_slots``) drop at the scatter; pad positions
    past a row's length are garbage above the length watermark until
    decode overwrites them — the target admit's discipline.
    """
    n_slots = dcache.n_slots
    kv_full = (dcache.k, dcache.v, dcache.k_scale, dcache.v_scale)
    row_src = jnp.minimum(slots, n_slots - 1)
    kv_rows = jax.tree.map(lambda c: jnp.take(c, row_src, axis=1), kv_full)
    zeros = jnp.zeros_like(new_lengths)
    _, kv_rows = _hidden_slots(draft_params, full_rows, kv_rows, zeros, dcfg)
    k_all, v_all, ks_all, vs_all = jax.tree.map(
        lambda c, u: c.at[:, slots].set(u, mode="drop"), kv_full, kv_rows
    )
    lengths = dcache.lengths.at[slots].set(new_lengths, mode="drop")
    return SlotCache(k_all, v_all, lengths, ks_all, vs_all)


# oimlint: hotpath
def _decode_chunk_spec_model(
    params, draft_params, cache, dcache: SlotCache, tables,
    tokens, temps, top_ps, min_ps, active, bases, counts,
    *, cfg, dcfg, chunk, draft_len, top_k, max_len, paged_kernel=False,
):
    """``_decode_chunk_spec`` with a TRAINED DRAFT MODEL instead of
    prompt lookup: each sub-step runs ``draft_len`` sequential greedy
    forwards of the small draft model from its own slot cache, then the
    target verifies all ``draft_len + 1`` positions in one forward.
    Prompt lookup accepts ~0 when the continuation is not in the prompt;
    a distilled draft drafts from the same learned distribution as the
    target, so acceptance follows model agreement, not prompt echo.

    Cache discipline (both caches share ONE lengths vector): at sub-step
    start, ``tok`` is the newest decided token with NO cache row yet in
    EITHER cache.  The draft scan runs ``draft_len + 1`` forwards —
    inputs [tok, d1..dL] — writing L+1 draft rows at positions
    lengths..lengths+L, exactly the rows the target's verify forward
    writes; the last forward exists only for its row (its output token
    is discarded), so an all-accepted sub-step leaves no gap at
    position lengths+L.  Rows past the accepted prefix are stale in
    both caches identically and are overwritten before they can be
    attended (next sub-step writes L+1 rows from the new length).
    Exactness: identical emission rule to ``_decode_chunk_spec`` —
    greedy output is verified equal to the target's own continuation,
    sampled slots emit one token from position-0 logits with the same
    fold_in keys.

    The TARGET cache may be paged (``tables``/``max_len`` per the
    ``_decode_chunk`` contract); the draft cache stays dense always —
    it is small by design (a fraction of the target's layers × width),
    so paging it would spend table-management complexity on the one
    cache that is not the capacity bottleneck.
    """
    paged = isinstance(cache, PagedCache)
    n_drafts = draft_len

    def one(carry, i):
        kv, dkv, lengths, tok = carry

        # One draft forward per position (the write position advances
        # with j); the final forward exists only to write d_L's cache
        # row — its output token is discarded.
        def dstep(c, j):
            dkv_c, cur = c
            x, dkv_c = _hidden_slots(
                draft_params, cur[:, None], dkv_c, lengths + j, dcfg
            )
            lg = _unembed(
                x, dequantize_named(draft_params, "wlm"), dcfg
            )
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            return (dkv_c, nxt), nxt

        (dkv, _), drafted = jax.lax.scan(
            dstep, (dkv, tok), jnp.arange(n_drafts + 1)
        )
        drafts = drafted[:n_drafts].T  # [S, L]

        kv, lengths, tok_next, emitted, lps, n_emit = _verify_emit(
            params, kv, lengths, tok, drafts, temps, top_ps, min_ps,
            active, bases, counts, i, cfg=cfg, top_k=top_k,
            max_len=max_len, n_drafts=n_drafts, paged_kernel=paged_kernel,
        )
        return (kv, dkv, lengths, tok_next), (emitted, lps, n_emit)

    kv0 = (cache.k, cache.v, cache.k_scale, cache.v_scale)
    if paged:
        kv0 = kv0 + (tables,)
    dkv0 = (dcache.k, dcache.v, dcache.k_scale, dcache.v_scale)
    (
        kv_out,
        (dk, dv, dks, dvs),
        lengths,
        last_tok,
    ), (out, lps, n_emit) = jax.lax.scan(
        one, (kv0, dkv0, cache.lengths, tokens), jnp.arange(chunk)
    )
    k_all, v_all, ks_all, vs_all = kv_out[:4]
    cls = PagedCache if paged else SlotCache
    return (
        cls(k_all, v_all, lengths, ks_all, vs_all),
        SlotCache(dk, dv, lengths, dks, dvs),
        out.transpose(1, 0, 2),
        lps.transpose(1, 0, 2),
        n_emit.T,
        last_tok,
    )


@dataclass
class GenRequest:
    """One generation request.  ``tokens`` are prompt token ids (the
    engine is tokenizer-agnostic, like the reference control plane is
    filesystem-agnostic); sampling params are per-request except
    top-k/top-p, which are engine-static (jit-friendly masks)."""

    tokens: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: int | None = None
    # Additional stop tokens: generation ends at the first token in this
    # set (emitted, like eos_id).  For multi-token stop SEQUENCES do the
    # matching client-side — the engine is tokenizer-agnostic.
    stop_ids: tuple[int, ...] = ()
    # Per-request truncation: top_p (None → the engine's --top-p
    # default) and min_p (keep tokens with at least min_p × the max
    # probability).  Engine top_k stays engine-static (a dynamic k
    # would be a gather, not a mask).
    top_p: float | None = None
    min_p: float = 0.0
    # Sampling penalties (models/decode.py ``apply_penalties``):
    # repetition (HF convention, over prompt+generated; 1.0 = off),
    # presence/frequency (OpenAI convention, over generated; 0.0 = off).
    # Neutral values are bit-exact no-ops; non-neutral values are
    # rejected on speculative engines (draft verification would need
    # within-block count evolution).
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # Store this request's prompt KV in the engine's prefix cache after
    # admission (mark system prompts); later prompts sharing the prefix
    # skip re-prefilling it.
    cache_prefix: bool = False
    # Absolute deadline (time.monotonic() clock), None = none.  Expired
    # in the queue → shed before touching a slot (HTTP 429 +
    # Retry-After); expired mid-decode → the slot is freed at the next
    # pipeline boundary and the waiter gets a RequestFailedError with
    # kind "deadline" (HTTP 504).
    deadline: float | None = None
    # Caller's span context (tracing.SpanContext): the engine records
    # this request's phase spans (queue/admit/prefill/decode/stream)
    # as its children, so `oimctl trace` renders
    # router→server→engine as one tree.  None mints a fresh trace —
    # the phase record (ring + histograms) exists either way.
    span: "_tracing.SpanContext | None" = None
    # mTLS tenant identity (the HTTP layer's peer cert CN): labels the
    # per-tenant SLO histograms and the completed-request ring.  Empty
    # = unauthenticated deployment; exported as "anon".
    tenant: str = ""
    # Disaggregated prefill/decode (serve/disagg.py).  ``hold_kv``:
    # retain this request's KV blocks after completion (one incref
    # each, TTL'd) for a ``GET /v1/kv`` export — the prefill leg of a
    # ship.  A no-op on dense engines (the dense-ineligible guard: the
    # later export 404s and the router falls back to splice recompute).
    hold_kv: bool = False
    # ``kv_import``: admit from a staged ingest (``PUT /v1/kv``) —
    # the continuation resumes decode at the shipped frontier instead
    # of re-prefilling.  An expired/unknown import falls back to a
    # normal (recompute) admission, token-identical either way.
    kv_import: int | None = None
    # Sampling-key offset for continuations (ISSUE 17): every sampled
    # token's PRNG key is ``fold_in(PRNGKey(seed), i)`` where ``i`` is
    # the token's GLOBAL emission index.  A fresh request starts at 0;
    # a migrated/spliced continuation sets this to the count of tokens
    # the client already received, so its key indices line up with the
    # undisturbed stream's — that is what makes a continuation
    # sampled-exact, not just greedy-exact.  Host-side data only: no
    # jit signature changes, no recompiles.
    sample_base: int = 0


class QueueFullError(RuntimeError):
    """Admission queue at capacity — back off and retry (HTTP 429)."""


class DrainingError(RuntimeError):
    """Engine is draining for shutdown — no new admissions (HTTP 503)."""


class DeadlineExpiredError(RuntimeError):
    """Request deadline already expired at submission — shed without
    touching the queue (HTTP 429 + Retry-After)."""


class EngineFailedError(RuntimeError):
    """The engine latched a driver-thread crash (``step`` raised) — no
    new work is accepted until the process restarts (HTTP 503)."""


_KIND_TEXT = {
    "aborted": "aborted",
    "cancelled": "cancelled",
    "deadline": "deadline exceeded",
    "deadline_queue": "shed (deadline expired in queue)",
    "stalled": "stalled",
    "migrated": "suspended for migration (resume on a sibling)",
}


class RequestFailedError(RuntimeError):
    """One request failed without a result.  ``kind`` tells the HTTP
    layer which status to answer: "aborted" (driver died, 500),
    "cancelled" (client went away), "deadline" (expired mid-decode,
    504), "deadline_queue" (shed before a slot, 429 + Retry-After),
    "stalled" (watchdog failed it fast, 503 + Retry-After — retryable
    on another replica), "migrated" (suspended by a migrate-out drain —
    the stream layer hands the rid to the router, which resumes the
    request on a sibling; non-stream callers see 503 + Retry-After)."""

    def __init__(self, rid: int, kind: str, message: str):
        super().__init__(
            f"request {rid} {_KIND_TEXT.get(kind, kind)}: {message}"
        )
        self.rid = rid
        self.kind = kind


@dataclass
class _PhaseTrace:
    """Host-side per-request phase clock (monotonic timestamps) — the
    substrate for engine phase spans, the completed-request ring, and
    the per-tenant SLO histograms.  Pure bookkeeping on timestamps the
    step loop already takes (or cheap host clock reads beside them):
    recording never touches the device, so tracing cannot perturb the
    dispatch-ahead pipeline or add a sync.

    The boundaries PARTITION the request's lifetime: queue =
    [t_submit, t_admitted], admit = [t_admitted, t_prefill] (the
    wave-scheduling slice between queue exit and the wave's first
    device work — near-zero by design on this engine; per-row host
    prep like prefix-cache lookups and prompt-array building
    interleaves with the wave's device dispatches and books into
    prefill alongside them), prefill = [t_prefill, t_first] (wave
    device work starts → first-token readback processed), decode =
    one interval per chunk
    the slot participated in (marginal: clipped to the previous
    chunk's completion, the oim_serve_token_seconds convention), and
    stream = [last chunk done, finalize] (tail emission +
    end-of-stream callbacks).  Summing the phases therefore reconciles
    with the e2e span up to inter-chunk host gaps (tests assert the
    tolerance)."""

    t_submit: float
    t_admitted: float = 0.0
    t_prefill: float = 0.0
    t_first: float = 0.0
    # Which path produced the leading KV rows (ISSUE 14): "local" /
    # "fetched" prefix-cache hit, or "recomputed" prefill — stamped at
    # admission, surfaced in the request ring (`oimctl requests`).
    prefix_source: str = "recomputed"
    # Chunked-prefill attribution (ISSUE 20): how many prompt segments
    # this request's admission dispatched (1 = one-shot; > 1 = the
    # long-prompt interleaved path), plus one host dispatch wall per
    # segment.  The interleaved segments all fall inside [t_prefill,
    # t_first] — decode chunks the engine ran BETWEEN them belong to
    # the slots that emitted, so the phase partition is untouched (the
    # PR 9 reconciliation test keeps passing by construction).
    prefill_segments: int = 0
    segment_walls: list[float] = field(default_factory=list)
    # One record per decode chunk this request consumed tokens from:
    # (chunk seq, span start, done, tokens, dispatch_wait_s,
    # fetch_wait_s) — dispatch-wait vs fetch-wait from the step loop's
    # accumulator split, per chunk.  Bounded by
    # ceil(max_new_tokens / chunk).
    chunks: list[tuple] = field(default_factory=list)


@dataclass
class _SlotState:
    rid: int
    req: GenRequest
    base: jax.Array  # per-request PRNG base key (PRNGKey(req.seed))
    t_submit: float
    emitted: list[int] = field(default_factory=list)
    logprobs: list[float] = field(default_factory=list)
    last_token: int = 0
    phases: _PhaseTrace | None = None
    # Host-tier parking (ISSUE 15): set when this slot was just
    # restored from the host tier, cleared at its next emitted token —
    # a restored slot must make progress before it can be parked
    # again, or a saturated admission queue ping-pongs one victim.
    park_immune: bool = False


@dataclass
class _InFlightChunk:
    """One dispatched-but-unread decode chunk — the pipeline's unit.

    ``handles`` are the device futures the host will fetch (out/lps[/
    n_emit]); ``next_tok`` is the [S] device array of each slot's
    post-chunk latest token, which a CHAINED dispatch feeds straight
    back in so chunk N+1 never waits on chunk N's readback;
    ``counts`` is the host-side per-slot generated-token count fed to
    THIS dispatch (a chained dispatch sends ``counts + chunk`` — exact
    for every slot whose sampling keys matter, see
    ``_dispatch_chunk``); ``inputs`` are the per-slot host sampling
    arrays, reused verbatim by a chained dispatch (a slot that
    finished meanwhile keeps computing garbage the host truncates —
    the EOS-lags-one-chunk contract extended by one pipeline stage);
    ``snapshot`` maps slot → the state that OWNED it at dispatch time,
    so processing can never attribute a chunk's tokens to a later
    occupant.  The engine holds at most one (pipeline depth 2); it is
    consumed by ``_process_chunk`` or dropped unread by
    ``abort``/the all-slots-finished tail."""

    kind: str  # "plain" | "spec" | "spec_model"
    snapshot: dict[int, _SlotState]
    handles: tuple
    next_tok: jax.Array
    counts: np.ndarray
    inputs: tuple
    t_dispatch: float
    # Phase-attribution fields (ISSUE 9): the chunk's sequence number
    # (monotonic per engine) and its dispatch-wait wall — recorded at
    # dispatch so _process_chunk can stamp per-request decode spans
    # with the dispatch-wait vs fetch-wait split without re-measuring.
    seq: int = 0
    dispatch_wall: float = 0.0


@dataclass
class _PendingPrefill:
    """A long-prompt admission mid-flight through chunked prefill
    (ISSUE 20): the slot is assigned and its blocks committed, the
    first segment(s) dispatched, and ``segs`` holds what remains.  The
    admission wave advances each pending by ONE segment per wave, so
    decode chunks for active slots interleave between segments at
    pipeline boundaries instead of stalling behind the whole prompt
    (Sarathi-style stall-free scheduling); when the last segment is
    gone the request JOINS that wave's normal group dispatch (final
    ``tail``, real first-token sample).  The rid stays in
    ``_admitting`` throughout, so abort() reclaims the slot exactly as
    for a one-shot admission; cancel/deadline are reaped at the wave's
    advance pass (the pending twin of _reap's slot loop)."""

    rid: int
    req: GenRequest
    slot: int
    plan: dict | None
    segs: list[list[int]]  # remaining non-final segments
    tail: list[int]        # final segment (group dispatch samples it)
    start: int             # next segment's write position
    t_submit: float
    trace: _PhaseTrace


class Engine:
    """Continuous-batching engine: submit → step/run → result.

    Thread-safe for one driver thread calling ``step``/``run`` while any
    number of threads call ``submit``/``result`` (the HTTP server's
    usage).  Every decode dispatch runs exactly ``chunk`` steps — a slot
    whose budget or EOS lands mid-chunk keeps computing and the host
    truncates the overshoot (bounded waste; a shrinking chunk would
    instead cost one ~70 ms readback per token for the *whole batch*
    whenever any request nears completion).  Compile count: one decode
    program + one admit per prompt bucket.

    **Pipelined decode** (``pipeline_depth=2``, the default): the step
    loop is a two-deep pipeline — chunk N+1 is dispatched against the
    donated cache BEFORE chunk N's readback, so device compute overlaps
    host readback, EOS truncation, and streaming emission (JAX arrays
    are futures; the chained dispatch consumes the previous chunk's
    device-side token carry, never a host value).  Semantically safe by
    the engine's own design: EOS detection already lags by at most one
    chunk — pipelining extends that lag by exactly one more dispatch of
    bounded wasted compute, never wrong tokens, and output is
    token-for-token identical to ``pipeline_depth=1`` (the serial A/B
    control) for greedy, sampled, speculative, and prefix-cache-injected
    requests alike (tests/test_serve_pipeline.py pins the matrix).
    Admissions join at pipeline boundaries: a step with queued requests
    completes the in-flight chunk before re-prefilling freed slots, and
    ``drain``/``abort`` quiesce the in-flight dispatch (processed to
    completion or dropped unread, never leaking a slot).
    """

    _instance_lock = locksan.new_lock("Engine._instance_lock")
    _instance_count = 0

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        *,
        n_slots: int = 4,
        max_len: int = 1024,
        chunk: int = 8,
        prompt_buckets: tuple[int, ...] | None = None,
        top_k: int = 0,
        top_p: float = 1.0,
        kv_int8: bool = False,
        kv_int4: bool = False,
        prefix_cache_size: int = 0,
        mesh=None,
        spec_decode: int = 0,
        spec_ngram: int = 2,
        draft_params=None,
        draft_cfg: TransformerConfig | None = None,
        penalties: bool = True,
        max_queue: int = 0,
        prefill_chunk: int = 0,
        pipeline_depth: int = 2,
        brownout_max_tokens: int = 0,
        brownout_queue_fraction: float = 0.75,
        brownout_hold_s: float = 1.0,
        request_ring: int = 256,
        kv_block: int = 0,
        kv_blocks: int = 0,
        paged_kernel: bool | None = None,
        prefill_kernel: bool | None = None,
        kv_host_bytes: int = 0,
        kv_park: bool = True,
        qos=None,
        slow_capture_e2e_s: float = 0.0,
        slow_capture_tpot_mult: float = 0.0,
        slow_capture_interval_s: float = 60.0,
    ):
        if pipeline_depth not in (1, 2):
            raise ValueError(
                f"pipeline_depth must be 1 (serial) or 2 (dispatch-ahead "
                f"double buffering), got {pipeline_depth}"
            )
        if n_slots < 1 or max_len < 2 or chunk < 1 or prefix_cache_size < 0:
            raise ValueError(
                f"need n_slots>=1, max_len>=2, chunk>=1, "
                f"prefix_cache_size>=0; got {n_slots}, {max_len}, {chunk}, "
                f"{prefix_cache_size}"
            )
        # Paged KV cache (ISSUE 10): kv_block > 0 switches the cache
        # from one contiguous max_len region per slot to a global pool
        # of kv_block-token blocks + a host-side per-slot block table.
        # max_len must divide into blocks exactly: the gathered per-row
        # view is then the SAME [B, max_len, ...] shape the dense
        # attention math sees, which is what keeps paged output
        # token-identical to dense (bit-equal masked scores, not a
        # parallel code path).  kv_blocks sizes the pool; 0 = the dense
        # cache's footprint (n_slots × max_len rows) — the capacity win
        # comes from raising n_slots above what that pool could hold at
        # full length, since admissions reserve only each request's
        # worst case (prompt + budget + spec headroom), block-rounded.
        if kv_block < 0 or kv_blocks < 0:
            raise ValueError(
                f"need kv_block>=0 and kv_blocks>=0; got {kv_block}, "
                f"{kv_blocks}"
            )
        self.paged = kv_block > 0
        self.kv_block = kv_block
        if self.paged:
            if max_len % kv_block:
                raise ValueError(
                    f"kv_block={kv_block} must divide max_len={max_len} "
                    f"(the block table covers the region exactly)"
                )
            self._n_tables = max_len // kv_block
            if not kv_blocks:
                kv_blocks = n_slots * self._n_tables
            # A pool SMALLER than one full-length slot is legal (a
            # short-request deployment can cap per-request length well
            # under max_len); per-request fit is enforced in
            # _validate, so an impossible request rejects at submit
            # instead of deadlocking the queue.
            if kv_blocks < 1:
                raise ValueError(f"need kv_blocks >= 1, got {kv_blocks}")
        elif kv_blocks:
            raise ValueError("kv_blocks needs kv_block > 0")
        self.kv_blocks = kv_blocks if self.paged else 0
        # KV quant ladder: int8 everywhere, int4 (kv4) on the paged
        # layout only — the fused-dequant kernel gathers per-block
        # scale tiles straight from the pool, and the dense layout has
        # no block-structured scale arrays to carry them (kv4's whole
        # point is halving PAGED cache bytes again; a dense deployment
        # wanting deeper quant should go paged first).
        if kv_int8 and kv_int4:
            raise ValueError("kv_int8 and kv_int4 are mutually exclusive")
        if kv_int4 and not self.paged:
            raise ValueError(
                "kv_int4 needs the paged cache (kv_block > 0): only the "
                "block pool carries the per-block scales the fused "
                "dequant reads"
            )
        self.kv_quant = "int4" if kv_int4 else ("int8" if kv_int8 else "")
        # Paged flash-decode kernel (ops/paged_attention.py): None =
        # auto (on for TPU paged engines, where the gather's extra HBM
        # round-trip per layer per chunk is the cost; CPU XLA gathers
        # are cheap and interpret-mode pallas is not, so auto stays
        # off there).  Explicit True runs the kernel anywhere —
        # interpret mode off-TPU, which is how the exactness matrix
        # executes in tier-1.  False = today's gather, the A/B control.
        if paged_kernel and not self.paged:
            raise ValueError("paged_kernel needs a paged cache (kv_block)")
        self.paged_kernel = bool(self.paged) and (
            paged_kernel if paged_kernel is not None
            else jax.default_backend() == "tpu"
        )
        if self.paged_kernel:
            from oim_tpu.ops.paged_attention import supported_block_size

            # Fail at construction with the constraint named — not as
            # an assertion out of the first decode trace on the driver
            # thread (which would latch the server's error state).
            if not supported_block_size(kv_block, cfg.head_dim):
                raise ValueError(
                    f"paged_kernel needs kv_block and head_dim each "
                    f"<= 128 or a multiple of 128 (lane tiling); got "
                    f"kv_block={kv_block}, head_dim={cfg.head_dim} — "
                    f"run this geometry with the gather path "
                    f"(paged_kernel=False / --paged-kernel off)"
                )
        # Paged flash-PREFILL kernel (ISSUE 20): same auto policy as
        # paged_kernel — prompt-segment K/V lands straight in the
        # slot's blocks with fused quant and the segment attends off
        # the pool, no dense intermediate.  Gather stays the off-TPU
        # default, the A/B control, and the exactness oracle.
        if prefill_kernel and not self.paged:
            raise ValueError("prefill_kernel needs a paged cache (kv_block)")
        self.prefill_kernel = bool(self.paged) and (
            prefill_kernel if prefill_kernel is not None
            else jax.default_backend() == "tpu"
        )
        if self.prefill_kernel:
            from oim_tpu.ops.paged_attention import supported_block_size

            if not supported_block_size(kv_block, cfg.head_dim):
                raise ValueError(
                    f"prefill_kernel needs kv_block and head_dim each "
                    f"<= 128 or a multiple of 128 (lane tiling); got "
                    f"kv_block={kv_block}, head_dim={cfg.head_dim} — "
                    f"run this geometry with the gather path "
                    f"(prefill_kernel=False / --prefill-kernel off)"
                )
        if spec_decode < 0 or (spec_decode and spec_ngram < 1):
            raise ValueError(
                f"need spec_decode>=0 and spec_ngram>=1; got "
                f"{spec_decode}, {spec_ngram}"
            )
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "draft_params and draft_cfg come together or not at all"
            )
        if draft_cfg is not None:
            if not spec_decode:
                raise ValueError(
                    "a draft model needs spec_decode >= 1 (draft length)"
                )
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}"
                )
        self.spec_decode = spec_decode
        self.spec_ngram = spec_ngram
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        # Speculative mode reserves draft_len+1 cache rows per slot so a
        # verify step's L+1 writes always fit inside the region even
        # during post-EOS overshoot (clamped starts must never slide
        # back over live rows).
        self._usable_len = max_len - (spec_decode + 1 if spec_decode else 0)
        if self._usable_len < 2:
            raise ValueError(
                f"max_len={max_len} leaves no usable room after the "
                f"spec_decode={spec_decode} headroom reserve"
            )
        if mesh is not None:
            # Tensor-parallel serving: shard params by logical axes and
            # the KV cache over kv-heads, commit both to the mesh, and
            # let GSPMD propagate through the jitted admit/decode fns
            # (decode has no manual-axis schedule — sharding propagation
            # is the whole mechanism, models/decode.py module docstring).
            tp = mesh.shape.get("tp", 1)
            if cfg.n_heads % tp or cfg.kv_heads % tp:
                raise ValueError(
                    f"n_heads={cfg.n_heads} and kv_heads={cfg.kv_heads} "
                    f"must divide by mesh tp={tp}"
                )
            ep = mesh.shape.get("ep", 1)
            if ep > 1 and (not cfg.n_experts or cfg.n_experts % ep):
                # Silently replicating every expert over ep devices would
                # reserve chips for zero sharding; the misconfiguration
                # must be as loud as the heads one.
                raise ValueError(
                    f"n_experts={cfg.n_experts} must be a positive "
                    f"multiple of mesh ep={ep}"
                )
            params = jax.device_put(
                params, serve_param_shardings(params, cfg, mesh)
            )
        self.mesh = mesh
        self.params = params
        self.cfg = cfg
        self.chunk = chunk
        if prompt_buckets is None:
            prompt_buckets, b = [], 16
            while b < self._usable_len:
                prompt_buckets.append(b)
                b *= 2
            prompt_buckets.append(self._usable_len - 1)
        self.prompt_buckets = tuple(sorted(set(prompt_buckets)))
        # Chunked prefill: admissions whose (post-injection) tail
        # exceeds this run extra KV-write-only dispatches of this
        # length first, capping peak admission activations at
        # [S, chunk, d] regardless of prompt length (0 = one-shot).
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}"
            )
        if prefill_chunk and prefill_chunk not in self.prompt_buckets:
            # A bucket-exact chunk keeps every non-final segment's
            # bucketed KV-write window exactly [p, p + chunk) — no
            # padding past the next segment's start, so only the FINAL
            # window needs the fit check in the admission loop.
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be one of the "
                f"prompt buckets {self.prompt_buckets}"
            )
        self.prefill_chunk = prefill_chunk
        bad_buckets = [
            b for b in self.prompt_buckets
            if not 1 <= b <= self._usable_len - 1
        ]
        if bad_buckets:
            # Fail at construction, not as an XLA shape error inside the
            # first admit (which would kill a server's driver thread).
            raise ValueError(
                f"prompt_buckets must fit 1..{self._usable_len - 1} "
                f"(each admitted prompt needs >=1 generated token, and "
                f"speculative mode reserves spec_decode+1 rows): "
                f"{bad_buckets}"
            )
        from oim_tpu.models.decode import _validate_truncation

        # An out-of-range engine default (oim-serve --top-p 0.0) must
        # fail at construction — inside the jitted path it would mask
        # every logit and sample uniform garbage with no error.
        _validate_truncation(top_k, top_p, cfg.vocab_size)
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        # 0 = unbounded (tests, trusted callers).  A bound turns a
        # flood into immediate backpressure (QueueFullError → HTTP 429)
        # instead of unbounded host memory + 600 s client timeouts.
        self.max_queue = max_queue
        # Brownout: under SUSTAINED queue pressure (queue ≥ fraction of
        # max_queue continuously for hold_s), clamp incoming requests'
        # max_new_tokens to brownout_max_tokens instead of letting the
        # backlog grow until the hard 429 — degraded answers beat
        # errors.  0 = off; needs max_queue (pressure is measured
        # against the bound).
        if brownout_max_tokens < 0 or not 0.0 < brownout_queue_fraction <= 1.0:
            raise ValueError(
                f"need brownout_max_tokens>=0 and brownout_queue_fraction "
                f"in (0, 1]; got {brownout_max_tokens}, "
                f"{brownout_queue_fraction}"
            )
        self.brownout_max_tokens = brownout_max_tokens
        self.brownout_hold_s = brownout_hold_s
        self._brownout_at = max(
            1, int(round(max_queue * brownout_queue_fraction))
        ) if max_queue else 0
        self._pressure_since: float | None = None
        self.top_k = top_k
        self.kv_int8 = kv_int8
        self.kv_int4 = kv_int4
        self.weight_quant = weight_quant_mode(params)
        self.weights_int8 = self.weight_quant == "int8"
        self.n_params = int(sum(
            int(np.prod(v.shape)) for name, v in params.items()
            if not name.endswith("_wscale")
        ))
        self.default_top_p = top_p
        self.max_len = max_len
        if self.paged:
            self._cache = PagedCache.create(
                cfg, n_slots, self.kv_blocks, kv_block,
                quantized=self.kv_quant,
            )
            # Host-side paging state, all mutated under self._lock: the
            # refcounted allocator, the per-slot block table (sentinel
            # kv_blocks = unallocated — OOB on the device, so a freed
            # slot's garbage writes drop at the pool edge), and the
            # dirty flag that rebuilds the device copy lazily at the
            # next dispatch.
            self._alloc = BlockAllocator(self.kv_blocks)
            self._tables_host = np.full(
                (n_slots, self._n_tables), self.kv_blocks, np.int32
            )
            self._tables_dirty = True
            self._tables_dev = None
            # Copy-on-write: one compile copies any (src, dst) block
            # pair across all four pools (k/v and their scales).
            self._cow = jax.jit(_cow_block, donate_argnums=(0,))
            # KV-ship ingest: one compile writes any shipped block into
            # the pool (serve/disagg.py; traced dst like _cow's pair).
            self._ingest = jax.jit(_ingest_block, donate_argnums=(0,))
            # Bytes of one KV row (k + v + scales, all layers): the
            # unit the prefix-aliasing bytes-saved accounting counts.
            # Per-vector payload bits: 4 for kv4, 8 for int8, else the
            # compute dtype's width; quantized rows add a 4-byte f32
            # scale per (token, head).
            if self.kv_quant:
                payload_bits = 4 if kv_int4 else 8
            else:
                payload_bits = 8 * jnp.dtype(cfg.compute_dtype).itemsize
            self._kv_row_bytes = 2 * cfg.n_layers * cfg.kv_heads * (
                (cfg.head_dim * payload_bits) // 8
                + (4 if self.kv_quant else 0)
            )
        else:
            self._cache = SlotCache.create(
                cfg, n_slots, max_len, quantized=kv_int8
            )
            self._alloc = None
            self._tables_host = None
            self._kv_row_bytes = 0
        # Host-RAM overflow tier (ISSUE 15): a second, host-side block
        # pool under a byte budget.  Prefix shortfalls DEMOTE idle
        # entries here (batched stream-ordered read_block fetches off
        # the driver's critical path) instead of destroying them, a
        # later hit PROMOTES them back through the staged-install path
        # (warmup-precompiled ingest, double-buffered ahead of the tail
        # prefill), and an admission that cannot fit can PARK the
        # coldest idle slot's table here and restore it exactly when
        # blocks free — the swap mechanism QoS preemption will drive.
        if kv_host_bytes < 0:
            raise ValueError(
                f"kv_host_bytes must be >= 0, got {kv_host_bytes}"
            )
        if kv_host_bytes and not self.paged:
            raise ValueError(
                "kv_host_bytes needs the paged cache (kv_block > 0): "
                "only the block pool has a block-granular unit to "
                "demote/promote"
            )
        self.kv_host_bytes = kv_host_bytes
        if kv_host_bytes:
            block_bytes = self._kv_row_bytes * kv_block
            n_host = kv_host_bytes // block_bytes
            if n_host < 1:
                raise ValueError(
                    f"kv_host_bytes={kv_host_bytes} holds no block "
                    f"(one {kv_block}-token block is {block_bytes} "
                    f"bytes here)"
                )
            self._host = HostBlockPool(self._cache, n_host)
            # One compile per pool leaf shape: the kv pools share one
            # read program, the scale planes another (traced src).
            self._read_block = jax.jit(read_block)
            # Fixed-shape filler for the restore program's unused rows
            # (track_history/penalize off): non-donated, safe to reuse.
            self._restore_dummy_row = jnp.zeros((1,), jnp.int32)
            self._restore = jax.jit(
                partial(
                    _restore_slot,
                    track_history=(
                        bool(spec_decode) and draft_cfg is None
                    ),
                    penalize=penalties,
                ),
                donate_argnums=(0, 1, 2, 3),
            )
        else:
            self._host = None
            self._read_block = None
            self._restore = None
        # Slot parking needs the host tier and a per-slot state that is
        # fully host-reconstructible: the draft model's slot cache is
        # device-derived state a restore cannot rebuild without a
        # draft prefill, so draft-model engines refuse to park
        # (demote/promote of prefix entries still works there).
        self.kv_park = bool(
            self._host is not None and kv_park and draft_cfg is None
        )
        # Host-tier state, all under self._lock like the device
        # allocator: demoted prefix entries (covered-token key →
        # (host block ids, rows)), their residency metadata, parked
        # slots (rid → _ParkedSlot, FIFO restore order), and tier
        # movements dispatched but not yet fetched.
        from collections import OrderedDict as _OD

        self._host_prefix: "_OD[tuple, tuple]" = _OD()
        self._host_meta: dict[tuple, dict] = {}
        self._parked: "_OD[int, _ParkedSlot]" = _OD()
        self._pending_host_writes: list[_HostWrite] = []
        # Promotions planned (device blocks reserved, host blocks
        # pinned) but whose payload copy is still running off-lock:
        # the submit-time idempotency guard, so a cohort burst stages
        # one install per entry, not one per request.
        self._promote_staging: set[tuple] = set()
        # Tier accounting (stats()/load(); the shared metric twins are
        # SERVE_KV_TIER_MOVES / SERVE_KV_TIER_SECONDS).
        self.kv_demotions = 0  # blocks moved device → host
        self.kv_promotions = 0  # blocks moved host → device
        self.kv_parks = 0  # slots swapped out
        self.kv_unparks = 0  # slots restored
        self.kv_demote_seconds = 0.0
        self.kv_promote_seconds = 0.0
        # Byte twins of the block counters (ISSUE 18 fleet KV-tier
        # flow telemetry): blocks * _block_bytes at each move site, so
        # the fleet view and oim_serve_kv_tier_bytes_total speak
        # bandwidth, not just block counts.
        self.kv_demote_bytes = 0
        self.kv_promote_bytes = 0
        # Prefix-shortage outcome split (ISSUE 15 satellite): an entry
        # moved to the host tier is recoverable; one destroyed — no
        # host tier, host budget exhausted, or host-LRU pressure — is
        # prefill lost forever.  Capacity incidents must tell the two
        # apart.
        self.prefix_demotions = 0
        self.prefix_evictions = 0
        self._promote_walls: deque[float] = deque(maxlen=64)
        # Dense engines pass this inert dummy where the paged layout
        # passes its block table (one jit signature for both).
        self._tables_dummy = jnp.zeros((1, 1), jnp.int32)
        # Prefix-aliasing + backpressure accounting (host-side, under
        # self._lock like the hit/miss counters).
        self.prefix_injects = 0
        self.prefix_bytes_saved = 0
        self.kv_admit_deferrals = 0
        # Disaggregated prefill/decode state (serve/disagg.py), all
        # under self._lock: completed hold_kv requests' retained blocks
        # (rid → KvHold, one extra ref per block) and staged ingests
        # (import id → KvImport, freshly reserved blocks + host
        # payload the driver writes at admission).  Both TTL'd and
        # count-capped so a ship that died mid-flight leaks nothing.
        self._kv_holds: dict[int, KvHold] = {}
        self._kv_imports: dict[int, KvImport] = {}
        self._next_import_id = 0
        # Per-engine transfer counters for load()/stats(): this
        # backend's share of the fleet's ship traffic (exports served,
        # ingests staged, bytes both ways).
        self.kv_exports = 0
        self.kv_imports_total = 0
        self.kv_ship_bytes = 0
        # Live slot migration (ISSUE 17): suspended-slot records minted
        # by the migrate wave (rid → SlotRecord — captured device
        # blocks hold-style, or a parked request's transferred host
        # payload), served by GET /v1/slot until shipped, released, or
        # TTL-swept.  ``_migrate_out`` latches begin_migrate_out(): the
        # driver suspends everything at the next step boundary and
        # keeps the wave armed for parked slots whose tier write is
        # still in flight.
        self._migrated: dict[int, SlotRecord] = {}
        self._migrate_out = False
        self.slot_exports = 0
        self.slot_imports = 0
        # Model-drafted speculation: the draft model keeps its OWN slot
        # cache (full precision — it is small) in lockstep with the
        # target's lengths; prompt lookup's device-side history is then
        # unused and shrinks to a dummy.
        self._draft_cache = (
            SlotCache.create(draft_cfg, n_slots, max_len, quantized=False)
            if draft_cfg is not None
            else None
        )
        # Device-side token record per slot (admission writes the full
        # prompt; speculative decode appends) — the draft source for
        # prompt-lookup speculation.
        self._history = jnp.zeros(
            (n_slots, max_len)
            if (spec_decode and draft_cfg is None)
            else (1, 1),
            jnp.int32,
        )
        # Sampling-penalty occurrence state: prompt+generated and
        # generated-only counts per slot (models/decode.apply_penalties).
        # With penalties disabled the state shrinks to [1, 1] dummies and
        # the jitted paths skip the count math entirely (the
        # track_history trace-time-gating precedent) — big-vocab many-
        # slot deployments that never penalize pay nothing.
        counts_shape = (
            (n_slots, cfg.vocab_size) if penalties else (1, 1)
        )
        self.penalties = penalties
        self._tok_counts = jnp.zeros(counts_shape, jnp.int32)
        self._gen_counts = jnp.zeros(counts_shape, jnp.int32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._cache = jax.device_put(
                self._cache, cache_shardings(self._cache, mesh)
            )
            self._history = jax.device_put(
                self._history, NamedSharding(mesh, P())
            )
            if self._draft_cache is not None:
                # The draft model is small by design: replicate it and
                # its cache rather than sharding (no collective traffic
                # on the draft's sequential forwards).
                self.draft_params = jax.device_put(
                    self.draft_params, NamedSharding(mesh, P())
                )
                self._draft_cache = jax.device_put(
                    self._draft_cache, NamedSharding(mesh, P())
                )
            self._tok_counts, self._gen_counts = jax.device_put(
                (self._tok_counts, self._gen_counts),
                NamedSharding(mesh, P()),
            )
        # Hot-path constants (ISSUE 11 harvest): the PRNG filler key and
        # _prefill_segment's neutral sampling rows are identical on
        # every call — building them per chunk/segment re-dispatched
        # the same tiny host→device transfers on the decode spine.
        # Hoisted once per engine; all ride non-donated positions, so
        # reuse is safe (the jitted callees never consume their buffers).
        n_slots_c = self._cache.n_slots
        self._zero_key = jax.random.PRNGKey(0)
        self._seg_zero_counts = jnp.asarray(
            np.zeros(counts_shape, np.int32)
        )
        self._seg_zero_rows = jnp.zeros((1, 1), jnp.int32)
        self._seg_sampling = (
            jnp.zeros((n_slots_c,), jnp.float32),  # temps
            jnp.ones((n_slots_c,), jnp.float32),   # top_ps
            jnp.zeros((n_slots_c,), jnp.float32),  # min_ps
            jnp.ones((n_slots_c,), jnp.float32),   # reps
            jnp.zeros((n_slots_c,), jnp.float32),  # press
            jnp.zeros((n_slots_c,), jnp.float32),  # freqs
        )
        self._zero_keys = jnp.stack([self._zero_key] * n_slots_c)
        self._admit = jax.jit(
            partial(_admit_batch, cfg=cfg, top_k=top_k,
                    track_history=bool(spec_decode) and draft_cfg is None,
                    penalize=penalties,
                    prefill_kernel=self.prefill_kernel),
            # cache, history, tok_counts, gen_counts (row_tables at 2
            # is NOT donated: dense engines pass a shared dummy).
            donate_argnums=(1, 3, 4, 5),
        )
        self._admit_d = (
            jax.jit(
                partial(_admit_draft, dcfg=draft_cfg), donate_argnums=(1,)
            )
            if draft_cfg is not None
            else None
        )
        # Prefix cache: LRU of prompt-KV entries (tuple(tokens) →
        # (kv pytree, true length)).  Each entry costs about one slot's
        # worth of HBM at its bucket length.  Extraction/injection jit
        # per bucket length.
        from collections import OrderedDict

        self.prefix_cache_size = prefix_cache_size
        # Entry value: dense = (kv pytree copy, true rows); paged =
        # (tuple of pool block ids the entry holds one ref each on,
        # true rows — always block-aligned).  Paged entries cost no
        # extra HBM at all: the blocks ARE the slot's prefilled blocks,
        # kept alive by the refcount, aliased read-only into every
        # later slot that shares the prefix.
        self._prefix_cache: OrderedDict = OrderedDict()
        # Fleet residency metadata, one record per entry (same key,
        # same lock): the stable content digest (disagg.prefix_digest
        # over the covered tokens — the entry's fleet-wide identity),
        # covered rows, hit count, last-hit instant, and origin
        # ("local" = stored from this engine's own traffic, "fetched"
        # = installed from a sibling's exported entry) — the substrate
        # for prefix_digest_summary() and the per-request
        # fetched-vs-local-vs-recomputed attribution.
        self._prefix_meta: dict[tuple, dict] = {}
        # Staged prefix installs (import_kv_prefix + the host tier's
        # promote path): (digest, KvImport, promote_key) triples —
        # freshly reserved blocks + host payload, landed in the pool by
        # the DRIVER thread (install_prefix_imports) at the next
        # admission boundary — the single-writer cache discipline,
        # exactly like staged KV-ship imports.  TTL'd and count-capped
        # the same way.  promote_key is None for sibling-shipped
        # installs; for a host-tier promotion it is the demoted entry's
        # key, cleared (entry freed back to the host budget) once the
        # install lands.
        self._prefix_installs: list[tuple[str, KvImport, tuple | None]] = []
        self.prefix_fetch_installs = 0
        self.prefix_exports = 0
        self._extract = {
            b: jax.jit(partial(_extract_prefix, rows=b))
            for b in (
                self.prompt_buckets
                if prefix_cache_size and not self.paged else ()
            )
        }
        self._inject = jax.jit(_inject_prefix, donate_argnums=(0,))
        self.prefix_hits = 0
        self.prefix_misses = 0
        self._embed = jax.jit(partial(embed_tokens, cfg=cfg))
        if spec_decode and draft_cfg is not None:
            self._decode = jax.jit(
                partial(_decode_chunk_spec_model, cfg=cfg, dcfg=draft_cfg,
                        chunk=chunk, draft_len=spec_decode, top_k=top_k,
                        max_len=max_len, paged_kernel=self.paged_kernel),
                donate_argnums=(2, 3),  # target + draft caches
            )
        elif spec_decode:
            self._decode = jax.jit(
                partial(_decode_chunk_spec, cfg=cfg, chunk=chunk,
                        draft_len=spec_decode, ngram=spec_ngram,
                        top_k=top_k, max_len=max_len,
                        paged_kernel=self.paged_kernel),
                donate_argnums=(1, 3),  # cache + history
            )
        else:
            self._decode = jax.jit(
                partial(_decode_chunk, cfg=cfg, chunk=chunk, top_k=top_k,
                        penalize=penalties, max_len=max_len,
                        paged_kernel=self.paged_kernel),
                donate_argnums=(1, 3, 4),  # cache + the penalty counts
            )
        self.spec_drafted = 0
        self.spec_accepted = 0
        # Host↔device readbacks (the tunnel-cost unit benchmarks account
        # in): one per admission wave, one per decode chunk.
        self.readbacks = 0
        # Swing forensics (BASELINE 665↔1112 tok/s, host-contention
        # hypothesis): wall time inside device_get readbacks vs the
        # rest of step() (host-side array building, queue bookkeeping,
        # emission).  A slow run with flat readback_seconds and fat
        # host_seconds is host contention; the reverse is the chip/
        # tunnel.  Accumulated per engine, exported via stats().
        self.host_seconds = 0.0
        self.readback_seconds = 0.0
        # Pipelined decode (dispatch-ahead double buffering): at depth 2
        # the engine dispatches chunk N+1 against the donated cache
        # BEFORE reading back chunk N, so device compute overlaps host
        # readback + EOS truncation + emission.  Depth 1 is the serial
        # dispatch→readback→emit loop (the A/B control).
        self.pipeline_depth = pipeline_depth
        self._inflight: _InFlightChunk | None = None
        # The readback split: dispatch_seconds is wall time spent
        # ENQUEUEING jitted work (donation/queue backpressure shows up
        # here), readback_seconds is wall time blocked in device_get
        # (device execution + tunnel rtt), and overlap_seconds is the
        # part of readback_seconds that ran while another chunk was
        # already dispatched — readback the device did NOT idle
        # through.  device_idle_seconds estimates the converse: wall
        # time between a completed fetch and the next dispatch with
        # nothing queued on the device.
        self.dispatch_seconds = 0.0
        self.overlap_seconds = 0.0
        self.device_idle_seconds = 0.0
        # overlap_ratio's denominator: step()'s fetch-wait only.
        # readback_seconds also absorbs embed/beam (_fetch_aux) — right
        # for the tunnel-cost forensics, but those fetches can never
        # overlap a decode dispatch, so counting them would report a
        # healthy pipelined replica as serial under embed-heavy traffic.
        self.decode_readback_seconds = 0.0
        # Chained dispatches elided because the in-flight chunk was
        # already guaranteed (by token budget alone) to finish every
        # active slot — each elision is one whole chunk of device
        # compute the pipeline did NOT waste at a batch tail.
        self.tail_elisions = 0
        self._t_device_free: float | None = None
        # When the previous chunk's processing finished (driver-thread
        # only): the per-token latency histogram clips each chunk's
        # dispatch-to-emission window to this, so a pipelined chunk
        # that sat dispatched-but-unread while its predecessor was
        # emitted reports its MARGINAL wall, not the deliberate
        # one-chunk pipeline lag (which would read as a 2x latency
        # regression at depth 2 with no hardware change).
        self._t_last_chunk_done: float | None = None
        self._lock = locksan.new_lock("Engine._lock")
        # Recently-completed-request ring (ISSUE 9): one compact record
        # per finalized request — rid, tenant CN, trace id, per-phase
        # durations, token counts, outcome (ok / deadline / cancelled /
        # stalled / ...).  Bounded drop-oldest with the drop counted
        # (ring_dropped; the flight-recorder discipline — silent
        # truncation would read as "nothing slow happened").  Served as
        # GET /debugz/requests and merged fleet-wide by the router at
        # /v1/requests.
        if request_ring < 0:
            raise ValueError(
                f"request_ring must be >= 0, got {request_ring}"
            )
        self._ring: deque[dict] = deque(maxlen=request_ring)
        self.ring_dropped = 0
        # Own lock, not self._lock: finalization (driver thread, after
        # the engine lock is released) appends while /debugz handler
        # threads read — and keeping it separate means ring access
        # never nests inside the engine lock in either order.
        self._ring_lock = locksan.new_lock("Engine._ring_lock")
        # Failure-path finalizations queued under self._lock and
        # drained OUTSIDE it (the `ended`-callbacks pattern): span
        # serialization + trace-file writes + histogram observes must
        # not run with the engine lock held — a deadline-shed storm
        # reaped on the driver thread would otherwise block every
        # submit() behind per-victim disk I/O.
        self._fail_obs: list[tuple] = []
        self._queue: list[tuple[int, GenRequest, float]] = []
        self._slots: dict[int, _SlotState] = {}  # slot index → state
        self._free = list(range(n_slots))
        # rid → slot for admissions popped from _queue but not yet in
        # _slots: abort() fails these too (and reclaims their slots), so
        # a crash mid-admission can never strand a blocked result() call.
        self._admitting: dict[int, int] = {}
        # rid → _PendingPrefill: long-prompt admissions advancing one
        # segment per admission wave (ISSUE 20).  Every rid here is
        # ALSO in _admitting (slot assigned, blocks committed) — this
        # dict only carries the segment cursor and phase trace between
        # waves.  Driver-thread-written under self._lock.
        self._prefilling: dict[int, "_PendingPrefill"] = {}
        # Cumulative prompt segments dispatched (final group segments
        # included): stats()/load() surface it so operators can see
        # how much admission work runs chunked vs one-shot.
        self.prefill_segments = 0
        # rid → (tokens, logprobs), consumed by result_full/result.
        self._results: dict[int, tuple[list[int], list[float]]] = {}
        self._events: dict[int, threading.Event] = {}
        # (beam_size, alpha, eos_id) → jitted beam program (Engine.beam);
        # _beam_traces tracks every (config, prompt_len, max_new) trace
        # for the total compile budget; one lock covers both.
        self._beam_fns: dict[tuple, object] = {}
        self._beam_traces: set[tuple] = set()
        self._beam_lock = locksan.new_lock("Engine._beam_lock")
        # rid → (kind, message); result_full raises RequestFailedError.
        self._errors: dict[int, tuple[str, str]] = {}
        self._callbacks: dict[int, object] = {}  # rid → on_token
        self._forgotten: set[int] = set()
        # rids cancelled via cancel() (client disconnect) but still
        # queued / admitting / active — reaped on the driver thread at
        # the next step so the slot machinery stays single-writer.
        self._cancelled: set[int] = set()
        self._draining = False
        # Latched by step() on a driver-thread crash: every later
        # submit fails fast (EngineFailedError) instead of queueing
        # work nothing will ever drive — and result() waiters were
        # already failed by the latch, so nobody blocks forever.
        self._fatal: str | None = None
        # Stall-watchdog hooks (driver thread writes, watchdog thread
        # reads — both under self._lock): when the driver is blocked in
        # a device dispatch or readback, _device_wait_since holds the
        # monotonic instant the wait began; _chunk_wall_ewma tracks the
        # typical dispatch-to-fetch wall of a decode chunk, the
        # baseline a wedged chunk is judged against.
        self._device_wait_since: float | None = None
        self._chunk_wall_ewma: float | None = None
        # Observed marginal token rate (tokens/s EWMA over processed
        # chunks) — the denominator Retry-After hints are computed
        # from.
        self._token_rate_ewma: float | None = None
        # Slot-free work (beam/embed) runs outside the queue machinery
        # but must still hold off a drain — counted here.
        self._aux_active = 0
        self._next_rid = 0
        self._step_count = 0
        self.tokens_generated = 0
        # Prometheus instruments (oim_tpu/common/metrics.py — shared with
        # the control-plane components; idempotent by name).  Counters and
        # histograms are cumulative so several engines in one process can
        # share them; the point-in-time gauges carry a per-engine label so
        # one engine's updates cannot stomp another's.
        reg = _metrics.registry()
        with Engine._instance_lock:
            self._engine_label = str(Engine._instance_count)
            Engine._instance_count += 1
        self._m_requests = reg.counter(
            "oim_serve_requests_total",
            "Generation requests by outcome.",
            ("outcome",),
        )
        self._m_tokens = reg.counter(
            "oim_serve_tokens_total", "Tokens generated (after truncation)."
        )
        self._m_dispatches = reg.counter(
            "oim_serve_decode_dispatches_total",
            "Chunked decode dispatches (one device round trip each).",
        )
        self._m_prefix = reg.counter(
            "oim_serve_prefix_cache_total",
            "Prompt-prefix cache activity by outcome: hit/miss are "
            "LOOKUPS at admission (hit = cached rows replaced prefill "
            "work — copied in dense mode, block-aliased copy-free in "
            "paged; hit rate = hit / (hit + miss)); inject counts "
            "entry STORES (cache_prefix requests populating the "
            "cache), a separate event stream.  Capacity pressure "
            "splits by recoverability (ISSUE 15): demote = the entry "
            "moved to the host-RAM overflow tier (a later hit "
            "promotes it back, no prefill lost), evict = the entry "
            "was destroyed (no host tier, host budget exhausted, or "
            "host-LRU pressure — that prefill is lost forever).  The "
            "affinity router exists to raise the hit rate; watch "
            "this to see it working.",
            ("outcome",),
        )
        self._m_latency = reg.histogram(
            "oim_serve_request_seconds",
            "Submit-to-completion latency per request.",
            # Generation latencies, not control-plane RPCs: a queued
            # 128-token request over a tunneled link legitimately takes
            # minutes (the HTTP server waits up to 600 s).
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0, 600.0),
        )
        self._m_ttft = reg.histogram(
            "oim_serve_ttft_seconds",
            "Submit-to-first-token latency per request (queue wait + "
            "admission + prefill).",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0),
        )
        self._m_token_latency = reg.histogram(
            "oim_serve_token_seconds",
            "Per-token decode latency: one chunk's marginal wall time "
            "(dispatch-to-emission, clipped to the previous chunk's "
            "completion so pipelined dispatch-ahead lag is not "
            "double-counted) amortized over the tokens it emitted — "
            "sub-millisecond on a healthy chip, so FAST_BUCKETS.",
            buckets=_metrics.FAST_BUCKETS,
        )
        # Fleet-load gauges — shared definitions (common/metrics.py,
        # the resilience-instrument pattern) so the autoscaler's fleet
        # view and every engine export one series shape; the instance
        # label is this engine's per-process label.
        self._m_active = _metrics.SERVE_ACTIVE_SLOTS
        self._m_queued = _metrics.SERVE_QUEUE_DEPTH
        # Paged-KV occupancy (shared definitions): the capacity the
        # fleet actually has left, by block state, plus the bytes
        # prefix aliasing did NOT copy (the copy-free-reuse win).
        self._m_kv_blocks = _metrics.SERVE_KV_BLOCKS
        self._m_prefix_bytes = _metrics.SERVE_PREFIX_BYTES_SAVED
        # Host-tier movement counters (ISSUE 15): blocks and wall
        # seconds per direction, shared definitions like the KV gauges.
        self._m_tier_moves = _metrics.SERVE_KV_TIER_MOVES
        self._m_tier_seconds = _metrics.SERVE_KV_TIER_SECONDS
        if self.paged:
            # Constructor is single-threaded; the _locked suffix is the
            # call-site contract for every later caller.
            self._update_kv_gauges_locked()
        # Pipeline health triad — shared definitions (common/metrics.py,
        # the resilience-instrument pattern) so fleet-wide queries see
        # one series shape.
        self._m_pipeline_depth = _metrics.SERVE_PIPELINE_DEPTH
        self._m_device_idle = _metrics.SERVE_DEVICE_IDLE
        self._m_overlap = _metrics.SERVE_OVERLAP_RATIO
        # Fault-tolerance instruments (shared definitions, like the
        # pipeline triad): sheds/clamps, deadline expirations, stalls.
        self._m_shed = _metrics.SERVE_SHED
        self._m_deadline = _metrics.SERVE_DEADLINE_EXPIRED
        self._m_stalls = _metrics.SERVE_STALLS
        # Per-tenant SLO attribution (shared definitions, ISSUE 9):
        # the request's phase clock keyed by the mTLS tenant CN the
        # HTTP layer hands in on GenRequest.tenant.
        self._m_queue_wait = _metrics.SERVE_QUEUE_WAIT
        self._m_prefill = _metrics.SERVE_PREFILL
        self._m_tpot = _metrics.SERVE_TPOT
        self._m_e2e = _metrics.SERVE_E2E
        # Host-side shed counters beside the shared counter metric:
        # the load/<cn> snapshot (load(), /v1/info "load") needs THIS
        # engine's totals, and the process-wide metric cannot be read
        # back per engine.
        self._shed_counts = {"queue_full": 0, "deadline": 0, "brownout": 0}
        self._m_pipeline_depth.set(
            float(pipeline_depth), self._engine_label
        )
        # Multi-tenant QoS (ISSUE 16).  ``qos`` is a
        # ``oim_tpu.qos.policy.QosPolicy`` or None; None means QoS is
        # OFF — admission stays pure FIFO and nothing preempts, the
        # exact pre-QoS behavior (the bench's A/B control and every
        # policy-less deployment).  Tenant ACCOUNTING runs either way:
        # per-tenant rows (virtual admission time for the stride
        # scheduler, cumulative tokens, enforcement counters) under
        # self._lock, mirrored into stats()/load()/info().
        self._qos_policy = qos
        self._tenants: dict[str, dict] = {}
        self.qos_preemptions = 0  # admissions that parked a victim
        self._m_qos = _metrics.SERVE_QOS
        self._m_tenant_tokens = _metrics.SERVE_TENANT_TOKENS
        # warmup() routes dummy requests through the normal paths; they
        # must not pollute the cumulative request metrics (a fresh daemon
        # would otherwise report phantom traffic and 20-40 s compile
        # latencies in the histogram forever).
        self._warming = False
        # -- performance forensics (ISSUE 18) --------------------------
        # Recompile sentinel: warmup()'s final act is sentinel.arm(self);
        # the listener reads _sentinel_ctx WITHOUT any lock (it can fire
        # on the driver thread mid-dispatch, engine lock held), so the
        # driver REPLACES the dict wholesale at phase boundaries and
        # never mutates it in place.
        self._sentinel_ctx: dict = {"phase": "idle"}
        self.recompiles = 0  # post-warm compiles attributed to this engine
        # Tail-latency auto-capture: absolute e2e threshold and/or
        # marginal-TPOT EWMA multiple (either 0.0 = that trigger off),
        # rate-limited to one artifact per interval.
        if (slow_capture_e2e_s < 0 or slow_capture_tpot_mult < 0
                or slow_capture_interval_s < 0):
            raise ValueError(
                "slow-capture knobs must be >= 0; got "
                f"e2e={slow_capture_e2e_s}, mult={slow_capture_tpot_mult}, "
                f"interval={slow_capture_interval_s}"
            )
        self._slow_e2e_s = float(slow_capture_e2e_s)
        self._slow_tpot_mult = float(slow_capture_tpot_mult)
        self._slow_interval_s = float(slow_capture_interval_s)
        self._slow_last_capture = 0.0  # monotonic; 0 = never
        self.slow_captures = 0
        self._m_slow_captures = _metrics.SERVE_SLOW_CAPTURES
        # Shared twins for the ring-drop counter and tier byte/residency
        # flow (common/metrics.py definitions; ISSUE 18 satellites).
        self._m_ring_dropped = _metrics.SERVE_REQUEST_RING_DROPPED
        self._m_tier_bytes = _metrics.SERVE_KV_TIER_BYTES
        self._m_tier_resident = _metrics.SERVE_KV_TIER_RESIDENT
        # Bytes in one paged block (0 on dense): the tier-flow byte
        # counters are blocks * this at every move site.
        self._block_bytes = self._kv_row_bytes * self.kv_block

    # -- submission / results (any thread) --------------------------------

    def _validate(self, req: GenRequest) -> None:
        if not req.tokens:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.sample_base < 0:
            raise ValueError("sample_base must be >= 0")
        if len(req.tokens) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {len(req.tokens)} exceeds largest bucket "
                f"{self.prompt_buckets[-1]}"
            )
        if len(req.tokens) + req.max_new_tokens > self._usable_len:
            raise ValueError(
                f"prompt {len(req.tokens)} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_len "
                f"{self.max_len}"
                + (
                    f" minus the spec_decode+1={self.spec_decode + 1} "
                    f"headroom reserve"
                    if self.spec_decode else ""
                )
            )
        if self.paged:
            # A request whose WORST case (no prefix hit: full bucketed
            # prefill plus the whole token budget and spec headroom)
            # cannot fit the pool even when it is completely free must
            # be rejected here — queued, it would deadlock admissions
            # forever (backpressure only helps requests that fit).
            need = self._pool_blocks_needed(
                len(req.tokens), req.max_new_tokens
            )
            if need > self.kv_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks worst-case "
                    f"(prompt {len(req.tokens)} + max_new_tokens "
                    f"{req.max_new_tokens}) but the pool holds only "
                    f"{self.kv_blocks} blocks of {self.kv_block}"
                )
        if req.kv_import is not None:
            if not self.paged:
                raise ValueError(
                    "kv_import needs a paged engine (oim-serve "
                    "--kv-block); this engine runs the dense cache"
                )
            with self._lock:
                imp = self._kv_imports.get(req.kv_import)
            # The import may legitimately TTL-expire before admission
            # (the planner then falls back to a recompute prefill), but
            # a PRESENT import whose token record is not a prefix of
            # this request's prompt would decode against someone else's
            # KV — reject loudly.
            if imp is not None and (
                len(imp.tokens) > len(req.tokens)
                or list(req.tokens[: len(imp.tokens)]) != list(imp.tokens)
            ):
                raise ValueError(
                    f"kv_import {req.kv_import} token record does not "
                    f"prefix this request's prompt"
                )
        if req.top_p is not None and not 0.0 < req.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {req.top_p}")
        if not 0.0 <= req.min_p < 1.0:
            raise ValueError(f"min_p must be in [0, 1), got {req.min_p}")
        if req.repetition_penalty <= 0:
            raise ValueError(
                f"repetition_penalty must be > 0, got "
                f"{req.repetition_penalty}"
            )
        wants_penalties = (
            req.repetition_penalty != 1.0
            or req.presence_penalty != 0.0
            or req.frequency_penalty != 0.0
        )
        if self.spec_decode and wants_penalties:
            # Draft verification samples draft_len+1 positions from ONE
            # forward; penalties evolve the counts WITHIN that block, so
            # exactness would need per-position count replay.  Reject
            # rather than silently approximate.
            raise ValueError(
                "sampling penalties are not supported on a speculative "
                "engine (start oim-serve without --spec-decode)"
            )
        if not self.penalties and wants_penalties:
            raise ValueError(
                "this engine was built with penalties=False "
                "(oim-serve --no-penalties); restart without it"
            )
        bad = [t for t in req.tokens if not 0 <= t < self.cfg.vocab_size]
        if bad:
            # Without this, the embedding gather clamps silently and the
            # client gets plausible-looking output for a garbage prompt.
            raise ValueError(
                f"token ids out of range [0, {self.cfg.vocab_size}): "
                f"{bad[:5]}"
            )

    def submit(self, req: GenRequest, on_token=None) -> int:
        """Queue a request; returns its id.  ``on_token`` (optional)
        streams the generation: called as ``on_token(token, logprob)``
        once per emitted token, in order, then once with ``(None, None)``
        as end-of-stream (completion OR abort).  Callbacks run on the
        engine driver thread and must not block — hand off to a queue
        (the HTTP streaming handler's pattern)."""
        try:
            self._validate(req)
        except ValueError:
            if not self._warming:
                self._m_requests.inc("rejected")
            raise
        now = time.monotonic()
        if req.deadline is not None and now >= req.deadline:
            # Dead on arrival: shed before it costs anything.
            if not self._warming:
                self._m_requests.inc("rejected")
                self._m_shed.inc("deadline")
                self._m_deadline.inc()
                with self._lock:
                    self._shed_counts["deadline"] += 1
            raise DeadlineExpiredError(
                "request deadline already expired at submission"
            )
        with self._lock:
            if self._fatal is not None:
                if not self._warming:
                    self._m_requests.inc("rejected")
                raise EngineFailedError(f"engine failed: {self._fatal}")
            if self._draining:
                if not self._warming:
                    self._m_requests.inc("rejected")
                raise DrainingError("engine is draining; not admitting")
            if (
                self.max_queue
                and not self._warming  # warmup's own dummies are exempt
                and len(self._queue) >= self.max_queue
            ):
                self._m_requests.inc("rejected")
                self._m_shed.inc("queue_full")
                self._shed_counts["queue_full"] += 1
                raise QueueFullError(
                    f"admission queue full ({self.max_queue}); retry later"
                )
            if self.max_queue and not self._warming:
                # Brownout bookkeeping: pressure is "queue at or above
                # the threshold", sustained across submissions.  Clamp
                # only once pressure has held for brownout_hold_s — a
                # momentary burst should not degrade answers.
                if len(self._queue) >= self._brownout_at:
                    if self._pressure_since is None:
                        self._pressure_since = now
                else:
                    self._pressure_since = None
                if (
                    self.brownout_max_tokens
                    and self._pressure_since is not None
                    and now - self._pressure_since >= self.brownout_hold_s
                    and req.max_new_tokens > self.brownout_max_tokens
                ):
                    req = replace(
                        req, max_new_tokens=self.brownout_max_tokens
                    )
                    self._m_shed.inc("brownout")
                    self._shed_counts["brownout"] += 1
            rid = self._next_rid
            self._next_rid += 1
            self._queue.append((rid, req, time.monotonic()))
            promote_plan = (
                # Host-tier promotion (ISSUE 15): if a demoted entry
                # covers this prompt better than anything resident,
                # reserve its install NOW (the payload copy runs after
                # the lock drops) so it is back on the device by this
                # request's admission boundary — double-buffered ahead
                # of the tail prefill, recompute the unconditional
                # fallback.
                self._plan_promote_locked(req)
                if self._host is not None and not self._warming
                else None
            )
            self._events[rid] = threading.Event()
            if on_token is not None:
                self._callbacks[rid] = on_token
            self._m_queued.set(float(len(self._queue)), self._engine_label)
        if promote_plan is not None:
            self._stage_promote(promote_plan)  # the copy, off-lock
        return rid

    @contextmanager
    def _aux_request(self):
        """Drain-aware guard for slot-free work (beam/embed): rejected
        while draining, counted in ``in_flight`` while running."""
        with self._lock:
            if self._fatal is not None:
                if not self._warming:
                    self._m_requests.inc("rejected")
                raise EngineFailedError(f"engine failed: {self._fatal}")
            if self._draining:
                if not self._warming:
                    self._m_requests.inc("rejected")
                raise DrainingError("engine is draining; not admitting")
            self._aux_active += 1
        try:
            yield
        finally:
            with self._lock:
                self._aux_active -= 1

    def embed(self, tokens: list[int]) -> list[float]:
        """Mean-pooled, L2-normalized final hidden state of ``tokens`` —
        the embeddings surface (models.decode.embed_tokens).  Stateless
        and slot-free: safe to call from any thread concurrently with the
        decode loop (it touches neither the cache nor the queue); one
        compile per prompt bucket, absorbed by ``warmup``."""
        with self._aux_request():
            return self._embed_inner(tokens)

    def _embed_inner(self, tokens: list[int]) -> list[float]:
        self._validate(
            GenRequest(tokens=tokens, max_new_tokens=1)
        )
        bucket = self._bucket(len(tokens))
        padded = jnp.asarray(
            [tokens + [0] * (bucket - len(tokens))], jnp.int32
        )
        vec = self._embed(
            self.params, padded, jnp.asarray([len(tokens)], jnp.int32)
        )
        # Through the readback accumulator, not raw device_get: embed
        # pays the same tunnel rtt as a decode chunk and must show in
        # readbacks/readback_seconds or the swing forensics undercount.
        return [float(x) for x in self._fetch_aux(vec[0])]

    def beam(
        self,
        tokens: list[int],
        max_new_tokens: int,
        beam_size: int = 4,
        alpha: float = 0.6,
        eos_id: int | None = None,
    ) -> tuple[list[int], float]:
        """Latency-mode beam search on the engine's model: returns
        (generated tokens of the best hypothesis, normalized score).

        The slot engine continuous-batches greedy/sampled decoding;
        beam-k maintains k interdependent hypotheses whose cache rows
        reorder every step, so it runs as a dedicated jitted program
        (models/beam.py — one compile per (beam_size, alpha, eos_id,
        max_new_tokens) configuration, cached here) rather than through
        the slot machinery.  Like ``embed``, it is stateless and
        slot-free: safe to call from any thread concurrently with the
        decode loop (device compute serializes; no cache/queue state is
        touched).  Beam-1 reproduces the engine's greedy output exactly
        (tests pin this).

        Validation is beam-specific: the slot engine's prompt buckets
        and spec-decode headroom do not apply (beam builds its own cache
        of exactly ``len(tokens) + max_new_tokens`` rows), but the
        engine's ``max_len`` still bounds the total as the server-side
        memory policy, ``beam_size`` is capped (the cache replicates
        across the beam axis), and compile growth is bounded two ways:
        the program cache is FIFO-bounded over client-controlled
        (beam_size, alpha, eos_id) configs, and each program's
        per-(prompt_len, max_new) trace count is budgeted — when the
        total crosses ``_MAX_BEAM_TRACES`` the cache is cleared, so a
        client sweeping shapes costs recompiles, never unbounded memory.
        """
        with self._aux_request():
            return self._beam_inner(
                tokens, max_new_tokens, beam_size, alpha, eos_id
            )

    def _beam_inner(
        self, tokens, max_new_tokens, beam_size, alpha, eos_id
    ) -> tuple[list[int], float]:
        import math

        if not tokens:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"need max_new_tokens >= 1, got {max_new_tokens}"
            )
        bad = [t for t in tokens if not 0 <= t < self.cfg.vocab_size]
        if bad:
            raise ValueError(
                f"token ids out of range [0, {self.cfg.vocab_size}): "
                f"{bad[:5]}"
            )
        if len(tokens) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(tokens)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_len {self.max_len}"
            )
        if not 1 <= beam_size <= _MAX_BEAM_SIZE:
            raise ValueError(
                f"beam_size must be in [1, {_MAX_BEAM_SIZE}], "
                f"got {beam_size}"
            )
        alpha = float(alpha)
        if not math.isfinite(alpha):
            # NaN would also poison the cache key (nan != nan -> every
            # request becomes a fresh compile, defeating the DoS bound).
            raise ValueError(f"alpha must be finite, got {alpha}")
        from oim_tpu.models.beam import make_beam_search_fn

        key = (beam_size, alpha, eos_id)
        trace_key = (key, len(tokens), max_new_tokens)
        with self._beam_lock:
            # One lock for all cache bookkeeping (ThreadingHTTPServer
            # calls beam() concurrently); the compile itself runs under
            # the lock too — serializing concurrent first-compiles is
            # the behavior a server wants anyway.
            if len(self._beam_traces) >= _MAX_BEAM_TRACES:
                # Shapes are client-controlled and each distinct
                # (prompt_len, max_new) is a separate trace inside a
                # cached program, invisible to the FIFO below — clear
                # everything when the TOTAL trace budget is crossed.
                self._beam_fns.clear()
                self._beam_traces.clear()
            fn = self._beam_fns.get(key)
            if fn is None:
                while len(self._beam_fns) >= _MAX_BEAM_PROGRAMS:
                    # FIFO eviction: the key is client-controlled, and
                    # an unbounded cache of jitted programs is a memory
                    # leak an adversarial client can drive.
                    evicted = next(iter(self._beam_fns))
                    self._beam_fns.pop(evicted, None)
                    self._beam_traces = {
                        t for t in self._beam_traces if t[0] != evicted
                    }
                fn = self._beam_fns[key] = make_beam_search_fn(
                    self.cfg, beam_size=beam_size, alpha=alpha,
                    eos_id=eos_id,
                )
            self._beam_traces.add(trace_key)
        prompt = jnp.asarray([tokens], jnp.int32)
        out, stats = fn(self.params, prompt, max_new_tokens=max_new_tokens)
        # ONE accounted readback for tokens + stats (the decode-chunk
        # attribution contract: beam pays the same tunnel rtt and must
        # show in readbacks/readback_seconds, not bypass them via raw
        # device_get).
        out_h, stats = self._fetch_aux((out[0], stats))
        generated = [int(t) for t in out_h[len(tokens):]]
        if eos_id is not None:
            # Tokens past the winner's EOS are 0-padding; trim to the
            # real generation (EOS itself included, matching GenRequest
            # eos semantics).
            generated = generated[: int(stats["length"])]
        # Observability parity with the slot path: beam requests count in
        # the same exposition, under their own outcome label.
        self._m_requests.inc("beam")
        self._m_tokens.inc(by=float(len(generated)))
        return generated, float(stats["normalized_score"])

    def result(self, rid: int, timeout: float | None = None) -> list[int]:
        """Block until request ``rid`` completes; returns generated tokens
        (prompt not included, truncated at EOS if one was set).

        Fetching a result *consumes* it — a daemon engine must not retain
        every historical request forever.  A second fetch raises KeyError.
        ``run()`` returns (but does not consume) unfetched results.
        Raises RuntimeError for a request failed by ``abort()``."""
        return self.result_full(rid, timeout)[0]

    def result_full(
        self, rid: int, timeout: float | None = None
    ) -> tuple[list[int], list[float]]:
        """Like ``result`` but returns ``(tokens, logprobs)`` — the
        logprob of each generated token under the model's raw
        (temperature-1, untruncated) distribution."""
        try:
            event = self._events[rid]
        except KeyError:
            raise KeyError(f"request {rid} unknown or already fetched")
        if not event.wait(timeout):
            raise TimeoutError(f"request {rid} not done")
        with self._lock:
            del self._events[rid]
            if rid in self._errors:
                kind, message = self._errors.pop(rid)
                raise RequestFailedError(rid, kind, message)
            return self._results.pop(rid)

    def forget(self, rid: int) -> None:
        """Drop a request's future result (caller gave up, e.g. an HTTP
        timeout): frees the stored tokens now or, if still in flight,
        the moment it completes — nothing is retained either way."""
        with self._lock:
            if rid in self._results or rid in self._errors:
                self._events.pop(rid, None)
                self._results.pop(rid, None)
                self._errors.pop(rid, None)
            elif rid in self._events:
                self._forgotten.add(rid)
            self._callbacks.pop(rid, None)  # streaming consumer left

    def cancel(self, rid: int, message: str = "cancelled by client") -> bool:
        """Cancel ONE request (client disconnect): a queued entry is
        failed on the spot; an admitting or active one is marked and
        reaped by the driver thread at the next pipeline boundary (its
        slot freed, its chip time stops burning).  Safe from any
        thread; returns False when ``rid`` is unknown or already done.
        The waiter (if any) gets a RequestFailedError with kind
        "cancelled"; an abandoned stream just ends."""
        ended = None
        with self._lock:
            if rid in self._results or rid in self._errors:
                return False  # already finished; result() will see it
            for i, (qrid, qreq, qt) in enumerate(self._queue):
                if qrid == rid:
                    self._queue.pop(i)
                    self._fail_locked(
                        rid, "cancelled", message, req=qreq, t_submit=qt
                    )
                    ended = self._callbacks.pop(rid, None)
                    self._m_queued.set(
                        float(len(self._queue)), self._engine_label
                    )
                    break
            else:
                if (
                    rid in self._admitting
                    or rid in self._parked  # reaped at the next step
                    or any(s.rid == rid for s in self._slots.values())
                ):
                    self._cancelled.add(rid)
                else:
                    return False
        self._drain_fail_obs()
        if ended is not None:
            ended(None, None)  # end-of-stream outside the lock
        return True

    def _fail_locked(
        self,
        rid: int,
        kind: str,
        message: str,
        *,
        req: GenRequest | None = None,
        t_submit: float | None = None,
        state: "_SlotState | None" = None,
    ) -> None:
        """Record a failed request's error and wake its waiter (lock
        held; streaming callbacks are the CALLER's to end — outside the
        lock).  ``req``/``t_submit`` (queued entries) or ``state``
        (slotted requests) carry what the caller knows about the
        request so the forensics record — ring entry, e2e{outcome}
        observation, partial phase spans — exists for failures too;
        all-None (abort of a mid-admission rid) records a minimal
        entry."""
        if not self._warming:
            self._m_requests.inc(kind)
            if state is not None:
                req = state.req
                phases = state.phases or _PhaseTrace(
                    t_submit=state.t_submit
                )
                tokens_out = len(state.emitted)
            else:
                phases = (
                    _PhaseTrace(t_submit=t_submit)
                    if t_submit is not None else None
                )
                tokens_out = 0
            # Queued, not finalized here: the caller drains via
            # _drain_fail_obs() after releasing the lock.
            self._fail_obs.append((rid, req, phases, kind, tokens_out))
        self._cancelled.discard(rid)
        if rid in self._forgotten:
            self._forgotten.discard(rid)
            self._events.pop(rid, None)
            return
        self._errors[rid] = (kind, message)
        if rid in self._events:
            self._events[rid].set()

    def abort(self, message: str, *, kind: str = "aborted") -> None:
        """Fail every queued and in-flight request (the server's driver
        thread calls this when ``step`` raises, so blocked ``result()``
        callers get a RuntimeError instead of waiting out their
        timeout; the stall watchdog calls it with kind="stalled" so
        those failures answer 503-retryable, not 500)."""
        ended = []
        with self._lock:
            # Quiesce the pipeline: an in-flight dispatch references
            # only requests failed right here, so its handle is dropped
            # unread (the device completes the work; nothing consumes
            # it; the cache future in self._cache stays consistent).
            # The idle clock resets too — after an abort the engine is
            # out of work by fiat, and the lull until the next request
            # is light load, not host-induced chip stall (the
            # _clear_idle_clock_if_drained contract).
            self._inflight = None
            self._t_device_free = None
            # (rid, req, t_submit, state): what each failure site knows
            # about its request, threaded through so the forensics ring
            # records every abort victim with whatever phase clock it
            # had accumulated.
            pending: list[tuple] = [
                (rid, req, t, None) for rid, req, t in self._queue
            ]
            # Mid-prefill rids are in _admitting too; let their entry
            # carry the request + partial phase clock for the ring.
            pending += [
                (rid, None, None, None)
                for rid in self._admitting
                if rid not in self._prefilling
            ]
            pending += [
                (p.rid, p.req, p.t_submit, None)
                for p in self._prefilling.values()
            ]
            self._prefilling.clear()
            pending += [
                (s.rid, None, None, s) for s in self._slots.values()
            ]
            # Parked requests die with everyone else (their host
            # blocks return to the tier budget; an in-flight swap-out
            # fetch finds its rid gone and self-cleans).
            pending += [
                (p.state.rid, None, None, p.state)
                for p in self._parked.values()
            ]
            for rid in list(self._parked):
                self._drop_parked_locked(rid)
            self._queue.clear()
            reclaimed = sorted(
                set(self._slots) | set(self._admitting.values())
            )
            self._free += reclaimed
            for slot in reclaimed:
                self._release_slot_blocks_locked(slot)
            self._slots.clear()
            self._admitting.clear()
            for rid, req, t_sub, state in pending:
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
                self._fail_locked(
                    rid, kind, message,
                    req=req, t_submit=t_sub, state=state,
                )
            self._cancelled.clear()
            self._m_active.set(0.0, self._engine_label)
            self._m_queued.set(0.0, self._engine_label)
        self._drain_fail_obs()
        for cb in ended:  # end-of-stream for streaming consumers
            cb(None, None)

    # -- engine loop (one driver thread) ----------------------------------

    def pending(self) -> bool:
        with self._lock:
            # Staged prefix installs, parked slots, and in-flight tier
            # writes count as pending work: the serve loop's idle path
            # must call step() so the driver thread lands installs,
            # completes demote fetches, and restores parked slots at
            # the next admission boundary.
            return bool(
                self._queue or self._slots or self._prefix_installs
                or self._parked or self._pending_host_writes
                # Mid-prefill long prompts (ISSUE 20): the loop must
                # keep stepping so their remaining segments dispatch
                # and the final segment's wave samples their first
                # token.
                or self._prefilling
            )

    def info(self) -> dict:
        """Static engine/model description (GET /v1/info): what an
        operator needs to know which replica serves what — geometry,
        capacity shape, and which optional features are live.  Static
        by construction: safe to cache client-side."""
        cfg = self.cfg
        return {
            "model": {
                "vocab_size": cfg.vocab_size,
                "d_model": cfg.d_model,
                "n_layers": cfg.n_layers,
                "n_heads": cfg.n_heads,
                "n_kv_heads": cfg.kv_heads,
                "d_ff": cfg.ff_dim,
                "n_experts": cfg.n_experts,
                "moe_top_k": cfg.moe_top_k if cfg.n_experts else 0,
                "rope_theta": cfg.rope_theta,
                "rope_scaling": list(cfg.rope_scaling),
                "sliding_window": cfg.sliding_window,
                "norm_eps": cfg.norm_eps,
                "dtype": cfg.dtype,
                "n_params": self.n_params,
            },
            "engine": {
                "n_slots": self._cache.n_slots,
                "max_len": self.max_len,
                "usable_len": self._usable_len,
                "chunk": self.chunk,
                "prompt_buckets": list(self.prompt_buckets),
                "max_queue": self.max_queue,
                "top_k": self.top_k,
                "default_top_p": self.default_top_p,
                "kv_int8": self.kv_int8,
                "kv_int4": self.kv_int4,
                "kv_quant": self.kv_quant,
                "weights_int8": self.weights_int8,
                "weight_quant": self.weight_quant,
                "spec_decode": self.spec_decode,
                "spec_draft_model": self.draft_cfg is not None,
                "draft_n_layers": (
                    self.draft_cfg.n_layers if self.draft_cfg else 0
                ),
                "draft_d_model": (
                    self.draft_cfg.d_model if self.draft_cfg else 0
                ),
                "penalties": self.penalties,
                "prefix_cache_size": self.prefix_cache_size,
                "prefill_chunk": self.prefill_chunk,
                "pipeline_depth": self.pipeline_depth,
                "paged": self.paged,
                "kv_block": self.kv_block,
                "kv_blocks": self.kv_blocks,
                "kv_host_bytes": self.kv_host_bytes,
                "kv_host_blocks": (
                    self._host.n_blocks if self._host else 0
                ),
                "kv_park": self.kv_park,
                "paged_kernel": self.paged_kernel,
                "prefill_kernel": self.prefill_kernel,
                # Whether a tenant policy is loaded (ISSUE 16): with
                # False, admission is FIFO and nothing preempts.
                "qos": self._qos_policy is not None,
                "tp": self.mesh.shape.get("tp", 1) if self.mesh else 1,
                "ep": self.mesh.shape.get("ep", 1) if self.mesh else 1,
            },
        }

    def stats(self) -> dict:
        with self._lock:
            # Decode fetch-wait only: embed/beam readbacks (counted in
            # readback_seconds for the tunnel forensics) can never
            # overlap a decode dispatch and must not dilute the ratio.
            total = self.decode_readback_seconds
            return {
                "active_slots": len(self._slots),
                "free_slots": len(self._free),
                "queued": len(self._queue),
                "steps": self._step_count,
                "tokens_generated": self.tokens_generated,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_injects": self.prefix_injects,
                "prefix_entries": len(self._prefix_cache),
                # Copy-free prefix reuse + paged-pool occupancy (ISSUE
                # 10; all zeros on a dense engine).  Fragmentation is
                # the allocated-but-idle fraction of used block rows —
                # approximate under sharing (an aliased row counts once
                # per reader), an operator signal not an invariant.
                "prefix_bytes_saved": self.prefix_bytes_saved,
                # Fleet prefix residency (ISSUE 14): the resident
                # digest summary (hottest-first, capped), the count of
                # entries installed from sibling exports, exports
                # served, and installs still staged for the driver.
                "prefix_digests": self._prefix_digest_summary_locked(
                    PREFIX_DIGEST_CAP
                ),
                "prefix_fetch_installs": self.prefix_fetch_installs,
                "prefix_exports": self.prefix_exports,
                "prefix_installs_staged": len(self._prefix_installs),
                "kv_block_size": self.kv_block,
                "kv_blocks_total": self.kv_blocks,
                "kv_blocks_free": (
                    self._alloc.free_blocks if self.paged else 0
                ),
                "kv_blocks_used": (
                    self._alloc.used_blocks if self.paged else 0
                ),
                "kv_blocks_shared": (
                    self._alloc.shared_blocks if self.paged else 0
                ),
                "kv_fragmentation": self._kv_fragmentation_locked(),
                "kv_admit_deferrals": self.kv_admit_deferrals,
                # Host-RAM overflow tier (ISSUE 15; zeros without
                # --kv-host-bytes): the second capacity tier's
                # occupancy, the demote/promote movement counters +
                # wall seconds (the thrash signature is promote rate ≈
                # demote rate at high kv_fragmentation), the
                # park/restore counts, and the demote-vs-evict split —
                # "moved to host" vs "lost forever".
                "kv_host_bytes": self.kv_host_bytes,
                "kv_host_blocks_total": (
                    self._host.n_blocks if self._host else 0
                ),
                "kv_host_blocks_free": (
                    self._host.alloc.free_blocks if self._host else 0
                ),
                "kv_host_blocks_used": (
                    self._host.alloc.used_blocks if self._host else 0
                ),
                "kv_host_fragmentation": (
                    self._kv_host_fragmentation_locked()
                ),
                "host_prefix_entries": len(self._host_prefix),
                "parked_slots": len(self._parked),
                "kv_park": self.kv_park,
                "kv_demotions": self.kv_demotions,
                "kv_promotions": self.kv_promotions,
                "kv_parks": self.kv_parks,
                "kv_unparks": self.kv_unparks,
                "kv_demote_seconds": round(self.kv_demote_seconds, 4),
                "kv_promote_seconds": round(self.kv_promote_seconds, 4),
                "kv_promote_wall_p50": round(
                    statistics.median(self._promote_walls), 6
                ) if self._promote_walls else 0.0,
                "prefix_demotions": self.prefix_demotions,
                "prefix_evictions": self.prefix_evictions,
                # Which decode path and quant rung this engine runs
                # (the A/B triage handles in doc/operations.md:
                # mismatches → restart with the kernel off).
                "paged_kernel": self.paged_kernel,
                "kv_quant": self.kv_quant,
                # Chunked flash-prefill (ISSUE 20): which prefill path
                # this engine runs, the segment size, the cumulative
                # prompt-segment dispatch count (one-shot admissions
                # count 1), and how many long prompts are mid-
                # interleave right now.
                "prefill_kernel": self.prefill_kernel,
                "prefill_chunk": self.prefill_chunk,
                "prefill_segments": self.prefill_segments,
                "prefilling": len(self._prefilling),
                # Disaggregated-serving transfer state (serve/disagg.py;
                # zeros on a dense engine).
                "kv_holds": len(self._kv_holds),
                "kv_imports_staged": len(self._kv_imports),
                "kv_exports": self.kv_exports,
                "kv_imports": self.kv_imports_total,
                "kv_ship_bytes": self.kv_ship_bytes,
                # Live slot migration (ISSUE 17): suspended-slot
                # records still awaiting a /v1/slot pickup (each pins
                # its KV blocks until shipped, released, or TTL-swept)
                # plus this backend's lifetime export/import counts.
                "migrated_slots": len(self._migrated),
                "slot_exports": self.slot_exports,
                "slot_imports": self.slot_imports,
                "spec_drafted": self.spec_drafted,
                "spec_accepted": self.spec_accepted,
                "readbacks": self.readbacks,
                "host_seconds": round(self.host_seconds, 4),
                "readback_seconds": round(self.readback_seconds, 4),
                # Pipeline forensics: the dispatch-wait vs fetch-wait
                # split plus how much fetch-wait the device computed
                # through (doc/operations.md "Serving pipeline tuning").
                "dispatch_seconds": round(self.dispatch_seconds, 4),
                "overlap_seconds": round(self.overlap_seconds, 4),
                "overlap_ratio": round(
                    self.overlap_seconds / total if total > 0 else 0.0, 4
                ),
                "device_idle_seconds": round(self.device_idle_seconds, 4),
                "tail_elisions": self.tail_elisions,
                "pipeline_depth": self.pipeline_depth,
                "inflight_dispatches": int(self._inflight is not None),
                # Fault-tolerance forensics: the watchdog baseline, the
                # Retry-After denominator, brownout state, and the
                # fatal latch (non-null = this engine is dead).
                "chunk_wall_ewma": round(self._chunk_wall_ewma or 0.0, 6),
                "token_rate": round(self._token_rate_ewma or 0.0, 2),
                # Live pressure, not the last submit's view: with
                # traffic stopped, _pressure_since only resets on the
                # next submission — forensics must not read a drained
                # queue as still browning out.
                "brownout_active": bool(
                    self.brownout_max_tokens
                    and self._pressure_since is not None
                    and len(self._queue) >= self._brownout_at
                ),
                "fatal": self._fatal,
                # Completed-request ring health: entries evicted
                # drop-oldest (int read is atomic; the ring itself is
                # under its own lock).
                "ring_dropped": self.ring_dropped,
                # Performance forensics (ISSUE 18): tier flow in bytes,
                # post-warm compiles the sentinel attributed to this
                # engine, and tail-latency artifacts dumped.
                "kv_demote_bytes": self.kv_demote_bytes,
                "kv_promote_bytes": self.kv_promote_bytes,
                "recompiles": self.recompiles,
                "slow_captures": self.slow_captures,
                # Multi-tenant QoS (ISSUE 16): whether a policy is
                # enforced, how many admissions parked a victim, and
                # the per-tenant live/cumulative rows (`oimctl
                # tenants` reads these through the router).
                "qos": self._qos_policy is not None,
                "qos_preemptions": self.qos_preemptions,
                "tenants": self._tenant_snapshot_locked(),
            }

    def _worst_case_rows(
        self, n_tokens: int, max_new: int, start: int = 0
    ) -> int:
        """Worst-case slot rows a request can touch: the bucketed
        prefill window from ``start`` (0 = no prefix hit) vs prompt +
        token budget + spec headroom.  THE one definition of the paged
        reservation's upper bound — submit-time rejection (_validate),
        warmup dummy sizing, and the admission planner all call this,
        so they can never disagree about what fits the pool."""
        headroom = self.spec_decode + 1 if self.spec_decode else 0
        return min(self.max_len, max(
            start + self._bucket(n_tokens - start),
            n_tokens + max_new + headroom,
        ))

    def _pool_blocks_needed(self, n_tokens: int, max_new: int) -> int:
        """Worst-case (prefix-free) block reservation — the one
        ceil-divide shared by _validate's submit-time rejection and
        warmup's dummy sizing."""
        return -(-self._worst_case_rows(n_tokens, max_new)
                 // self.kv_block)

    def _kv_fragmentation_locked(self) -> float:
        """Allocated-but-idle fraction of used pool rows (lock held):
        0.0 = every used block row holds live KV, 1.0 = all padding.
        Approximate under sharing (aliased rows count once per reader
        slot) — block-size tuning signal, not an invariant."""
        if not self.paged or not self._alloc.used_blocks:
            return 0.0
        live = sum(
            len(s.req.tokens) + len(s.emitted)
            for s in self._slots.values()
        ) + sum(rows for _, rows in self._prefix_cache.values())
        used_rows = self._alloc.used_blocks * self.kv_block
        return round(max(0.0, 1.0 - live / used_rows), 4)

    def _kv_host_fragmentation_locked(self) -> float:
        """Allocated-but-idle fraction of HOST-tier block rows (lock
        held) — the device definition applied to the overflow tier:
        live rows are demoted entries' covered rows plus parked
        frontiers; the rest of each allocated block is padding tail.
        An operator signal for block-size tuning, like its device
        twin."""
        if self._host is None or not self._host.alloc.used_blocks:
            return 0.0
        live = sum(
            rows for _, rows in self._host_prefix.values()
        ) + sum(p.rows for p in self._parked.values())
        used_rows = self._host.alloc.used_blocks * self.kv_block
        return round(max(0.0, 1.0 - live / used_rows), 4)

    def load(self) -> dict:
        """Compact live-pressure snapshot — the ``load/<cn>`` registry
        value (oim_tpu/autoscale/load.py) and the ``load`` section of
        ``GET /v1/info``.  A strict subset of stats(), shaped for the
        autoscaler's utilization math: busy work is
        ``queue_depth + active_slots`` over ``total_slots`` capacity;
        the kv_blocks_* triple is per-backend KV headroom (zeros on a
        dense engine) so the fleet view can see WHICH replica is out of
        cache, not just out of slots."""
        with self._lock:
            return {
                "queue_depth": len(self._queue),
                "active_slots": len(self._slots),
                "total_slots": self._cache.n_slots,
                "kv_blocks_total": self.kv_blocks,
                "kv_blocks_free": (
                    self._alloc.free_blocks if self.paged else 0
                ),
                "kv_blocks_shared": (
                    self._alloc.shared_blocks if self.paged else 0
                ),
                "kv_fragmentation": self._kv_fragmentation_locked(),
                # Host-RAM overflow tier (ISSUE 15; zeros from dense
                # engines, tier-less engines, and publishers predating
                # the fields): the second capacity tier's headroom and
                # movement counters, plus the demote-vs-evict split —
                # `oimctl top`'s host column and PROMO p/d column, and
                # the capacity-incident queries in doc/operations.md,
                # all read these off the same leased load key.
                "kv_host_blocks_total": (
                    self._host.n_blocks if self._host else 0
                ),
                "kv_host_blocks_free": (
                    self._host.alloc.free_blocks if self._host else 0
                ),
                "kv_host_fragmentation": (
                    self._kv_host_fragmentation_locked()
                ),
                "kv_demotions": self.kv_demotions,
                "kv_promotions": self.kv_promotions,
                "parked_slots": len(self._parked),
                "prefix_demotions": self.prefix_demotions,
                "prefix_evictions": self.prefix_evictions,
                # KV-tier flow telemetry (ISSUE 18, tolerant decode:
                # zeros from publishers predating the fields): park /
                # restore counts and per-direction wall seconds and
                # bytes — `oimctl kv`'s flow-rate columns and the
                # cache-aware autoscaling input (ROADMAP item 5) read
                # these off the same leased load key.
                "kv_parks": self.kv_parks,
                "kv_unparks": self.kv_unparks,
                "kv_demote_seconds": round(self.kv_demote_seconds, 6),
                "kv_promote_seconds": round(self.kv_promote_seconds, 6),
                "kv_demote_bytes": self.kv_demote_bytes,
                "kv_promote_bytes": self.kv_promote_bytes,
                # Fast-path discovery (ISSUE 13): whether this backend
                # decodes through the paged flash kernel and whether
                # its cache runs the kv4 rung — `oimctl top` and the
                # router surface these so an operator can see which
                # replicas run the fast path (and which to bounce when
                # the mismatch counter says the kernel misbehaves).
                "paged_kernel": self.paged_kernel,
                "kv_int4": self.kv_int4,
                # Chunked flash-prefill (ISSUE 20, tolerant decode:
                # zeros/False from publishers predating the fields):
                # which prefill path this backend runs, its segment
                # size, and the cumulative segment-dispatch count —
                # the fleet view of long-prompt admission pressure.
                "prefill_kernel": self.prefill_kernel,
                "prefill_chunk": self.prefill_chunk,
                "prefill_segments": self.prefill_segments,
                # KV-transfer counters (serve/disagg.py): this
                # backend's share of the fleet's ship traffic, for the
                # router's /v1/stats and `oimctl top` pool columns.
                "kv_exports": self.kv_exports,
                "kv_imports": self.kv_imports_total,
                "kv_ship_bytes": self.kv_ship_bytes,
                # Fleet prefix residency (ISSUE 14): the capped digest
                # summary the router's residency map and the pre-warm
                # donor pick ride on, plus the hit/miss counters the
                # fleet prefix-hit rate aggregates — all through the
                # same leased load/serve.<id> value the probe tick
                # already refetches.
                "prefix_digests": self._prefix_digest_summary_locked(
                    PREFIX_DIGEST_CAP
                ),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "token_rate": round(self._token_rate_ewma or 0.0, 2),
                "shed_queue_full": self._shed_counts["queue_full"],
                "shed_deadline": self._shed_counts["deadline"],
                "shed_brownout": self._shed_counts["brownout"],
                # Multi-tenant QoS (ISSUE 16): per-tenant queue/active
                # pressure + enforcement counters, mirrored through
                # the same leased load key (tolerant decode: absent
                # from publishers predating the fields), and the
                # engine-total preemption count.
                "tenants": self._tenant_snapshot_locked(),
                "qos_preemptions": self.qos_preemptions,
                # Migrate-out drain state (ISSUE 17, tolerant decode:
                # absent from publishers predating the field): the
                # router stops routing NEW work at a draining backend
                # while /v1/kv and /v1/slot pulls keep flowing, and
                # `oimctl top` renders the DRAIN marker off it.
                "draining": bool(self._draining),
                "brownout": bool(
                    self.brownout_max_tokens
                    and self._pressure_since is not None
                    and len(self._queue) >= self._brownout_at
                ),
                "ts": time.time(),
            }

    def set_pipeline_depth(self, depth: int) -> None:
        """Switch between serial (1) and dispatch-ahead (2) decode on a
        WARM engine — the bench's A/B lever (same compiled programs,
        only the step loop's overlap changes).  Only legal at a
        pipeline boundary: call while the engine is idle (no chunk in
        flight), e.g. between ``run()`` batches or before the driver
        thread starts."""
        if depth not in (1, 2):
            raise ValueError(
                f"pipeline_depth must be 1 or 2, got {depth}"
            )
        with self._lock:
            if self._inflight is not None:
                raise RuntimeError(
                    "set_pipeline_depth needs an idle engine (a decode "
                    "chunk is in flight; drain or finish run() first)"
                )
            self.pipeline_depth = depth
        self._m_pipeline_depth.set(float(depth), self._engine_label)

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise AssertionError("submit() bounds prompt length")

    # -- request forensics (ISSUE 9) --------------------------------------

    def requests(self) -> dict:
        """The recently-completed-request ring (``GET /debugz/requests``
        on oim-serve; merged fleet-wide by the router at
        ``/v1/requests``): oldest→newest entries with per-phase
        durations, plus the drop-oldest eviction count."""
        with self._ring_lock:
            return {
                "requests": [dict(e) for e in self._ring],
                "dropped": self.ring_dropped,
            }

    def _finalize_request(
        self,
        rid: int,
        req: GenRequest | None,
        phases: _PhaseTrace | None,
        outcome: str,
        tokens_out: int,
        t_end: float,
    ) -> None:
        """Record one request's full observability story: phase spans
        (children of the caller's ``GenRequest.span`` so the trace tree
        reads router→server→engine), the completed-request ring entry,
        and the per-tenant SLO histograms.  NEVER call this with the
        engine lock held (span serialization + the trace-file write
        must not block submit()): the success path runs it on the
        driver thread after the final callbacks fired (so the stream
        phase covers the real tail emission), and the failure funnel
        queues contexts under the lock (``_fail_locked`` →
        ``_fail_obs``) for ``_drain_fail_obs`` to finalize outside it
        with ``t_end = now``.  Everything here is host-side clock
        arithmetic — no device work, no sync.

        Span budget: exactly 1 request span + ≤4 phase spans + one
        decode span per chunk participated in (the regression test's
        "spans per request ≤ phases + chunks" bound)."""
        if self._warming:
            return
        t_fin = time.monotonic()
        tenant = (req.tenant if req is not None else "") or "anon"
        parent = req.span if req is not None else None
        # Phase boundaries ride the monotonic clock (immune to wall
        # steps mid-request); one offset sampled here converts them to
        # the wall-clock nanoseconds the span format carries.
        off = time.time_ns() - int(time.monotonic() * 1e9)

        def ns(t: float) -> int:
            return int(t * 1e9) + off

        t0 = phases.t_submit if phases is not None else t_fin
        root = _tracing.record_span(
            "engine.request",
            component="engine",
            trace_id=parent.trace_id if parent else "",
            parent_id=parent.span_id if parent else "",
            start_ns=ns(t0),
            end_ns=ns(t_fin),
            status="ok" if outcome == "ok" else f"error: {outcome}",
            rid=rid,
            tenant=tenant,
            outcome=outcome,
            tokens_in=len(req.tokens) if req is not None else 0,
            tokens_out=tokens_out,
        )

        def child(name: str, a: float, b: float, **attrs) -> None:
            _tracing.record_span(
                name, component="engine", trace_id=root.trace_id,
                parent_id=root.span_id, start_ns=ns(a), end_ns=ns(b),
                **attrs,
            )

        queue_s = admit_s = prefill_s = decode_s = stream_s = 0.0
        chunk_count = 0
        if phases is not None:
            t_admit = phases.t_admitted or t_fin
            queue_s = max(0.0, t_admit - phases.t_submit)
            child("engine.queue", phases.t_submit, t_admit)
            if phases.t_admitted and phases.t_prefill:
                admit_s = max(0.0, phases.t_prefill - phases.t_admitted)
                child("engine.admit", phases.t_admitted, phases.t_prefill)
            if phases.t_prefill and phases.t_first:
                prefill_s = max(0.0, phases.t_first - phases.t_prefill)
                child("engine.prefill", phases.t_prefill, phases.t_first)
            for seq, a, b, ntok, disp, fetch in phases.chunks:
                decode_s += max(0.0, b - a)
                child(
                    "engine.decode", a, b, chunk=seq, tokens=ntok,
                    dispatch_wait_s=round(disp, 6),
                    fetch_wait_s=round(fetch, 6),
                )
            chunk_count = len(phases.chunks)
            if outcome == "ok" and phases.t_first:
                # Tail emission: last chunk processed → end-of-stream
                # callbacks delivered.  Mid-request detok/callback time
                # is attributed inside the following chunk's span (the
                # callbacks fire between chunk boundaries).
                stream_s = max(0.0, t_fin - t_end)
                child("engine.stream", t_end, t_fin)
        e2e_s = max(0.0, t_fin - t0)
        entry = {
            "rid": rid,
            "tenant": tenant,
            "tier": self._qos_lookup(tenant).tier,
            "trace": root.trace_id,
            "outcome": outcome,
            "queue_s": round(queue_s, 6),
            "admit_s": round(admit_s, 6),
            "prefill_s": round(prefill_s, 6),
            "decode_s": round(decode_s, 6),
            "stream_s": round(stream_s, 6),
            "e2e_s": round(e2e_s, 6),
            "chunks": chunk_count,
            "tokens_in": len(req.tokens) if req is not None else 0,
            "tokens_out": tokens_out,
            # fetched-vs-local-vs-recomputed prefix attribution
            # (`oimctl requests` PREFIX column).
            "prefix": (
                phases.prefix_source if phases is not None
                else "recomputed"
            ),
            # Chunked-prefill attribution (ISSUE 20; `oimctl requests`
            # SEGS column): how many prompt-segment dispatches this
            # admission took (1 = one-shot) and the host walls of the
            # non-final segments — the long-prompt interference
            # forensic: a neighbor's slow TPOT window lining up with a
            # many-SEGS admission is interleaved prefill, not a stall.
            "prefill_segments": (
                phases.prefill_segments if phases is not None else 0
            ),
            "segment_walls": (
                [round(w, 6) for w in phases.segment_walls]
                if phases is not None else []
            ),
            "ts": time.time(),
        }
        with self._ring_lock:
            if self._ring.maxlen == 0:
                self.ring_dropped += 1
                self._m_ring_dropped.inc(self._engine_label)
            else:
                if len(self._ring) == self._ring.maxlen:
                    self.ring_dropped += 1
                    self._m_ring_dropped.inc(self._engine_label)
                self._ring.append(entry)
        self._m_e2e.observe(e2e_s, tenant, outcome)
        # Per-tenant consumption (ISSUE 16): the series token quotas
        # bill against and fair-share convergence checks read.
        if tokens_out:
            self._m_tenant_tokens.inc(tenant, by=float(tokens_out))
        with self._lock:
            row = self._tenant_row_locked(tenant)
            row["requests"] += 1
            row["tokens_out"] += tokens_out
            row["ts"] = time.time()
        if phases is not None and phases.t_admitted:
            self._m_queue_wait.observe(queue_s, tenant)
        if prefill_s > 0.0:
            self._m_prefill.observe(prefill_s, tenant)
        if chunk_count and tokens_out > 1:
            self._m_tpot.observe(decode_s / (tokens_out - 1), tenant)
        # Tail-latency auto-capture (ISSUE 18): runs here, after every
        # metric/ring write and with NO locks held, so a slow dump can
        # never stall the driver's next step or a submit().
        self._maybe_slow_capture(entry, phases)

    def _maybe_slow_capture(
        self, entry: dict, phases: "_PhaseTrace | None"
    ) -> None:
        """Dump the full forensic story of a slow request to the flight
        dir BEFORE anyone asks: the ring entry, its per-chunk phase
        trace, a stats()/KV-occupancy snapshot, and the ring
        neighborhood it completed among.  Triggers: absolute e2e
        threshold, or marginal TPOT above an EWMA multiple of the
        engine's live token rate.  Rate-limited (one artifact per
        interval) and best-effort — a full disk must not fail the
        request that was merely slow."""
        trigger = ""
        if self._slow_e2e_s and entry["e2e_s"] >= self._slow_e2e_s:
            trigger = "e2e"
        elif self._slow_tpot_mult and entry["chunks"]:
            tokens_out = entry["tokens_out"]
            rate = self._token_rate_ewma or 0.0
            if tokens_out > 1 and rate > 0.0:
                tpot = entry["decode_s"] / (tokens_out - 1)
                if tpot * rate >= self._slow_tpot_mult:
                    trigger = "tpot"
        if not trigger:
            return
        now = time.monotonic()
        if (self._slow_last_capture
                and now - self._slow_last_capture < self._slow_interval_s):
            return
        self._slow_last_capture = now
        with self._ring_lock:
            neighborhood = list(self._ring)[-16:]
        artifact = {
            "kind": "slow_capture",
            "trigger": trigger,
            "thresholds": {
                "e2e_s": self._slow_e2e_s,
                "tpot_mult": self._slow_tpot_mult,
                "token_rate_ewma": round(self._token_rate_ewma or 0.0, 2),
            },
            "entry": entry,
            # Per-chunk decode forensics: (seq, start, end, tokens,
            # dispatch-wait, fetch-wait) — the spans' raw material, so
            # the artifact's chunk sums reconcile with entry.decode_s.
            "chunks": [
                {
                    "seq": seq,
                    "wall_s": round(max(0.0, b - a), 6),
                    "tokens": ntok,
                    "dispatch_wait_s": round(disp, 6),
                    "fetch_wait_s": round(fetch, 6),
                }
                for seq, a, b, ntok, disp, fetch in (
                    phases.chunks if phases is not None else ()
                )
            ],
            "stats": self.stats(),
            "ring": neighborhood,
        }
        path = os.path.join(
            _events.flight_dir(),
            f"oim-slowcap-{os.getpid()}-{entry['rid']}-"
            f"{int(time.time())}.json",
        )
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(artifact, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            return  # best-effort: no flight dir is not a request failure
        self.slow_captures += 1
        self._m_slow_captures.inc(self._engine_label, trigger)
        _events.emit(
            "serve.slow_capture",
            component="serve",
            severity=_events.WARNING,
            subject=str(entry["rid"]),
            trigger=trigger,
            tenant=entry["tenant"],
            e2e_s=entry["e2e_s"],
            decode_s=entry["decode_s"],
            tokens_out=entry["tokens_out"],
            path=path,
        )

    def _drain_fail_obs(self) -> None:
        """Finalize failure records queued by ``_fail_locked`` — called
        by every failure site right after it releases the engine lock
        (cancel / abort / _reap / the admission-cancel path)."""
        with self._lock:
            if not self._fail_obs:
                return
            pending, self._fail_obs = self._fail_obs, []
        now = time.monotonic()
        for rid, req, phases, kind, tokens_out in pending:
            self._finalize_request(rid, req, phases, kind, tokens_out, now)

    def _finalize_done(self, finished: list[_SlotState]) -> None:
        """Success-path finalization for this step's completed requests
        — called after their callbacks fired so the stream phase is the
        real tail-emission window.

        Runs on the driver thread but OFF the engine lock, and the cost
        is per-REQUEST (≤ 5 + chunks span records, amortized over the
        whole generation), not per-token.  The one I/O is the span
        collector's line-buffered --trace-file append; deployments
        tracing to slow storage pay that per completed request — keep
        trace files on local disk (the collector is the shared sink
        every component writes from its own hot threads already)."""
        for state in finished:
            phases = state.phases
            t_end = 0.0
            if phases is not None:
                t_end = (
                    phases.chunks[-1][2] if phases.chunks
                    else phases.t_first
                ) or phases.t_submit
            self._finalize_request(
                state.rid, state.req, phases, "ok",
                len(state.emitted), t_end,
            )

    def _finish_locked(self, slot: int, state: _SlotState) -> None:
        # Caller holds self._lock (both call sites are inside the
        # emission critical section; the *_locked helpers below require
        # it).  pop with default: a request finishing on its very first
        # (admit) token was never registered in _slots.
        self._slots.pop(slot, None)
        self._free.append(slot)
        # Disaggregated prefill (serve/disagg.py): a hold_kv request's
        # blocks are retained for export BEFORE the slot release below
        # decrefs them — the hold's own incref keeps them alive.
        if state.req.hold_kv and self.paged and not self._warming:
            self._hold_kv_locked(slot, state)
        # Paged: the request's blocks go back to the pool (prefix-cache
        # entries keep their own refs on any shared run) — the free
        # that makes admission backpressure drain.
        self._release_slot_blocks_locked(slot)
        # A cancel() that raced this completion (landed after _reap but
        # before the finishing chunk processed) must not leave its mark
        # behind: a stale _cancelled entry would defeat _reap's early
        # exit on every future step.
        self._cancelled.discard(state.rid)
        if not self._warming:
            self._m_requests.inc("completed")
            self._m_tokens.inc(by=float(len(state.emitted)))
            self._m_latency.observe(time.monotonic() - state.t_submit)
        self._m_active.set(float(len(self._slots)), self._engine_label)
        if state.rid in self._forgotten:  # caller gave up; retain nothing
            self._forgotten.discard(state.rid)
            self._events.pop(state.rid, None)
            return
        self._results[state.rid] = (state.emitted, state.logprobs)
        self._events[state.rid].set()

    def _emit(self, state: _SlotState, token: int, logprob: float) -> bool:
        """Record one generated token; True when the request is done."""
        if not state.emitted and not self._warming:
            # Time to first token: the interactive-latency number
            # (queue wait + admission + prefill), vs the throughputy
            # submit-to-completion histogram.
            self._m_ttft.observe(time.monotonic() - state.t_submit)
        state.emitted.append(token)
        state.logprobs.append(logprob)
        state.park_immune = False  # progress made: parkable again
        if token == state.req.eos_id or token in state.req.stop_ids:
            return True
        state.last_token = token
        return len(state.emitted) >= state.req.max_new_tokens

    def _flush_host_tier_locked(self) -> None:
        """Drop every demoted entry from the host tier (lock held,
        counter-silent): warmup's post-dummy cleanup and the bench's
        per-leg cache reset — ONE definition of host-tier teardown, so
        the two cannot drift.  Blocks pinned by an in-flight promotion
        snapshot survive their entry's removal (the pin holds its own
        ref) and free when the snapshot completes."""
        if self._host is None:
            return
        for _, (blocks, _) in self._host_prefix.items():
            self._host.alloc.decref(blocks)
        self._host_prefix.clear()
        self._host_meta.clear()
        self._update_kv_gauges_locked()

    def _best_match_locked(self, entries, req: GenRequest) -> tuple:
        """THE prefix matching rule (lock held): longest entry among
        ``entries`` — (key, (payload, true rows)) pairs — usable for
        ``req``, as (key, usable rows) or (None, 0).  One definition
        shared by the dense inject path, the paged aliasing planner,
        and the host tier's promotion pick, so every tier and layout
        hits on exactly the same traffic."""
        best_key, best_usable = None, 0
        for key, (entry, true_len) in entries:
            usable = min(true_len, len(req.tokens) - 1)
            if usable <= best_usable:
                continue
            if tuple(req.tokens[:usable]) == key[:usable]:
                # The tail, bucketed, must still fit the slot region.
                tail_bucket = self._bucket(len(req.tokens) - usable)
                if usable + tail_bucket <= self.max_len:
                    best_key, best_usable = key, usable
        return best_key, best_usable

    def _best_prefix_locked(self, req: GenRequest) -> tuple:
        """Longest DEVICE-resident cached prefix usable for ``req``
        (lock held)."""
        return self._best_match_locked(self._prefix_cache.items(), req)

    def _try_prefix_inject(
        self, slot: int, req: GenRequest
    ) -> tuple[int, str]:
        """Inject the longest cached prefix of ``req.tokens`` into
        ``slot``; returns (start offset for the tail prefill, prefix
        source) — start 0 / "recomputed" when no usable entry, else the
        hit entry's origin ("local"/"fetched") for the request-ring
        attribution.  Exact for dense AND MoE models: a KV row depends
        only on the tokens before it, and MoE routing is per-token
        (``_moe_exact``), so injected rows plus a tail prefill reproduce
        a full prefill bit-for-bit.  Dense engines only — the paged
        layout aliases blocks instead of copying rows
        (``_plan_paged_admission_locked``)."""
        if not self.prefix_cache_size:
            return 0, "recomputed"
        with self._lock:
            best_key, best_usable = self._best_prefix_locked(req)
            if best_key is None:
                if not self._warming:
                    self.prefix_misses += 1
                    self._m_prefix.inc("miss")
                return 0, "recomputed"
            self._prefix_cache.move_to_end(best_key)  # LRU touch
            entry, _ = self._prefix_cache[best_key]
            source = self._touch_prefix_meta_locked(best_key)
            if not self._warming:
                self.prefix_hits += 1
                self._m_prefix.inc("hit")
        self._cache = self._inject(self._cache, entry, jnp.int32(slot))
        return best_usable, source

    def _store_prefix(
        self, slot: int, tokens: list[int], tenant: str = ""
    ) -> None:
        """Cache ``slot``'s freshly prefilled prompt KV.

        Dense: copy the bucketed rows out (only the first len(tokens)
        are valid and only they are used).  Paged: NO copy — take one
        ref on the slot's blocks that the prompt FULLY covers and
        remember their ids.  Only full blocks are shareable: the
        prompt's partial last block is the very block this slot's
        decode writes next, so sharing it would mutate the entry under
        its readers (the shared-block-immutability invariant the CoW
        tests pin).  The refcount keeps entry blocks alive after the
        slot frees; LRU eviction drops the ref."""
        if self.paged:
            full = len(tokens) // self.kv_block
            if full == 0:
                return  # nothing block-aligned to share
            with self._lock:
                blocks = tuple(
                    int(b) for b in self._tables_host[slot][:full]
                )
                if any(b >= self.kv_blocks for b in blocks):
                    # abort() on another thread reclaimed this slot
                    # mid-wave (sentinel row): nothing left to share.
                    return
                key = tuple(tokens)
                old = self._prefix_cache.pop(key, None)
                if old is not None:
                    self._alloc.decref(old[0])
                self._alloc.incref(blocks)
                self._prefix_cache[key] = (blocks, full * self.kv_block)
                self._set_prefix_meta_locked(
                    key, full * self.kv_block, "local", tenant=tenant
                )
                while len(self._prefix_cache) > self.prefix_cache_size:
                    # LRU size cap: demote to the host tier when
                    # configured (ISSUE 15) — a cache sized for hot
                    # entries keeps its warm tail promotable instead
                    # of recomputing it on the next hit.
                    ev_key = next(iter(self._prefix_cache))
                    ev_blocks, ev_rows = self._prefix_cache[ev_key]
                    self._retire_prefix_entry_locked(
                        ev_key, ev_blocks, ev_rows
                    )
                if not self._warming:
                    self.prefix_injects += 1
                    self._m_prefix.inc("inject")
                self._update_kv_gauges_locked()
            return
        bucket = self._bucket(len(tokens))
        entry = self._extract[bucket](self._cache, jnp.int32(slot))
        with self._lock:
            key = tuple(tokens)
            self._prefix_cache[key] = (entry, len(tokens))
            self._prefix_cache.move_to_end(key)
            self._set_prefix_meta_locked(
                key, len(tokens), "local", tenant=tenant
            )
            while len(self._prefix_cache) > self.prefix_cache_size:
                ev_key, _ = self._prefix_cache.popitem(last=False)
                self._prefix_meta.pop(ev_key, None)
                if not self._warming:
                    # Dense entries have no block tier to demote to:
                    # an LRU drop is a true eviction.
                    self.prefix_evictions += 1
                    self._m_prefix.inc("evict")
            if not self._warming:
                self.prefix_injects += 1
                self._m_prefix.inc("inject")

    def _clear_prefix_cache_locked(self, demote: bool = False) -> None:
        """Drop every prefix entry (lock held) — paged entries release
        their block refs (warmup's dummy prompts must not pin pool
        blocks forever).  ``demote=True`` (the admission planner's
        idle fallback) moves each entry to the host tier first when it
        can — the permanent-shortage flush stops burning the whole
        cache's prefill, it just pages it out."""
        if self.paged:
            for key, (blocks, rows) in self._prefix_cache.items():
                demoted = demote and self._demote_entry_locked(
                    key, blocks, rows
                )
                self._alloc.decref(blocks)
                if not self._warming:
                    if demoted:
                        self.prefix_demotions += 1
                        self._m_prefix.inc("demote")
                    else:
                        self.prefix_evictions += 1
                        self._m_prefix.inc("evict")
            self._update_kv_gauges_locked()
        self._prefix_cache.clear()
        self._prefix_meta.clear()

    def _set_prefix_meta_locked(
        self, key: tuple, covered: int, origin: str, tenant: str = ""
    ) -> None:
        """Create/refresh one entry's residency record (lock held).
        The digest hashes the COVERED tokens only — for paged entries
        the block-aligned prefix, which is exactly what an export
        ships and what the router must recompute over a request's
        leading tokens to match.  ``tenant`` is the CN whose request
        prefilled the entry ("" when unknown — fetched/promoted
        entries); its QoS tier decides the entry's demotion rank."""
        policy = self._qos_policy or _QOS_DEFAULT
        self._prefix_meta[key] = {
            "digest": prefix_digest(key[:covered]),
            "covered": covered,
            "hits": 0,
            "last_hit": time.monotonic(),
            "origin": origin,
            "tenant": tenant,
            # An unknown owner ranks at the DEFAULT tier, not anon's:
            # a fetched entry is usually a hot fleet prefix, and
            # punishing it to best-effort would churn exactly the
            # entries residency routing works to keep resident.
            "tier": (
                policy.lookup(tenant).tier if tenant
                else policy.default_tier
            ),
        }

    def _touch_prefix_meta_locked(self, key: tuple) -> str:
        """Record one hit on an entry (lock held); returns its origin
        ("local"/"fetched") for the per-request attribution."""
        meta = self._prefix_meta.get(key)
        if meta is None:
            return "local"
        meta["hits"] += 1
        meta["last_hit"] = time.monotonic()
        return meta["origin"]

    def prefix_digest_summary(self, cap: int = PREFIX_DIGEST_CAP) -> list:
        """Compact resident-prefix summary for ``load/serve.<id>`` and
        ``stats()``: the ``cap`` hottest entries (most recent hit
        first — the pre-warm donor's "top-K hottest digests" order),
        each as {digest, tokens covered, block count, age since last
        hit, hits, origin}.  Truncation keeps the leased registry
        value small no matter how large the cache grows."""
        with self._lock:
            return self._prefix_digest_summary_locked(cap)

    def _prefix_digest_summary_locked(self, cap: int) -> list:
        now = time.monotonic()
        entries = []
        for key, (entry, true_len) in self._prefix_cache.items():
            meta = self._prefix_meta.get(key)
            if meta is None:
                continue
            entries.append((meta["last_hit"], meta["hits"], {
                "digest": meta["digest"],
                "tokens": meta["covered"],
                # Dense entries report 0 blocks: still routable (the
                # residency map is layout-agnostic) but not fetchable
                # (export is paged-only; the router's fetch path reads
                # this as ineligible without a wasted roundtrip).
                "blocks": len(entry) if self.paged else 0,
                "age_s": round(now - meta["last_hit"], 1),
                "hits": meta["hits"],
                "origin": meta["origin"],
            }))
        # Hottest first on the RAW last-hit instant (the rounded age_s
        # ties at 0.0 for anything hit in the same tenth of a second —
        # sorting on it would fall back to dict order, not hotness),
        # hit count breaking exact ties.
        entries.sort(key=lambda e: (-e[0], -e[1]))
        return [doc for _, _, doc in entries[: max(0, cap)]]

    # -- paged-KV host machinery (ISSUE 10) --------------------------------

    def _device_tables(self):  # oimlint: hotpath
        """The block table as the device array the next dispatch needs
        (rebuilt lazily when admissions/frees dirtied the host copy;
        replicated over the mesh under tp — the table is tiny and every
        shard gathers its own heads' rows through it)."""
        if not self.paged:
            return self._tables_dummy
        with self._lock:
            if self._tables_dirty:
                tables = jnp.asarray(self._tables_host)
                if self.mesh is not None:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P

                    tables = jax.device_put(
                        tables, NamedSharding(self.mesh, P())
                    )
                self._tables_dev = tables
                self._tables_dirty = False
            return self._tables_dev

    def _release_slot_blocks_locked(self, slot: int) -> None:
        """Return ``slot``'s block refs to the allocator and reset its
        table row to the sentinel (lock held; called by every path that
        frees a slot — finish, reap, abort, admission-cancel).  The
        sentinel row makes any still-in-flight chunk's writes for this
        slot drop at the pool edge from the NEXT dispatch on; a chunk
        already dispatched against the old table can only write into
        these exact blocks, which the single device stream orders
        before any prefill that reuses them."""
        if not self.paged:
            return
        row = self._tables_host[slot]
        live = row[row < self.kv_blocks]
        if live.size:
            self._alloc.decref(live.tolist())
        row[:] = self.kv_blocks
        self._tables_dirty = True
        self._update_kv_gauges_locked()

    def _update_kv_gauges_locked(self) -> None:
        if not self.paged:
            return
        self._m_kv_blocks.set(
            float(self._alloc.free_blocks), self._engine_label, "free"
        )
        self._m_kv_blocks.set(
            float(self._alloc.used_blocks), self._engine_label, "used"
        )
        self._m_kv_blocks.set(
            float(self._alloc.shared_blocks), self._engine_label, "shared"
        )
        if self._host is not None:
            # The third tier state (ISSUE 15): blocks resident in host
            # RAM — demoted prefix entries, parked slots, and
            # in-flight tier writes.
            self._m_kv_blocks.set(
                float(self._host.alloc.used_blocks),
                self._engine_label, "host",
            )
        # Per-tier resident BYTES (ISSUE 18): the block gauges times
        # block bytes, so fleet dashboards read hierarchical-KV-store
        # occupancy without knowing each engine's block geometry.
        # Module-level instrument (not the self._m_* alias): the ctor
        # calls this before the forensics aliases exist.
        block_bytes = float(self._kv_row_bytes * self.kv_block)
        _metrics.SERVE_KV_TIER_RESIDENT.set(
            self._alloc.used_blocks * block_bytes,
            self._engine_label, "device",
        )
        if self._host is not None:
            _metrics.SERVE_KV_TIER_RESIDENT.set(
                self._host.alloc.used_blocks * block_bytes,
                self._engine_label, "host",
            )

    def _plan_paged_admission_locked(self, req: GenRequest, idle: bool):
        """Reserve everything ``req``'s admission needs from the pool
        (lock held): alias the longest cached prefix's full blocks
        copy-free (one incref each), plan a copy-on-write duplicate of
        the entry's last block when the usable prefix ends mid-block
        (the tail prefill writes into that block — the divergent
        write), and allocate fresh blocks for the rest of the request's
        worst case.  All-or-nothing: returns None when the pool cannot
        cover it, after evicting idle prefix entries LRU-first (cached
        prompts must never starve live admissions) — the caller leaves
        the request QUEUED (OOM-of-blocks is backpressure, not a
        crash).

        ``idle`` = no active or admitting slot anywhere (nothing will
        EVER free a block except prefix entries): when even the aliased
        plan cannot be covered then, the MATCHED entry itself is
        pinning the pool shut — evict it too and re-plan prefix-free,
        which the submit-time fit check guarantees succeeds on an empty
        pool.  Without this fallback, a request that fits the pool but
        not the pool-minus-its-own-matched-entry would wedge the queue
        forever (a copy-free hit is never worth a deadlock); with slots
        active the shortage is transient and the entry is kept."""
        bs = self.kv_block
        best_key, usable = (None, 0)
        if self.prefix_cache_size:
            best_key, usable = self._best_prefix_locked(req)
        aliased: list[int] = []
        cow_src = None
        if best_key is not None and usable:
            entry_blocks, _ = self._prefix_cache[best_key]
            full = usable // bs
            aliased = list(entry_blocks[:full])
            if usable % bs:
                cow_src = entry_blocks[full]
        start = usable
        needed_rows = self._worst_case_rows(
            len(req.tokens), req.max_new_tokens, start
        )
        total_blocks = -(-needed_rows // bs)
        fresh_needed = total_blocks - len(aliased)
        if fresh_needed > self._alloc.free_blocks:
            self._evict_prefix_for_locked(fresh_needed, keep_key=best_key)
        fresh = self._alloc.alloc(fresh_needed)
        if fresh is None and idle and self._prefix_cache:
            # Permanent shortage: the engine is empty of work, so ONLY
            # prefix entries hold blocks — possibly a mutually-aliased
            # set no per-entry exclusivity test can free, possibly the
            # matched entry itself.  Drop the whole cache and re-plan
            # prefix-free: _validate guarantees that bound fits an
            # empty pool, so the queue can never wedge on cached
            # prompts (no refs were taken above).  With the host tier
            # on, the flush DEMOTES what it can first — the shortage
            # clears either way, but the prefill survives.
            self._clear_prefix_cache_locked(demote=True)
            best_key, usable, aliased, cow_src = None, 0, [], None
            start = 0
            total_blocks = fresh_needed = self._pool_blocks_needed(
                len(req.tokens), req.max_new_tokens
            )  # start=0: exactly the bound _validate admitted on
            fresh = self._alloc.alloc(fresh_needed)
        if fresh is None:
            if not self._warming:
                self.kv_admit_deferrals += 1
            return None
        self._alloc.incref(aliased)
        source = "recomputed"
        if best_key is not None:
            self._prefix_cache.move_to_end(best_key)  # LRU touch
            if usable:
                source = self._touch_prefix_meta_locked(best_key)
        if not self._warming:
            if usable:
                self.prefix_hits += 1
                self._m_prefix.inc("hit")
                # Copy-free reuse accounting: the aliased full blocks
                # are KV bytes a dense engine would have COPIED into
                # the slot's region (and, pre-prefix-cache, recomputed
                # outright).  The CoW'd partial block is a real copy,
                # so it does not count.  Source label splits the two
                # savings paths: "alias" = a locally stored entry,
                # "fetched" = an entry installed from a sibling's
                # export — without the split, a fleet whose hits all
                # ride fetched installs reads identically to one whose
                # router affinity alone is doing the work (ISSUE 14).
                saved = len(aliased) * bs * self._kv_row_bytes
                self.prefix_bytes_saved += saved
                self._m_prefix_bytes.inc(
                    self._engine_label,
                    "fetched" if source == "fetched" else "alias",
                    by=float(saved),
                )
            elif self.prefix_cache_size:
                self.prefix_misses += 1
                self._m_prefix.inc("miss")
        # Table row order IS the position map: entry i covers rows
        # [i*bs, (i+1)*bs).  The CoW destination is fresh[0] — the
        # first block after the aliased run, exactly where the partial
        # entry block's copy must sit.
        return {
            "start": start,
            "blocks": aliased + fresh,
            "cow": None if cow_src is None else (cow_src, fresh[0]),
            "source": source,
        }

    def _evict_prefix_for_locked(
        self, fresh_needed: int, keep_key=None
    ) -> None:
        """Reclaim pool blocks from idle prefix entries LRU-first
        (never ``keep_key``) — but ONLY when that can cover the
        shortfall now: entries whose blocks are still aliased by
        running slots (or by a sibling entry) free nothing, and
        flushing the cache without admitting anyone trades future hits
        for zero blocks — the head-of-line request retries every step,
        which would otherwise empty the whole cache on one transient
        shortage.  The exclusive-count sum undercounts mutually-aliased
        entry SETS (evicting both would free what neither frees alone)
        — conservative by design; the admission planner's idle fallback
        covers that case when it matters.  Lock held; shared by the
        prefix planner and the KV-import planner.

        With the host tier configured (ISSUE 15), each victim is
        DEMOTED — its block contents dispatched to host RAM before the
        refs drop, so a later hit promotes instead of recomputing —
        and destroyed only when the host tier cannot take it (no tier,
        budget exhausted after host-LRU pressure).  Either way the
        device blocks free right here; the two outcomes split into
        prefix_demotions vs prefix_evictions.

        Under a QoS policy (ISSUE 16) the victim order is TIER-then-
        LRU: best-effort entries go first, premium last — a premium
        tenant's warm prefix effectively pins against demotion for as
        long as any lower-tier entry can cover the shortfall.  A soft
        pin on purpose: when only premium entries remain they still
        retire (the reclaimable precheck's no-wedge guarantee beats
        the pin — an unadmittable queue serves no tier)."""
        victims = [
            (key, blocks, rows)
            for key, (blocks, rows) in self._prefix_cache.items()
            if key != keep_key
        ]
        if self._qos_policy is not None and len(victims) > 1:
            victims.sort(key=lambda item: _QOS_TIER_PRIORITY.get(
                (self._prefix_meta.get(item[0]) or {}).get(
                    "tier", "standard"
                ),
                0,
            ))  # stable: LRU order preserved within a tier
        reclaimable = self._alloc.free_blocks + sum(
            self._alloc.exclusive(blocks) for _, blocks, _ in victims
        )
        if reclaimable < fresh_needed:
            return
        for key, blocks, rows in victims:
            if fresh_needed <= self._alloc.free_blocks:
                break
            if not self._alloc.exclusive(blocks):
                continue
            self._retire_prefix_entry_locked(key, blocks, rows)

    def _retire_prefix_entry_locked(
        self, key: tuple, blocks, rows: int
    ) -> None:
        """Remove one prefix entry from the device cache, demoting its
        blocks to the host tier when possible and destroying them
        otherwise (lock held; the one retirement path shared by the
        shortfall planners and the LRU size cap, so the
        demote-vs-evict accounting cannot drift between call sites)."""
        demoted = self._demote_entry_locked(key, blocks, rows)
        self._prefix_cache.pop(key, None)
        self._prefix_meta.pop(key, None)
        self._alloc.decref(blocks)
        if not self._warming:
            if demoted:
                self.prefix_demotions += 1
                self._m_prefix.inc("demote")
            else:
                self.prefix_evictions += 1
                self._m_prefix.inc("evict")

    def _commit_plan_locked(self, slot: int, plan: dict) -> None:
        row = self._tables_host[slot]
        row[:] = self.kv_blocks
        row[: len(plan["blocks"])] = plan["blocks"]
        self._tables_dirty = True
        self._update_kv_gauges_locked()

    # -- host-RAM KV overflow tier (ISSUE 15) ------------------------------

    def _read_blocks_dispatch(self, blocks) -> list | None:
        """Dispatch a ``read_block`` per pool leaf for each of
        ``blocks`` against the CURRENT cache generation (lock held,
        any thread) — returns per-block lists of device futures in
        ``HostBlockPool.pools()`` leaf order, or None after losing the
        donation race repeatedly.  On the driver thread the race
        cannot happen (the driver is the only donor); a handler-thread
        caller (a KV-ingest shortfall demoting entries) retries by
        re-snapshotting ``self._cache``, the ``_gather_blocks``
        pattern.  The reads are stream-ordered BEFORE any dispatch
        that reuses the blocks, so the fetched bytes are always the
        pre-reuse contents — the caller may decref immediately after
        this returns."""
        for _ in range(8):
            cache = self._cache
            pools = [
                getattr(cache, name) for name, _ in self._host.pools()
            ]
            try:
                return [
                    [
                        self._read_block(pool, jnp.int32(b))
                        for pool in pools
                    ]
                    for b in blocks
                ]
            except (RuntimeError, ValueError):
                # Donated mid-build (the dispatch surfaces a deleted
                # buffer as INVALID_ARGUMENT ValueError, unlike the
                # fetch path's RuntimeError): re-snapshot and retry.
                continue
        return None

    def _demote_entry_locked(self, key: tuple, blocks, rows: int) -> bool:
        """Move one idle prefix entry's block contents to the host
        tier (lock held): allocate host blocks (evicting host-LRU
        entries under budget pressure), dispatch the stream-ordered
        reads, and queue the fetch for ``_complete_host_writes`` —
        the entry becomes promotable only once the bytes land.
        Returns False (caller falls back to true eviction) when the
        host tier is off, cannot make room, or the read dispatch lost
        the donation race out."""
        if self._host is None or not blocks:
            return False
        host_key = tuple(key[:rows])
        n = len(blocks)
        if n > self._host.alloc.free_blocks:
            self._evict_host_for_locked(n)
        host_blocks = self._host.alloc.alloc(n)
        if host_blocks is None:
            return False
        dev = self._read_blocks_dispatch(blocks)
        if dev is None:
            self._host.alloc.decref(host_blocks)
            return False
        meta = self._prefix_meta.get(key)
        self._pending_host_writes.append(_HostWrite(
            kind="prefix",
            host_blocks=tuple(host_blocks),
            dev=dev,
            key=host_key,
            rows=rows,
            meta=dict(meta) if meta else None,
        ))
        if not self._warming:
            self.kv_demotions += n
            self.kv_demote_bytes += n * self._block_bytes
            self._m_tier_moves.inc("demote", by=float(n))
            self._m_tier_bytes.inc(
                "demote", by=float(n * self._block_bytes)
            )
        self._update_kv_gauges_locked()
        return True

    def _evict_host_for_locked(self, need: int) -> None:
        """Drop demoted entries host-LRU-first until ``need`` host
        blocks are free (lock held) — but ONLY when eviction can
        actually cover the need (the device-side reclaimable
        precheck): flushing resident entries for a demotion or park
        that cannot fit the budget anyway trades promotable prefill
        for nothing.  Refcount-aware like its device twin: an entry
        whose blocks are PINNED by an in-flight promotion snapshot
        (``_plan_promote_locked``'s off-lock memcpy window) frees
        nothing on decref, so it neither counts as reclaimable nor
        gets destroyed for zero gained capacity.  A host eviction is
        prefill lost forever — the tier's own budget pressure — so it
        counts under prefix_evictions beside the device-side
        destroys."""
        victims = list(self._host_prefix.items())
        reclaimable = self._host.alloc.free_blocks + sum(
            self._host.alloc.exclusive(blocks)
            for _, (blocks, _) in victims
        )
        if reclaimable < need:
            return
        for key, (blocks, _) in victims:
            if self._host.alloc.free_blocks >= need:
                break
            if not self._host.alloc.exclusive(blocks):
                continue  # pinned by an in-flight promote: skip
            self._host_prefix.pop(key)
            self._host_meta.pop(key, None)
            self._host.alloc.decref(blocks)
            if not self._warming:
                self.prefix_evictions += 1
                self._m_prefix.inc("evict")
        self._update_kv_gauges_locked()

    def _best_host_prefix_locked(self, req: GenRequest) -> tuple:
        """Longest DEMOTED prefix usable for ``req`` (lock held) —
        the host-tier view of the one matching rule, so promotion and
        aliasing hit on exactly the same traffic."""
        return self._best_match_locked(self._host_prefix.items(), req)

    def _plan_promote_locked(self, req: GenRequest) -> dict | None:
        """If a demoted entry covers more of ``req`` than anything
        device-resident, reserve its promotion (lock held, submit
        path, any thread): device blocks from FREE space only (a tier
        under enough pressure to have demoted must not thrash entries
        back and forth — budget exhausted degrades to recompute) and
        one pin ref on the host blocks, so ``_stage_promote`` can
        snapshot the payload OFF the engine lock — a multi-MB host
        memcpy must not stall the driver's step behind a submit."""
        if (
            self._host is None
            or not self.prefix_cache_size
            or not self._host_prefix
        ):
            return None
        host_key, host_usable = self._best_host_prefix_locked(req)
        if host_key is None:
            return None
        _, dev_usable = self._best_prefix_locked(req)
        if host_usable <= dev_usable:
            return None  # the device tier already covers as much
        if (
            host_key in self._prefix_cache
            or host_key in self._promote_staging
            or any(
                tuple(st.tokens) == host_key
                for _, st, _ in self._prefix_installs
            )
        ):
            return None  # already resident or staged (a cohort burst)
        blocks, rows = self._host_prefix[host_key]
        n = len(blocks)
        self._sweep_prefix_installs_locked(time.monotonic())
        if (
            len(self._prefix_installs) >= PREFIX_IMPORT_MAX
            or n > self._alloc.free_blocks
        ):
            return None
        dev_blocks = self._alloc.alloc(n)
        if dev_blocks is None:
            return None
        # Pin the host blocks for the off-lock copy: a host-LRU
        # eviction may drop the ENTRY meanwhile, but the pinned rows
        # cannot be reallocated (and only the driver's completion path
        # ever writes pool rows), so the snapshot stays coherent.
        self._host.alloc.incref(blocks)
        self._promote_staging.add(host_key)
        return {
            "key": host_key,
            "digest": self._host_meta.get(host_key, {}).get(
                "digest", prefix_digest(host_key)
            ),
            "host_blocks": tuple(blocks),
            "dev_blocks": tuple(dev_blocks),
            "rows": rows,
        }

    def _stage_promote(self, plan: dict) -> None:
        """Snapshot a planned promotion's payload (lock NOT held — the
        copy is the expensive part) and stage it as a prefix install
        for the driver's next admission boundary.  The host entry
        stays resident and LRU-evictable while the install is staged:
        a TTL'd or capacity-dropped install loses only the staged
        copy, never the entry."""
        try:
            data = {
                name: np.ascontiguousarray(
                    pool[:, list(plan["host_blocks"])]
                )
                for name, pool in self._host.pools()
            }
        except BaseException:
            with self._lock:
                self._promote_staging.discard(plan["key"])
                self._host.alloc.decref(plan["host_blocks"])
                self._alloc.decref(plan["dev_blocks"])
                self._update_kv_gauges_locked()
            raise
        with self._lock:
            self._promote_staging.discard(plan["key"])
            self._host.alloc.decref(plan["host_blocks"])
            self._prefix_installs.append((
                plan["digest"],
                KvImport(
                    import_id=-1,
                    blocks=plan["dev_blocks"],
                    rows=plan["rows"],
                    tokens=list(plan["key"]),
                    data=data,
                    t_created=time.monotonic(),
                ),
                plan["key"],  # promote tag: clears the host entry
            ))
            self._update_kv_gauges_locked()

    # oimlint: hotpath
    def _complete_host_writes(self) -> None:
        """Land every dispatched tier demotion in the host pool (one
        BATCHED fetch through the readback accumulator — never a raw
        device_get on the driver's spine) and make the results
        visible: prefix entries become promotable, parked slots
        become restorable.  Driver thread (or the serve loop's idle
        path via step()); safe to call with nothing pending."""
        with self._lock:
            if not self._pending_host_writes:
                return
            staged, self._pending_host_writes = (
                self._pending_host_writes, []
            )
        t0 = time.monotonic()
        fetched = self._fetch_aux([w.dev for w in staged])
        moved = 0
        with self._lock:
            for w, host_arrs in zip(staged, fetched):
                pools = [pool for _, pool in self._host.pools()]
                for hb, leaves in zip(w.host_blocks, host_arrs):
                    for pool, arr in zip(pools, leaves):
                        pool[:, hb] = np.asarray(arr)
                moved += len(w.host_blocks)
                if w.kind == "prefix":
                    old = self._host_prefix.pop(w.key, None)
                    if old is not None:
                        # Re-demotion of a re-stored entry: same
                        # contents, keep the fresh copy.
                        self._host.alloc.decref(old[0])
                    self._host_prefix[w.key] = (w.host_blocks, w.rows)
                    meta = w.meta or {
                        "digest": prefix_digest(w.key),
                        "covered": w.rows,
                        "hits": 0,
                        "last_hit": time.monotonic(),
                        "origin": "local",
                    }
                    self._host_meta[w.key] = meta
                elif w.rid in self._parked:
                    self._parked[w.rid].ready = True
                else:
                    # The parked request was reaped/cancelled/aborted
                    # while its swap-out was in flight: nothing left
                    # to restore, return the host blocks.
                    self._host.alloc.decref(w.host_blocks)
            if not self._warming:
                dt = time.monotonic() - t0
                self.kv_demote_seconds += dt
                self._m_tier_seconds.inc("demote", by=dt)
            self._update_kv_gauges_locked()

    # -- multi-tenant QoS (ISSUE 16) ---------------------------------------

    def set_qos_policy(self, policy) -> None:
        """Swap the tenant policy (None turns QoS off).  Existing
        accounting rows re-resolve their tier/weight; virtual times
        carry over — a policy reload must not reset the fairness
        ledger mid-backlog."""
        with self._lock:
            self._qos_policy = policy
            for name, row in self._tenants.items():
                pol = self._qos_lookup(name)
                row["tier"] = pol.tier
                row["weight"] = pol.effective_weight

    def _qos_lookup(self, tenant: str):
        return (self._qos_policy or _QOS_DEFAULT).lookup(tenant)

    def _tenant_row_locked(self, tenant: str) -> dict:
        """The accounting row for ``tenant`` (lock held), created on
        first contact.  Newcomers start their virtual time at the
        fleet minimum — starting at zero would hand any tenant that
        merely stayed idle unbounded catch-up credit."""
        row = self._tenants.get(tenant)
        if row is None:
            pol = self._qos_lookup(tenant)
            floor = min(
                (r["vtime"] for r in self._tenants.values()), default=0.0
            )
            if len(self._tenants) >= _MAX_TENANT_ROWS:
                # Advisory accounting must not become a cardinality
                # leak: drop the least-recently-touched row.  Its
                # cumulative counters vanish from stats() (the shared
                # Prometheus series keep the history).
                stale = min(
                    self._tenants, key=lambda t: self._tenants[t]["ts"]
                )
                del self._tenants[stale]
            row = {
                "tier": pol.tier,
                "weight": pol.effective_weight,
                "vtime": floor,
                "admitted": 0,
                "preempted": 0,
                "parked_victim": 0,
                "requests": 0,
                "tokens_out": 0,
                "ts": time.time(),
            }
            self._tenants[tenant] = row
        return row

    def _qos_head_locked(self) -> int:
        """Index of the next admission candidate in ``self._queue``.

        QoS off → 0 (pure FIFO, the pre-QoS contract).  QoS on →
        deficit-weighted fair share via stride scheduling: each
        tenant's requests stay FIFO among themselves, and the tenant
        whose virtual time lags most admits next (ties to arrival
        order).  Head-of-line backpressure is PRESERVED on the chosen
        head — the admission loop still blocks on ITS plan rather
        than skipping to a smaller latecomer, it just gets to choose
        whose head that is."""
        if self._qos_policy is None or len(self._queue) < 2:
            return 0
        best_i, best_key = 0, None
        seen: set[str] = set()
        for i, (rid, req, t_sub) in enumerate(self._queue):
            tenant = req.tenant or "anon"
            if tenant in seen:
                continue  # only each tenant's own head competes
            seen.add(tenant)
            row = self._tenant_row_locked(tenant)
            key = (row["vtime"], t_sub, rid)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        return best_i

    def _qos_charge_locked(self, req: GenRequest) -> None:
        """Account one granted admission (lock held, queue already
        popped).  The stride charge is the request's worst-case token
        footprint over the tenant's weight, so token throughput — not
        request count — converges to the weight ratio.  The vtime
        floor clamp forgives debt accrued while a tenant had no
        backlog (standard virtual-time hygiene: an idle tenant must
        not bank unbounded credit, nor carry unpayable debt)."""
        if self._warming:
            # Warmup's dummy admissions must not seed an anon
            # accounting row or skew the fairness ledger.
            return
        tenant = req.tenant or "anon"
        row = self._tenant_row_locked(tenant)
        if self._qos_policy is not None:
            backlog = {r.tenant or "anon" for _, r, _ in self._queue}
            floor = min(
                (
                    self._tenants[t]["vtime"]
                    for t in backlog if t in self._tenants
                ),
                default=row["vtime"],
            )
            charge = float(max(1, len(req.tokens) + req.max_new_tokens))
            row["vtime"] = (
                max(row["vtime"], floor) + charge / max(row["weight"], 1e-9)
            )
        row["admitted"] += 1
        row["ts"] = time.time()
        if not self._warming:
            self._m_qos.inc(row["tier"], "admitted")

    def _qos_preempt_pending_locked(self) -> bool:
        """Would the slot-shortage preemption path act right now?  The
        pipeline-boundary predicate asks this (``_step_inner``):
        queued work with no free slot normally does NOT force a
        boundary — but when the fair-share head could preempt, the
        admission wave must actually RUN, or a saturated engine would
        pipeline straight past every preemption opportunity and the
        premium tenant would wait out the flood's full streams anyway.
        Pure read: same checks as ``_qos_preempt_locked`` minus the
        park itself."""
        if self._qos_policy is None or not self._queue or self._free:
            return False
        if not self.kv_park or self._host is None:
            return False
        _, req, _ = self._queue[self._qos_head_locked()]
        prio = self._qos_lookup(req.tenant or "anon").priority
        if prio <= 0:
            return False
        return self._pick_park_victim_locked(prio) is not None

    def _qos_preempt_locked(self) -> bool:
        """Slot-shortage priority preemption (lock held, admission
        boundary, no free slot): when the fair-share head belongs to
        a tenant with preemption priority above some running slot's,
        park one STRICTLY-lower-priority victim so the admission loop
        can run at all.  Strictly lower only — equal tiers never
        preempt each other, which is what makes a policy-less fleet
        (everyone standard) behave exactly as before this PR and
        keeps two premium tenants from ping-ponging one slot.
        Returns True when a victim was parked (a slot and its blocks
        freed)."""
        if not self._queue or self._free:
            return False
        _, req, _ = self._queue[self._qos_head_locked()]
        prio = self._qos_lookup(req.tenant or "anon").priority
        if prio <= 0:
            return False
        return self._try_park_locked(req, below_priority=prio)

    def _tenant_snapshot_locked(self) -> dict:
        """Per-tenant live + cumulative view (lock held) for
        stats()/load()/info: queued/active/parked counted from ground
        truth (the queue, the slot table, the parked set — no
        increment/decrement bookkeeping to leak), counters from the
        accounting rows."""
        queued: dict[str, int] = {}
        for _, req, _ in self._queue:
            t = req.tenant or "anon"
            queued[t] = queued.get(t, 0) + 1
        active: dict[str, int] = {}
        for state in self._slots.values():
            t = state.req.tenant or "anon"
            active[t] = active.get(t, 0) + 1
        parked: dict[str, int] = {}
        for rec in self._parked.values():
            t = rec.state.req.tenant or "anon"
            parked[t] = parked.get(t, 0) + 1
        out: dict[str, dict] = {}
        for name in (
            set(self._tenants) | set(queued) | set(active) | set(parked)
        ):
            row = self._tenants.get(name, {})
            pol = self._qos_lookup(name)
            out[name] = {
                "tier": pol.tier,
                "weight": pol.effective_weight,
                "queued": queued.get(name, 0),
                "active": active.get(name, 0),
                "parked": parked.get(name, 0),
                "admitted": row.get("admitted", 0),
                "preempted": row.get("preempted", 0),
                "parked_victim": row.get("parked_victim", 0),
                "requests": row.get("requests", 0),
                "tokens_out": row.get("tokens_out", 0),
            }
        return out

    def _pick_park_victim_locked(self, below_priority: int | None = None):
        """The best park victim (lock held, admission boundary — no
        chunk in flight, so every active slot is between chunks):
        lowest QoS preemption priority first (with no policy every
        tenant is standard, so this term is inert), then the largest
        remaining token budget, ties to the youngest stream — the
        tier-then-coldest order.  The coldest slot will pin pool
        blocks longest, so swapping it buys the most capacity per
        byte moved.  ``below_priority`` (the slot-shortage preemption
        path) admits only victims of STRICTLY lower priority.  Slots
        that have not emitted since their own restore are immune — a
        restored slot must make progress before it can be parked
        again, or a saturated queue ping-pongs one victim forever."""
        best, best_key = None, None
        for slot, state in self._slots.items():
            if state.park_immune:
                continue
            rem = state.req.max_new_tokens - len(state.emitted)
            if rem < 1:
                continue  # finishing this chunk anyway
            prio = self._qos_lookup(state.req.tenant or "anon").priority
            if below_priority is not None and prio >= below_priority:
                continue
            key = (-prio, rem, state.t_submit)
            if best_key is None or key > best_key:
                best, best_key = (slot, state.rid, state), key
        return best

    def _try_park_locked(
        self, req: GenRequest, below_priority: int | None = None
    ) -> bool:
        """Park the coldest idle slot to make room for ``req``'s
        admission (lock held, driver thread): copy its live blocks to
        the host tier, free its device blocks AND its slot, and
        remember everything a later restore needs.  Returns True when
        a victim was parked (the caller re-plans against the freed
        blocks).  The victim's stream simply pauses — its waiters and
        callbacks stay registered, its deadline keeps running (a
        parked request can still be reaped), and restore is exact:
        block contents are bit-copies and every other per-slot input
        is rebuilt from host truth."""
        if not self.kv_park or self._host is None:
            return False
        pick = self._pick_park_victim_locked(below_priority)
        if pick is None:
            return False
        slot, rid, state = pick
        rows = len(state.req.tokens) + len(state.emitted) - 1
        if rows < 1:
            return False
        bs = self.kv_block
        n_cov = -(-rows // bs)
        row = self._tables_host[slot]
        live = row[row < self.kv_blocks]
        n_live = int(live.size)
        if n_cov > n_live:
            return False  # abort/reap raced: nothing coherent to park
        cov = [int(b) for b in row[:n_cov]]
        if n_cov > self._host.alloc.free_blocks:
            self._evict_host_for_locked(n_cov)
        host_blocks = self._host.alloc.alloc(n_cov)
        if host_blocks is None:
            return False
        dev = self._read_blocks_dispatch(cov)
        if dev is None:
            self._host.alloc.decref(host_blocks)
            return False
        self._pending_host_writes.append(_HostWrite(
            kind="park",
            host_blocks=tuple(host_blocks),
            dev=dev,
            rid=rid,
        ))
        self._parked[rid] = _ParkedSlot(
            state=state,
            host_blocks=tuple(host_blocks),
            n_cov=n_cov,
            n_live=n_live,
            rows=rows,
        )
        self._slots.pop(slot)
        self._free.append(slot)
        self._release_slot_blocks_locked(slot)
        if not self._warming:
            self.kv_parks += 1
            self.kv_demotions += n_cov
            self.kv_demote_bytes += n_cov * self._block_bytes
            self._m_tier_moves.inc("demote", by=float(n_cov))
            self._m_tier_bytes.inc(
                "demote", by=float(n_cov * self._block_bytes)
            )
            if self._qos_policy is not None:
                # Under a policy every park IS a QoS decision (the
                # victim order came from tenant tiers): count both
                # sides and leave a flight-recorder trail.  WARNING
                # severity — preemptions are rare, operator-visible
                # capacity events (throttles, the high-volume cousin,
                # stay INFO at the router).
                preemptor = req.tenant or "anon"
                victim = state.req.tenant or "anon"
                prow = self._tenant_row_locked(preemptor)
                vrow = self._tenant_row_locked(victim)
                prow["preempted"] += 1
                vrow["parked_victim"] += 1
                self.qos_preemptions += 1
                self._m_qos.inc(prow["tier"], "preempted")
                self._m_qos.inc(vrow["tier"], "parked_victim")
                _events.emit(
                    "qos.preempt",
                    component="oim-serve",
                    severity=_events.WARNING,
                    subject=victim,
                    preemptor=preemptor,
                    preemptor_tier=prow["tier"],
                    victim_tier=vrow["tier"],
                    victim_rid=rid,
                    blocks=n_cov,
                )
        self._m_active.set(float(len(self._slots)), self._engine_label)
        return True

    def _drop_parked_locked(self, rid: int) -> "_ParkedSlot | None":
        """Forget one parked request and return its host blocks (lock
        held) — the reap/cancel/abort path for a request that dies
        while swapped out.  An in-flight swap-out fetch for this rid
        finds it gone and returns the blocks itself."""
        parked = self._parked.pop(rid, None)
        if parked is None:
            return None
        if parked.ready:
            # Not yet landed = the pending-write completion owns the
            # decref (the blocks are its write target until then).
            self._host.alloc.decref(parked.host_blocks)
        self._update_kv_gauges_locked()
        return parked

    def _unpark_wave(self) -> None:
        """Restore parked slots whose KV fits the pool again (driver
        thread, admission boundary, lock NOT held): FIFO over parked
        requests — the oldest victim gets its capacity back first —
        stopping at the first that does not fit (restore order is a
        fairness promise, not best-fit packing).  Restores never park
        other slots; they only reclaim idle prefix blocks, so a
        restore cannot cascade."""
        while True:
            with self._lock:
                target = None
                for rid, parked in self._parked.items():
                    if parked.ready:
                        target = (rid, parked)
                    break  # FIFO: only ever consider the oldest
                if target is None:
                    return
                rid, parked = target
                if not self._free:
                    return
                if parked.n_live > self._alloc.free_blocks:
                    self._evict_prefix_for_locked(parked.n_live)
                blocks = self._alloc.alloc(parked.n_live)
                if blocks is None and not (
                    self._slots or self._admitting or self._queue
                ):
                    # The engine is otherwise idle, so ONLY prefix
                    # entries hold blocks — possibly a mutually-
                    # aliased set no per-entry exclusivity test can
                    # free (the admission planner's idle-fallback
                    # case).  Flush the cache (demoting what fits the
                    # host budget) rather than spin on a restore that
                    # can never fit: the parked reservation fit this
                    # pool once, so an empty pool must cover it.
                    self._clear_prefix_cache_locked(demote=True)
                    blocks = self._alloc.alloc(parked.n_live)
                if blocks is None:
                    return
                parked.restoring = True  # stays in _parked: visible to
                slot = self._free.pop(0)  # cancel/reap/abort/in_flight
                state = parked.state
            # Device writes outside the lock (driver thread owns the
            # cache): land the covered payload, then rebuild the
            # per-slot device state from host truth.  Stream order
            # ingest → restore → next dispatch keeps it exact.  The
            # host pool rows are stable through this window — only the
            # driver thread (us) ever writes them, and the record's
            # continued _parked membership means nothing freed them.
            t0 = time.monotonic()
            self._write_host_payload(
                parked.host_blocks, blocks[: parked.n_cov]
            )
            self._restore_slot_state(slot, state, parked.rows)
            with self._lock:
                if self._parked.pop(rid, None) is None:
                    # abort() landed during the device writes: the
                    # request is already failed and the host blocks
                    # already returned by whoever popped the record —
                    # unwind our reservation and move on.
                    self._alloc.decref(blocks)
                    self._free.append(slot)
                    self._update_kv_gauges_locked()
                    continue
                row = self._tables_host[slot]
                row[:] = self.kv_blocks
                row[: parked.n_live] = blocks
                self._tables_dirty = True
                self._host.alloc.decref(parked.host_blocks)
                state.park_immune = True
                self._slots[slot] = state
                if not self._warming:
                    dt = time.monotonic() - t0
                    self.kv_unparks += 1
                    self.kv_promotions += parked.n_cov
                    self.kv_promote_bytes += (
                        parked.n_cov * self._block_bytes
                    )
                    self.kv_promote_seconds += dt
                    self._promote_walls.append(dt)
                    self._m_tier_moves.inc(
                        "promote", by=float(parked.n_cov)
                    )
                    self._m_tier_bytes.inc(
                        "promote",
                        by=float(parked.n_cov * self._block_bytes),
                    )
                    self._m_tier_seconds.inc("promote", by=dt)
                self._update_kv_gauges_locked()
                self._m_active.set(
                    float(len(self._slots)), self._engine_label
                )

    def _write_host_payload(self, host_blocks, dev_blocks) -> None:
        """Write host-tier blocks back into the device pool (driver
        thread): one warmup-precompiled ``_ingest`` per block, chained
        through ``self._cache`` so the device stream orders the
        promote ahead of everything dispatched after it."""
        dummy = jnp.zeros((1,), jnp.float32)
        quant = self._host.k_scale is not None
        for hb, dst in zip(host_blocks, dev_blocks):
            self._cache = self._ingest(
                self._cache,
                jnp.asarray(self._host.k[:, hb]),
                jnp.asarray(self._host.v[:, hb]),
                jnp.asarray(self._host.k_scale[:, hb]) if quant else dummy,
                jnp.asarray(self._host.v_scale[:, hb]) if quant else dummy,
                jnp.int32(dst),
            )

    def _restore_slot_state(
        self, slot: int, state: "_SlotState", rows: int
    ) -> None:
        """Rebuild one restored slot's per-slot DEVICE state from host
        truth (driver thread): the cache frontier, the spec-decode
        history row, and the penalty occurrence rows — everything the
        next fresh dispatch reads besides the KV blocks themselves.
        Sampling needs nothing: the PRNG base is PRNGKey(req.seed) and
        the key index is the host-side emitted count, so a restored
        sampled stream continues exactly where it paused."""
        tokens = list(state.req.tokens) + list(state.emitted)
        track = bool(self.spec_decode) and self.draft_cfg is None
        if track:
            hist = np.zeros((self.max_len,), np.int32)
            hist[: len(tokens)] = tokens
            hist_row = jnp.asarray(hist)
        else:
            hist_row = self._restore_dummy_row
        if self.penalties:
            tok_row = jnp.asarray(np.bincount(
                tokens, minlength=self.cfg.vocab_size
            ).astype(np.int32))
            gen_row = jnp.asarray(np.bincount(
                state.emitted, minlength=self.cfg.vocab_size
            ).astype(np.int32))
        else:
            tok_row = gen_row = self._restore_dummy_row
        (
            self._cache, self._history,
            self._tok_counts, self._gen_counts,
        ) = self._restore(
            self._cache, self._history,
            self._tok_counts, self._gen_counts,
            jnp.int32(slot), jnp.int32(rows),
            hist_row, tok_row, gen_row,
        )

    # -- disaggregated prefill/decode: KV export/ingest (ISSUE 12) --------

    def kv_geometry(self) -> dict:
        """The geometry contract a KV ship must match exactly
        (serve/disagg.py ``validate_geometry``): shipping between
        heterogeneous replicas is refused at the manifest, before any
        payload moves."""
        return {
            "n_layers": self.cfg.n_layers,
            "kv_heads": self.cfg.kv_heads,
            "head_dim": self.cfg.head_dim,
            "block_size": self.kv_block,
            "kv_int8": self.kv_int8,
            "dtype": str(self._cache.k.dtype),
        }

    def _hold_kv_locked(self, slot: int, state: _SlotState) -> None:
        """Retain a finishing hold_kv request's KV for export (lock
        held, called by _finish_locked BEFORE the slot's blocks release): one
        extra ref on every block the valid rows cover, recorded under
        the rid with a TTL.  The frontier is ``tokens - 1`` rows — the
        last emitted token has no cache row yet, exactly the state a
        continuation prefill expects to extend."""
        if self.kv_int4:
            return  # kv4 pools don't ship: holding would pin for nothing
        tokens = list(state.req.tokens) + list(state.emitted)
        rows = len(tokens) - 1
        if rows < 1:
            return
        n_ship = -(-rows // self.kv_block)
        row = self._tables_host[slot]
        blocks = tuple(int(b) for b in row[:n_ship])
        if any(b >= self.kv_blocks for b in blocks):
            return  # abort() sentineled the row mid-wave: nothing held
        now = time.monotonic()
        self._sweep_kv_holds_locked(now)
        while len(self._kv_holds) >= KV_HOLD_MAX:
            # Oldest evicted first: a flood of prefill legs must never
            # pin the pool shut waiting on ships that may never come.
            _, old = min(
                self._kv_holds.items(), key=lambda kv: kv[1].t_created
            )
            self._release_kv_hold_locked(old.rid)
        self._alloc.incref(blocks)
        req = state.req
        self._kv_holds[state.rid] = KvHold(
            rid=state.rid,
            blocks=blocks,
            rows=rows,
            prompt_tokens=list(req.tokens),
            tokens=list(state.emitted),
            sampling={
                "seed": req.seed,
                "temperature": req.temperature,
                "top_p": req.top_p,
                "min_p": req.min_p,
            },
            t_created=now,
        )
        self._update_kv_gauges_locked()

    def _release_kv_hold_locked(self, rid: int) -> bool:
        hold = self._kv_holds.pop(rid, None)
        if hold is None:
            return False
        self._alloc.decref(hold.blocks)
        self._update_kv_gauges_locked()
        return True

    def _sweep_kv_holds_locked(self, now: float) -> None:
        for rid in [
            r for r, h in self._kv_holds.items()
            if now - h.t_created > KV_HOLD_TTL_S
        ]:
            self._release_kv_hold_locked(rid)

    def _sweep_kv_imports_locked(self, now: float) -> None:
        for iid in [
            i for i, imp in self._kv_imports.items()
            if now - imp.t_created > KV_IMPORT_TTL_S
        ]:
            self._release_kv_import_locked(iid)

    def _release_kv_import_locked(self, import_id: int) -> bool:
        imp = self._kv_imports.pop(import_id, None)
        if imp is None:
            return False
        self._alloc.decref(imp.blocks)
        self._update_kv_gauges_locked()
        return True

    def release_kv_hold(self, rid: int) -> bool:
        """Drop a held export (the router's post-ship release, or the
        DELETE /v1/kv handler); idempotent."""
        if not self.paged:
            return False
        with self._lock:
            return self._release_kv_hold_locked(rid)

    def release_kv_import(self, import_id: int) -> bool:
        """Drop a staged ingest nobody will consume; idempotent."""
        if not self.paged:
            return False
        with self._lock:
            return self._release_kv_import_locked(import_id)

    def _gather_blocks(self, blocks, what: str = "") -> tuple[list, list]:
        """Read ``blocks`` out of the pool as host arrays — (leaf
        names, arrays), the shared payload read for KV-hold AND
        prefix-entry exports.  Safe from any thread: the caller
        guarantees the blocks are referenced and never written (a
        hold's own ref, a pinned prefix entry), so their contents are
        IDENTICAL in every generation of the donated cache — the read
        retries through a donation race (the driver consuming
        ``self._cache`` mid-gather) by re-snapshotting the current
        cache."""
        with self._lock:
            cache = self._cache
        ids = jnp.asarray(blocks, jnp.int32)
        names = ["k", "v"] + (
            ["k_scale", "v_scale"] if self.kv_int8 else []
        )
        for attempt in range(8):
            pools = [getattr(cache, name) for name in names]
            try:
                data = self._fetch_aux(
                    [jnp.take(pool, ids, axis=1) for pool in pools]
                )
                return names, [np.asarray(a) for a in data]
            except RuntimeError:
                # The driver donated this cache generation away while
                # the gather was being built; re-snap and retry.
                with self._lock:
                    cache = self._cache
        raise RuntimeError(
            f"KV export for {what} lost the donation race 8 times"
        )

    def export_kv(self, rid: int):
        """One held request's KV as (manifest, leaf arrays in manifest
        order) — the ``GET /v1/kv`` payload (serve/disagg.py framing).

        Safe from any thread: held blocks belong to no slot and are
        never written after the hold was taken, so their contents are
        IDENTICAL in every generation of the donated cache — the read
        retries through a donation race (the driver consuming
        ``self._cache`` mid-gather) by simply re-snapshotting the
        current cache.  Raises ``KvIneligibleError`` on a dense engine
        (the dense-ineligible guard) or an unknown/expired rid."""
        if not self.paged:
            raise KvIneligibleError(
                "KV export needs a paged engine (oim-serve --kv-block)"
            )
        if self.kv_int4:
            # kv4 pools don't ship: int4 has no stable numpy wire dtype
            # for the manifest framing, and a mixed-quant fleet would
            # refuse the geometry anyway.  The router's recompute
            # fallback covers the continuation, token-identically.
            raise KvIneligibleError("KV export unsupported on kv_int4")
        with self._lock:
            self._sweep_kv_holds_locked(time.monotonic())
            hold = self._kv_holds.get(rid)
            if hold is None:
                raise KvIneligibleError(f"no held KV for request {rid}")
        names, arrays = self._gather_blocks(hold.blocks, what=f"rid {rid}")
        leaves = [
            {
                "name": name,
                "dtype": str(arr.dtype),
                "shape": [int(d) for d in arr.shape],
            }
            for name, arr in zip(names, arrays)
        ]
        manifest = build_manifest(
            geometry=self.kv_geometry(),
            rows=hold.rows,
            prompt_tokens=hold.prompt_tokens,
            tokens=hold.tokens,
            sampling=hold.sampling,
            leaves=leaves,
        )
        total = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.kv_exports += 1
            self.kv_ship_bytes += total
        return manifest, arrays

    def import_kv(self, manifest: dict, data: dict) -> tuple[int, int]:
        """Stage one shipped KV state for a continuation (``PUT
        /v1/kv``): geometry-validate the manifest, reserve the shipped
        block count from the pool (all-or-nothing —
        ``KvCapacityError`` is capacity backpressure, HTTP 429), and
        keep the host payload for the driver thread to scatter-write
        at the continuation's admission.  Returns (import_id, rows).
        Safe from handler threads: nothing here touches the device —
        the single-writer cache discipline stays with the driver."""
        if not self.paged:
            raise KvIneligibleError(
                "KV ingest needs a paged engine (oim-serve --kv-block)"
            )
        if self.kv_int4:
            raise KvIneligibleError("KV ingest unsupported on kv_int4")
        validate_geometry(manifest, self.kv_geometry())
        rows = int(manifest["rows"])
        tokens = [int(t) for t in manifest["prompt_tokens"]] + [
            int(t) for t in manifest["tokens"]
        ]
        n_ship = -(-rows // self.kv_block)
        if rows >= self.max_len:
            raise KvGeometryError(
                f"shipped rows {rows} exceed max_len {self.max_len}"
            )
        names = self._validate_ship_leaves(data, n_ship)
        total = sum(int(data[name].nbytes) for name in names)
        with self._lock:
            now = time.monotonic()
            self._sweep_kv_imports_locked(now)
            while len(self._kv_imports) >= KV_IMPORT_MAX:
                _, old = min(
                    self._kv_imports.items(),
                    key=lambda kv: kv[1].t_created,
                )
                self._release_kv_import_locked(old.import_id)
            blocks = self._alloc.alloc(n_ship)
            if blocks is None:
                raise KvCapacityError(
                    f"pool cannot reserve {n_ship} blocks for the "
                    f"shipped KV ({self._alloc.free_blocks} free) — "
                    f"retry or fall back to recompute"
                )
            import_id = self._next_import_id
            self._next_import_id += 1
            self._kv_imports[import_id] = KvImport(
                import_id=import_id,
                blocks=tuple(blocks),
                rows=rows,
                tokens=tokens,
                data={name: data[name] for name in names},
                t_created=now,
            )
            self.kv_imports_total += 1
            self.kv_ship_bytes += total
            self._update_kv_gauges_locked()
        return import_id, rows

    def _validate_ship_leaves(self, data: dict, n_ship: int) -> list[str]:
        """FULL leaf validation — exact shape AND dtype, not just the
        leading dims: anything less reaches the jitted ingest write on
        the DRIVER thread, where a mis-shaped update is a crash that
        latches the whole backend's error state.  A bad transfer must
        die HERE, as the 409 the protocol promises.  Shared by the
        KV-ship and prefix-entry ingests; returns the leaf names in
        manifest order."""
        from oim_tpu.serve.disagg import _np_dtype

        cfg = self.cfg
        kv_shape = (
            cfg.n_layers, n_ship, self.kv_block, cfg.kv_heads,
            cfg.head_dim,
        )
        pool_dtype = _np_dtype(str(self._cache.k.dtype))
        want = {"k": (kv_shape, pool_dtype), "v": (kv_shape, pool_dtype)}
        if self.kv_int8:
            scale_shape = kv_shape[:-1]
            want["k_scale"] = (scale_shape, np.dtype(np.float32))
            want["v_scale"] = (scale_shape, np.dtype(np.float32))
        for name, (shape, dtype) in want.items():
            arr = data.get(name)
            if (
                arr is None
                or tuple(arr.shape) != shape
                or arr.dtype != dtype
            ):
                raise KvGeometryError(
                    f"leaf {name} missing or mis-shaped/typed: want "
                    f"{shape} {dtype}, got "
                    + (
                        "nothing" if arr is None
                        else f"{tuple(arr.shape)} {arr.dtype}"
                    )
                )
        return list(want)

    # -- fleet prefix residency: prefix-entry export/ingest (ISSUE 14) ----

    def export_kv_prefix(self, digest: str):
        """One RESIDENT PREFIX ENTRY's KV as (manifest, leaf arrays) —
        the ``GET /v1/kv?prefix=<digest>`` payload: the block-aligned
        entry a sibling can install without recomputing the prefill.
        The entry's blocks are pinned (one extra ref) for the gather's
        duration — LRU eviction or an admission shortage decref'ing
        them mid-read must not free pool blocks under the fetch.
        Raises ``KvIneligibleError`` on dense/kv4 engines (the
        ship-ineligible taxonomy) or an unknown digest — the router's
        recompute path is the unconditional fallback."""
        if not self.paged:
            raise KvIneligibleError(
                "prefix export needs a paged engine (oim-serve "
                "--kv-block)"
            )
        if self.kv_int4:
            raise KvIneligibleError(
                "prefix export unsupported on kv_int4"
            )
        with self._lock:
            for key, (blocks, _) in self._prefix_cache.items():
                meta = self._prefix_meta.get(key)
                if meta is not None and meta["digest"] == digest:
                    covered = meta["covered"]
                    entry_blocks = tuple(blocks)
                    tokens = [int(t) for t in key[:covered]]
                    break
            else:
                raise KvIneligibleError(
                    f"no resident prefix {digest!r}"
                )
            self._alloc.incref(entry_blocks)  # pin for the gather
        try:
            names, arrays = self._gather_blocks(
                entry_blocks, what=f"prefix {digest}"
            )
        finally:
            with self._lock:
                self._alloc.decref(entry_blocks)
                self._update_kv_gauges_locked()
        leaves = [
            {
                "name": name,
                "dtype": str(arr.dtype),
                "shape": [int(d) for d in arr.shape],
            }
            for name, arr in zip(names, arrays)
        ]
        manifest = build_manifest(
            geometry=self.kv_geometry(),
            rows=covered,
            prompt_tokens=tokens,
            tokens=[],
            sampling={},
            leaves=leaves,
        )
        manifest["prefix"] = digest
        total = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.prefix_exports += 1
            self.kv_ship_bytes += total
        return manifest, arrays

    def import_kv_prefix(self, manifest: dict, data: dict) -> tuple[str, int]:
        """Stage one shipped PREFIX ENTRY for installation (``PUT
        /v1/kv`` with a prefix manifest): geometry-validate (the digest
        ↔ token-record consistency rides ``validate_geometry``),
        reserve the entry's blocks all-or-nothing (``KvCapacityError``
        = 429 backpressure, idle entries evicted first like every
        planner), and keep the payload host-side for the DRIVER thread
        to land (``install_prefix_imports``) — the single-writer cache
        discipline, exactly like KV-ship ingests.  Returns (digest,
        rows); rows 0 = already resident (idempotent: re-shipping a
        resident prefix is success, not an error).  kv4 pools keep
        refusing ships."""
        if not self.paged:
            raise KvIneligibleError(
                "prefix ingest needs a paged engine (oim-serve "
                "--kv-block)"
            )
        if self.kv_int4:
            raise KvIneligibleError(
                "prefix ingest unsupported on kv_int4"
            )
        if not self.prefix_cache_size:
            raise KvIneligibleError(
                "no prefix cache on this backend (oim-serve "
                "--prefix-cache)"
            )
        digest = manifest.get("prefix")
        if not digest:
            raise KvGeometryError("manifest is not a prefix transfer")
        validate_geometry(manifest, self.kv_geometry())
        rows = int(manifest["rows"])
        if rows % self.kv_block:
            raise KvGeometryError(
                f"prefix rows {rows} not block-aligned "
                f"(block_size {self.kv_block})"
            )
        if rows >= self.max_len:
            raise KvGeometryError(
                f"shipped rows {rows} exceed max_len {self.max_len}"
            )
        tokens = [int(t) for t in manifest["prompt_tokens"]]
        n_ship = rows // self.kv_block
        names = self._validate_ship_leaves(data, n_ship)
        total = sum(int(data[name].nbytes) for name in names)
        with self._lock:
            key = tuple(tokens)
            if key in self._prefix_cache or any(
                tuple(st.tokens) == key
                for _, st, _ in self._prefix_installs
            ):
                return digest, 0  # already resident/staged: idempotent
            now = time.monotonic()
            self._sweep_prefix_installs_locked(now)
            while len(self._prefix_installs) >= PREFIX_IMPORT_MAX:
                _, old, _ = self._prefix_installs.pop(0)  # oldest first
                self._alloc.decref(old.blocks)
            if n_ship > self._alloc.free_blocks:
                self._evict_prefix_for_locked(n_ship)
            blocks = self._alloc.alloc(n_ship)
            if blocks is None:
                raise KvCapacityError(
                    f"pool cannot reserve {n_ship} blocks for the "
                    f"shipped prefix ({self._alloc.free_blocks} free) "
                    f"— retry or fall back to recompute"
                )
            self._prefix_installs.append((digest, KvImport(
                import_id=-1,  # prefix installs are digest-addressed
                blocks=tuple(blocks),
                rows=rows,
                tokens=tokens,
                data={name: data[name] for name in names},
                t_created=now,
            ), None))
            self.kv_ship_bytes += total
            self._update_kv_gauges_locked()
        return digest, rows

    def _sweep_prefix_installs_locked(self, now: float) -> None:
        """TTL the staged prefix installs (lock held): an orchestrator
        that died between PUT and the next admission boundary leaks
        zero blocks past the TTL."""
        keep = []
        for digest, st, promote_key in self._prefix_installs:
            if now - st.t_created > PREFIX_IMPORT_TTL_S:
                # A TTL'd PROMOTE loses only its staged copy — the
                # demoted entry is still resident in the host tier.
                self._alloc.decref(st.blocks)
            else:
                keep.append((digest, st, promote_key))
        if len(keep) != len(self._prefix_installs):
            self._prefix_installs = keep
            self._update_kv_gauges_locked()

    def install_prefix_imports(self) -> int:
        """Land every staged prefix payload in the pool and make the
        entries visible — returns the number installed.  MUST run on
        the thread that owns the device cache (the driver thread's
        admission boundary in ``_admit_wave``; or the bring-up thread
        before the serve loop starts — the pre-warm path): each block
        writes through the warmup-precompiled ``_ingest`` program,
        chained through ``self._cache`` so the device stream orders
        install → any later prefill that aliases the entry.  Zero
        steady-state compiles by construction (the jit-guard pin)."""
        if not self.paged:
            return 0
        with self._lock:
            if not self._prefix_installs:
                return 0
            staged, self._prefix_installs = self._prefix_installs, []
        installed = 0
        for digest, st, promote_key in staged:
            t0 = time.monotonic()
            self._write_import_blocks(st)
            with self._lock:
                key = tuple(st.tokens)
                if key in self._prefix_cache:
                    # A local store for the same prompt raced the ship:
                    # keep the resident entry, return the staged blocks.
                    self._alloc.decref(st.blocks)
                else:
                    self._prefix_cache[key] = (tuple(st.blocks), st.rows)
                    origin = "fetched"
                    if promote_key is not None:
                        # Host-tier promotion: the entry keeps its
                        # original origin — a promoted local entry is
                        # still local traffic's prefill, not a sibling
                        # ship.
                        origin = (
                            self._host_meta.get(promote_key, {})
                            .get("origin", "local")
                        )
                    self._set_prefix_meta_locked(key, st.rows, origin)
                    while len(self._prefix_cache) > self.prefix_cache_size:
                        ev_key = next(iter(self._prefix_cache))
                        ev_entry, ev_rows = self._prefix_cache[ev_key]
                        self._retire_prefix_entry_locked(
                            ev_key, ev_entry, ev_rows
                        )
                    if promote_key is None:
                        self.prefix_fetch_installs += 1
                    installed += 1
                if promote_key is not None:
                    # Promotion landed (or lost a race to a local
                    # store, same outcome — the prefix is device-
                    # resident): the host copy is redundant now, so
                    # its budget frees for the next demotion.
                    host = self._host_prefix.pop(promote_key, None)
                    if host is not None:
                        self._host_meta.pop(promote_key, None)
                        self._host.alloc.decref(host[0])
                    if not self._warming:
                        dt = time.monotonic() - t0
                        n = len(st.blocks)
                        self.kv_promotions += n
                        self.kv_promote_bytes += n * self._block_bytes
                        self.kv_promote_seconds += dt
                        self._promote_walls.append(dt)
                        self._m_tier_moves.inc("promote", by=float(n))
                        self._m_tier_bytes.inc(
                            "promote", by=float(n * self._block_bytes)
                        )
                        self._m_tier_seconds.inc("promote", by=dt)
                self._update_kv_gauges_locked()
        return installed

    # -- live slot migration: suspend/export/import (ISSUE 17) ------------

    def begin_migrate_out(self) -> None:
        """Enter migrate-out drain: stop admitting (``submit`` raises
        DrainingError, like ``drain()``) AND have the driver suspend
        every queued, active, and parked request at the next step
        boundary into "migrated" failures — active slots leaving a
        SlotRecord behind for ``GET /v1/slot`` so the router can
        resume them on a sibling with zero recompute.  Idempotent;
        safe from any thread (the wave itself runs on the driver)."""
        with self._lock:
            self._draining = True
            self._migrate_out = True

    def _slot_meta_locked(self, state: "_SlotState", now: float) -> dict:
        """The manifest's ``"slot"`` branch for one suspended request
        (lock held): the GLOBAL sampling offset (this backend's
        emitted count on top of whatever offset the request already
        carried — a re-migrated continuation accumulates), the
        deadline remainder in ms, tenant/tier, and trace context.
        Spec-decode history needs no field: the admission path
        rebuilds it from the full token record the manifest already
        carries."""
        req = state.req
        tenant = req.tenant or "anon"
        return {
            "sample_base": len(state.emitted) + req.sample_base,
            "deadline_ms": (
                int(max(0.0, req.deadline - now) * 1000)
                if req.deadline is not None else None
            ),
            "tenant": tenant,
            "tier": self._qos_lookup(tenant).tier,
            "trace": req.span.traceparent() if req.span else None,
        }

    def _capture_slot_locked(
        self, slot: int, state: "_SlotState", now: float
    ) -> bool:
        """Mint one active slot's migration record (lock held, driver
        thread, BEFORE the slot's blocks release): one extra ref on
        every block the valid rows cover — ``_hold_kv_locked``'s
        frontier shape (rows = tokens - 1) and its in-flight-chunk
        safety argument verbatim: a chained chunk only writes rows at
        or beyond this frontier, the refs keep the blocks from
        reallocation, and the importer masks garbage beyond ``rows``.
        Returns False on ineligible state (dense, kv4, a sentineled
        table, nothing decoded yet) — the router's splice-recompute
        fallback covers those, so no capture is ever load-bearing."""
        if not self.paged or self.kv_int4:
            return False
        rows = len(state.req.tokens) + len(state.emitted) - 1
        if rows < 1:
            return False
        n_ship = -(-rows // self.kv_block)
        row = self._tables_host[slot]
        blocks = tuple(int(b) for b in row[:n_ship])
        if any(b >= self.kv_blocks for b in blocks):
            return False  # abort() sentineled the row mid-wave
        self._alloc.incref(blocks)
        self._migrated[state.rid] = SlotRecord(
            rid=state.rid,
            blocks=blocks,
            host_blocks=(),
            rows=rows,
            prompt_tokens=list(state.req.tokens),
            tokens=list(state.emitted),
            sampling={
                "seed": state.req.seed,
                "temperature": state.req.temperature,
                "top_p": state.req.top_p,
                "min_p": state.req.min_p,
            },
            meta=self._slot_meta_locked(state, now),
            t_created=now,
        )
        self._update_kv_gauges_locked()
        return True

    def _migrate_wave(self) -> None:
        """Suspend everything for migrate-out (driver thread, step
        start, right after ``_reap`` — the same pop/fail/collect-
        callbacks shape).  Queued entries fail "migrated" with no
        record (nothing is admitted yet; the router's fallback
        resubmits from scratch, token-identical).  Active slots are
        captured premium-first (the QoS migration order: the router
        sees premium migrate markers first and ships them first),
        then freed and failed.  Ready parked slots transfer their
        host payload to a record wholesale — no device traffic at
        all; a parked slot whose tier write is still in flight stays
        parked, and the armed wave takes it on a later step once
        ``_complete_host_writes`` marks it ready."""
        ended = []
        now = time.monotonic()
        with self._lock:
            if not self._migrate_out:
                return
            if not (
                self._queue or self._slots or self._parked
                or self._prefilling
            ):
                return
            for rid, req, t_sub in self._queue:
                self._fail_locked(
                    rid, "migrated",
                    "backend draining before admission",
                    req=req, t_submit=t_sub,
                )
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            if self._queue:
                self._queue.clear()
                self._m_queued.set(0.0, self._engine_label)
            # Mid-prefill long prompts (ISSUE 20) have no emitted
            # tokens and no complete KV to capture: fail them like
            # queued entries (sibling recomputes token-identically)
            # and reclaim slot + blocks.
            for rid in list(self._prefilling):
                pend = self._prefilling.pop(rid)
                self._admitting.pop(rid, None)
                self._free.append(pend.slot)
                self._release_slot_blocks_locked(pend.slot)
                self._fail_locked(
                    rid, "migrated",
                    (
                        f"backend draining mid-prefill "
                        f"({pend.trace.prefill_segments} segments "
                        f"written; recompute on a sibling)"
                    ),
                    req=pend.req, t_submit=pend.t_submit,
                )
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            order = sorted(
                self._slots.items(),
                key=lambda kv: (
                    -self._qos_lookup(
                        kv[1].req.tenant or "anon"
                    ).priority,
                    kv[1].t_submit,
                ),
            )
            for slot, state in order:
                captured = self._capture_slot_locked(slot, state, now)
                self._slots.pop(slot)
                self._free.append(slot)
                self._release_slot_blocks_locked(slot)
                self._fail_locked(
                    state.rid, "migrated",
                    (
                        f"suspended after {len(state.emitted)} tokens "
                        f"(KV captured for /v1/slot)"
                        if captured else
                        f"suspended after {len(state.emitted)} tokens "
                        f"(no capture: recompute on a sibling)"
                    ),
                    state=state,
                )
                cb = self._callbacks.pop(state.rid, None)
                if cb is not None:
                    ended.append(cb)
            for rid in [
                r for r, p in self._parked.items()
                if p.ready and not p.restoring
            ]:
                parked = self._parked.pop(rid)
                state = parked.state
                if not self.kv_int4:
                    # Ownership transfer, not a copy: the record now
                    # owns the parked host blocks and their refs —
                    # export reads them straight off the host pool.
                    self._migrated[rid] = SlotRecord(
                        rid=rid,
                        blocks=(),
                        host_blocks=parked.host_blocks,
                        rows=parked.rows,
                        prompt_tokens=list(state.req.tokens),
                        tokens=list(state.emitted),
                        sampling={
                            "seed": state.req.seed,
                            "temperature": state.req.temperature,
                            "top_p": state.req.top_p,
                            "min_p": state.req.min_p,
                        },
                        meta=self._slot_meta_locked(state, now),
                        t_created=now,
                    )
                    msg = (
                        f"suspended while parked "
                        f"({len(state.emitted)} tokens; host payload "
                        f"captured for /v1/slot)"
                    )
                else:
                    # kv4 never ships (no wire dtype): return the host
                    # blocks and let the fallback recompute.
                    self._host.alloc.decref(parked.host_blocks)
                    msg = (
                        f"suspended while parked "
                        f"({len(state.emitted)} tokens; kv4 payload "
                        f"not shippable — recompute on a sibling)"
                    )
                self._fail_locked(rid, "migrated", msg, state=state)
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            self._update_kv_gauges_locked()
            self._m_active.set(float(len(self._slots)), self._engine_label)
        self._drain_fail_obs()
        for cb in ended:  # end-of-stream outside the lock
            cb(None, None)

    def _sweep_migrated_locked(self, now: float) -> None:
        for rid in [
            r for r, rec in self._migrated.items()
            if now - rec.t_created > MIGRATE_TTL_S
        ]:
            self._release_migrated_locked(rid)

    def _release_migrated_locked(self, rid: int) -> bool:
        rec = self._migrated.pop(rid, None)
        if rec is None:
            return False
        if rec.blocks:
            self._alloc.decref(rec.blocks)
        if rec.host_blocks and self._host is not None:
            self._host.alloc.decref(rec.host_blocks)
        self._update_kv_gauges_locked()
        return True

    def release_migrated(self, rid: int) -> bool:
        """Drop a suspended-slot record (the router's post-ship
        release, or the DELETE /v1/slot handler); idempotent."""
        if not self.paged:
            return False
        with self._lock:
            return self._release_migrated_locked(rid)

    def export_slot(self, rid: int):
        """One suspended slot's full request state as (manifest, leaf
        arrays in manifest order) — the ``GET /v1/slot`` payload:
        the PR 12 KV framing plus the ``"slot"`` manifest branch.
        Device-captured records gather through ``_gather_blocks``
        (safe from handler threads: the record's refs pin the blocks,
        and any in-flight writes land beyond ``rows`` — masked by the
        importer); parked records read the host pool directly, no
        device traffic at all.  Raises ``KvIneligibleError`` on a
        dense/kv4 engine or an unknown/expired rid."""
        if not self.paged:
            raise KvIneligibleError(
                "slot export needs a paged engine (oim-serve --kv-block)"
            )
        if self.kv_int4:
            raise KvIneligibleError("slot export unsupported on kv_int4")
        with self._lock:
            self._sweep_migrated_locked(time.monotonic())
            rec = self._migrated.get(rid)
            if rec is None:
                raise KvIneligibleError(
                    f"no migrated slot for request {rid}"
                )
        if rec.host_blocks:
            names = ["k", "v"] + (
                ["k_scale", "v_scale"] if self.kv_int8 else []
            )
            ids = list(rec.host_blocks)
            # The host pool mirrors the device leaf layout (axis 1 =
            # blocks), so the gather lands in the exact wire shape
            # [n_layers, n_ship, bs, kvh, hd].  Rows are stable: only
            # the driver writes host blocks, and this record's refs
            # (transferred from the parked slot) keep them allocated.
            arrays = [
                np.ascontiguousarray(
                    np.take(getattr(self._host, name), ids, axis=1)
                )
                for name in names
            ]
        else:
            names, arrays = self._gather_blocks(
                rec.blocks, what=f"slot rid {rid}"
            )
        leaves = [
            {
                "name": name,
                "dtype": str(arr.dtype),
                "shape": [int(d) for d in arr.shape],
            }
            for name, arr in zip(names, arrays)
        ]
        manifest = build_manifest(
            geometry=self.kv_geometry(),
            rows=rec.rows,
            prompt_tokens=rec.prompt_tokens,
            tokens=rec.tokens,
            sampling=rec.sampling,
            leaves=leaves,
        )
        manifest["slot"] = dict(rec.meta)
        total = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.slot_exports += 1
            self.kv_ship_bytes += total
        return manifest, arrays

    def import_slot(self, manifest: dict, data: dict):
        """Stage one shipped slot state (``PUT /v1/slot``): the
        ``import_kv`` staging path verbatim — the continuation
        consumes it through the same ``kv_import`` admission, tail
        prefill, and warmup-precompiled ingest writes, so migration
        costs zero steady-state compiles — plus the slot-branch
        check.  Returns (import_id, rows, slot branch)."""
        slot_meta = manifest.get("slot")
        if not isinstance(slot_meta, dict):
            raise KvGeometryError(
                "not a slot manifest (no slot branch)"
            )
        import_id, rows = self.import_kv(manifest, data)
        with self._lock:
            self.slot_imports += 1
        return import_id, rows, slot_meta

    def _plan_import_admission_locked(self, req: GenRequest, imp: KvImport):
        """Admission plan for a staged-import continuation (lock
        held): the shipped blocks become the slot's leading table
        entries (refs transfer — no aliasing, no CoW: the import owns
        them exclusively), the tail prefill starts at the shipped
        frontier, and fresh blocks cover the rest of the worst case.
        All-or-nothing like the prefix planner: a shortfall leaves the
        request QUEUED with the import still staged (its TTL bounds
        how long it can pin the pool)."""
        bs = self.kv_block
        start = imp.rows
        needed_rows = self._worst_case_rows(
            len(req.tokens), req.max_new_tokens, start
        )
        fresh_needed = max(0, -(-needed_rows // bs) - len(imp.blocks))
        if fresh_needed > self._alloc.free_blocks:
            self._evict_prefix_for_locked(fresh_needed)
        fresh = self._alloc.alloc(fresh_needed)
        if fresh is None:
            if not self._warming:
                self.kv_admit_deferrals += 1
            return None
        # Consumed: the slot's release path owns the decrefs from here.
        self._kv_imports.pop(imp.import_id, None)
        return {
            "start": start,
            "blocks": list(imp.blocks) + fresh,
            "cow": None,
            "ingest": imp,
        }

    def _write_import_blocks(self, imp: KvImport) -> None:
        """Land a consumed import's payload in the pool (driver
        thread, admission path): one jitted write per shipped block,
        chained through ``self._cache`` BEFORE the continuation's
        prefill dispatch so the single device stream orders
        import → tail prefill → decode (the CoW chaining pattern)."""
        dummy = jnp.zeros((1,), jnp.float32)
        # Scales ride whenever the pool carries them: int8 for KV-ship
        # ingests, int8 OR int4 for host-tier promotions (kv4 never
        # ships, but it demotes/promotes locally — same process, no
        # wire dtype to worry about).
        quant = self._cache.k_scale is not None
        for j, dst in enumerate(imp.blocks):
            kb = jnp.asarray(imp.data["k"][:, j])
            vb = jnp.asarray(imp.data["v"][:, j])
            ksb = (
                jnp.asarray(imp.data["k_scale"][:, j])
                if quant else dummy
            )
            vsb = (
                jnp.asarray(imp.data["v_scale"][:, j])
                if quant else dummy
            )
            self._cache = self._ingest(
                self._cache, kb, vb, ksb, vsb, jnp.int32(dst)
            )

    # oimlint: hotpath
    def _prefill_segment(
        self, slot: int, req, seg, start: int, plan: dict | None = None,
    ) -> None:
        """One non-final chunked-prefill dispatch: write ``seg``'s KV
        rows for ``slot`` at position ``start`` through the SAME jitted
        admit program (one active row, padding rows inert) and discard
        the sampled token — the final segment's normal group dispatch
        samples for real and overwrites the penalty/length bookkeeping
        this call touches (idempotent by construction).  No readback:
        the discarded sample is never fetched.  ``plan`` (paged) holds
        the slot's reserved blocks; every segment's window lies inside
        them (needed_rows covers the final bucket end)."""
        n_slots = self._cache.n_slots
        max_len = self.max_len
        bucket = self._bucket(len(seg))
        if plan is not None:
            seg_tables = np.full(
                (n_slots, self._n_tables), self.kv_blocks, np.int32
            )
            seg_tables[0, : len(plan["blocks"])] = plan["blocks"]
            seg_tables = jnp.asarray(seg_tables)
        else:
            seg_tables = self._tables_dummy
        prompts = np.zeros((n_slots, bucket), np.int32)
        prompts[0, : len(seg)] = seg
        full_rows = np.zeros(
            (n_slots, max_len)
            if (self.spec_decode and self.draft_cfg is None)
            else (1, 1),
            np.int32,
        )
        if self.spec_decode and self.draft_cfg is None:
            full_rows[0, : len(req.tokens)] = req.tokens
        slot_idx = np.full((n_slots,), n_slots, np.int32)
        slot_idx[0] = slot
        starts = np.zeros((n_slots,), np.int32)
        starts[0] = start
        tails = np.ones((n_slots,), np.int32)
        tails[0] = len(seg)
        (
            self._cache, self._history,
            self._tok_counts, self._gen_counts,
            _first, _lp,
        ) = self._admit(
            self.params,
            self._cache,
            seg_tables,
            self._history,
            self._tok_counts,
            self._gen_counts,
            # Hoisted constants (__init__): the neutral prompt counts,
            # sampling rows, and filler keys are identical every
            # segment and ride non-donated positions.
            self._seg_zero_counts,
            (
                self._seg_zero_rows
                if full_rows.shape == (1, 1)
                else jnp.asarray(full_rows)
            ),
            jnp.asarray(prompts),
            jnp.asarray(slot_idx),
            jnp.asarray(starts),
            jnp.asarray(tails),
            *self._seg_sampling,  # temps/top_ps/min_ps/reps/press/freqs
            self._zero_keys,
        )

    def _fetch(self, tree, acc: list):
        """jax.device_get with the wait attributed to the caller's
        readback accumulator (device execution + tunnel rtt);
        everything else in step() is host time minus the dispatch-wait
        split.  The split adjudicates the serving swing.  ``acc`` is
        step()'s PER-CALL accumulator — local state, so a second
        concurrent step() cannot corrupt the attribution.  A fetch that
        runs while another chunk is already dispatched also counts
        toward ``overlap_seconds`` — readback wall time the device
        computed through rather than idled through — and a fetch with
        NOTHING dispatched starts the device-idle clock the next
        dispatch stops."""
        overlapped = self._inflight is not None
        self._watch_begin()
        t0 = time.monotonic()
        out = jax.device_get(tree)
        t1 = time.monotonic()
        self._watch_end()
        acc[0] += t1 - t0
        if not self._warming:
            if overlapped:
                # Deferred to step()'s finally (same lock-held commit as
                # the fetch-wait denominator) so a stats() scrape never
                # sees the numerator ahead of it — overlap_ratio stays
                # a [0,1] fraction even mid-step.
                acc[2] += t1 - t0
            else:
                self._t_device_free = t1
        return out

    def _fetch_aux(self, tree):
        """Readback accounting for the slot-free surfaces (embed/beam):
        same accumulators as step()'s ``_fetch`` — a tunneled
        deployment pays the same rtt for these, so hiding them from
        ``readbacks``/``readback_seconds`` skewed the swing forensics —
        but lock-guarded, because embed/beam run on server handler
        threads concurrent with the driver."""
        t0 = time.monotonic()
        out = jax.device_get(tree)
        dt = time.monotonic() - t0
        if not self._warming:
            with self._lock:
                self.readbacks += 1
                self.readback_seconds += dt
        return out

    def _watch_begin(self) -> None:
        """Open a device-wait window for the stall watchdog: the driver
        thread is about to block handing work to (or fetching from) the
        device.  The watchdog thread reads the instant under the same
        lock; a window left open past a multiple of the chunk-wall EWMA
        is a stall (device hang / XLA wedge)."""
        with self._lock:
            self._device_wait_since = time.monotonic()

    def _watch_end(self) -> None:
        with self._lock:
            self._device_wait_since = None

    def watchdog_state(self) -> tuple[float | None, float | None]:
        """(seconds the driver has been blocked in the current device
        wait — None when not blocked, typical chunk wall EWMA — None
        until the first chunk completes).  The stall watchdog's whole
        read surface; safe from any thread."""
        now = time.monotonic()
        with self._lock:
            since = self._device_wait_since
            return (
                None if since is None else max(0.0, now - since),
                self._chunk_wall_ewma,
            )

    def retry_after_s(self) -> int:
        """Back-off hint for 429/503 responses: estimated seconds until
        the current backlog (queued + active remaining token budgets)
        drains at the observed marginal token rate.  Conservative
        default of 5 s before any chunk has been processed; clamped to
        [1, 120] so a cold or wedged engine never tells clients to go
        away for an hour."""
        with self._lock:
            backlog = sum(
                req.max_new_tokens for _, req, _ in self._queue
            ) + sum(
                max(0, s.req.max_new_tokens - len(s.emitted))
                for s in self._slots.values()
            ) + sum(
                max(0, p.state.req.max_new_tokens - len(p.state.emitted))
                for p in self._parked.values()
            )
            rate = self._token_rate_ewma
        if rate is None or rate <= 0.0:
            return 5
        return max(1, min(120, int(backlog / rate) + 1))

    def _mark_dispatch(self, t0: float, acc: list) -> None:
        """Close one jitted-enqueue window: wall time since ``t0`` is
        dispatch-wait, and any open device-idle window ends at ``t0``
        (the device has work again)."""
        now = time.monotonic()
        acc[1] += now - t0
        # _t_device_free is driver-thread-only state (decl comment); the
        # one locked write is abort()'s quiesce, which only runs against
        # a wedged or dead driver — no concurrent check-then-act here.
        if self._t_device_free is not None:  # oimlint: disable=atomicity
            if not self._warming:
                idle = max(0.0, t0 - self._t_device_free)
                self.device_idle_seconds += idle
                self._m_device_idle.inc(self._engine_label, by=idle)
            self._t_device_free = None

    def _clear_idle_clock_if_drained(self) -> None:
        """Out of work entirely (no active slots, nothing queued, no
        chunk in flight): the chip is idle because there is nothing to
        run, not because the host held it up.  Stop the device-idle
        clock so the next admission's ``_mark_dispatch`` doesn't book a
        no-traffic lull as wasted chip time — ``device_idle_seconds``
        must rank replicas by host-induced stall, not by light load."""
        if self._inflight is not None:
            return
        with self._lock:
            drained = not self._slots and not self._queue
        if drained:
            self._t_device_free = None

    def step(self) -> None:
        """Admit whatever fits, then decode one chunk for active slots
        (the full contract is on ``_step_inner``), accumulating the
        host / dispatch-wait / fetch-wait wall split for the swing
        forensics."""
        t0 = time.monotonic()
        acc = [0.0, 0.0, 0.0]  # [fetch-wait, dispatch-wait, overlapped]
        try:
            self._step_inner(acc)
        except Exception as exc:
            # Latch the crash and fail everything NOW: a result() waiter
            # must never depend on whoever owns the driver thread
            # remembering to call abort() — a direct embedder's crashed
            # loop would otherwise strand waiters forever.  Later
            # submits fail fast with EngineFailedError.
            message = f"driver step failed: {type(exc).__name__}: {exc}"
            # The raise may have escaped from inside an open watchdog
            # window (_fetch / an admit or decode dispatch): close it,
            # or the watchdog would read an ever-growing device wait
            # from a call that already returned (by raising) and file a
            # bogus stall verdict on top of the real crash.
            self._watch_end()
            with self._lock:
                if self._fatal is None:
                    self._fatal = message
            self.abort(message)
            raise
        finally:
            if not self._warming:
                # Lock-held: _fetch_aux (embed/beam on server handler
                # threads) adds to readback_seconds concurrently, and an
                # unlocked += here would lose its increment.
                with self._lock:
                    self.readback_seconds += acc[0]
                    self.decode_readback_seconds += acc[0]
                    self.dispatch_seconds += acc[1]
                    self.overlap_seconds += acc[2]
                    self.host_seconds += (
                        time.monotonic() - t0 - acc[0] - acc[1]
                    )
                    total = self.decode_readback_seconds
                    ratio = (
                        self.overlap_seconds / total if total > 0 else 0.0
                    )
                self._m_overlap.set(ratio, self._engine_label)

    def _step_inner(self, acc: list) -> None:  # oimlint: hotpath
        """One engine step: reconcile the pipeline, admit, dispatch,
        emit.

        At ``pipeline_depth`` 2 (the default) the step dispatches chunk
        N+1 against the donated cache BEFORE reading back chunk N, so
        device compute for the next chunk overlaps host readback, EOS
        truncation, detokenization, and streaming emission for the
        previous one.  Exactness is preserved by construction: a
        chained dispatch takes its tokens from the device-side carry
        (``next_tok``) and its PRNG counts from ``counts + chunk`` —
        both identical to what the serial engine would send for every
        slot whose output is consumed (a slot that finished meanwhile
        keeps computing inside its own cache region and the host
        truncates, the engine's existing EOS-lags-one-chunk contract
        extended by exactly one pipeline stage).

        Admissions join at PIPELINE BOUNDARIES: a slot freed by chunk
        N's EOS may only be re-prefilled after the in-flight chunk that
        still references it completes, so a step with queued work AND a
        slot to put it in first completes the outstanding dispatch,
        then admits.  Queued work with no free slot does NOT force a
        boundary — a saturated engine would otherwise run fully serial
        exactly when the overlap matters most; the step that frees a
        slot makes the next step a boundary, costing one chunk of
        admission latency instead.  Depth 1 is the serial loop (every
        step is a boundary).

        TAIL ELISION: when every active slot's remaining token budget
        is covered by the chunk already in flight (each dispatch
        delivers at least ``chunk`` tokens per slot — plain decode
        exactly ``chunk``, speculative at least one per sub-step), the
        chained dispatch would be 100% guaranteed waste: the in-flight
        chunk finishes every slot before its output could ever be
        consumed.  Force a boundary instead — process the in-flight
        chunk, then dispatch fresh only if admissions refilled the
        batch.  EOS-truncated waste stays bounded-and-unpredictable as
        before; budget exhaustion is host-deterministic, so this waste
        is simply never dispatched.
        """
        # Land tier demotions dispatched on earlier steps (one batched
        # accumulator fetch): demoted entries become promotable and
        # parked slots restorable before this step's admission
        # boundary looks at either.
        self._complete_host_writes()
        self._reap()
        self._migrate_wave()
        with self._lock:
            elide_tail = (
                self._inflight is not None
                and self.pipeline_depth >= 2
                and all(
                    state.req.max_new_tokens - len(state.emitted)
                    <= self.chunk
                    for state in self._slots.values()
                )
            )
            admit_boundary = bool(self._queue) and (
                bool(self._free)
                # A pending priority preemption is an admission
                # opportunity too (ISSUE 16): the wave's pre-pass will
                # park a lower-tier victim to MAKE the free slot, so
                # the boundary must happen for it to run at all.
                or self._qos_preempt_pending_locked()
            )
            # A mid-prefill long prompt forces the boundary too
            # (ISSUE 20): its next segment may only dispatch from the
            # admission wave, and the wave early-returns while a chunk
            # is in flight — without this, a saturated depth-2 engine
            # would chain decode chunks forever and never finish the
            # newcomer's prefill.
            admit_boundary = admit_boundary or bool(self._prefilling)
            boundary = (
                admit_boundary or self.pipeline_depth < 2 or elide_tail
            )
            if elide_tail and not admit_boundary and not self._warming:
                # Only count when elision is the REASON for the
                # boundary — an admission boundary never chains anyway.
                self.tail_elisions += 1
        # _inflight is driver-thread pipelining state: only step() on
        # the driver thread reads or swaps it; abort()'s locked clear
        # runs only against a wedged/dead driver (watchdog contract).
        if boundary and self._inflight is not None:  # oimlint: disable=atomicity
            prev, self._inflight = self._inflight, None
            self._process_chunk(prev, acc)
        self._admit_wave(acc)
        with self._lock:
            have_slots = bool(self._slots)
        if not have_slots:
            # Every live request finished while a chunk was still in
            # flight: that chunk references only finished slots
            # (admissions join at boundaries), so drop the handle
            # unread — no emission, no readback, bounded wasted
            # compute.
            self._inflight = None
            self._clear_idle_clock_if_drained()
            return
        prev = self._inflight
        handle = self._dispatch_chunk(acc, prev)
        # Driver-thread-only _inflight handoff, same contract as above.
        if self.pipeline_depth >= 2:  # oimlint: disable=atomicity
            self._inflight = handle
            if prev is not None:
                # Chunk N's readback + emission run while the device
                # works on chunk N+1 — the overlap this pipeline
                # exists for.
                self._process_chunk(prev, acc)
            with self._lock:
                empty = not self._slots
            if empty:
                self._inflight = None  # tail chunk: dead slots only
        else:
            self._process_chunk(handle, acc)
        self._clear_idle_clock_if_drained()

    def _reap(self) -> None:
        """Fail deadline-expired and cancelled requests (driver thread,
        start of every step).  Queued entries are shed before they ever
        touch a slot (kind "deadline_queue" → HTTP 429 + Retry-After);
        active slots are freed right here, which IS the next pipeline
        boundary from the request's point of view — the in-flight
        chunk's snapshot check already skips slots whose state is gone,
        so a freed slot's post-reap garbage is never emitted, and
        admissions can only re-prefill it after that chunk completes."""
        now = time.monotonic()
        ended = []
        with self._lock:
            if self.paged and (
                self._kv_holds or self._kv_imports
                or self._prefix_installs or self._migrated
            ):
                # Drive the KV-transfer TTLs from the step loop too: a
                # ship whose orchestrator died must return its blocks
                # without waiting for the next export/ingest call.
                self._sweep_kv_holds_locked(now)
                self._sweep_kv_imports_locked(now)
                self._sweep_prefix_installs_locked(now)
                self._sweep_migrated_locked(now)
            if not (
                self._cancelled
                or any(req.deadline is not None for _, req, _ in self._queue)
                or any(
                    s.req.deadline is not None for s in self._slots.values()
                )
                or any(
                    p.state.req.deadline is not None
                    for p in self._parked.values()
                )
            ):
                return
            keep = []
            for rid, req, t_sub in self._queue:
                if rid in self._cancelled:
                    self._fail_locked(
                        rid, "cancelled", "client went away",
                        req=req, t_submit=t_sub,
                    )
                elif req.deadline is not None and now >= req.deadline:
                    if not self._warming:
                        self._m_shed.inc("deadline")
                        self._m_deadline.inc()
                        self._shed_counts["deadline"] += 1
                    self._fail_locked(
                        rid, "deadline_queue",
                        f"expired after {now - t_sub:.1f}s queued",
                        req=req, t_submit=t_sub,
                    )
                else:
                    keep.append((rid, req, t_sub))
                    continue
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            if len(keep) != len(self._queue):
                self._queue[:] = keep
                self._m_queued.set(
                    float(len(self._queue)), self._engine_label
                )
            for slot, state in list(self._slots.items()):
                if state.rid in self._cancelled:
                    kind, msg = "cancelled", "client went away mid-decode"
                elif (
                    state.req.deadline is not None
                    and now >= state.req.deadline
                ):
                    kind = "deadline"
                    msg = f"expired after {len(state.emitted)} tokens"
                    if not self._warming:
                        self._m_deadline.inc()
                else:
                    continue
                self._slots.pop(slot)
                self._free.append(slot)
                self._release_slot_blocks_locked(slot)
                self._fail_locked(state.rid, kind, msg, state=state)
                cb = self._callbacks.pop(state.rid, None)
                if cb is not None:
                    ended.append(cb)
            # Parked requests (ISSUE 15) keep their deadlines running —
            # a swap-out is invisible to the failure taxonomy, so a
            # parked victim expires/cancels exactly like an active one
            # (its host blocks return to the tier budget).
            for rid in list(self._parked):
                state = self._parked[rid].state
                if rid in self._cancelled:
                    kind, msg = "cancelled", "client went away while parked"
                elif (
                    state.req.deadline is not None
                    and now >= state.req.deadline
                ):
                    kind = "deadline"
                    msg = (
                        f"expired after {len(state.emitted)} tokens "
                        f"(parked in the host tier)"
                    )
                    if not self._warming:
                        self._m_deadline.inc()
                else:
                    continue
                self._drop_parked_locked(rid)
                self._fail_locked(rid, kind, msg, state=state)
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            self._m_active.set(float(len(self._slots)), self._engine_label)
        self._drain_fail_obs()
        for cb in ended:  # end-of-stream outside the lock
            cb(None, None)

    def _advance_prefills(self) -> "list[_PendingPrefill]":
        """One admission-boundary advance of every mid-prefill long
        prompt (ISSUE 20).  Reap cancelled/expired pendings first
        (slot and blocks freed, both tiers — the pending twin of
        _reap's active-slot loop), then dispatch exactly ONE further
        segment per pending — the pacing unit that bounds how much
        prefill work lands between two decode chunks, which is the
        whole point of interleaving.  Pendings whose segments were
        already exhausted JOIN this wave's group dispatch (the caller
        appends them to its rows) for their real first-token sample;
        they are popped from ``_prefilling`` here but stay in
        ``_admitting`` until registration, so abort() still reclaims
        them if the group dispatch dies."""
        now = time.monotonic()
        ended = []
        with self._lock:
            for rid in list(self._prefilling):
                pend = self._prefilling[rid]
                if rid in self._cancelled:
                    kind = "cancelled"
                    msg = "client went away during chunked prefill"
                elif (
                    pend.req.deadline is not None
                    and now >= pend.req.deadline
                ):
                    kind = "deadline"
                    msg = (
                        f"expired mid-prefill "
                        f"({pend.trace.prefill_segments} segments written)"
                    )
                    if not self._warming:
                        self._m_deadline.inc()
                else:
                    continue
                self._prefilling.pop(rid)
                self._admitting.pop(rid, None)
                self._free.append(pend.slot)
                self._release_slot_blocks_locked(pend.slot)
                self._fail_locked(
                    rid, kind, msg, req=pend.req, t_submit=pend.t_submit
                )
                cb = self._callbacks.pop(rid, None)
                if cb is not None:
                    ended.append(cb)
            advancing = list(self._prefilling.values())
        self._drain_fail_obs()
        for cb in ended:  # end-of-stream outside the lock
            cb(None, None)
        joining = []
        for pend in advancing:
            if not pend.segs:
                joining.append(pend)
                continue
            seg = pend.segs.pop(0)
            # Sentinel context: a recompile during this segment
            # dispatch names the request (replaced wholesale, never
            # mutated — the compile listener reads it lock-free).
            self._sentinel_ctx = {"phase": "admit", "rids": (pend.rid,)}
            t0 = time.monotonic()
            self._prefill_segment(
                pend.slot, pend.req, seg, pend.start, pend.plan
            )
            pend.trace.segment_walls.append(time.monotonic() - t0)
            pend.trace.prefill_segments += 1
            pend.start += len(seg)
            self.prefill_segments += 1
        if joining:
            with self._lock:
                for pend in joining:
                    self._prefilling.pop(pend.rid, None)
        return joining

    def _admit_wave(self, acc: list) -> None:  # oimlint: hotpath
        """Admit whatever fits into free slots.

        Admissions are BATCHED: one prefill dispatch per distinct prompt
        bucket among this step's admissions (grouping keeps every row at
        its own bucket, so a prefix-injected row can never overflow its
        slot region the way padding everything to the step-max bucket
        would), then ONE combined readback for all first tokens — on a
        tunneled deployment (~70 ms/readback) this is the difference
        between paying the tunnel once per step and once per request.
        Only admits with no chunk in flight (the pipeline-boundary
        rule): a submit() that lands between _step_inner's boundary
        check and this call must wait one step — the in-flight chunk
        still references every slot, including any freed since its
        dispatch, so admitting here would chain the new occupant onto
        the OLD occupant's token carry and sampling params.  The next
        step with a free slot for the queued work sees the boundary,
        completes the in-flight chunk, and admits.
        """
        if self._inflight is not None:
            return
        # Admission boundary = the device-write window: land any staged
        # prefix installs first (sibling ships AND host-tier
        # promotions), so a request admitted in THIS wave can already
        # alias the just-shipped entry.
        self.install_prefix_imports()
        # Parked slots restore BEFORE new admissions (ISSUE 15): the
        # victim was admitted first, and restore-priority is what
        # bounds how long a swap-out lasts once capacity returns.
        if self._parked:
            self._unpark_wave()
        # Mid-prefill long prompts advance ONE segment each, and the
        # ones whose prompt is fully written join this wave's group
        # dispatch below (ISSUE 20) — before new admissions, because
        # they were admitted first.
        joins = (
            self._advance_prefills() if self._prefilling else []
        )
        with self._lock:
            admissions = []
            # Slot-shortage priority preemption (ISSUE 16): with every
            # slot busy the loop below cannot even START, so a
            # latency-sensitive tenant would wait out a best-effort
            # flood's full streams.  Park one strictly-lower-priority
            # victim (swap, never kill — PR 15 semantics) so the
            # fair-share head gets a slot this wave.  One victim per
            # wave, mirroring the block-shortage path's gradualism.
            if (
                self._qos_policy is not None
                and self._queue
                and not self._free
            ):
                self._qos_preempt_locked()
            while self._queue and self._free:
                qi = self._qos_head_locked()
                rid, req, t_submit = self._queue[qi]
                plan = None
                if self.paged:
                    # Reserve blocks (aliasing the cached prefix) BEFORE
                    # taking the request off the queue: a pool that
                    # cannot cover the head-of-line request's worst
                    # case leaves it QUEUED — admission backpressure,
                    # exactly like a fleet with no free slot — and the
                    # blocks freed by finishing requests admit it on a
                    # later wave.  Head-of-line by design: the
                    # scheduler's ordering promise (FIFO, or the QoS
                    # fair-share pick above, which only chooses WHOSE
                    # head is at the line) beats opportunistically
                    # admitting a smaller latecomer forever.
                    imp = (
                        self._kv_imports.get(req.kv_import)
                        if req.kv_import is not None else None
                    )
                    if imp is not None:
                        # KV-ship continuation: resume at the shipped
                        # frontier.  An expired import (imp is None)
                        # falls through to the normal plan below — a
                        # recompute prefill, token-identical output.
                        plan = self._plan_import_admission_locked(req, imp)
                    else:
                        plan = self._plan_paged_admission_locked(
                            req,
                            # Nothing running, nothing admitted earlier
                            # in THIS wave: only prefix entries can
                            # ever free blocks, so the planner may
                            # sacrifice even the matched one rather
                            # than wedge the queue.
                            idle=(
                                not self._slots
                                and not self._admitting
                                and not admissions
                            ),
                        )
                        if plan is None and self._try_park_locked(req):
                            # Swap-based parking (ISSUE 15): the
                            # coldest idle slot's table moved to the
                            # host tier, freeing its blocks AND its
                            # slot — re-plan once against them.  One
                            # victim per wave per head request keeps
                            # pressure gradual; the next step can park
                            # another if the shortage persists.
                            plan = self._plan_paged_admission_locked(
                                req, idle=False,
                            )
                    if plan is None:
                        break
                self._queue.pop(qi)
                self._qos_charge_locked(req)
                slot = self._free.pop(0)
                if plan is not None:
                    self._commit_plan_locked(slot, plan)
                admissions.append((slot, rid, req, t_submit, plan))
            # Registered before any device work so abort() can fail these
            # and reclaim their slots if an admission dispatch dies.
            # update(), not assignment: entries stranded by a previous
            # step() crash must survive until abort() reclaims them.
            self._admitting.update(
                {rid: slot for slot, rid, _, _, _ in admissions}
            )
            self._m_queued.set(float(len(self._queue)), self._engine_label)

        if admissions or joins:
            # Sentinel context (ISSUE 18): replaced wholesale, never
            # mutated — the compile listener reads it lock-free, so a
            # recompile during this wave's prefill dispatches names the
            # admitted requests.
            self._sentinel_ctx = {
                "phase": "admit",
                "rids": tuple(rid for _, rid, _, _, _ in admissions)
                + tuple(p.rid for p in joins),
            }
            # Phase clock: every admission in this wave left the queue
            # at the pop above — one boundary instant serves the wave.
            t_admitted = time.monotonic()
            n_slots = self._cache.n_slots
            # (slot, rid, req, t_submit, start, tail, bucket, trace,
            #  plan)
            rows = []
            # The wave's prefill work (prefix-cache injections,
            # chunked-prefill segments, host array building, the group
            # dispatches below — host and device interleave per row)
            # starts here.  ONE boundary for the whole wave: a per-row
            # stamp taken inside the loop would book an earlier
            # wave-mate's prefill dispatches into later rows' admit
            # phase.  The admit phase is therefore the near-zero
            # scheduling slice between pop and wave start — by design;
            # admission overhead being ~0 is itself a signal.
            t_pf = time.monotonic()
            # Per-rid prefix attribution for the request ring: which
            # path produced this admission's leading KV rows —
            # "local"/"fetched" entry hit, or "recomputed" prefill.
            prefix_sources: dict[int, str] = {}
            # Fully-prefilled joiners first (their final segment is
            # the group dispatch below — the real first-token sample).
            # Their trace keeps the ORIGINAL wave's t_admitted /
            # t_prefill, so the engine.prefill span covers the whole
            # interleaved window and the phase partition still
            # reconciles against e2e (the PR 9 test).
            for pend in joins:
                pend.trace.prefill_segments += 1
                self.prefill_segments += 1
                rows.append((
                    pend.slot, pend.rid, pend.req, pend.t_submit,
                    pend.start, pend.tail,
                    self._bucket(len(pend.tail)), pend.trace, pend.plan,
                ))
            for slot, rid, req, t_submit, plan in admissions:
                if plan is not None:
                    # Paged: the prefix was aliased (copy-free) at plan
                    # time; the one device copy is the CoW duplicate of
                    # a partially-covered entry block, chained through
                    # self._cache BEFORE the prefill dispatch below so
                    # the device stream orders copy → tail writes.  A
                    # KV-ship continuation lands its imported blocks
                    # here the same way (import → tail prefill order).
                    ingest = plan.pop("ingest", None)
                    if ingest is not None:
                        self._write_import_blocks(ingest)
                    if plan["cow"] is not None:
                        src, dst = plan["cow"]
                        self._cache = self._cow(
                            self._cache, jnp.int32(src), jnp.int32(dst)
                        )
                    start = plan["start"]
                    prefix_sources[rid] = plan.get(
                        "source", "recomputed"
                    )
                else:
                    start, prefix_sources[rid] = self._try_prefix_inject(
                        slot, req
                    )
                tail = req.tokens[start:]
                # Chunked prefill (long-context admission): write the
                # prompt's KV in prefill_chunk-sized segments so peak
                # admission activations are [S, chunk, d] instead of
                # [S, prompt, d]; only the FINAL segment (the normal
                # group path below) samples the first token.  Exact by
                # the same argument as prefix-cache injection: a KV row
                # depends only on the tokens before it, and each
                # segment attends its predecessors through ``starts``.
                trace = _PhaseTrace(
                    t_submit=t_submit, t_admitted=t_admitted,
                    t_prefill=t_pf,
                    prefix_source=prefix_sources.get(rid, "recomputed"),
                )
                if self.prefill_chunk and len(tail) > self.prefill_chunk:
                    segs = []
                    while len(tail) > self.prefill_chunk:
                        segs.append(tail[: self.prefill_chunk])
                        tail = tail[self.prefill_chunk:]
                    # The FINAL dispatch pads its tail to a bucket;
                    # dynamic_update_slice CLAMPS an out-of-range start,
                    # which would silently overwrite earlier live rows.
                    # Un-chunk from the back (pure list surgery — these
                    # segments were not dispatched yet) until the final
                    # bucketed window fits the cache; worst case this
                    # degenerates to the always-fitting one-shot.
                    fstart = start + len(segs) * self.prefill_chunk
                    while segs and (
                        fstart + self._bucket(len(tail))
                        > self.max_len
                    ):
                        tail = segs.pop() + tail
                        fstart -= self.prefill_chunk
                    if segs:
                        # Interleaved long-prompt admission (ISSUE 20):
                        # dispatch only the FIRST segment now.  The
                        # rest advance one per admission wave — decode
                        # chunks for active slots run between them —
                        # and the request joins a later wave's group
                        # dispatch for its first token.  Exact by the
                        # same argument as same-wave chunking: each
                        # segment's KV depends only on the tokens
                        # before it, decode writes touching this
                        # slot's frontier are overwritten by the next
                        # segment before any read, and the first-token
                        # sample happens once, keyed by the request's
                        # absolute emission index.
                        seg = segs.pop(0)
                        t0 = time.monotonic()
                        self._prefill_segment(slot, req, seg, start, plan)
                        trace.segment_walls.append(
                            time.monotonic() - t0
                        )
                        trace.prefill_segments = 1
                        self.prefill_segments += 1
                        with self._lock:
                            self._prefilling[rid] = _PendingPrefill(
                                rid=rid, req=req, slot=slot, plan=plan,
                                segs=segs, tail=tail,
                                start=start + len(seg),
                                t_submit=t_submit, trace=trace,
                            )
                        continue
                trace.prefill_segments = 1
                self.prefill_segments += 1
                rows.append((slot, rid, req, t_submit, start, tail,
                             self._bucket(len(tail)), trace, plan))
            zero_key = self._zero_key  # hoisted: one PRNGKey per engine
            max_len = self.max_len
            groups = []  # (group rows, first_tokens, first_logprobs)
            for bucket in sorted({r[6] for r in rows}):
                group = [r for r in rows if r[6] == bucket]
                prompts = np.zeros((n_slots, bucket), np.int32)
                # Dummy when history isn't tracked: _admit_batch passes
                # it through, so skip the [S, max_len] transfer.
                full_rows = np.zeros(
                    (n_slots, max_len) if self.spec_decode else (1, 1),
                    np.int32,
                )
                slot_idx = np.full((n_slots,), n_slots, np.int32)  # inert
                starts = np.zeros((n_slots,), np.int32)
                tails = np.ones((n_slots,), np.int32)
                temps = np.zeros((n_slots,), np.float32)
                top_ps = np.ones((n_slots,), np.float32)
                min_ps = np.zeros((n_slots,), np.float32)
                # [1, 1] dummy when penalties are off — _admit_batch
                # passes the state through untouched (track_history's
                # dead-transfer discipline).
                prompt_counts = np.zeros(
                    (n_slots, self.cfg.vocab_size) if self.penalties
                    else (1, 1),
                    np.int32,
                )
                reps = np.ones((n_slots,), np.float32)
                press = np.zeros((n_slots,), np.float32)
                freqs = np.zeros((n_slots,), np.float32)
                keys = [zero_key] * n_slots
                # Paged: per-ROW block tables for the group dispatch —
                # live rows carry their plan's blocks, padding rows
                # stay all-sentinel so their writes drop at the pool
                # edge (the paged twin of the dense scatter's
                # out-of-bounds slot index).  Built from the PLAN, not
                # _tables_host: an abort() on another thread may
                # sentinel the host row mid-wave, and this dispatch's
                # writes must still land in the blocks the plan owns
                # (they are released, garbage, and device-ordered
                # before any reuse either way).
                row_tables = (
                    np.full(
                        (n_slots, self._n_tables), self.kv_blocks,
                        np.int32,
                    )
                    if self.paged else None
                )
                for i, (
                    slot, rid, req, _, start, tail, _, _, plan
                ) in enumerate(group):
                    if row_tables is not None:
                        row_tables[i, : len(plan["blocks"])] = plan[
                            "blocks"
                        ]
                    prompts[i, : len(tail)] = tail
                    if self.spec_decode:
                        full_rows[i, : len(req.tokens)] = req.tokens
                    slot_idx[i] = slot
                    starts[i] = start
                    tails[i] = len(tail)
                    temps[i] = req.temperature
                    top_ps[i] = (
                        self.default_top_p if req.top_p is None else req.top_p
                    )
                    min_ps[i] = req.min_p
                    if self.penalties:
                        prompt_counts[i] = np.bincount(
                            req.tokens, minlength=self.cfg.vocab_size
                        )
                    reps[i] = req.repetition_penalty
                    press[i] = req.presence_penalty
                    freqs[i] = req.frequency_penalty
                    # First-token key at the request's GLOBAL emission
                    # index: 0 for fresh requests, the already-emitted
                    # count for migrated/spliced continuations — what
                    # keeps a continuation sampled-exact (ISSUE 17).
                    keys[i] = jax.random.fold_in(
                        jax.random.PRNGKey(req.seed), req.sample_base
                    )
                t_disp = time.monotonic()
                self._watch_begin()
                (
                    self._cache, self._history,
                    self._tok_counts, self._gen_counts,
                    first, first_lp,
                ) = self._admit(
                    self.params,
                    self._cache,
                    (
                        self._tables_dummy if row_tables is None
                        else jnp.asarray(row_tables)
                    ),
                    self._history,
                    self._tok_counts,
                    self._gen_counts,
                    jnp.asarray(prompt_counts),
                    # Draft mode jits _admit with track_history=False:
                    # the [S, max_len] transfer would be dead there.
                    jnp.asarray(
                        full_rows if self._admit_d is None
                        else np.zeros((1, 1), np.int32)
                    ),
                    jnp.asarray(prompts),
                    jnp.asarray(slot_idx),
                    jnp.asarray(starts),
                    jnp.asarray(tails),
                    jnp.asarray(temps),
                    jnp.asarray(top_ps),
                    jnp.asarray(min_ps),
                    jnp.asarray(reps),
                    jnp.asarray(press),
                    jnp.asarray(freqs),
                    jnp.stack(keys),
                )
                if self._admit_d is not None:
                    # Draft prefill from position 0 over the FULL prompt
                    # (prefix-cache injection is target-rows-only), then
                    # lock the draft cache to the target's new lengths.
                    # Bucketed like the target's prefill: a 50-token
                    # prompt must not pay an O(max_len^2)-attention
                    # draft forward (one _admit_d compile per bucket).
                    full_b = self._bucket(int(np.max(starts + tails)))
                    self._draft_cache = self._admit_d(
                        self.draft_params,
                        self._draft_cache,
                        jnp.asarray(full_rows[:, :full_b]),
                        jnp.asarray(slot_idx),
                        jnp.asarray(starts + tails),
                    )
                self._watch_end()
                self._mark_dispatch(t_disp, acc)
                groups.append((group, first, first_lp))
            for slot, rid, req, _, start, tail, _, _, _ in rows:
                if req.cache_prefix and self.prefix_cache_size:
                    self._store_prefix(
                        slot, req.tokens, tenant=req.tenant or "anon"
                    )
            # ONE combined readback for every admission this step.
            fetched = self._fetch([(f, lp) for _, f, lp in groups], acc)
            # First-token instant for the whole wave (the combined
            # readback IS each admission's first-token arrival).
            t_first = time.monotonic()
            if not self._warming:
                with self._lock:  # vs _fetch_aux on handler threads
                    self.readbacks += 1
            notices = []
            finished: list[_SlotState] = []
            with self._lock:
                for (group, _, _), (f_host, lp_host) in zip(groups, fetched):
                    for i, (
                        slot, rid, req, t_submit, _, _, _, trace, _
                    ) in enumerate(group):
                        if rid not in self._admitting:
                            # abort() (watchdog stall verdict on a live
                            # driver) landed while this admission was
                            # wedged in dispatch/readback: the rid is
                            # already failed, its callback ended, and
                            # the slot already returned to _free —
                            # registering the ghost state here would
                            # double-assign that slot to whoever takes
                            # it next.
                            continue
                        token, lp = int(f_host[i]), float(lp_host[i])
                        self.tokens_generated += 1
                        # Row 7 is the _PhaseTrace built at admission
                        # prep (or carried through an interleaved
                        # pending) — stamp first-token arrival and
                        # adopt it as the slot's phase record, keeping
                        # the prefill span covering the WHOLE
                        # interleaved window (PR 9's partition still
                        # reconciles: queue/prefill/decode sum to e2e).
                        trace.t_first = t_first
                        state = _SlotState(
                            rid=rid, req=req,
                            base=jax.random.PRNGKey(req.seed),
                            t_submit=t_submit,
                            phases=trace,
                        )
                        if rid in self._cancelled:
                            # cancel() landed while this admission was
                            # mid-dispatch: reclaim the slot now, end
                            # the stream, never register the state.
                            self._admitting.pop(rid, None)
                            self._free.append(slot)
                            self._release_slot_blocks_locked(slot)
                            self._fail_locked(
                                rid, "cancelled",
                                "client went away during admission",
                                state=state,
                            )
                            cb = self._callbacks.pop(rid, None)
                            if cb is not None:
                                notices.append((cb, None, None, False))
                            continue
                        done = self._emit(state, token, lp)
                        self._admitting.pop(rid, None)
                        if done:
                            self._finish_locked(slot, state)
                            finished.append(state)
                        else:
                            self._slots[slot] = state
                        cb = (
                            self._callbacks.pop(rid, None) if done
                            else self._callbacks.get(rid)
                        )
                        if cb is not None:
                            notices.append((cb, token, lp, done))
                self._m_active.set(
                    float(len(self._slots)), self._engine_label
                )
            for cb, token, lp, done in notices:  # stream outside the lock
                cb(token, lp)
                if done:
                    cb(None, None)
            self._finalize_done(finished)
            self._drain_fail_obs()  # admission-cancelled rids

    # oimlint: hotpath
    def _dispatch_chunk(
        self, acc: list, chained: _InFlightChunk | None
    ) -> _InFlightChunk:
        """Dispatch one decode chunk; returns its in-flight handle
        WITHOUT reading anything back.

        Fresh (``chained is None``, always the dispatch right after a
        pipeline boundary): every input is built from host slot state,
        exactly the serial engine's arrays.  Chained (a dispatch while
        the previous chunk is still unread): ``tokens`` is the previous
        dispatch's device-side ``next_tok`` carry and ``counts``
        advances by ``chunk`` — exact because every consumed slot
        either emitted exactly ``chunk`` tokens in the unread chunk
        (plain decode; speculative sampled slots emit one per sub-step,
        so their key indices advance by ``chunk`` too) or finished and
        is truncated by the snapshot check in ``_process_chunk``
        (greedy slots never consume the keys at all).  The per-slot
        sampling arrays are reused verbatim: slots that finished while
        the previous chunk was in flight stay marked active and compute
        garbage confined to their own cache region — bounded waste,
        never wrong tokens, and never visible (their states are gone
        from ``_slots`` by processing time and admissions only join at
        boundaries).
        """
        with self._lock:
            slots = dict(self._slots)
            n_slots = self._cache.n_slots

        # Sentinel context (ISSUE 18): a recompile during this chunk's
        # dispatch names the slots' live requests (replaced wholesale;
        # the compile listener reads it lock-free).
        self._sentinel_ctx = {
            "phase": "decode",
            "rids": tuple(sorted(s.rid for s in slots.values())),
        }

        if chained is not None:
            temps_etc = chained.inputs
            tokens = chained.next_tok
            counts = chained.counts + np.int32(self.chunk)
        else:
            temps = jnp.asarray(
                [
                    slots[i].req.temperature if i in slots else 0.0
                    for i in range(n_slots)
                ],
                jnp.float32,
            )
            active = jnp.asarray(
                [i in slots for i in range(n_slots)], bool
            )
            top_ps = jnp.asarray(
                [
                    (
                        self.default_top_p
                        if slots[i].req.top_p is None
                        else slots[i].req.top_p
                    )
                    if i in slots else 1.0
                    for i in range(n_slots)
                ],
                jnp.float32,
            )
            min_ps = jnp.asarray(
                [
                    slots[i].req.min_p if i in slots else 0.0
                    for i in range(n_slots)
                ],
                jnp.float32,
            )
            zero_key = self._zero_key  # hoisted: one PRNGKey per engine
            bases = jnp.stack(
                [
                    slots[i].base if i in slots else zero_key
                    for i in range(n_slots)
                ]
            )
            if self.spec_decode:
                temps_etc = (temps, top_ps, min_ps, active, bases)
            else:
                reps = jnp.asarray(
                    [
                        slots[i].req.repetition_penalty
                        if i in slots else 1.0
                        for i in range(n_slots)
                    ],
                    jnp.float32,
                )
                press = jnp.asarray(
                    [
                        slots[i].req.presence_penalty
                        if i in slots else 0.0
                        for i in range(n_slots)
                    ],
                    jnp.float32,
                )
                freqs = jnp.asarray(
                    [
                        slots[i].req.frequency_penalty
                        if i in slots else 0.0
                        for i in range(n_slots)
                    ],
                    jnp.float32,
                )
                temps_etc = (
                    temps, top_ps, min_ps, reps, press, freqs, active,
                    bases,
                )
            tokens = jnp.asarray(
                [
                    slots[i].last_token if i in slots else 0
                    for i in range(n_slots)
                ],
                jnp.int32,
            )
            counts = np.asarray(
                [
                    # Global emission index, not the slot-local count:
                    # a continuation's sample_base offsets every key
                    # to where the undisturbed stream's would be
                    # (fresh requests carry 0 — bit-identical then).
                    len(slots[i].emitted) + slots[i].req.sample_base
                    if i in slots else 0
                    for i in range(n_slots)
                ],
                np.int32,
            )

        # CURRENT device tables every dispatch (fresh or chained): a
        # slot freed since the last dispatch has a sentinel row by now,
        # so its post-EOS garbage writes drop at the pool edge instead
        # of landing in blocks the allocator may hand to the next
        # admission.
        tables = self._device_tables()
        t_dispatch = time.monotonic()
        self._watch_begin()
        if self.spec_decode and self._draft_cache is not None:
            temps, top_ps, min_ps, active, bases = temps_etc
            (
                self._cache, self._draft_cache, out3, lps3, n_emit,
                next_tok,
            ) = self._decode(
                self.params, self.draft_params, self._cache,
                self._draft_cache, tables, tokens, temps, top_ps, min_ps,
                active, bases, jnp.asarray(counts),
            )
            kind, handles = "spec_model", (out3, lps3, n_emit)
        elif self.spec_decode:
            temps, top_ps, min_ps, active, bases = temps_etc
            (
                self._cache, self._history, out3, lps3, n_emit, next_tok
            ) = self._decode(
                self.params, self._cache, tables, self._history, tokens,
                temps, top_ps, min_ps, active, bases, jnp.asarray(counts),
            )
            kind, handles = "spec", (out3, lps3, n_emit)
        else:
            temps, top_ps, min_ps, reps, press, freqs, active, bases = (
                temps_etc
            )
            (
                self._cache, self._tok_counts, self._gen_counts, out,
                lps, next_tok,
            ) = self._decode(
                self.params, self._cache, tables, self._tok_counts,
                self._gen_counts, tokens, temps, top_ps, min_ps,
                reps, press, freqs, active, bases, jnp.asarray(counts),
            )
            kind, handles = "plain", (out, lps)
        self._watch_end()
        self._mark_dispatch(t_dispatch, acc)
        self._step_count += 1
        self._m_dispatches.inc()
        return _InFlightChunk(
            kind=kind,
            snapshot=slots,
            handles=handles,
            next_tok=next_tok,
            counts=counts,
            inputs=temps_etc,
            t_dispatch=t_dispatch,
            seq=self._step_count,
            dispatch_wall=time.monotonic() - t_dispatch,
        )

    # oimlint: hotpath
    def _process_chunk(self, handle: _InFlightChunk, acc: list) -> None:
        """Fetch one dispatched chunk's tokens and emit them: ONE
        readback per chunk, speculative or not, then EOS/stop/budget
        truncation, completion bookkeeping, and streaming callbacks (in
        submission order per request — the driver thread is the only
        emitter, so pipelining cannot reorder a stream)."""
        t_fetch0 = time.monotonic()
        if handle.kind == "plain":
            out, lps = self._fetch(handle.handles, acc)
            out3, lps3 = out[:, :, None], lps[:, :, None]
            n_emit = np.ones(out3.shape[:2], np.int32)
        else:
            out3, lps3, n_emit = self._fetch(handle.handles, acc)
        if not self._warming:
            with self._lock:  # vs _fetch_aux on handler threads
                self.readbacks += 1
        t_done = time.monotonic()
        # Per-chunk fetch-wait for the decode-span attribution: the
        # wall this processing blocked in device_get (the per-call
        # slice of the step accumulator's fetch-wait total).
        fetch_wall = t_done - t_fetch0
        emitted_total = 0
        notices = []  # (callback, tokens..., end?) fired outside the lock
        finished: list[_SlotState] = []
        with self._lock:
            for slot, state in list(handle.snapshot.items()):
                if self._slots.get(slot) is not state:
                    # The request finished in an earlier chunk while
                    # this one was in flight (pipeline lag): its rows
                    # here are post-EOS garbage — emit nothing.
                    continue
                emitted_total += int(n_emit[slot].sum())
                done = False
                fresh = []
                greedy = state.req.temperature <= 0.0
                for i in range(out3.shape[1]):
                    nem = int(n_emit[slot, i])
                    if self.spec_decode and greedy and not self._warming:
                        self.spec_drafted += self.spec_decode
                    for j in range(nem):
                        token = int(out3[slot, i, j])
                        lp = float(lps3[slot, i, j])
                        self.tokens_generated += 1
                        fresh.append((token, lp))
                        if (
                            self.spec_decode and greedy and j < nem - 1
                            and not self._warming
                        ):
                            # Accepted-AND-consumed drafts only, so the
                            # acceptance-rate diagnostic stays honest at
                            # request tails (host truncation).
                            self.spec_accepted += 1
                        if self._emit(state, token, lp):
                            done = True
                            break
                    if done:
                        break
                if state.phases is not None:
                    # One decode record per chunk this slot consumed
                    # tokens from, marginal (clipped to the previous
                    # chunk's completion — the pipelined dispatch-ahead
                    # lag must not double-count) with the dispatch-wait
                    # vs fetch-wait split riding along.
                    prev_end = (
                        state.phases.chunks[-1][2]
                        if state.phases.chunks
                        else state.phases.t_first
                    )
                    span_start = max(
                        handle.t_dispatch, prev_end or handle.t_dispatch
                    )
                    state.phases.chunks.append((
                        handle.seq, span_start, t_done, len(fresh),
                        handle.dispatch_wall, fetch_wall,
                    ))
                cb = (
                    self._callbacks.pop(state.rid, None) if done
                    else self._callbacks.get(state.rid)
                )
                if cb is not None:
                    notices.append((cb, fresh, done))
                if done and slot in self._slots:
                    self._finish_locked(slot, state)
                    finished.append(state)
        start = handle.t_dispatch
        if self._t_last_chunk_done is not None:
            start = max(start, self._t_last_chunk_done)
        if not self._warming and emitted_total:
            # emitted_total == 0 means nobody consumed this chunk — a
            # tail chunk whose slots an abort/reap already cleared,
            # INCLUDING a transient stall's wedged chunk.  Folding that
            # wall into the EWMA would inflate the watchdog threshold
            # by the stall's own duration and blind it to a re-wedge.
            wall = t_done - handle.t_dispatch
            with self._lock:
                # Stall-watchdog baseline (typical chunk wall) and the
                # marginal token rate Retry-After hints divide by.
                self._chunk_wall_ewma = (
                    wall if self._chunk_wall_ewma is None
                    else 0.7 * self._chunk_wall_ewma + 0.3 * wall
                )
                if t_done > start:
                    rate = emitted_total / (t_done - start)
                    self._token_rate_ewma = (
                        rate if self._token_rate_ewma is None
                        else 0.7 * self._token_rate_ewma + 0.3 * rate
                    )
            self._m_token_latency.observe(
                (t_done - start) / emitted_total
            )
        self._t_last_chunk_done = t_done
        for cb, fresh, done in notices:
            for token, lp in fresh:
                cb(token, lp)
            if done:
                cb(None, None)
        self._finalize_done(finished)

    def run(self) -> dict[int, list[int]]:
        """Drain the queue and all active slots; returns {rid: tokens}."""
        while self.pending():
            self.step()
        with self._lock:
            return {
                rid: list(toks) for rid, (toks, _) in self._results.items()
            }

    def drain(self) -> None:
        """Stop admitting (submit raises ``DrainingError``); already
        queued and active requests run to completion.  The graceful-
        shutdown half-step: the server calls this on SIGTERM, waits for
        in-flight work, then stops — an orchestrator rolling the
        deployment never truncates a client's generation."""
        with self._lock:
            self._draining = True

    def in_flight(self) -> int:
        """Queued + admitting + active + parked + slot-free (beam/
        embed) request count — what a drain waits on (a parked request
        still owes its client tokens)."""
        with self._lock:
            return (
                len(self._queue)
                + len(self._admitting)
                + len(self._slots)
                + len(self._parked)
                + self._aux_active
            )

    def warmup(self, embed: bool = False) -> "Engine":
        """Pre-compile every admit bucket and the whole chunk ladder.

        One dummy request per prompt bucket, sized so the chunk walks
        down the full power-of-two ladder as requests drain.  Serving
        deployments warm before going live: a TPU compile is 20-40 s and
        must never land on live traffic (the control-plane analog is the
        registry pre-dialing controllers it proxies for)."""
        max_len = self._usable_len
        self._warming = True  # dummies must not pollute request metrics
        # The recompile sentinel (serve/sentinel.py) must stay quiet
        # for warmup's own legitimate compiles — including when ANOTHER
        # already-armed engine shares this process (tests, multi-engine
        # embedders): begin/end bracket the whole recipe.
        _sentinel.begin_warmup()

        def fits_pool(tokens: int, max_new: int) -> bool:
            # A small paged pool (legal: short-request deployments) may
            # not hold the largest buckets' worst case — skip those
            # dummies rather than trip the submit-time fit check; live
            # requests that large are rejected the same way.
            if not self.paged:
                return True
            return self._pool_blocks_needed(tokens, max_new) <= (
                self.kv_blocks
            )

        try:
            rids = []
            for b in self.prompt_buckets:
                headroom = max_len - b
                if headroom < 1:
                    continue
                if not fits_pool(b, min(2 * self.chunk, headroom)):
                    continue
                rids.append(self.submit(GenRequest(
                    tokens=[0] * b,
                    max_new_tokens=min(2 * self.chunk, headroom),
                    # With the prefix cache on, also compile its
                    # extract path at every bucket.
                    cache_prefix=bool(self.prefix_cache_size),
                )))
            self.run()
            if self.prefix_cache_size:
                # Compile the inject path per entry bucket: one request
                # extending each cached dummy by one token (its tail
                # rides the smallest bucket, already compiled above; the
                # extended prompt must itself still fit a bucket).
                for b in self.prompt_buckets:
                    if (
                        b + self.prompt_buckets[0] > max_len - 1
                        or b + 1 > self.prompt_buckets[-1]
                        or not fits_pool(b + 1, 1)
                    ):
                        continue
                    rids.append(self.submit(GenRequest(
                        tokens=[0] * (b + 1), max_new_tokens=1,
                    )))
                self.run()
            if self.paged and self.prefix_cache_size:
                # Compile the copy-on-write block duplicate too: the
                # warmup dummies above are block-aligned, so the CoW
                # program (first PARTIALLY-covered prefix hit) would
                # otherwise land its 20-40s compile on live traffic —
                # the recompile guard (tests/test_jit_guard.py) pins
                # this.  src == dst == 0 copies a block onto itself:
                # semantically a no-op, and the indices are traced, so
                # one compile covers every live (src, dst) pair.
                self._cache = self._cow(
                    self._cache, jnp.int32(0), jnp.int32(0)
                )
            if self.paged and (not self.kv_int4 or self._host is not None):
                # Compile the KV-ship ingest write too (ONE program, dst
                # traced): the first PUT /v1/kv continuation must not
                # pay a mid-stream compile — the CoW-precompile rule
                # applied to disaggregation.  Pool contents here are
                # warmup dummies (cleared below), so zeroing block 0 is
                # inert.  kv4 engines skip it UNLESS the host tier is
                # on: their ships are refused at import/export, but
                # host-tier promotions ride the same program locally
                # (ISSUE 15) and must not compile mid-stream either.
                zk = jnp.zeros(
                    (self.cfg.n_layers, self.kv_block, self.cfg.kv_heads,
                     self.cfg.head_dim),
                    self._cache.k.dtype,
                )
                zs = (
                    jnp.zeros(
                        (self.cfg.n_layers, self.kv_block,
                         self.cfg.kv_heads),
                        jnp.float32,
                    )
                    if self.kv_quant
                    else jnp.zeros((1,), jnp.float32)
                )
                self._cache = self._ingest(
                    self._cache, zk, zk, zs, zs, jnp.int32(0)
                )
            if self._host is not None:
                # Compile the host tier's whole device surface (ISSUE
                # 15) so warm demote/promote/park cycles run at zero
                # steady-state compiles (the jit-guard pin): the
                # per-leaf read_block programs (one per pool leaf
                # shape — k/v share, scales share) and the slot-restore
                # scatter.  Reads of block 0 and a self-restore of
                # slot 0's current state are inert; the fetch below
                # also compiles nothing (device_get is not a program).
                reads = [
                    self._read_block(getattr(self._cache, name),
                                     jnp.int32(0))
                    for name, _ in self._host.pools()
                ]
                self._fetch_aux(reads)
                if self.kv_park:
                    dummy_state = _SlotState(
                        rid=-1,
                        req=GenRequest(tokens=[0], max_new_tokens=1),
                        base=self._zero_key,
                        t_submit=time.monotonic(),
                        emitted=[0],
                    )
                    self._restore_slot_state(0, dummy_state, 0)
            if embed:
                # Optional: one full-forward compile per bucket — only
                # deployments that actually serve /v1/embed should pay it.
                for b in self.prompt_buckets:
                    self.embed([0] * min(b, max_len - 1))
            for rid in rids:  # consume the dummies; warmup must not retain
                self.result(rid, timeout=0)
            with self._lock:  # dummy prompts must not occupy live entries
                self._clear_prefix_cache_locked()
                # Warmup pressure may have DEMOTED dummy entries
                # (exercising the tier is fine — precompiled paths are
                # the point) but they must not squat in the host
                # budget after: flush the host tier too.
                self._flush_host_tier_locked()
        finally:
            self._warming = False
            _sentinel.end_warmup()
        # Steady-state latch (ISSUE 18): every surface above is now
        # precompiled, so from here on any XLA compile in this process
        # is a production incident — arm the sentinel (inert unless the
        # daemon installed the listener) so it fires a serve.recompile
        # WARNING with this engine's live request context.
        _sentinel.arm(self)
        return self
