"""Serving router: registry-discovered load balancing over oim-serve.

The reference's central routing idea — clients address components by ID
through the registry, never by network address
(/root/reference/pkg/oim-registry/registry.go:162-189) — applied to the
inference data plane: N ``oim-serve`` backends self-register
``serve/<id>/address`` keys (the controller heartbeat pattern,
/root/reference/pkg/oim-controller/controller.go:425-443), and this
router discovers them by prefix query, health-checks them, and
least-active balances the HTTP serving API across them.

Scope: the router is a *dispatcher*, not a batch merger — each request
runs wholly on one backend (continuous batching happens inside the
backend engine).  That keeps the router stateless and restartable, the
same property the reference's transparent proxy has.

Behavior:
- Balancing: least active in-flight requests among healthy backends
  (ties broken round-robin).  Generate requests sharing a long prompt
  prefix prefer one rendezvous-hashed backend (whose prefix cache
  holds that prefix) unless it is overloaded — cache locality without
  hot-prefix starvation.
- Health: GET /healthz per backend on an interval; a backend is out
  after ``unhealthy_after`` consecutive failures and back on the first
  success.  A request-level connection failure counts too, so a dead
  backend stops receiving traffic immediately, not at the next probe.
- Retry: a request that fails at the CONNECTION level before any
  response byte is retried once on a different backend; once a backend
  has begun answering, errors pass through (the request may have side
  effects — generation is not idempotent under sampling seeds... it is
  by seed, but the single-retry bound keeps tail latency sane anyway).
- Streaming: NDJSON bodies are piped through chunk-by-chunk unchanged.

Endpoints: the serving API (POST /v1/generate, /v1/beam, /v1/embed,
and the OpenAI-compatible /v1/completions) proxied; GET /healthz (ok while ≥1 backend is healthy), /v1/stats
(router counters + per-backend state), /metrics (Prometheus).
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.error
import urllib.request
from concurrent import futures
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oim_tpu import log
from oim_tpu.common import metrics
from oim_tpu.serve.httptls import check_serving_peer

PROXIED = (
    "/v1/generate",
    "/v1/beam",
    "/v1/embed",
    "/v1/completions",
    "/v1/chat/completions",
)


@dataclass
class Backend:
    """One oim-serve instance as the router sees it."""

    id: str
    url: str  # http://host:port, no trailing slash
    from_registry: bool = False
    healthy: bool = True
    active: int = 0
    completed: int = 0
    fails: int = 0  # consecutive health/connection failures
    # From the backend's /v1/info (fetched once at the first successful
    # probe): whether its engine runs a prompt-prefix cache.  Affinity
    # routing only applies to cache-running backends — pinning a hot
    # prefix to one backend is pure load skew if nothing caches it.
    prefix_cache: bool = False
    # Also from /v1/info: the engine's decode pipeline depth (2 =
    # dispatch-ahead double buffering).  Surfaced in the router's
    # /v1/stats so a fleet operator can spot a replica accidentally
    # running serial (pipeline_depth 1) — roughly a 2x throughput skew
    # on tunneled deployments — without curling every backend.
    pipeline_depth: int = 0
    info_fetched: bool = False


class Router:
    """Owns the backend table, the health/discovery loops, and the HTTP
    listener.  ``start()`` returns self; ``port`` is the bound port
    (0 → ephemeral, the ``NonBlockingGRPCServer.addr()`` pattern)."""

    def __init__(
        self,
        backends: tuple[str, ...] = (),
        registry_address: str = "",
        tls=None,
        host: str = "127.0.0.1",
        port: int = 0,
        health_interval: float = 2.0,
        discover_interval: float = 5.0,
        unhealthy_after: int = 2,
        request_timeout: float = 600.0,
        ssl_context=None,
        client_ssl_context=None,
        affinity_prefix_tokens: int = 32,
        affinity_slack: int = 2,
    ):
        """``ssl_context`` wraps the router's own listener in mTLS;
        ``client_ssl_context`` authenticates the router to mTLS
        backends (httptls module — the reference's mTLS-everywhere
        stance on the serving data plane)."""
        if not backends and not registry_address:
            raise ValueError(
                "router needs static --backend urls or a registry address"
            )
        self._lock = threading.Lock()
        self._backends: dict[str, Backend] = {
            url.rstrip("/"): Backend(id=url.rstrip("/"), url=url.rstrip("/"))
            for url in backends
        }
        self.registry_address = registry_address
        self._tls = tls
        self.health_interval = health_interval
        self.discover_interval = discover_interval
        self.unhealthy_after = unhealthy_after
        self.request_timeout = request_timeout
        self.affinity_prefix_tokens = affinity_prefix_tokens
        self.affinity_slack = affinity_slack
        self._stop = threading.Event()
        self._rr = 0
        self._probing: set[str] = set()
        self._probe_pool = futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="router-probe"
        )
        self._watch_call = None  # in-flight WatchValues stream, for stop()
        from oim_tpu.serve.httptls import opener as _tls_opener

        self._client_ssl = client_ssl_context
        self._opener = _tls_opener(client_ssl_context)
        self._requests = metrics.registry().counter(
            "oim_route_requests_total",
            "Requests proxied by the serving router",
            labels=("backend", "outcome"),
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # Serving-plane CN pinning (httptls module docstring):
                # under mTLS the peer must carry a serve./route./user.
                # identity, not merely any deployment-CA cert.
                if not check_serving_peer(self):
                    return
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    metrics.write_exposition(self)
                elif path == "/v1/info":
                    # Backends are homogeneous replicas of one model;
                    # answer from any healthy one so clients behind the
                    # router can introspect without backend addresses.
                    # Full _proxy semantics apply: single retry,
                    # error attribution, metrics, trace propagation.
                    outer._proxy(self, "/v1/info", None, self._fwd_headers())
                elif path == "/healthz":
                    n = len(outer.healthy_backends())
                    self._json(
                        200 if n else 503,
                        {"ok": bool(n), "healthy_backends": n},
                    )
                elif path == "/v1/stats":
                    self._json(200, outer.stats())
                else:
                    self._json(404, {"error": f"no such path {path}"})

            def _fwd_headers(self, extra: dict | None = None) -> dict:
                """Outbound headers for the backend hop: propagate the
                caller's trace context, like every other component
                boundary here."""
                headers = dict(extra or {})
                if self.headers.get("traceparent"):
                    headers["traceparent"] = self.headers["traceparent"]
                return headers

            def do_POST(self):
                if not check_serving_peer(self):
                    return
                if self.path not in PROXIED:
                    self._json(404, {"error": f"no such path {self.path}"})
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                headers = self._fwd_headers(
                    {"Content-Type": "application/json"}
                )
                outer._proxy(self, self.path, body, headers)

        if ssl_context is not None:
            from oim_tpu.serve.httptls import TLSThreadingHTTPServer

            self._httpd = TLSThreadingHTTPServer(
                (host, port), Handler, ssl_context
            )
        else:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.tls = ssl_context is not None
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._discover_thread = (
            threading.Thread(target=self._discover_loop, daemon=True)
            if registry_address
            else None
        )

    # -- backend table -----------------------------------------------------

    def healthy_backends(self) -> list[Backend]:
        with self._lock:
            return [b for b in self._backends.values() if b.healthy]

    def _pick(
        self,
        exclude: set[str] = frozenset(),
        affinity_key: str | None = None,
    ) -> Backend | None:
        """Least-active healthy backend, round-robin among ties.

        ``affinity_key`` biases the choice: the key's rendezvous-hash
        winner (stable under backend churn, no shared state) is taken
        as long as it isn't overloaded — more than ``affinity_slack``
        in-flight requests above the least-active backend.  This is how
        per-backend prompt-prefix caches stay useful behind the router:
        requests sharing a prefix land on the backend whose cache holds
        it, but a hot prefix cannot starve the fleet."""
        with self._lock:
            ready = [
                b
                for b in self._backends.values()
                if b.healthy and b.id not in exclude
            ]
            if not ready:
                return None
            least = min(b.active for b in ready)
            cacheable = [b for b in ready if b.prefix_cache]
            if affinity_key is not None and cacheable:
                affine = max(
                    cacheable,
                    key=lambda b: hashlib.sha256(
                        f"{affinity_key}|{b.id}".encode()
                    ).digest(),
                )
                if affine.active <= least + self.affinity_slack:
                    affine.active += 1
                    return affine
            tied = [b for b in ready if b.active == least]
            self._rr += 1
            chosen = tied[self._rr % len(tied)]
            chosen.active += 1
            return chosen

    def _release(self, backend: Backend, ok: bool) -> None:
        with self._lock:
            backend.active = max(0, backend.active - 1)
            if ok:
                backend.completed += 1
                backend.fails = 0
            # NOTE: HTTP-level errors (4xx/5xx) are NOT connection
            # failures — only _connection_failed flips health.

    def _connection_failed(self, backend: Backend) -> None:
        """A connect-level failure counts against health immediately —
        a dead backend must stop receiving traffic before the next
        probe tick."""
        with self._lock:
            backend.fails += 1
            if backend.fails >= self.unhealthy_after:
                if backend.healthy:
                    log.current().warning(
                        "backend unhealthy", backend=backend.id
                    )
                backend.healthy = False

    # -- proxying ----------------------------------------------------------

    def _affinity_key(self, path: str, body: bytes | None) -> str | None:
        """Prompt-prefix affinity for the generation endpoints
        (/v1/generate and the OpenAI-compatible /v1/completions):
        requests whose first ``affinity_prefix_tokens`` token ids match
        should share a backend (that backend's prefix cache holds their
        prefix).  Any parse problem means no affinity — never an
        error."""
        if (
            self.affinity_prefix_tokens <= 0
            or path not in (
                "/v1/generate", "/v1/completions", "/v1/chat/completions"
            )
            or not body
        ):
            return None
        try:
            payload = json.loads(body)
            ids = payload.get("tokens")
            text = payload.get("text")
            if path == "/v1/completions":
                # OpenAI field: prompt is a string or a token list.
                prompt = payload.get("prompt")
                if isinstance(prompt, list):
                    ids = prompt
                elif isinstance(prompt, str):
                    text = prompt
            elif path == "/v1/chat/completions":
                # Chat requests sharing a system prompt share their
                # leading messages; the serialized role:content stream
                # proxies the templated token prefix (the router has no
                # tokenizer or template).
                messages = payload.get("messages")
                if isinstance(messages, list):
                    text = "".join(
                        f"{m.get('role', '')}:{m.get('content', '')};"
                        for m in messages
                        if isinstance(m, dict)
                    )
            if ids is not None:
                prefix = ids[: self.affinity_prefix_tokens]
                if len(prefix) < self.affinity_prefix_tokens:
                    return None  # short prompts: balance freely
                return ",".join(str(int(t)) for t in prefix)
            # Text surface: the router has no tokenizer, so the leading
            # CHARACTERS proxy the token prefix (~4 chars/token).  Same
            # shared-prefix requests → same key → same backend cache.
            if isinstance(text, str):
                n_chars = 4 * self.affinity_prefix_tokens
                if len(text) < n_chars:
                    return None
                return "txt:" + text[:n_chars]
            return None
        except Exception:
            return None

    def _proxy(
        self, handler, path: str, body: bytes | None, headers: dict
    ) -> None:
        """Proxy one request to a healthy backend (``body`` None = GET —
        urllib's method selection; bytes = POST)."""
        tried: set[str] = set()
        affinity_key = self._affinity_key(path, body)
        while len(tried) < 2:  # the documented single-retry bound
            backend = self._pick(exclude=tried, affinity_key=affinity_key)
            if backend is None:
                handler._json(
                    503,
                    {
                        "error": "no healthy serving backend"
                        + (f" (tried {sorted(tried)})" if tried else "")
                    },
                )
                return
            tried.add(backend.id)
            req = urllib.request.Request(
                backend.url + path, data=body, headers=headers
            )
            try:
                resp = self._opener.open(req, timeout=self.request_timeout)
            except urllib.error.HTTPError as exc:
                # The backend answered — pass its error through verbatim
                # (its body is JSON already) and do not retry.
                self._release(backend, ok=False)
                self._requests.inc(backend.id, f"http_{exc.code}")
                payload = exc.read()
                handler.send_response(exc.code)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Content-Length", str(len(payload)))
                handler.end_headers()
                handler.wfile.write(payload)
                return
            except (urllib.error.URLError, OSError) as exc:
                # Connection-level failure before any response byte:
                # safe to retry once elsewhere.
                self._release(backend, ok=False)
                self._connection_failed(backend)
                self._requests.inc(backend.id, "connect_error")
                log.current().warning(
                    "backend connect failed",
                    backend=backend.id,
                    error=str(getattr(exc, "reason", exc)),
                )
                continue
            # Copy the response, attributing socket errors to the right
            # side: resp.* errors are the BACKEND's (health penalty, no
            # retry — bytes may already be with the client), wfile.*
            # errors are OUR client leaving (backend is fine).
            backend_died = client_gone = False
            copied = 0
            clen = resp.headers.get("Content-Length")
            with resp:
                try:
                    handler.send_response(resp.status)
                    handler.send_header(
                        "Content-Type",
                        resp.headers.get("Content-Type", "application/json"),
                    )
                    if clen is not None:
                        handler.send_header("Content-Length", clen)
                    if resp.headers.get("traceparent"):
                        handler.send_header(
                            "traceparent", resp.headers["traceparent"]
                        )
                    handler.end_headers()
                except (BrokenPipeError, ConnectionResetError):
                    client_gone = True
                # Chunked copy keeps NDJSON streams streaming.
                while not (backend_died or client_gone):
                    try:
                        chunk = resp.read(8192)
                    except OSError:
                        backend_died = True
                        break
                    if not chunk:
                        break
                    try:
                        handler.wfile.write(chunk)
                        handler.wfile.flush()
                        copied += len(chunk)
                    except (BrokenPipeError, ConnectionResetError):
                        client_gone = True
            # A backend killed mid-response often closes with a clean
            # FIN, indistinguishable from end-of-body on close-delimited
            # streams — but when Content-Length was declared, a short
            # copy is proof of truncation.
            if clen is not None and not client_gone and copied < int(clen):
                backend_died = True
            if backend_died:
                self._release(backend, ok=False)
                self._connection_failed(backend)
                self._requests.inc(backend.id, "truncated")
            elif client_gone:
                self._release(backend, ok=True)
                self._requests.inc(backend.id, "client_disconnected")
            else:
                self._release(backend, ok=True)
                self._requests.inc(backend.id, "ok")
            return
        handler._json(
            503,
            {"error": f"no healthy serving backend (tried {sorted(tried)})"},
        )

    # -- health + discovery ------------------------------------------------

    def _probe(self, backend: Backend) -> None:
        err: Exception | None = None
        try:
            with self._opener.open(
                backend.url + "/healthz", timeout=2
            ) as resp:
                ok = resp.status == 200
            if ok and not backend.info_fetched:
                self._fetch_info(backend)
        except Exception as exc:
            # Any probe failure means unhealthy — including non-OSError
            # ones like a malformed registry-advertised URL (ValueError);
            # swallowing those silently would pin the backend healthy
            # forever.  Logged below on the healthy→unhealthy transition
            # only, never per-tick.
            err = exc
            ok = False
        with self._lock:
            if ok:
                if not backend.healthy:
                    log.current().info(
                        "backend recovered", backend=backend.id
                    )
                backend.healthy = True
                backend.fails = 0
            else:
                backend.fails += 1
                if backend.fails >= self.unhealthy_after:
                    if backend.healthy:
                        log.current().warning(
                            "backend unhealthy",
                            backend=backend.id,
                            error=str(err) if err else "probe failed",
                        )
                    backend.healthy = False

    def _fetch_info(self, backend: Backend) -> None:
        """One-time /v1/info fetch for affinity capability (the payload
        is static by contract).  Failure leaves info_fetched False, so
        the next probe retries."""
        try:
            with self._opener.open(
                backend.url + "/v1/info", timeout=2
            ) as resp:
                info = json.loads(resp.read())
        except Exception:
            return
        with self._lock:
            backend.prefix_cache = bool(
                info.get("engine", {}).get("prefix_cache_size", 0)
            )
            backend.pipeline_depth = int(
                info.get("engine", {}).get("pipeline_depth", 0)
            )
            backend.info_fetched = True

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval):
            with self._lock:
                snapshot = [
                    b
                    for b in self._backends.values()
                    if b.id not in self._probing
                ]
                self._probing.update(b.id for b in snapshot)
            # Probe concurrently: N dead backends each eat their full
            # 2 s connect timeout, and a serial sweep would stall the
            # whole loop N× past health_interval, delaying both
            # unhealthy detection and recovery of live backends.  The
            # _probing guard means a stalled probe skips (not overlaps)
            # its backend on later ticks, so results never go stale.
            for backend in snapshot:
                try:
                    self._probe_pool.submit(self._probe_tracked, backend)
                except RuntimeError:  # pool shut down mid-sweep (stop())
                    with self._lock:
                        self._probing.discard(backend.id)
                    return

    def _probe_tracked(self, backend: Backend) -> None:
        try:
            self._probe(backend)
        finally:
            with self._lock:
                self._probing.discard(backend.id)

    def _discover_loop(self) -> None:
        """Event-driven discovery: hold a registry WatchValues stream on
        the ``serve/`` prefix and apply each mutation as it happens — a
        deregistered or lease-expired backend leaves the table at the
        DELETE event, in milliseconds, not at the next poll tick.  On
        stream failure, back off ``discover_interval`` and reconnect
        (the controller heartbeat's never-die rule); each reconnect
        starts with a full reconcile, so missed events can't strand a
        stale backend."""
        while not self._stop.is_set():
            try:
                self._watch_discover()
            except Exception as exc:
                if self._stop.is_set():
                    return
                log.current().warning(
                    "registry watch discovery failed; polling this tick",
                    registry=self.registry_address,
                    error=str(exc),
                )
                # Degrade to poll cadence while the watch path is broken
                # (old server, watcher cap RESOURCE_EXHAUSTED, registry
                # bounce): slower discovery beats none.
                try:
                    self._discover_once()
                except Exception:
                    pass
            if self._stop.wait(self.discover_interval):
                return

    def _watch_discover(self) -> None:
        """One watch session.  ``send_initial`` snapshot → reconcile at
        the ``initial_done`` marker → apply live events.  The server
        subscribes BEFORE snapshotting, so nothing falls between the
        snapshot and the event stream (doc/spec.md WatchValuesReply)."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        with registry_channel(self.registry_address, self._tls) as channel:
            stub = REGISTRY.stub(channel)
            call = stub.WatchValues(
                oim_pb2.WatchValuesRequest(path="serve", send_initial=True)
            )
            self._watch_call = call
            # stop() sets _stop BEFORE reading _watch_call; if it ran in
            # the window before the assignment above it found None and
            # cancelled nothing — re-check here so the discover thread
            # cannot block forever in the stream iteration on a quiet
            # registry.
            if self._stop.is_set():
                call.cancel()
                self._watch_call = None
                return
            try:
                snapshot: dict[str, str] = {}
                in_snapshot = True
                for event in call:
                    if self._stop.is_set():
                        return
                    if in_snapshot:
                        if event.initial_done:
                            self._reconcile(snapshot)
                            in_snapshot = False
                            continue
                        sid = self._serve_id(event.value.path)
                        if sid is not None and event.value.value:
                            snapshot[sid] = event.value.value.rstrip("/")
                        continue
                    self._apply_event(event.value.path, event.value.value)
            finally:
                self._watch_call = None
                call.cancel()

    @staticmethod
    def _serve_id(path: str) -> str | None:
        parts = path.split("/")
        if len(parts) == 3 and parts[0] == "serve" and parts[2] == "address":
            return parts[1]
        return None

    def _apply_event(self, path: str, value: str) -> None:
        sid = self._serve_id(path)
        if sid is None:
            return
        with self._lock:
            if value == "":
                b = self._backends.get(sid)
                if b is not None and b.from_registry:
                    log.current().info("backend withdrawn", backend=sid)
                    del self._backends[sid]
                return
            self._upsert_locked(sid, value.rstrip("/"))

    def _upsert_locked(self, sid: str, url: str) -> None:
        existing = self._backends.get(sid)
        if existing is None:
            log.current().info("backend discovered", backend=sid, url=url)
            self._backends[sid] = Backend(id=sid, url=url, from_registry=True)
        elif existing.url != url:
            # Same id, new address: the instance moved (the
            # channel-cache-era controller-move semantics).  A restart
            # may change capabilities too — re-fetch /v1/info.
            log.current().info("backend moved", backend=sid, url=url)
            existing.url = url
            existing.healthy = True
            existing.fails = 0
            existing.info_fetched = False
            existing.prefix_cache = False

    def _reconcile(self, found: dict[str, str]) -> None:
        """Full-state reconcile: registry-sourced entries come and go
        with their keys; static ones are permanent."""
        with self._lock:
            for sid, url in found.items():
                self._upsert_locked(sid, url)
            for sid in list(self._backends):
                b = self._backends[sid]
                if b.from_registry and sid not in found:
                    log.current().info("backend withdrawn", backend=sid)
                    del self._backends[sid]

    def _discover_once(self) -> None:
        """One-shot poll + reconcile (kept for embedders and tests; the
        running router uses the watch stream)."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        with registry_channel(self.registry_address, self._tls) as channel:
            reply = REGISTRY.stub(channel).GetValues(
                oim_pb2.GetValuesRequest(path="serve"), timeout=10
            )
        found: dict[str, str] = {}
        for value in reply.values:
            sid = self._serve_id(value.path)
            if sid:
                found[sid] = value.value.rstrip("/")
        self._reconcile(found)

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "backends": {
                    b.id: {
                        "url": b.url,
                        "healthy": b.healthy,
                        "active": b.active,
                        "completed": b.completed,
                        "from_registry": b.from_registry,
                        # 0 until the first /v1/info fetch succeeds.
                        "pipeline_depth": b.pipeline_depth,
                    }
                    for b in self._backends.values()
                },
            }

    def start(self) -> "Router":
        self._http_thread.start()
        self._health_thread.start()
        if self._discover_thread is not None:
            self._discover_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        call = self._watch_call
        if call is not None:
            call.cancel()  # unblock the discover thread's stream iteration
        # shutdown() handshakes with serve_forever and deadlocks if the
        # listener thread never started (constructed-but-unstarted
        # routers are legal — unit tests, failed startups).
        if self._http_thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
        # Join the loops before tearing down what they touch: an
        # unjoined health/discover thread can fire one more probe or
        # reconcile against the closed probe pool after stop() returns
        # (and a stopped-then-restarted test registry would see a ghost
        # watcher from the previous router).  Bounded: both loops
        # observe _stop within one wait() tick and the watch call is
        # already cancelled.
        for thread in (
            self._http_thread, self._health_thread, self._discover_thread
        ):
            if thread is not None and thread.is_alive():
                thread.join(timeout=5)
        self._probe_pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            # Cancelled futures never reach _probe_tracked's finally.
            self._probing.clear()
